"""InferenceTranspiler conv+batch_norm fold
(reference: transpiler/inference_transpiler.py:300 _fuse_batch_norm,
test analogue: the reference exercises the fold through
test_inference_model_io / book image-classification inference runs).

Trains a small convnet a few steps so the BN moving statistics are
non-trivial, then checks the folded inference program (a) no longer
contains batch_norm ops, (b) produces the same outputs, and (c) keeps
residual-style multi-consumer conv outputs unfused."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers


def _train_convnet(steps=3, with_bias=False, branchy=False):
    x = layers.data("x", [3, 8, 8], dtype="float32")
    y = layers.data("y", [1], dtype="int64")
    bias_attr = True if with_bias else False
    c1 = layers.conv2d(x, num_filters=4, filter_size=3, padding=1,
                       bias_attr=bias_attr)
    b1 = layers.batch_norm(c1)
    h = layers.relu(b1)
    if branchy:
        # conv output consumed by BN *and* a residual add: must not fold
        c2 = layers.conv2d(h, num_filters=4, filter_size=3, padding=1,
                           bias_attr=False)
        b2 = layers.batch_norm(c2)
        h = layers.elementwise_add(layers.relu(b2), c2)
    pool = layers.pool2d(h, pool_size=8, pool_type="avg")
    pred = layers.fc(pool, size=3, act="softmax")
    loss = layers.mean(layers.cross_entropy(pred, y))
    fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(7)
    xv = rng.randn(4, 3, 8, 8).astype("float32")
    yv = rng.randint(0, 3, size=(4, 1)).astype("int64")
    for _ in range(steps):
        exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])
    return exe, pred, xv


def _bn_count(program):
    return sum(op.type == "batch_norm" for op in program.global_block().ops)


def _run_fold_case(with_bias):
    exe, pred, xv = _train_convnet(with_bias=with_bias)
    infer = fluid.io.get_inference_program([pred])
    (ref,) = exe.run(program=infer, feed={"x": xv}, fetch_list=[pred])

    assert _bn_count(infer) == 1
    t = fluid.InferenceTranspiler()
    t.transpile(infer, fluid.CPUPlace())
    assert _bn_count(infer) == 0
    # the fold leaves one channel-bias add where the bn used to be (the fc
    # layer contributes its own bias add; only the conv-side one matters)
    conv_out = next(op for op in infer.global_block().ops
                    if op.type == "conv2d").output("Output")[0]
    adds = [op for op in infer.global_block().ops
            if op.type == "elementwise_add" and conv_out in op.input("X")]
    assert len(adds) == 1 and adds[0].attr("axis") == 1

    (out,) = exe.run(program=infer, feed={"x": xv}, fetch_list=[pred])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_fold_conv_without_bias():
    _run_fold_case(with_bias=False)


def test_fold_conv_with_bias():
    _run_fold_case(with_bias=True)


def test_multi_consumer_conv_not_folded():
    exe, pred, xv = _train_convnet(branchy=True)
    infer = fluid.io.get_inference_program([pred])
    (ref,) = exe.run(program=infer, feed={"x": xv}, fetch_list=[pred])

    assert _bn_count(infer) == 2
    fluid.InferenceTranspiler().transpile(infer, fluid.CPUPlace())
    # first conv folds; the residual conv (two consumers) must survive
    assert _bn_count(infer) == 1

    (out,) = exe.run(program=infer, feed={"x": xv}, fetch_list=[pred])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_unused_bn_params_pruned_from_desc():
    exe, pred, xv = _train_convnet()
    infer = fluid.io.get_inference_program([pred])
    block = infer.global_block()
    bn_op = next(op for op in block.ops if op.type == "batch_norm")
    stat_vars = [bn_op.input("Scale")[0], bn_op.input("Mean")[0],
                 bn_op.input("Variance")[0]]
    for name in stat_vars:
        assert block.desc.has_var(name)
    fluid.InferenceTranspiler().transpile(infer, fluid.CPUPlace())
    for name in stat_vars:
        assert not block.desc.has_var(name)


def test_protected_fetch_target_not_folded():
    """A conv output that is itself a fetch target must keep its values:
    passing it via protected_vars disqualifies the fold."""
    exe, pred, xv = _train_convnet()
    infer = fluid.io.get_inference_program([pred])
    conv_out = next(op for op in infer.global_block().ops
                    if op.type == "conv2d").output("Output")[0]
    fluid.InferenceTranspiler().transpile(
        infer, fluid.CPUPlace(), protected_vars=[conv_out])
    assert _bn_count(infer) == 1  # fold skipped


def test_analysis_predictor_applies_fold(tmp_path):
    """AnalysisPredictor with enable_ir_optim folds BN at build time and
    still matches the unoptimized NativePredictor (reference analogue:
    AnalysisPredictor::OptimizeInferenceProgram)."""
    from paddle_tpu.inference import (AnalysisConfig, NativeConfig,
                                      create_paddle_predictor)

    exe, pred, xv = _train_convnet()
    d = str(tmp_path / "model")
    fluid.io.save_inference_model(d, ["x"], [pred], exe)

    native = create_paddle_predictor(NativeConfig(model_dir=d))
    (ref,) = native.run_dict({"x": xv})
    assert _bn_count(native.program) == 1

    analysis = create_paddle_predictor(AnalysisConfig(model_dir=d))
    assert _bn_count(analysis.program) == 0
    (out,) = analysis.run_dict({"x": xv})
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_originals_survive_fold_for_live_training():
    """The reference's documented usage: transpile an inference clone()
    against the SHARED global scope while the training program is still
    live (reference _fuse_param writes '<name>_fuse_bn' copies,
    inference_transpiler.py:435).  The original Filter/Bias values must
    survive untouched so continued training and save_persistables see the
    true weights."""
    exe, pred, xv = _train_convnet(with_bias=True)
    infer = fluid.io.get_inference_program([pred])
    block = infer.global_block()
    conv = next(op for op in block.ops if op.type == "conv2d")
    w_name = conv.input("Filter")[0]
    scope = fluid.global_scope()
    w_before = np.array(np.asarray(scope.find_var(w_name)))

    fluid.InferenceTranspiler().transpile(infer, fluid.CPUPlace())

    # conv now reads a renamed persistable copy; the original is untouched
    new_w = conv.input("Filter")[0]
    assert new_w == w_name + "_fuse_bn"
    assert block.desc.has_var(new_w) and block.desc.vars[new_w].persistable
    np.testing.assert_array_equal(
        np.asarray(scope.find_var(w_name)), w_before)
    assert not np.array_equal(np.asarray(scope.find_var(new_w)), w_before)

    # training on the ORIGINAL program still runs and moves the true weights
    y = np.zeros((4, 1), dtype="int64")
    exe.run(feed={"x": xv, "y": y},
            fetch_list=[fluid.default_main_program().global_block().ops[-1]
                        .output("ParamOut")[0]])


def test_weight_shared_filter_folds_safely():
    """Two convs sharing one Filter parameter, each followed by its own BN:
    with copy-based folding each conv gets its OWN '<w>_fuse_bn' copy
    (unique-suffixed on collision), the shared original is never scaled,
    and both folds run."""
    x = layers.data("x", [3, 8, 8], dtype="float32")
    y = layers.data("y", [1], dtype="int64")
    shared = fluid.ParamAttr(name="shared_w")
    c1 = layers.conv2d(x, num_filters=4, filter_size=3, padding=1,
                       bias_attr=False, param_attr=shared)
    c2 = layers.conv2d(x, num_filters=4, filter_size=3, padding=1,
                       bias_attr=False, param_attr=shared)
    h = layers.elementwise_add(layers.batch_norm(c1), layers.batch_norm(c2))
    pool = layers.pool2d(h, pool_size=8, pool_type="avg")
    pred = layers.fc(pool, size=3, act="softmax")
    loss = layers.mean(layers.cross_entropy(pred, y))
    fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(9)
    xv = rng.randn(4, 3, 8, 8).astype("float32")
    yv = rng.randint(0, 3, size=(4, 1)).astype("int64")
    exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])

    infer = fluid.io.get_inference_program([pred])
    (ref,) = exe.run(program=infer, feed={"x": xv}, fetch_list=[pred])
    shared_before = np.array(
        np.asarray(fluid.global_scope().find_var("shared_w")))
    fluid.InferenceTranspiler().transpile(infer, fluid.CPUPlace())
    assert _bn_count(infer) == 0  # both fold, each into its own copy
    convs = [op for op in infer.global_block().ops if op.type == "conv2d"]
    names = sorted(op.input("Filter")[0] for op in convs)
    assert names == ["shared_w_fuse_bn", "shared_w_fuse_bn_2"]
    np.testing.assert_array_equal(
        np.asarray(fluid.global_scope().find_var("shared_w")), shared_before)
    (out,) = exe.run(program=infer, feed={"x": xv}, fetch_list=[pred])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_fused_bn_add_act_folds():
    """The default-built conv stacks emit fused_bn_add_act (Z-free); the
    transpiler must fold those exactly like batch_norm, re-emitting the
    activation as a standalone relu after the folded bias add."""
    fluid.reset_default_env()
    x = layers.data("x", [3, 8, 8], dtype="float32")
    y = layers.data("y", [1], dtype="int64")
    conv = layers.conv2d(x, num_filters=4, filter_size=3, padding=1,
                         bias_attr=False)
    h = layers.fused_bn_add_act(conv, None, act="relu")
    pool = layers.pool2d(h, pool_size=8, pool_type="avg")
    pred = layers.fc(pool, size=3, act="softmax")
    loss = layers.mean(layers.cross_entropy(pred, y))
    fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(4)
    xv = rng.randn(4, 3, 8, 8).astype("float32")
    yv = rng.randint(0, 3, size=(4, 1)).astype("int64")
    for _ in range(3):
        exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])

    infer = fluid.io.get_inference_program([pred])
    (ref,) = exe.run(program=infer, feed={"x": xv}, fetch_list=[pred])
    assert sum(op.type == "fused_bn_add_act"
               for op in infer.global_block().ops) == 1
    fluid.InferenceTranspiler().transpile(infer, fluid.CPUPlace())
    ops = [op.type for op in infer.global_block().ops]
    assert "fused_bn_add_act" not in ops and "batch_norm" not in ops
    # folded shape: conv -> add(folded bias) -> relu
    ci = ops.index("conv2d")
    assert ops[ci + 1] == "elementwise_add" and ops[ci + 2] == "relu"
    (out,) = exe.run(program=infer, feed={"x": xv}, fetch_list=[pred])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_fused_bn_with_residual_not_folded_but_test_mode():
    """A fused op WITH a residual input cannot fold (BN applies before the
    add), but transpile must still flip it to test mode."""
    fluid.reset_default_env()
    x = layers.data("x", [4, 8, 8], dtype="float32")
    y = layers.data("y", [1], dtype="int64")
    conv = layers.conv2d(x, num_filters=4, filter_size=3, padding=1,
                         bias_attr=False)
    h = layers.fused_bn_add_act(conv, x, act="relu")
    pool = layers.pool2d(h, pool_size=8, pool_type="avg")
    pred = layers.fc(pool, size=3, act="softmax")
    loss = layers.mean(layers.cross_entropy(pred, y))
    fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    xv = np.random.RandomState(6).randn(4, 4, 8, 8).astype("float32")
    yv = np.zeros((4, 1), dtype="int64")
    exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])

    infer = fluid.io.get_inference_program([pred])
    (ref,) = exe.run(program=infer, feed={"x": xv}, fetch_list=[pred])
    fluid.InferenceTranspiler().transpile(infer, fluid.CPUPlace())
    fused = [op for op in infer.global_block().ops
             if op.type == "fused_bn_add_act"]
    assert len(fused) == 1 and fused[0].attr("is_test") is True
    (out,) = exe.run(program=infer, feed={"x": xv}, fetch_list=[pred])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_resnet_fused_build_transpiles_to_foldless_graph():
    """models.resnet built with fuse_bn=True must still lose every
    foldable BN under the transpiler (the round-4 regression: fused ops
    were invisible to the fold).  fuse_bn defaults to False since round 5
    (defaults follow measurements), so the fused graph is requested
    explicitly here."""
    from paddle_tpu import models

    fluid.reset_default_env()
    spec = models.resnet_cifar10(depth=8, class_num=4, fuse_bn=True)
    fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(spec.loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    b = spec.synthetic_batch(4, seed=0)
    exe.run(feed=b, fetch_list=[spec.loss])

    infer = fluid.io.get_inference_program([spec.extras["predict"]])
    (ref,) = exe.run(program=infer, feed={"image": b["image"]},
                     fetch_list=[spec.extras["predict"]])
    before = sum(op.type == "fused_bn_add_act"
                 for op in infer.global_block().ops)
    assert before > 0
    fluid.InferenceTranspiler().transpile(infer, fluid.CPUPlace())
    after = [op for op in infer.global_block().ops
             if op.type == "fused_bn_add_act"]
    # only the residual-tail fused ops (Z present) remain
    assert all(op.desc.inputs.get("Z") for op in after)
    assert len(after) < before
    (out,) = exe.run(program=infer, feed={"image": b["image"]},
                     fetch_list=[spec.extras["predict"]])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)
