"""Tail layer-API coverage (reference: layers/nn.py rank_loss, dice_loss,
multiplex, space_to_depth, bilinear_tensor_product; layers/detection.py
multi_box_head; layers/tensor.py sum/load; layers/io.py shuffle/batch)."""

import os
import tempfile

import numpy as np

import paddle_tpu as fluid


def test_multi_box_head_prior_channel_agreement():
    fluid.reset_default_env()
    img = fluid.layers.data(name="img", shape=[3, 64, 64], dtype="float32")
    f1 = fluid.layers.conv2d(img, 8, 3, stride=4, padding=1)
    f2 = fluid.layers.conv2d(f1, 8, 3, stride=2, padding=1)
    locs, confs, boxes, variances = fluid.layers.multi_box_head(
        [f1, f2], img, base_size=64, num_classes=5,
        aspect_ratios=[[2.0], [2.0, 3.0]], min_ratio=20, max_ratio=90,
        steps=[4.0, 8.0])
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    x = np.random.RandomState(0).rand(2, 3, 64, 64).astype("float32")
    lv, cv, bv, vv = exe.run(feed={"img": x},
                             fetch_list=[locs, confs, boxes, variances])
    assert lv.shape == (2, bv.shape[0], 4)
    assert cv.shape == (2, bv.shape[0], 5)
    assert vv.shape == bv.shape


def test_multi_box_head_min_max_order_and_reciprocal_ars():
    """Reciprocal aspect-ratio pairs dedupe in the kernel; the head's conv
    channel count must agree (review finding r2)."""
    fluid.reset_default_env()
    img = fluid.layers.data(name="img", shape=[3, 32, 32], dtype="float32")
    f1 = fluid.layers.conv2d(img, 4, 3, stride=4, padding=1)
    locs, confs, boxes, _ = fluid.layers.multi_box_head(
        [f1], img, base_size=32, num_classes=3,
        aspect_ratios=[[2.0, 0.5]], min_sizes=[10.0], max_sizes=[20.0],
        steps=[4.0], min_max_aspect_ratios_order=True)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    x = np.random.RandomState(1).rand(1, 3, 32, 32).astype("float32")
    lv, bv = exe.run(feed={"img": x}, fetch_list=[locs, boxes])
    assert lv.shape[1] == bv.shape[0]


def test_crop_keeps_batch_dim():
    """-1 dims in the crop shape keep the full extent (review finding)."""
    fluid.reset_default_env()
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    out = fluid.layers.crop(x, shape=[-1, 2])
    exe = fluid.Executor(fluid.CPUPlace())
    xs = np.arange(12, dtype="float32").reshape(3, 4)
    (got,) = exe.run(feed={"x": xs}, fetch_list=[out])
    np.testing.assert_allclose(got, xs[:, :2])  # all 3 rows survive


def test_dice_loss_empty_mask_is_maximal():
    fluid.reset_default_env()
    p = fluid.layers.data(name="p", shape=[3], dtype="float32")
    lab = fluid.layers.data(name="l", shape=[1], dtype="int64")
    loss = fluid.layers.dice_loss(p, lab)
    exe = fluid.Executor(fluid.CPUPlace())
    # prediction puts no mass on the labeled class -> dice -> loss 1
    probs = np.array([[1.0, 0.0, 0.0]], dtype="float32")
    (got,) = exe.run(feed={"p": probs,
                           "l": np.array([[2]], dtype="int64")},
                     fetch_list=[loss])
    np.testing.assert_allclose(got, 1.0, atol=1e-4)


def test_sum_and_load_roundtrip():
    fluid.reset_default_env()
    with tempfile.TemporaryDirectory() as d:
        np.save(os.path.join(d, "w.npy"),
                np.arange(6, dtype="float32").reshape(2, 3))
        prog = fluid.default_main_program()
        w = prog.global_block().create_var(
            name="w", shape=[2, 3], dtype="float32", persistable=True)
        fluid.layers.load(w, os.path.join(d, "w"))
        total = fluid.layers.sum([w, w])
        exe = fluid.Executor(fluid.CPUPlace())
        (got,) = exe.run(feed={}, fetch_list=[total])
        np.testing.assert_allclose(got,
                                   np.arange(6).reshape(2, 3) * 2.0)


def test_reader_aliases():
    def rd():
        for i in range(10):
            yield (np.full((2,), i, dtype="float32"),)

    batched = fluid.layers.batch(fluid.layers.shuffle(rd, 4), 2)
    out = list(batched())
    assert len(out) == 5
    assert len(out[0]) == 2  # batch of 2 samples


def test_weight_norm_param_attr_reparameterizes():
    """WeightNormParamAttr creates v (direction) + g (magnitude) params
    with w = g * v / ||v|| recomputed each step (reference:
    layer_helper.py _create_weight_normalize; Salimans & Kingma 2016);
    g initializes to ||v_0|| over the non-dim axes."""
    from paddle_tpu import layers

    x = layers.data("x", [6], dtype="float32")
    y = layers.data("y", [1], dtype="float32")
    h = layers.fc(x, size=4,
                  param_attr=fluid.WeightNormParamAttr(dim=1, name="wn"),
                  bias_attr=False)
    out = layers.fc(h, size=1)
    loss = layers.mean(layers.square_error_cost(out, y))
    fluid.optimizer.SGD(0.05).minimize(loss)

    params = {p.name for p in
              fluid.default_main_program().global_block().all_parameters()}
    assert "wn.w_v" in params and "wn.w_g" in params
    assert "wn" not in params  # w is a computed var, not a Parameter

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    scope = fluid.global_scope()
    v = np.asarray(scope.find_var("wn.w_v"))
    g = np.asarray(scope.find_var("wn.w_g"))
    np.testing.assert_allclose(g, np.sqrt((v ** 2).sum(axis=0)), rtol=1e-5)

    rng = np.random.RandomState(0)
    xv = rng.randn(8, 6).astype("float32")
    yv = rng.randn(8, 1).astype("float32")
    losses = [
        float(np.ravel(np.asarray(
            exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])[0]))[0])
        for _ in range(6)
    ]
    assert losses[-1] < losses[0]
    # the magnitude parameter really trains (pure v-only training would
    # leave the startup ||v_0|| untouched)
    g_after = np.asarray(scope.find_var("wn.w_g"))
    assert not np.allclose(g_after, g)
