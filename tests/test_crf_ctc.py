"""CRF / CTC / chunk_eval ops vs brute-force & torch references
(reference tests: test_linear_chain_crf_op.py, test_crf_decoding_op.py,
test_warpctc_op.py, test_ctc_align_op.py, test_chunk_eval_op.py)."""

import itertools

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.core.lod import create_lod_tensor


def _run(feed, fetch_list):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    return exe.run(feed=feed, fetch_list=fetch_list)


def _brute_force_crf_nll(em, w, lab):
    """All-paths partition + gold score for ONE sequence (numpy)."""
    T, K = em.shape
    start, end, trans = w[0], w[1], w[2:]
    logZ_terms = []
    for path in itertools.product(range(K), repeat=T):
        s = start[path[0]] + end[path[-1]] + sum(em[t, path[t]] for t in range(T))
        s += sum(trans[path[t - 1], path[t]] for t in range(1, T))
        logZ_terms.append(s)
    logZ = np.logaddexp.reduce(logZ_terms)
    gold = (
        start[lab[0]] + end[lab[-1]] + sum(em[t, lab[t]] for t in range(T))
        + sum(trans[lab[t - 1], lab[t]] for t in range(1, T))
    )
    return logZ - gold


def test_linear_chain_crf_matches_brute_force():
    K = 3
    rng = np.random.RandomState(0)
    lens = [2, 4]
    seqs = [rng.randn(t, K).astype("float32") for t in lens]
    labs = [rng.randint(0, K, size=t) for t in lens]
    w = rng.randn(K + 2, K).astype("float32") * 0.5

    em = layers.data("em", [K], dtype="float32", lod_level=1)
    lab = layers.data("lab", [1], dtype="int64", lod_level=1)
    ll = layers.linear_chain_crf(
        em, lab, param_attr=fluid.ParamAttr(name="crfw")
    )
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    fluid.global_scope().set_var("crfw", w)
    (got,) = exe.run(
        feed={
            "em": create_lod_tensor(seqs),
            "lab": create_lod_tensor([l[:, None].astype("int64") for l in labs]),
        },
        fetch_list=[ll],
    )
    want = [_brute_force_crf_nll(s, w, l) for s, l in zip(seqs, labs)]
    np.testing.assert_allclose(np.ravel(np.asarray(got)), want, rtol=1e-4)


def test_crf_decoding_matches_brute_force():
    K = 3
    rng = np.random.RandomState(1)
    lens = [3, 5]
    seqs = [rng.randn(t, K).astype("float32") for t in lens]
    w = rng.randn(K + 2, K).astype("float32") * 0.5

    em = layers.data("em", [K], dtype="float32", lod_level=1)
    attr = fluid.ParamAttr(name="crfw2")
    # create the transition param via linear_chain_crf's helper
    lab = layers.data("lab", [1], dtype="int64", lod_level=1)
    layers.linear_chain_crf(em, lab, param_attr=attr)
    path = layers.crf_decoding(em, attr)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    fluid.global_scope().set_var("crfw2", w)
    (got,) = exe.run(
        feed={
            "em": create_lod_tensor(seqs),
            "lab": create_lod_tensor(
                [np.zeros((t, 1), dtype="int64") for t in lens]
            ),
        },
        fetch_list=[path],
        return_numpy=False,
    )

    start, end, trans = w[0], w[1], w[2:]
    for i, (s, t_len) in enumerate(zip(seqs, lens)):
        best, best_path = -1e30, None
        for p in itertools.product(range(K), repeat=t_len):
            sc = start[p[0]] + end[p[-1]] + sum(s[t, p[t]] for t in range(t_len))
            sc += sum(trans[p[t - 1], p[t]] for t in range(1, t_len))
            if sc > best:
                best, best_path = sc, p
        np.testing.assert_array_equal(
            np.asarray(got.data)[i, :t_len, 0], best_path
        )


def test_warpctc_matches_torch():
    torch = pytest.importorskip("torch")
    rng = np.random.RandomState(2)
    N, T, C, L = 3, 8, 5, 3
    x_lens = np.array([8, 6, 5], dtype=np.int32)
    y_lens = np.array([3, 2, 1], dtype=np.int32)
    logits = rng.randn(N, T, C).astype("float32")
    labels = rng.randint(1, C, size=(N, L)).astype("int64")

    lg = layers.data("lg", [C], dtype="float32", lod_level=1)
    lb = layers.data("lb", [1], dtype="int64", lod_level=1)
    loss = layers.warpctc(lg, lb, blank=0)
    (got,) = _run(
        {
            "lg": create_lod_tensor([logits[i, : x_lens[i]] for i in range(N)]),
            "lb": create_lod_tensor(
                [labels[i, : y_lens[i], None] for i in range(N)]
            ),
        },
        [loss],
    )

    lp = torch.log_softmax(torch.tensor(logits), dim=-1).transpose(0, 1)
    want = torch.nn.functional.ctc_loss(
        lp, torch.tensor(labels), torch.tensor(x_lens), torch.tensor(y_lens),
        blank=0, reduction="none",
    ).numpy()
    np.testing.assert_allclose(np.ravel(np.asarray(got)), want, rtol=1e-4)


def test_warpctc_grad_drives_loss_down():
    rng = np.random.RandomState(3)
    C = 5
    lg = layers.data("lg", [C], dtype="float32", lod_level=1)
    lb = layers.data("lb", [1], dtype="int64", lod_level=1)
    proj = layers.fc(lg, size=C, bias_attr=False)
    loss = layers.mean(layers.warpctc(proj, lb, blank=0))
    fluid.optimizer.AdamOptimizer(learning_rate=0.05).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    feed = {
        "lg": create_lod_tensor([rng.randn(7, C).astype("float32"),
                                 rng.randn(5, C).astype("float32")]),
        "lb": create_lod_tensor([np.array([[1], [2]], dtype="int64"),
                                 np.array([[3]], dtype="int64")]),
    }
    losses = [
        float(np.ravel(np.asarray(exe.run(feed=feed, fetch_list=[loss])[0]))[0])
        for _ in range(15)
    ]
    assert losses[-1] < losses[0] * 0.7


def test_ctc_align():
    # direct op: feed token sequences, merge repeats + drop blanks (0)
    x = layers.data("x", [1], dtype="int32", lod_level=1)
    from paddle_tpu.layer_helper import LayerHelper

    helper = LayerHelper("ctc_align_test")
    aligned = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="ctc_align", inputs={"Input": [x]},
        outputs={"Output": [aligned]}, attrs={"blank": 0},
    )
    seqs = [
        np.array([[0], [1], [1], [0], [2], [2], [0]], dtype="int32"),
        np.array([[3], [3], [0], [3]], dtype="int32"),
    ]
    exe = fluid.Executor(fluid.CPUPlace())
    (got,) = exe.run(
        feed={"x": create_lod_tensor(seqs)}, fetch_list=[aligned],
        return_numpy=False,
    )
    lens = np.asarray(got.lengths)
    data = np.asarray(got.data)
    assert list(data[0, : lens[0], 0]) == [1, 2]
    assert list(data[1, : lens[1], 0]) == [3, 3]


def test_chunk_eval_iob():
    # 1 chunk type, IOB: labels B=0, I=1, O=2
    inf = layers.data("inf", [1], dtype="int64", lod_level=1)
    lab = layers.data("lab", [1], dtype="int64", lod_level=1)
    outs = layers.chunk_eval(inf, lab, chunk_scheme="IOB", num_chunk_types=1)
    precision, recall, f1 = outs[0], outs[1], outs[2]
    # label:  B I O B I  -> 2 chunks
    # infer:  B I O B O  -> 2 chunks, 1 correct (first)
    seq_lab = np.array([[0], [1], [2], [0], [1]], dtype="int64")
    seq_inf = np.array([[0], [1], [2], [0], [2]], dtype="int64")
    got = _run(
        {
            "inf": create_lod_tensor([seq_inf]),
            "lab": create_lod_tensor([seq_lab]),
        },
        [precision, recall, f1],
    )
    p, r, f = (float(np.ravel(np.asarray(v))[0]) for v in got)
    assert p == pytest.approx(0.5)
    assert r == pytest.approx(0.5)
    assert f == pytest.approx(0.5)


def test_chunk_eval_no_leak_across_chunks():
    # label: B I B I -> 2 chunks; infer: B I I I -> 1 chunk, 0 correct
    inf = layers.data("inf2", [1], dtype="int64", lod_level=1)
    lab = layers.data("lab2", [1], dtype="int64", lod_level=1)
    outs = layers.chunk_eval(inf, lab, chunk_scheme="IOB", num_chunk_types=1)
    num_correct = outs[5]
    got = _run(
        {
            "inf2": create_lod_tensor(
                [np.array([[0], [1], [1], [1]], dtype="int64")]
            ),
            "lab2": create_lod_tensor(
                [np.array([[0], [1], [0], [1]], dtype="int64")]
            ),
        },
        [num_correct],
    )
    assert int(np.ravel(np.asarray(got[0]))[0]) == 0


def test_crf_decoding_with_label_marks_matches():
    K = 3
    em = layers.data("em3", [K], dtype="float32", lod_level=1)
    lab = layers.data("lab3", [1], dtype="int64", lod_level=1)
    attr = fluid.ParamAttr(name="crfw3")
    layers.linear_chain_crf(em, lab, param_attr=attr)
    marked = layers.crf_decoding(em, attr, label=lab)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    # strong emissions force the decoded path to [0, 1, 2]
    seq = np.array([[9, 0, 0], [0, 9, 0], [0, 0, 9]], dtype="float32")
    fluid.global_scope().set_var("crfw3", np.zeros((K + 2, K), dtype="float32"))
    (got,) = exe.run(
        feed={
            "em3": create_lod_tensor([seq]),
            "lab3": create_lod_tensor([np.array([[0], [0], [2]], dtype="int64")]),
        },
        fetch_list=[marked],
        return_numpy=False,
    )
    # reference semantics: 1 where decoded == label
    np.testing.assert_array_equal(np.asarray(got.data)[0, :, 0], [1, 0, 1])
