"""Chip-less program linter (paddle_tpu.analysis): detectors over jaxpr /
TPU StableHLO / AOT v5e HLO, the known-bad regression corpus, and the
model-zoo CI gate (tools/lint_programs.py).

The corpus tests are the regression teeth: each corpus program re-creates
a hazard class this repo actually shipped (the PR-1 lse/dvec broadcast,
the ROADMAP relayout sandwich, ...) and the linter must flag it with the
RIGHT detector id — so a detector that silently stops firing fails here,
not on a chip three PRs later.
"""

import json
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

REPO = os.path.join(os.path.dirname(__file__), os.pardir)

from paddle_tpu import analysis
from paddle_tpu.analysis import hlo as H
from paddle_tpu.analysis.corpus import CORPUS, build_corpus_program
from paddle_tpu.analysis.findings import Finding


def _skip_if_no_topology():
    try:
        from paddle_tpu.core.aot_tpu import tpu_topology

        tpu_topology()
    except Exception as e:  # pragma: no cover - environment-dependent
        pytest.skip(f"no chip-less TPU topology available: {e}")


# ---------------------------------------------------------------------------
# findings


def test_finding_severity_validated_and_json_stable():
    f = Finding(detector="host-sync", severity="error", program="p",
                message="m", bytes=3, where="w", fingerprint="abc")
    d = f.as_dict()
    assert d == {"detector": "host-sync", "severity": "error",
                 "program": "p", "message": "m", "bytes": 3,
                 "where": "w", "fingerprint": "abc"}
    assert "host-sync" in f.format() and "ERROR" in f.format()
    with pytest.raises(ValueError):
        Finding(detector="x", severity="fatal", program="p", message="m")


# ---------------------------------------------------------------------------
# HLO / StableHLO text parsers


_HLO_SNIPPET = """\
HloModule jit_fn, entry_computation_layout={(f32[2,8,8,4]{3,0,2,1:T(8,128)}, f32[4]{0:T(256)})->(f32[2,8,8,4]{3,2,1,0:T(8,128)}, f32[]{:T(128)})}, input_output_alias={ {0}: (0, {}, may-alias) }

ENTRY %main (p0: f32[2,8,8,4], p1: f32[4]) -> (f32[2,8,8,4], f32[]) {
  %p0 = f32[2,8,8,4]{3,0,2,1:T(8,128)} parameter(0)
  %p1 = f32[4]{0:T(256)} parameter(1)
  %copy.1 = f32[2,8,8,4]{3,2,1,0:T(8,128)} copy(f32[2,8,8,4]{3,0,2,1:T(8,128)} %p0)
  %cc = f32[2,8,8,4]{3,2,1,0:T(8,128)} custom-call(f32[2,8,8,4]{3,2,1,0:T(8,128)} %copy.1), custom_call_target="tpu_custom_call", metadata={op_name="x"}
  %copy.2 = f32[2,8,8,4]{3,0,2,1:T(8,128)} copy(f32[2,8,8,4]{3,2,1,0:T(8,128)} %cc)
  %copy.3 = f32[2,8,8,4]{3,2,1,0:T(8,128)} copy(f32[2,8,8,4]{3,0,2,1:T(8,128)} %copy.2)
  %sum = f32[]{:T(128)} constant(0)
  ROOT %tup = (f32[2,8,8,4]{3,2,1,0:T(8,128)}, f32[]{:T(128)}) tuple(%copy.3, %sum)
}
"""


def test_hlo_parse_shapes_layouts_and_operands():
    s = H.parse_shape("f32[2,56,56,64]{3,0,2,1:T(8,128)S(1)}")
    assert (s.dtype, s.dims, s.perm) == ("f32", (2, 56, 56, 64), "3,0,2,1")
    assert s.bytes == 2 * 56 * 56 * 64 * 4
    assert H.parse_shape("bf16[8]").perm == ""
    instrs = H.entry_instructions(_HLO_SNIPPET)
    by = {i.name: i for i in instrs}
    assert by["cc"].opcode == "custom-call"
    assert by["cc"].operand_names == ["copy.1"]
    # metadata attrs after the close paren must not contribute operands
    assert "x" not in by["cc"].operand_names
    assert by["copy.1"].operands[0][0].perm == "3,0,2,1"
    assert by["tup"].is_root


def test_hlo_parse_entry_layout_and_alias():
    params, outs = H.parse_entry_layout(_HLO_SNIPPET)
    assert [p.dims for p in params] == [(2, 8, 8, 4), (4,)]
    assert [o.dims for o in outs] == [(2, 8, 8, 4), ()]
    assert H.parse_input_output_alias(_HLO_SNIPPET) == {0: 0}
    assert H.parse_input_output_alias("HloModule x") == {}


def test_relayout_detector_on_synthetic_hlo():
    """The copy-pair bracketing the pinned custom call is found on both
    sides; the downstream same-destination copy.3 (a plain memory-space
    move in real dumps) is not double-counted as draining the call."""
    from paddle_tpu.analysis.capture import ProgramArtifacts
    from paddle_tpu.analysis.detectors import detect_relayout_copies

    art = ProgramArtifacts(name="synthetic", jaxpr=None, stablehlo="",
                           hlo=_HLO_SNIPPET, cost={})
    found = detect_relayout_copies(art)
    wheres = sorted(f.where for f in found)
    assert wheres == ["cc->copy.2", "copy.1->cc"]
    assert all(f.detector == "relayout-copy-pair" for f in found)
    assert all(f.bytes == 2 * 8 * 8 * 4 * 4 for f in found)


# ---------------------------------------------------------------------------
# the known-bad regression corpus: each program must trip its detector


@pytest.mark.parametrize("name", sorted(CORPUS))
def test_corpus_program_trips_its_detector(name):
    _skip_if_no_topology()
    builder, expected_detector = CORPUS[name]
    art = build_corpus_program(name)
    if expected_detector is None:
        # bytes-gated corpus entries (gqa_full_pool) are structurally
        # healthy by design — the dedicated bytes-gate test below is
        # their teeth; here just pin that they compile and analyze
        assert not art.compile_error
        return
    findings = analysis.run_detectors(art)
    hit = [f for f in findings if f.detector == expected_detector]
    assert hit, (
        f"corpus program {name!r} must be flagged by {expected_detector}; "
        f"got {[f.detector for f in findings]}")
    assert all(f.program == art.name and f.fingerprint == art.fingerprint
               for f in hit)


def test_corpus_gqa_full_pool_trips_bytes_gate():
    """ISSUE 12 satellite: a full-H_q pool on a GQA config must FAIL the
    gqa_decode bytes/step tolerance rather than silently passing — the
    corpus program carries the zoo entry's name, so the verdict lands on
    the banked grouped baseline (the page stream is H_q/H_kv = 4x it).
    No detector arm exists for this hazard: the bytes gate IS the
    check."""
    _skip_if_no_topology()
    from paddle_tpu.analysis.corpus import corpus_extra_bytes

    art = build_corpus_program("gqa_full_pool")
    assert art.name == "gqa_decode"  # deliberately the zoo entry's slot
    extra = corpus_extra_bytes("gqa_full_pool")
    assert extra > 0  # the analytic stream is what busts the budget
    bad = analysis.ZooResult(
        name=art.name, artifacts=art, findings=[],
        bytes_per_step=art.bytes_per_step + extra, flops_per_step=0.0)
    verdicts, failed = analysis.gate(
        [bad], analysis.default_baseline_path())
    assert failed
    v = [x for x in verdicts
         if x["metric"] == "gqa_decode_aot_bytes_per_step"]
    assert v and v[0]["verdict"] == "fail"
    # ~4x the banked grouped bytes: the full-head pool pays H_q/H_kv x
    assert v[0]["current"] > 3.0 * v[0]["baseline"]


def test_corpus_broadcast_lse_reports_materialized_bytes():
    """The PR-1 bug class: the [512] lse vector broadcast to [512,128]
    as a custom-call operand is charged at its full materialized size."""
    _skip_if_no_topology()
    art = build_corpus_program("broadcast_lse")
    hit = [f for f in analysis.run_detectors(art)
           if f.detector == "broadcast-operand"]
    assert hit[0].bytes == 512 * 128 * 4
    assert hit[0].severity == "error"


def test_corpus_missed_donation_sized_and_donated_arm_clean():
    """The un-donated state shows one finding per eligible buffer at the
    buffer's byte size; actually donating the same state clears them."""
    _skip_if_no_topology()
    from paddle_tpu.analysis.capture import capture_fn

    art = build_corpus_program("missed_donation")
    hit = [f for f in analysis.run_detectors(art)
           if f.detector == "missed-donation"]
    assert len(hit) == 3  # three eligible state buffers, none aliased
    assert all(f.bytes == 256 * 256 * 4 for f in hit)

    def fn(state, x):
        return [s + x for s in state], jnp.sum(x)

    a = jax.ShapeDtypeStruct((256, 256), jnp.float32)
    donated = capture_fn(fn, [a, a, a], a, donate_argnums=(0,),
                         name="donated")
    assert not [f for f in analysis.run_detectors(donated)
                if f.detector == "missed-donation"]


def test_master_weight_update_idiom_not_flagged():
    """AMP master weights: a bf16 grad cast to f32 to update f32 params
    joins an equally-sized already-f32 tensor — the f32 write-back is the
    params' own dtype, not a promotion leak (the resnet50_train zoo
    program relies on this staying clean)."""
    _skip_if_no_topology()
    from paddle_tpu.analysis.capture import capture_fn

    def step(p, v, g_bf16):
        g = g_bf16.astype(jnp.float32)
        v2 = 0.9 * v + g
        return p - 0.1 * v2, v2

    f32 = jax.ShapeDtypeStruct((512, 512), jnp.float32)
    bf = jax.ShapeDtypeStruct((512, 512), jnp.bfloat16)
    art = capture_fn(step, f32, f32, bf, name="master_weight")
    assert not [f for f in analysis.run_detectors(art)
                if f.detector == "dtype-promotion"]


def test_missed_donation_indices_survive_unused_arg():
    """jit would normally PRUNE an unused arg from the executable's
    entry parameters, shifting every index the analyzer computed from
    the python signature (trace_tpu pins them with keep_unused).  The
    detector must anchor the findings on the state leaves, not drift
    onto the feed."""
    _skip_if_no_topology()
    from paddle_tpu.analysis.capture import capture_fn

    def fn(unused, state, x):
        return [s + x for s in state], jnp.sum(x)

    u = jax.ShapeDtypeStruct((64, 64), jnp.float32)
    a = jax.ShapeDtypeStruct((256, 256), jnp.float32)
    art = capture_fn(fn, u, [a, a], a, donatable_argnums=(1,),
                     name="unused_arg")
    hit = [f for f in analysis.run_detectors(art)
           if f.detector == "missed-donation"]
    assert len(hit) == 2  # both state leaves, nothing anchored elsewhere
    assert all(f.bytes == 256 * 256 * 4 for f in hit)
    assert {f.where.split(" ")[1] for f in hit} == {"1", "2"}


def test_own_kernels_clean_of_corpus_bug_classes():
    """The tentpole's 'asserted dead in our own kernels' clause: the
    flash-attention and paged-decode custom calls must show zero
    broadcast-materialized operands and zero relayout copy-pairs."""
    _skip_if_no_topology()
    from paddle_tpu.analysis.capture import capture_fn
    from paddle_tpu.kernels.flash_attention import flash_attention
    from paddle_tpu.kernels.paged_attention import paged_decode_attention

    B, H_, S, D = 2, 4, 256, 128
    qkv = jax.ShapeDtypeStruct((B, H_, S, D), jnp.float32)
    art = capture_fn(lambda q, k, v: flash_attention(q, k, v, causal=True),
                     qkv, qkv, qkv, name="flash_fwd")
    bad = [f for f in analysis.run_detectors(art)
           if f.detector in ("broadcast-operand", "relayout-copy-pair")]
    assert not bad, [f.format() for f in bad]

    ps, maxp = 16, 8
    P = B * maxp
    q = jax.ShapeDtypeStruct((B, H_, 1, D), jnp.float32)
    kp = jax.ShapeDtypeStruct((H_, P, ps, D), jnp.float32)
    tb = jax.ShapeDtypeStruct((B, maxp), jnp.int32)
    ln = jax.ShapeDtypeStruct((B,), jnp.int32)
    art = capture_fn(
        lambda q, k, v, t, l: paged_decode_attention(
            q, k, v, t, l, impl="pallas"),
        q, kp, kp, tb, ln, name="paged")
    bad = [f for f in analysis.run_detectors(art)
           if f.detector in ("broadcast-operand", "relayout-copy-pair")]
    assert not bad, [f.format() for f in bad]


def test_corpus_host_callback_counted_once():
    """One pure_callback is ONE hazard: the jaxpr prim scan and the
    StableHLO custom-call marker scan must not both report the same
    callback — a double count would bank 2x and make the gate's
    new-finding comparison jax-version-sensitive."""
    _skip_if_no_topology()
    art = build_corpus_program("host_callback")
    hit = [f for f in analysis.run_detectors(art)
           if f.detector == "host-sync"]
    assert len(hit) == 1
    assert hit[0].where == "pure_callback"


def test_capture_time_hazards_python_scalar_feed_and_unhashable_key(
        monkeypatch):
    from paddle_tpu import flags as fl
    from paddle_tpu.analysis.capture import _capture_time_hazards

    hz = _capture_time_hazards("p", {"lr": 0.1, "x": np.zeros(3)}, "fp")
    assert [f.where for f in hz] == ["feed:lr"]
    assert hz[0].detector == "recompile-hazard"
    monkeypatch.setattr(fl, "trace_key", lambda: ["not", "hashable"])
    hz = _capture_time_hazards("p", {}, "fp")
    assert [f.where for f in hz] == ["flags.trace_key"]
    assert hz[0].severity == "error"


def test_capture_executor_unhashable_key_reports_not_crashes(monkeypatch):
    """The executor's own cache lookup hashes flags.trace_key() before
    anything else — a non-hashable key must come back as the
    recompile-hazard finding the detector advertises, not a TypeError."""
    _skip_if_no_topology()
    import paddle_tpu as fluid
    from paddle_tpu import flags as fl, layers

    fluid.reset_default_env()
    x = layers.data("x", [8, 8], dtype="float32")
    loss = layers.mean(layers.fc(x, size=4))
    fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    monkeypatch.setattr(fl, "trace_key", lambda: ["not", "hashable"])
    art = analysis.capture_executor(
        exe, feed={"x": np.zeros((2, 8, 8), "float32")},
        fetch_list=[loss], name="unhashable")
    assert art.compile_error  # nothing was compiled
    findings = analysis.run_detectors(art)
    assert any(f.detector == "recompile-hazard"
               and f.where == "flags.trace_key" for f in findings)


def test_capture_executor_current_tree_is_clean():
    """The executor seam: the exact chip program a small train step runs
    (same cache entry, state donation included) lints clean — donation is
    realized, no weak types, no host syncs."""
    _skip_if_no_topology()
    import paddle_tpu as fluid
    from paddle_tpu import layers

    fluid.reset_default_env()
    x = layers.data("x", [16, 16], dtype="float32")
    loss = layers.mean(layers.fc(x, size=8))
    fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    art = analysis.capture_executor(
        exe, feed={"x": np.zeros((4, 16, 16), "float32")},
        fetch_list=[loss], name="fc_train")
    assert art.hlo and art.bytes_per_step > 0
    assert art.compile_error == ""
    findings = analysis.run_detectors(art)
    assert not findings, [f.format() for f in findings]


# ---------------------------------------------------------------------------
# gate logic (pure — fabricated results, no compiles)


def _zr(name, counts, bytes_per_step, flops=0.0):
    from paddle_tpu.analysis.capture import ProgramArtifacts
    from paddle_tpu.analysis.zoo import ZooResult

    art = ProgramArtifacts(name=name, jaxpr=None, stablehlo="", hlo="",
                           cost={}, fingerprint="f" * 12)
    findings = [
        Finding(detector=det, severity="warning", program=name, message="x")
        for det, n in counts.items() for _ in range(n)
    ]
    return ZooResult(name=name, artifacts=art, findings=findings,
                     bytes_per_step=bytes_per_step, flops_per_step=flops)


def _bank_doc(tmp_path, programs, tolerance=0.02):
    p = tmp_path / "base.json"
    p.write_text(json.dumps(
        {"tolerance": tolerance, "programs": programs}))
    return str(p)


def test_gate_new_finding_fails(tmp_path):
    base = _bank_doc(tmp_path, {
        "a": {"findings": {}, "bytes_per_step": 100.0}})
    verdicts, failed = analysis.gate(
        [_zr("a", {"host-sync": 1}, 100.0)], base)
    assert failed
    assert any(v["metric"] == "a_findings[host-sync]"
               and v["verdict"] == "fail" for v in verdicts)


def test_gate_bytes_regression_fails_and_within_tol_passes(tmp_path):
    base = _bank_doc(tmp_path, {
        "a": {"findings": {}, "bytes_per_step": 100.0}})
    _, failed = analysis.gate([_zr("a", {}, 101.0)], base)
    assert not failed  # +1% within the 2% tolerance
    verdicts, failed = analysis.gate([_zr("a", {}, 110.0)], base)
    assert failed
    assert any("bytes_per_step" in v["metric"] and v["verdict"] == "fail"
               for v in verdicts)


def test_gate_unbanked_program_fails_and_fewer_findings_pass(tmp_path):
    base = _bank_doc(tmp_path, {
        "a": {"findings": {"host-sync": 2}, "bytes_per_step": 100.0}})
    verdicts, failed = analysis.gate(
        [_zr("a", {"host-sync": 1}, 100.0), _zr("new", {}, 1.0)], base)
    assert failed  # 'new' has no banked entry
    assert any(v["metric"] == "new_findings" and v["verdict"] == "fail"
               for v in verdicts)
    # strictly-fewer findings is a pass that nudges a re-bank
    better = [v for v in verdicts if v["metric"] == "a_findings[host-sync]"]
    assert better and better[0]["verdict"] == "pass"
    assert "re-bank" in better[0]["reason"]


def test_gate_fails_and_bank_refuses_on_compile_error(tmp_path):
    """A program the v5e pipeline rejects analyzed NOTHING HLO-side —
    bytes collapse to 0, which lower-is-better would wave through.  The
    gate must fail it and bank must refuse to freeze it."""
    base = _bank_doc(tmp_path, {
        "a": {"findings": {}, "bytes_per_step": 100.0}})
    r = _zr("a", {}, 0.0)
    r.artifacts.compile_error = "Mosaic rejected the kernel"
    verdicts, failed = analysis.gate([r], base)
    assert failed
    assert any(v["metric"] == "a_compile" and v["verdict"] == "fail"
               for v in verdicts)
    with pytest.raises(ValueError, match="compile failed"):
        analysis.bank([r], str(tmp_path / "out.json"))


def test_run_zoo_validates_detector_names_before_capturing():
    with pytest.raises(KeyError, match="unknown detector"):
        analysis.run_zoo(["paged_decode"], detectors=["host-synk"])


def test_gate_require_all_fails_on_vanished_banked_program(tmp_path):
    """Deleting/renaming a zoo entry must not silently shrink CI
    coverage: an unfiltered run gates banked-but-not-run programs."""
    base = _bank_doc(tmp_path, {
        "a": {"findings": {}, "bytes_per_step": 100.0},
        "b": {"findings": {}, "bytes_per_step": 50.0}})
    results = [_zr("a", {}, 100.0)]
    _, failed = analysis.gate(results, base)  # filtered run: fine
    assert not failed
    verdicts, failed = analysis.gate(results, base, require_all=True)
    assert failed
    assert any(v["metric"] == "b_coverage" and v["verdict"] == "fail"
               for v in verdicts)


def test_zoo_builder_sandbox_preserves_caller_env():
    """run_zoo is public API: building a zoo model must not clobber the
    caller's default program, scope, or name counters."""
    import paddle_tpu as fluid
    from paddle_tpu.analysis.zoo import _fresh_env

    fluid.reset_default_env()
    fluid.layers.data("keepme", [4], dtype="float32")
    main_before = fluid.default_main_program()
    scope_before = fluid.global_scope()
    with _fresh_env() as fl:
        assert fl.default_main_program() is not main_before
        assert fl.global_scope() is not scope_before
        fl.layers.data("inner", [2], dtype="float32")
    assert fluid.default_main_program() is main_before
    assert fluid.global_scope() is scope_before
    names = list(main_before.desc.block(0).vars)
    assert "keepme" in names and "inner" not in names


def test_gate_injected_corpus_programs_each_fail(tmp_path):
    """ISSUE acceptance: every known-bad corpus program splices into a
    zoo run as an UNBANKED program carrying findings — the gate must fail
    for each one."""
    base = _bank_doc(tmp_path, {
        "a": {"findings": {}, "bytes_per_step": 100.0},
        "gqa_decode": {"findings": {}, "bytes_per_step": 100.0}})
    clean = _zr("a", {}, 100.0)
    for name, (_, det) in sorted(CORPUS.items()):
        if det is None:
            # bytes-gated corpus entry: splices in UNDER the banked zoo
            # entry's own name and busts its bytes tolerance instead of
            # carrying a finding (the full-H_q-pool hazard has none)
            bad = _zr("gqa_decode", {}, 400.0)
        else:
            bad = _zr(f"corpus_{name}", {det: 1}, 5.0)
        _, failed = analysis.gate([clean, bad], base)
        assert failed, f"gate must trip on injected corpus {name!r}"


# ---------------------------------------------------------------------------
# the CLI end-to-end (cheapest zoo program only; the full-zoo gate runs
# as tools/lint_programs.py --gate in CI and in the slow tier below)


def _lint_main(argv):
    sys.path.insert(0, os.path.abspath(REPO))
    try:
        from tools.lint_programs import main

        return main(argv)
    finally:
        sys.path.pop(0)


def test_lint_cli_list_and_bank_refusal(tmp_path, capsys):
    assert _lint_main(["--list"]) == 0
    out = capsys.readouterr().out
    assert "paged_decode" in out and "broadcast_lse" in out
    # banking a filtered or injected run must refuse (exit 2), not
    # silently narrow the baseline — a --detectors subset would bank
    # under-counted findings that the next full run reports as "new"
    assert _lint_main(["--programs", "paged_decode", "--bank",
                       "--baseline", str(tmp_path / "b.json")]) == 2
    assert _lint_main(["--detectors", "host-sync", "--bank",
                       "--baseline", str(tmp_path / "b.json")]) == 2
    # --gate with a detector subset would let the OTHER detectors'
    # regressions gate green — refuse, same as --bank
    assert _lint_main(["--detectors", "host-sync", "--gate"]) == 2
    capsys.readouterr()


def test_lint_cli_gate_round_trip_and_regression(tmp_path, capsys):
    """bank -> re-gate passes; injected corpus program exits 3; a banked
    baseline with smaller bytes/step (i.e. the tree regressed) exits 3."""
    _skip_if_no_topology()
    base = str(tmp_path / "zoo.json")
    rc = _lint_main(["--programs", "paged_decode", "--json",
                     str(tmp_path / "r.json")])
    assert rc == 0
    run = json.loads((tmp_path / "r.json").read_text())
    prog = run["programs"]["paged_decode"]
    assert prog["finding_counts"] == {}  # current tree lints clean
    assert prog["bytes_per_step"] > 0

    doc = {"tolerance": 0.02, "programs": {"paged_decode": {
        "findings": {}, "bytes_per_step": prog["bytes_per_step"],
        "flops_per_step": prog["flops_per_step"]}}}
    (tmp_path / "zoo.json").write_text(json.dumps(doc))
    assert _lint_main(["--programs", "paged_decode",
                       "--baseline", base, "--gate"]) == 0

    # an injected known-bad program trips the gate end-to-end
    assert _lint_main(["--programs", "paged_decode", "--inject",
                       "weak_type", "--baseline", base, "--gate"]) == 3

    # a bytes/step rise past tolerance trips the gate
    doc["programs"]["paged_decode"]["bytes_per_step"] = (
        prog["bytes_per_step"] * 0.5)
    (tmp_path / "zoo.json").write_text(json.dumps(doc))
    assert _lint_main(["--programs", "paged_decode",
                       "--baseline", base, "--gate"]) == 3
    capsys.readouterr()


def test_lint_cli_gate_missing_baseline_is_usage_error(tmp_path, capsys):
    _skip_if_no_topology()
    rc = _lint_main(["--programs", "paged_decode", "--gate",
                     "--baseline", str(tmp_path / "nope.json")])
    assert rc == 2
    capsys.readouterr()


def test_ci_gate_exit_code_contract_shared_with_serve_bench(
        tmp_path, capsys):
    """README 'CI gates': all three gate tools exit 2 on usage errors
    (not 0, not a traceback) so CI wiring can tell 'gate broken' from
    'tree regressed' (exit 3)."""
    sys.path.insert(0, os.path.abspath(REPO))
    try:
        from tools.obsdump import main as obsdump_main
        from tools.serve_bench import main as bench_main
    finally:
        sys.path.pop(0)
    assert bench_main(["--gate"]) == 2  # --gate without --baseline
    assert bench_main(["--baseline", str(tmp_path / "nope.json")]) == 2
    assert obsdump_main([str(tmp_path), "--baseline",
                         str(tmp_path / "nope.json"), "--gate"]) == 2
    capsys.readouterr()


# ---------------------------------------------------------------------------
# the full zoo vs the committed baseline (the per-PR CI gate itself):
# resnet50+transformer AOT compiles make this the one heavy test here


@pytest.mark.slow
def test_full_zoo_gate_green_against_committed_baseline(capsys):
    _skip_if_no_topology()
    rc = _lint_main(["--gate"])
    assert rc == 0
    capsys.readouterr()


def test_gqa_decode_banked_ratio_and_coverage():
    """ISSUE 12 acceptance: the banked gqa_decode entry's KV bytes/step
    sits within 10% of H_kv/H_q x the paged_decode baseline (the
    grouped kernel streams each page once per KV head, not per query
    head), int8 pages price ~1/4 of that again (fp32 -> int8 elements;
    '2x on top of bf16'), and the entry is under require_all coverage —
    deleting it from the zoo fails the gate instead of shrinking CI."""
    with open(analysis.default_baseline_path()) as f:
        progs = json.load(f)["programs"]
    assert "gqa_decode" in progs
    cfg = progs["gqa_decode"]["config"]
    want = cfg["kv_heads"] / cfg["heads"]  # H_kv / H_q
    ratio = (progs["gqa_decode"]["bytes_per_step"]
             / progs["paged_decode"]["bytes_per_step"])
    assert abs(ratio - want) / want < 0.10, ratio
    # the further dtype arms of the same analytic model: int8 at 1/4
    # the fp32 stream (+ per-page scale reads), i.e. half of bf16 again
    from paddle_tpu.kernels.paged_attention import attention_bytes_per_step

    args = (4, cfg["max_pages"], cfg["page_size"], cfg["heads"],
            cfg["head_dim"])
    fp32 = attention_bytes_per_step("pallas", *args, num_kv_heads=2,
                                    dtype="float32")
    bf16 = attention_bytes_per_step("pallas", *args, num_kv_heads=2,
                                    dtype="bfloat16")
    i8 = attention_bytes_per_step("pallas", *args, num_kv_heads=2,
                                  dtype="int8")
    assert 0.24 <= i8 / fp32 <= 0.27
    assert 0.49 <= i8 / bf16 <= 0.52
    # require_all: a run missing the banked gqa_decode fails coverage
    others = [_zr(n, e.get("findings", {}), e["bytes_per_step"])
              for n, e in progs.items() if n != "gqa_decode"]
    verdicts, failed = analysis.gate(
        others, analysis.default_baseline_path(), require_all=True)
    assert failed
    assert any(v["metric"] == "gqa_decode_coverage"
               and v["verdict"] == "fail" for v in verdicts)


# ---------------------------------------------------------------------------
# satellite: resolve_paged_impl fallbacks are counted, not just logged


def test_paged_fallback_counted_and_metered(monkeypatch):
    from paddle_tpu import flags as fl
    from paddle_tpu import observability as obs
    from paddle_tpu.kernels import paged_attention as pa

    before = pa.fallback_count()
    # in-envelope explicit pallas resolves without counting
    assert pa.resolve_paged_impl("interpret", 16, 128, jnp.float32) \
        == "interpret"
    assert pa.fallback_count() == before
    # a CPU host's auto->reference is expected, not a fallback
    assert pa.resolve_paged_impl("auto", 16, 128, jnp.float32) \
        == "reference"
    assert pa.fallback_count() == before
    # auto on a TPU host wanted pallas: out-of-envelope degradation to
    # the reference gather must count (in-envelope must not)
    monkeypatch.setattr(pa, "_on_tpu", lambda: True)
    assert pa.resolve_paged_impl("auto", 16, 96, jnp.float32) \
        == "reference"
    assert pa.fallback_count() == before + 1
    assert pa.resolve_paged_impl("auto", 16, 128, jnp.float32) == "pallas"
    assert pa.fallback_count() == before + 1
    monkeypatch.setattr(pa, "_on_tpu", lambda: False)
    before = pa.fallback_count()
    # out-of-envelope explicit pallas falls back AND counts
    assert pa.resolve_paged_impl("pallas", 16, 96, jnp.float32) \
        == "reference"
    assert pa.fallback_count() == before + 1
    # with observability on, the labeled counter records it too
    obs.default_registry().reset()
    old = fl.flag("FLAGS_observability")
    fl.set_flags({"FLAGS_observability": True})
    try:
        pa.resolve_paged_impl("pallas", 16, 96, jnp.float32)
        snap = obs.default_registry().snapshot()["metrics"]
        fb = [m for m in snap
              if m["name"] == "paddle_tpu_serving_fallback"]
        assert fb and fb[0]["series"][0]["labels"] == {
            "kernel": "paged_attention"}
        assert fb[0]["series"][0]["value"] == 1
    finally:
        fl.set_flags({"FLAGS_observability": old})
        obs.default_registry().reset()
    assert pa.fallback_count() == before + 2


# ---------------------------------------------------------------------------
# kernel-interior tier (ISSUE 14): VMEM pricing + the two new detectors


def test_tile_padded_bytes_pads_to_whole_tiles():
    """The estimator prices buffers the way Mosaic stores them: last two
    dims padded to whole (sublane, lane) tiles per dtype."""
    from paddle_tpu.analysis.pallas import tile_padded_bytes

    assert tile_padded_bytes((8, 128), "float32") == 8 * 128 * 4
    assert tile_padded_bytes((8, 1), "float32") == 8 * 128 * 4
    assert tile_padded_bytes((1, 1, 3, 130), "float32") == 8 * 256 * 4
    assert tile_padded_bytes((9, 128), "bfloat16") == 16 * 128 * 2
    assert tile_padded_bytes((1, 128), "int8") == 32 * 128
    assert tile_padded_bytes((128,), "float32") == 8 * 128 * 4


def _traced_pallas_eqns(fn, *args):
    from paddle_tpu import flags as fl
    from paddle_tpu.analysis import pallas as AP

    with fl.tpu_trace_scope(True):
        jx = jax.make_jaxpr(fn)(*args)
    return list(AP.iter_pallas_calls(jx))


def test_kernel_vmem_bytes_prices_the_paged_kernel():
    """The traced paged-decode pallas_call prices exactly as the kernel
    allocates: double-buffered padded q/k/v/o blocks + fp32 softmax
    scratch in VMEM, the scalar-prefetched page table/lengths in SMEM."""
    from paddle_tpu.analysis import pallas as AP
    from paddle_tpu.kernels.paged_attention import paged_decode_attention

    B, H, D, ps, maxp = 4, 8, 128, 16, 32
    P = B * maxp
    q = jax.ShapeDtypeStruct((B, H, 1, D), jnp.float32)
    kp = jax.ShapeDtypeStruct((H, P, ps, D), jnp.float32)
    tb = jax.ShapeDtypeStruct((B, maxp), jnp.int32)
    ln = jax.ShapeDtypeStruct((B,), jnp.int32)
    eqns = _traced_pallas_eqns(
        lambda q, k, v, t, l: paged_decode_attention(
            q, k, v, t, l, impl="pallas"), q, kp, kp, tb, ln)
    assert len(eqns) == 1
    cost = AP.kernel_cost(eqns[0])
    # blocks: q/o (1,1,8,128) fp32 = 4 KB each, k/v (1,1,16,128) = 8 KB
    # each, double-buffered; scratch: two (8,1)->one tile each + (8,128)
    want_vmem = 2 * (4096 + 8192 + 8192 + 4096) + 3 * 4096
    assert cost.vmem_bytes == want_vmem
    assert AP.kernel_vmem_bytes(eqns[0]) == want_vmem
    assert cost.smem_bytes == B * maxp * 4 + B * 4  # tables + lengths
    assert cost.double_buffered and cost.grid == (B, H, maxp)
    assert cost.vmem_bytes < AP.default_vmem_budget()
    assert cost.name == "_paged_kernel"


def test_flash_fwd_vmem_estimate_matches_linter_price():
    """kernels/flash_attention.fwd_vmem_bytes is the kernel's own
    statement of its working set — it must equal what the linter prices
    off the traced call (blocks + packed-lse plane + scratch; the SMEM
    klen vector excluded from both)."""
    from paddle_tpu.analysis import pallas as AP
    from paddle_tpu.kernels.flash_attention import (
        flash_attention, fwd_vmem_bytes)

    B, H_, S, D = 2, 2, 256, 128
    qkv = jax.ShapeDtypeStruct((B, H_, S, D), jnp.float32)
    eqns = _traced_pallas_eqns(
        lambda q, k, v: flash_attention(q, k, v, causal=True,
                                        force="interpret"), qkv, qkv, qkv)
    assert len(eqns) == 1
    priced = AP.kernel_vmem_bytes(eqns[0])
    # the primal (inference) path drops the lse output entirely —
    # fwd_vmem_bytes(emit_lse=False) is its exact working set
    assert priced == fwd_vmem_bytes(
        block_q=128, block_k=128, head_dim=D, num_q_blocks=S // 128,
        emit_lse=False)
    # the training forward adds (only) the packed per-row lse plane
    with_lse = fwd_vmem_bytes(
        block_q=128, block_k=128, head_dim=D, num_q_blocks=S // 128,
        emit_lse=True)
    assert with_lse > priced
    assert with_lse < AP.default_vmem_budget()


def test_corpus_vmem_overflow_exactly_its_detector_with_fields():
    """ISSUE acceptance: the VMEM-busting BlockSpec trips EXACTLY
    vmem-overflow, carries the per-finding vmem_bytes/budget fields
    into JSON, and the budget is configurable (a raised budget clears
    it)."""
    _skip_if_no_topology()
    from paddle_tpu import flags as fl

    art = build_corpus_program("vmem_overflow")
    findings = analysis.run_detectors(art)
    assert {f.detector for f in findings} == {"vmem-overflow"}
    f = findings[0]
    assert f.severity == "error"
    assert f.vmem_bytes > f.budget
    assert f.vmem_bytes == 2 * 2 * 4096 * 4096 * 4  # in+out, 2x buffered
    d = f.as_dict()
    assert d["vmem_bytes"] == f.vmem_bytes and d["budget"] == f.budget
    assert "vmem" in f.format()
    # the chip pipeline rejects the same program (RESOURCE_EXHAUSTED) —
    # the detector sees it BEFORE any compile, which is the point
    assert "vmem" in art.compile_error.lower()
    old = fl.flag("FLAGS_analysis_vmem_budget")
    fl.set_flags({"FLAGS_analysis_vmem_budget": 1 << 30})
    try:
        assert not [x for x in analysis.run_detectors(art)
                    if x.detector == "vmem-overflow"]
    finally:
        fl.set_flags({"FLAGS_analysis_vmem_budget": old})


def test_corpus_scan_widening_exactly_its_detector():
    """The bf16->f32 scan-carry escape trips EXACTLY scan-widening: the
    stacked fp32 history (2x the bf16 bytes) escapes to the program
    output; the small carry itself sits under the size floor."""
    _skip_if_no_topology()
    art = build_corpus_program("scan_widening")
    findings = analysis.run_detectors(art)
    assert {f.detector for f in findings} == {"scan-widening"}
    assert len(findings) == 1
    f = findings[0]
    assert "stacked output" in f.where
    assert f.bytes == 512 * 1024 * 4  # the [T, N] fp32 history
    assert f.severity == "warning"


def test_scan_widening_narrowed_accumulator_stays_clean():
    """The dtype-promotion contract carries over: a DELIBERATE fp32
    accumulator over bf16 rows that narrows back before the HBM write
    is the stats idiom, not a finding."""
    _skip_if_no_topology()
    from paddle_tpu.analysis.capture import capture_fn

    N = 1 << 19  # the f32 carry alone is 2 MB — above the floor

    def fn(x):  # [8, N] bf16
        def body(c, row):
            return c + row, ()

        c0 = jnp.zeros((N,), jnp.float32)
        c, _ = jax.lax.scan(body, c0, x)
        return c.astype(jnp.bfloat16)  # narrowed before the write

    art = capture_fn(fn, jax.ShapeDtypeStruct((8, N), jnp.bfloat16),
                     name="narrowed_accumulator")
    assert not [f for f in analysis.run_detectors(art)
                if f.detector == "scan-widening"]


def test_lint_inject_new_corpus_entries_exit_3(tmp_path, capsys):
    """Both new known-bad entries must fail `--inject <name> --gate`
    end-to-end (the ISSUE acceptance wording): scan_widening carries a
    finding, vmem_overflow additionally fails its AOT compile — exit 3
    either way."""
    _skip_if_no_topology()
    assert _lint_main(["--programs", "paged_decode",
                       "--inject", "scan_widening", "--gate"]) == 3
    assert _lint_main(["--programs", "paged_decode",
                       "--inject", "vmem_overflow", "--gate"]) == 3
    capsys.readouterr()


def test_sharded_decode_layout_tax_banked_at_zero():
    """ISSUE 14 acceptance: the banked sharded_decode entry holds
    relayout-copy-pair at ZERO (the oldest open finding count in the
    bank) — the kernel consumes XLA's preferred pool-shard layout
    (pool_layout="xla" + the kv_pool_layout program-boundary pin) — and
    the bytes/step win is banked (the taxed program priced 51.3 MB/chip
    per step; relayout-free must stay well under 45 MB)."""
    with open(analysis.default_baseline_path()) as f:
        progs = json.load(f)["programs"]
    entry = progs["sharded_decode"]
    assert entry["findings"].get("relayout-copy-pair", 0) == 0
    assert entry["findings"] == {}  # clean across ALL detectors
    assert entry["bytes_per_step"] < 45e6
    # every banked program is clean on the two new detectors (they are
    # gated from day one, the ROADMAP clause)
    for name, e in progs.items():
        assert e["findings"].get("vmem-overflow", 0) == 0, name
        assert e["findings"].get("scan-widening", 0) == 0, name


def test_findings_sorted_severity_then_bytes():
    """The one report order (stable gate diffs): strongest severity
    first, then biggest cost — vmem_bytes counts as the cost for
    non-traffic kernel findings."""
    from paddle_tpu.analysis import sort_findings

    fs = [
        Finding(detector="a", severity="warning", program="p",
                message="m", bytes=10),
        Finding(detector="b", severity="error", program="p",
                message="m", bytes=1),
        Finding(detector="c", severity="info", program="p",
                message="m", bytes=99),
        Finding(detector="d", severity="error", program="p",
                message="m", vmem_bytes=500, budget=100),
        Finding(detector="e", severity="warning", program="p",
                message="m", bytes=20),
    ]
    got = [f.detector for f in sort_findings(fs)]
    assert got == ["d", "b", "e", "a", "c"]


def test_scan_widening_catches_carry_aliased_with_dead_ys():
    """A body `return c, c` (the carry also emitted as a stacked output)
    whose caller keeps only the FINAL carry: the shared body var fills
    two outvar slots, and the carry slot must still be examined even
    though the ys slot is dead — a last-wins slot map would silently
    drop the exact hazard class the detector exists for."""
    _skip_if_no_topology()
    from paddle_tpu.analysis.capture import capture_fn

    N = 1 << 18  # the f32 carry alone is 1 MB — at the size floor

    def fn(x):  # [8, N] bf16
        def body(c, row):
            c = c + row  # widens: bf16 row joins the f32 carry
            return c, c  # carry AND stacked output are the same var

        c0 = jnp.zeros((N,))  # silently fp32
        c, _ = jax.lax.scan(body, c0, x)
        return c  # only the widened final carry escapes

    art = capture_fn(fn, jax.ShapeDtypeStruct((8, N), jnp.bfloat16),
                     name="carry_aliased_ys")
    hit = [f for f in analysis.run_detectors(art)
           if f.detector == "scan-widening"]
    assert hit and any(f.where == "scan carry 0" for f in hit)
