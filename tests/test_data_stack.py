"""Data stack: reader decorators, datasets, DataFeeder, py_reader, recordio
(reference: python/paddle/reader/tests, test_data_feeder.py,
test_py_reader_push_pop.py, test_recordio_reader.py)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers, reader as rdr
from paddle_tpu.core.lod import LoDValue


def _counting_reader(n):
    def r():
        for i in range(n):
            yield i

    return r


def test_decorators_compose():
    r = rdr.firstn(_counting_reader(100), 10)
    assert list(r()) == list(range(10))
    r = rdr.chain(_counting_reader(3), _counting_reader(2))
    assert list(r()) == [0, 1, 2, 0, 1]
    r = rdr.map_readers(lambda a, b: a + b, _counting_reader(3), _counting_reader(3))
    assert list(r()) == [0, 2, 4]
    r = rdr.compose(_counting_reader(3), _counting_reader(3))
    assert list(r()) == [(0, 0), (1, 1), (2, 2)]
    r = rdr.buffered(_counting_reader(10), 4)
    assert sorted(r()) == list(range(10))
    r = rdr.shuffle(_counting_reader(10), 5)
    assert sorted(r()) == list(range(10))
    r = rdr.cache(_counting_reader(5))
    assert list(r()) == list(r())  # second pass identical
    r = rdr.xmap_readers(lambda x: x * 2, _counting_reader(10), 3, 4, order=True)
    assert list(r()) == [2 * i for i in range(10)]


def test_batch():
    b = rdr.batch(_counting_reader(7), 3)
    batches = list(b())
    assert [len(x) for x in batches] == [3, 3, 1]
    b = rdr.batch(_counting_reader(7), 3, drop_last=True)
    assert [len(x) for x in list(b())] == [3, 3]


def test_datasets_have_right_schema():
    img, lab = next(fluid.dataset.mnist.train()())
    assert img.shape == (784,) and img.dtype == np.float32
    assert 0 <= lab < 10
    x, y = next(fluid.dataset.uci_housing.train()())
    assert x.shape == (13,) and y.shape == (1,)
    ids, sent = next(fluid.dataset.imdb.train()())
    assert isinstance(ids, list) and sent in (0, 1)
    src, tin, tout = next(fluid.dataset.wmt16.train(1000, 1000)())
    assert tin[0] == 0 and tout[-1] == 1 and len(tin) == len(tout)


def test_dataset_deterministic():
    a = [lab for _, lab in rdr.firstn(fluid.dataset.mnist.train(), 20)()]
    b = [lab for _, lab in rdr.firstn(fluid.dataset.mnist.train(), 20)()]
    assert a == b


def test_data_feeder_dense_and_lod():
    x = layers.data("img", [4], dtype="float32")
    s = layers.data("seq", [2], dtype="float32", lod_level=1)
    feeder = fluid.DataFeeder(feed_list=[x, s], place=fluid.CPUPlace())
    batch = [
        (np.zeros(4, np.float32), np.ones((3, 2), np.float32)),
        (np.ones(4, np.float32), np.ones((5, 2), np.float32)),
    ]
    feed = feeder.feed(batch)
    assert feed["img"].shape == (2, 4)
    assert isinstance(feed["seq"], LoDValue)
    assert feed["seq"].data.shape == (2, 5, 2)
    np.testing.assert_array_equal(np.asarray(feed["seq"].lengths), [3, 5])


def test_py_reader_trains_to_eof():
    r = layers.py_reader(
        capacity=4, shapes=[[-1, 8], [-1, 1]], dtypes=["float32", "float32"]
    )
    x, y = layers.read_file(r)
    pred = layers.fc(x, size=1)
    loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
    fluid.optimizer.SGDOptimizer(learning_rate=0.01).minimize(loss)

    rng = np.random.RandomState(0)

    def source():
        for _ in range(5):
            yield [
                (rng.randn(8).astype("float32"), rng.randn(1).astype("float32"))
                for _ in range(4)
            ]

    r.decorate_paddle_reader(source)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    r.start()
    n = 0
    while True:
        try:
            exe.run(feed=None, fetch_list=[loss])
            n += 1
        except fluid.core.EOFException:
            r.reset()
            break
    assert n == 5


def test_recordio_roundtrip_native_and_python(tmp_path):
    from paddle_tpu import recordio

    path = str(tmp_path / "data.recordio")
    records = [bytes([i % 256]) * (i * 37 % 100 + 1) for i in range(257)]
    recordio.write_recordio(path, records, max_chunk_records=64)
    got = list(recordio.read_recordio(path))
    assert got == records

    # cross-check: the pure-python codec reads the native file and vice versa
    py_path = str(tmp_path / "py.recordio")
    with recordio.RecordIOWriter(py_path, 64, force_python=True) as w:
        for rec in records:
            w.write(rec)
    with recordio.RecordIOScanner(py_path) as s:
        assert list(s) == records
    with recordio.RecordIOScanner(path, force_python=True) as s:
        assert list(s) == records


def test_recordio_native_built():
    from paddle_tpu import native

    assert native.load("recordio") is not None, "native recordio failed to build"


def test_reader_over_recordio(tmp_path):
    import pickle

    from paddle_tpu import recordio

    path = str(tmp_path / "samples.recordio")
    samples = [(np.full(3, i, np.float32), i % 2) for i in range(10)]
    recordio.write_recordio(path, (pickle.dumps(s) for s in samples))

    def reader():
        for rec in recordio.read_recordio(path):
            yield pickle.loads(rec)

    got = list(reader())
    assert len(got) == 10
    np.testing.assert_array_equal(got[3][0], np.full(3, 3, np.float32))


def test_native_multislot_parser_matches_python():
    """native/multislot.cc parses identically to the Python fallback
    (reference: data_feed.cc MultiSlotDataFeed::ParseOneInstance)."""
    import tempfile

    import numpy as np
    from paddle_tpu import native
    from paddle_tpu.async_executor import (
        _parse_multislot_file, _parse_multislot_line,
    )
    from paddle_tpu.data_feed_desc import SlotDesc as Slot

    slots = [
        Slot(name="ids", type="uint64", is_dense=False, is_used=True),
        Slot(name="w", type="float", is_dense=False, is_used=True),
        Slot(name="skip", type="uint64", is_dense=False, is_used=False),
    ]
    lines = [
        "3 1 2 3 2 0.5 -1.5 1 9",
        "1 7 1 2.25 2 4 5",
    ]
    with tempfile.NamedTemporaryFile("w", suffix=".txt", delete=False) as f:
        f.write("\n".join(lines) + "\n\n")  # trailing blank line
        path = f.name
    rows = list(_parse_multislot_file(path, slots))
    want = [_parse_multislot_line(l, slots) for l in lines]
    assert len(rows) == 2
    for got_row, want_row in zip(rows, want):
        for g, w in zip(got_row, want_row):
            if w is None:
                continue  # unused slot
            np.testing.assert_array_equal(np.asarray(g), w)
    assert native.load("multislot") is not None, "native parser didn't build"

    # malformed line reports its number
    with tempfile.NamedTemporaryFile("w", suffix=".txt", delete=False) as f:
        f.write("3 1 2 3 2 0.5 1.5 1 9\n2 1\n")
        bad = f.name
    try:
        list(_parse_multislot_file(bad, slots))
        raise AssertionError("expected parse error")
    except ValueError as e:
        assert "line 2" in str(e)


def test_open_files_and_preprocessor():
    """open_files reads recordio'd npz records; Preprocessor maps samples
    (reference: layers/io.py open_files / Preprocessor)."""
    import io
    import os
    import tempfile

    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu.recordio import RecordIOWriter

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "a.recordio")
        with RecordIOWriter(path) as w:
            for i in range(3):
                buf = io.BytesIO()
                np.savez(buf, x=np.full((2,), i, dtype="float32"),
                         y=np.array([i], dtype="int64"))
                w.write(buf.getvalue())
        rd = fluid.layers.open_files([path], shapes=[[2], [1]],
                                     lod_levels=[0, 0],
                                     dtypes=["float32", "int64"])
        rows = list(rd())
        assert len(rows) == 3
        np.testing.assert_allclose(rows[2][0], [2.0, 2.0])

        p = fluid.layers.Preprocessor(rd)

        @p.block
        def _map(x, y):
            return x * 2.0, y

        rows2 = list(p())
        np.testing.assert_allclose(rows2[1][0], rows[1][0] * 2.0)


def test_random_data_generator():
    import paddle_tpu as fluid

    r = fluid.layers.random_data_generator(0.0, 1.0, [[2, 3], [1]])
    s = next(r())
    assert s[0].shape == (2, 3) and s[1].shape == (1,)
    assert (s[0] >= 0).all() and (s[0] <= 1).all()


def test_convert_reader_to_recordio_file_roundtrip(tmp_path):
    """fluid.recordio_writer.convert_reader_to_recordio_file writes the
    npz-record format layers.open_files reads back (reference:
    recordio_writer.py:34)."""
    import paddle_tpu as fluid
    from paddle_tpu import layers

    path = str(tmp_path / "batches.recordio")
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        img = layers.data("img", [4], dtype="float32")
        lbl = layers.data("lbl", [1], dtype="int64")
        feeder = fluid.DataFeeder(feed_list=[img, lbl],
                                  place=fluid.CPUPlace())

    rng = np.random.RandomState(0)
    batches = [
        [(rng.rand(4).astype("float32"), np.array([i], "int64"))
         for i in range(3)]
        for _ in range(5)
    ]
    n = fluid.recordio_writer.convert_reader_to_recordio_file(
        path, lambda: iter(batches), feeder)
    assert n == 5

    reader = layers.open_files(
        [path], shapes=[[-1, 4], [-1, 1]], lod_levels=[0, 0],
        dtypes=["float32", "int64"])
    got = list(reader())
    assert len(got) == 5
    np.testing.assert_allclose(
        got[0][0], np.stack([s[0] for s in batches[0]]), rtol=1e-6)


def test_convert_recordio_lod_roundtrip(tmp_path):
    """LoD slots written by convert_reader_to_recordio_file fold back into
    LoDValues through layers.open_files (the __lodK__ sidecar entries)."""
    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.core.lod import LoDValue

    path = str(tmp_path / "seqs.recordio")
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        seq = layers.data("seq", [2], dtype="float32", lod_level=1)
        lbl = layers.data("lbl", [1], dtype="int64")
        feeder = fluid.DataFeeder(feed_list=[seq, lbl],
                                  place=fluid.CPUPlace())

    rng = np.random.RandomState(1)
    batches = [
        [(rng.rand(lens, 2).astype("float32"), np.array([i], "int64"))
         for i, lens in enumerate((2, 4, 1))]
        for _ in range(3)
    ]
    n = fluid.recordio_writer.convert_reader_to_recordio_file(
        path, lambda: iter(batches), feeder)
    assert n == 3

    reader = layers.open_files(
        [path], shapes=[[-1, 2], [-1, 1]], lod_levels=[1, 0],
        dtypes=["float32", "int64"])
    got = list(reader())
    assert len(got) == 3
    first_seq = got[0][0]
    assert isinstance(first_seq, LoDValue)
    np.testing.assert_array_equal(np.asarray(first_seq.lengths), [2, 4, 1])
    np.testing.assert_allclose(
        np.asarray(first_seq.data)[1, :4], batches[0][1][0], rtol=1e-6)


def test_unique_name_switch_and_prefixed_guard():
    import paddle_tpu as fluid

    with fluid.unique_name.guard("pre_"):
        assert fluid.unique_name.generate("k").startswith("pre_k_")
    old = fluid.unique_name.switch()
    try:
        assert fluid.unique_name.generate("k") == "k_0"
    finally:
        fluid.unique_name.switch(old)


def test_reader_creators(tmp_path):
    """reader.creator np_array / text_file / recordio (reference:
    python/paddle/reader/creator.py)."""
    from paddle_tpu import reader as rdr
    from paddle_tpu.recordio import write_recordio

    assert [int(v) for v in rdr.creator.np_array(np.arange(3))()] == [0, 1, 2]

    p = tmp_path / "t.txt"
    p.write_text("a\nb\n")
    assert list(rdr.creator.text_file(str(p))()) == ["a", "b"]

    rp = str(tmp_path / "r.recordio")
    write_recordio(rp, [b"one", b"two"])
    assert list(rdr.creator.recordio(rp)()) == [b"one", b"two"]


def test_preprocessor_sub_block_compiled():
    """Reference-style Preprocessor (layers/io.py:1080 over
    create_custom_reader_op.cc): the sub-block lowers to one jitted fn the
    reader worker applies per batch; training consumes transformed slots."""
    r = layers.py_reader(
        capacity=4, shapes=[[-1, 8], [-1, 1]], dtypes=["float32", "float32"]
    )
    p = fluid.layers.Preprocessor(reader=r)
    with p.block():
        x_in, y_in = p.inputs()
        x_out = layers.scale(x_in, scale=0.5)
        y_out = layers.scale(y_in, scale=2.0)
        p.outputs(x_out, y_out)
    new_r = p()
    x, y = layers.read_file(new_r)
    pred = layers.fc(x, size=1)
    loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
    fluid.optimizer.SGDOptimizer(learning_rate=0.01).minimize(loss)

    rng = np.random.RandomState(0)
    batches = [
        [(rng.randn(8).astype("float32"), rng.randn(1).astype("float32"))
         for _ in range(4)]
        for _ in range(3)
    ]

    def source():
        yield from batches

    new_r.decorate_paddle_reader(source)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    new_r.start()
    n = 0
    while True:
        try:
            exe.run(feed=None, fetch_list=[loss])
            n += 1
        except fluid.core.EOFException:
            new_r.reset()
            break
    assert n == 3

    # the transform really applied: feed the halved/doubled batch manually
    # and the fetched x slot must equal 0.5 * raw
    got = new_r._transform(
        {r._names[0]: np.ones((2, 8), "float32"),
         r._names[1]: np.ones((2, 1), "float32")})
    xs = [v for k, v in got.items() if np.shape(v)[-1] == 8][0]
    np.testing.assert_allclose(np.asarray(xs), 0.5 * np.ones((2, 8)),
                               rtol=1e-6)
