"""SelectedRows sparse embedding gradients (reference:
framework/selected_rows.h, operators/lookup_table_op.cc:80 sparse grad path,
optimizers' sparse kernels e.g. adam_op.h:470) and the DeepFM CTR model."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers, models
from paddle_tpu.core.selected_rows import SelectedRowsValue


def test_merge_dedups_and_sentinels():
    ids = jnp.array([3, 1, 3, 7, 1], dtype=jnp.int32)
    rows = jnp.arange(10, dtype=jnp.float32).reshape(5, 2)
    srv = SelectedRowsValue(ids, rows, height=10).merge()
    dense = np.asarray(srv.to_dense())
    expected = np.zeros((10, 2), np.float32)
    for i, r in zip([3, 1, 3, 7, 1], np.arange(10).reshape(5, 2)):
        expected[i] += r
    np.testing.assert_allclose(dense, expected)
    # merged ids: one live slot per distinct id, rest are the sentinel
    live = np.asarray(srv.ids) < 10
    assert live.sum() == 3


def _embedding_net(is_sparse, opt_factory, vocab=64, dim=8):
    ids = layers.data("ids", [4], dtype="int64")
    label = layers.data("label", [1], dtype="float32")
    emb = layers.embedding(ids, size=[vocab, dim], is_sparse=is_sparse,
                           param_attr="srv_w")
    s = layers.reduce_sum(emb, dim=[1, 2], keep_dim=False)
    pred = layers.reshape(s, [-1, 1])
    loss = layers.mean(layers.square_error_cost(pred, label))
    opt_factory().minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    return exe, loss


OPTIMIZERS = {
    "sgd": lambda: fluid.optimizer.SGDOptimizer(learning_rate=0.05),
    "momentum": lambda: fluid.optimizer.MomentumOptimizer(
        learning_rate=0.05, momentum=0.9),
    "adam": lambda: fluid.optimizer.AdamOptimizer(learning_rate=0.05),
    "adagrad": lambda: fluid.optimizer.AdagradOptimizer(learning_rate=0.05),
}


@pytest.mark.parametrize("opt", sorted(OPTIMIZERS))
def test_sparse_matches_dense_update(opt):
    """Sparse (SelectedRows) and dense grad paths produce identical params,
    including batches that repeat ids (the merge/dedup case) AND ids that
    vary across steps — the case where a lazy row-wise adam/momentum would
    diverge (their moments decay even at zero grad), so this pins the
    default to dense-equivalence."""
    rng = np.random.RandomState(0)
    batches = [
        (np.array([[1, 3, 3, 7], [7, 7, 2, 1]], dtype=np.int64),
         rng.randn(2, 1).astype("float32")),
        (np.array([[9, 4, 4, 2], [11, 1, 5, 9]], dtype=np.int64),
         rng.randn(2, 1).astype("float32")),
        (np.array([[3, 3, 3, 3], [8, 10, 12, 1]], dtype=np.int64),
         rng.randn(2, 1).astype("float32")),
    ]
    results = {}
    for is_sparse in (False, True):
        fluid.reset_default_env()
        exe, loss = _embedding_net(is_sparse, OPTIMIZERS[opt])
        for idv, lv in batches:
            exe.run(feed={"ids": idv, "label": lv}, fetch_list=[loss])
        results[is_sparse] = np.asarray(
            fluid.global_scope().find_var("srv_w"))
    np.testing.assert_allclose(results[True], results[False],
                               rtol=1e-5, atol=1e-6)


def test_lazy_adam_freezes_untouched_rows():
    """Adam(lazy_mode=True): rows absent from a step's batch keep their
    exact values (TF LazyAdam semantics); dense adam would drift them via
    moment decay.  This is the mode the CTR bench runs, where sweeping the
    vocab every step would defeat the sparse path."""
    fluid.reset_default_env()
    exe, loss = _embedding_net(
        True,
        lambda: fluid.optimizer.AdamOptimizer(learning_rate=0.05,
                                              lazy_mode=True),
    )
    lv = np.zeros((1, 1), np.float32)
    exe.run(feed={"ids": np.array([[1, 2, 3, 4]], dtype=np.int64),
                  "label": lv}, fetch_list=[loss])
    w1 = np.asarray(fluid.global_scope().find_var("srv_w")).copy()
    exe.run(feed={"ids": np.array([[5, 6, 7, 8]], dtype=np.int64),
                  "label": lv}, fetch_list=[loss])
    w2 = np.asarray(fluid.global_scope().find_var("srv_w"))
    np.testing.assert_array_equal(w2[1:5], w1[1:5])  # untouched: frozen
    assert not np.allclose(w2[5:9], w1[5:9])  # touched: moved


def test_sparse_grad_fetch_is_selected_rows():
    fluid.reset_default_env()
    exe, loss = _embedding_net(True, OPTIMIZERS["sgd"])
    idv = np.array([[1, 3, 3, 7]], dtype=np.int64)
    lv = np.zeros((1, 1), np.float32)
    (g,) = exe.run(feed={"ids": idv, "label": lv},
                   fetch_list=["srv_w@GRAD"])
    assert isinstance(g, SelectedRowsValue)
    assert g.rows.shape == (4, 8) and g.height == 64


def test_padding_idx_grad_dropped():
    fluid.reset_default_env()
    ids = layers.data("ids", [3], dtype="int64")
    emb = layers.embedding(ids, size=[16, 4], is_sparse=True,
                           padding_idx=2, param_attr="pad_w")
    loss = layers.mean(emb)
    fluid.optimizer.SGDOptimizer(learning_rate=1.0).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    w0 = np.asarray(fluid.global_scope().find_var("pad_w")).copy()
    exe.run(feed={"ids": np.array([[1, 2, 5]], dtype=np.int64)},
            fetch_list=[loss])
    w1 = np.asarray(fluid.global_scope().find_var("pad_w"))
    assert not np.allclose(w1[1], w0[1])  # touched row moved
    np.testing.assert_allclose(w1[2], w0[2])  # padding row untouched


def test_sparse_path_avoids_dense_grad_buffer():
    """The point of SelectedRows: no [V, D] gradient buffer exists in the
    step.  Compare jaxpr-level dense [V, D] intermediates between the sparse
    and dense lowerings of the same net — sparse must create none beyond
    the in-place param/moment updates."""
    from paddle_tpu.core.compiler import CompiledBlock
    from paddle_tpu.core.executor import _RunPlan

    vocab, dim = 50_000, 16

    def build(is_sparse):
        fluid.reset_default_env()
        ids = layers.data("ids", [4], dtype="int64")
        label = layers.data("label", [1], dtype="float32")
        emb = layers.embedding(ids, size=[vocab, dim], is_sparse=is_sparse,
                               param_attr=f"big_w_{is_sparse}")
        s = layers.reduce_sum(emb, dim=[1, 2], keep_dim=False)
        loss = layers.mean(
            layers.square_error_cost(layers.reshape(s, [-1, 1]), label))
        fluid.optimizer.AdamOptimizer(
            learning_rate=0.01, lazy_mode=True).minimize(loss)
        program = fluid.default_main_program()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        plan = _RunPlan(program, ["ids", "label"], [loss.name])
        compiled = CompiledBlock(
            program, 0, plan.feed_names, plan.fetch_names, plan.state_names,
            donate_states=False,
        )
        block0 = program.desc.block(0)
        feed_vals = plan.feed_values(
            {"ids": np.zeros((2, 4), np.int64),
             "label": np.zeros((2, 1), np.float32)}, block0)
        state_vals = plan.state_values(fluid.global_scope(), block0)
        jaxpr = jax.make_jaxpr(compiled.raw_fn)(
            feed_vals, state_vals, jax.random.PRNGKey(0))
        count = 0
        for eqn in jaxpr.jaxpr.eqns:
            for v in eqn.outvars:
                if getattr(v, "aval", None) is not None and \
                        tuple(v.aval.shape) == (vocab, dim):
                    count += 1
        return count

    sparse_count = build(True)
    dense_count = build(False)
    # dense path: scatter-add grad buffer (+zeros) on top of the param and
    # moment updates; sparse path: only the three in-place row updates
    assert sparse_count < dense_count
    assert sparse_count <= 3


def test_deepfm_trains_and_large_vocab_compiles():
    fluid.reset_default_env()
    spec = models.deepfm(num_fields=6, vocab_size=100_000, embed_dim=8,
                         hidden_sizes=(32, 32))
    fluid.optimizer.AdamOptimizer(learning_rate=0.001).minimize(spec.loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    b = spec.synthetic_batch(32)
    losses = []
    for _ in range(8):
        (l,) = exe.run(feed=b, fetch_list=[spec.loss])
        losses.append(float(np.ravel(l)[0]))
    assert losses[-1] < losses[0]


def test_sparse_grads_on_mp_sharded_table():
    """The pserver sparse path, TPU-native and sparse end to end: the table
    shards over an mp axis (replacing pserver row slicing,
    distribute_transpiler.py:1119) AND the grads stay SelectedRows; XLA
    partitions the row gather/scatter over the mesh.  Parity vs serial."""
    from paddle_tpu.parallel import ParallelExecutor, make_mesh

    V, E = 64, 16
    idv = np.array([[1, 3, 3, 60], [60, 7, 2, 1], [5, 5, 5, 5],
                    [9, 11, 13, 1]], dtype=np.int64)
    lv = np.random.RandomState(1).randn(4, 1).astype("float32")

    def build(sharded):
        fluid.reset_default_env()
        ids = layers.data("ids", [4], dtype="int64")
        label = layers.data("label", [1], dtype="float32")
        attr = fluid.ParamAttr(
            name="mp_table", sharding=["mp", None] if sharded else None)
        emb = layers.embedding(ids, size=[V, E], is_sparse=True,
                               param_attr=attr)
        s = layers.reduce_sum(emb, dim=[1, 2], keep_dim=False)
        loss = layers.mean(
            layers.square_error_cost(layers.reshape(s, [-1, 1]), label))
        fluid.optimizer.AdamOptimizer(learning_rate=0.05).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        return exe, loss

    exe, loss = build(False)
    serial = [
        float(np.ravel(np.asarray(
            exe.run(feed={"ids": idv, "label": lv}, fetch_list=[loss])[0]))[0])
        for _ in range(4)
    ]
    w_serial = np.asarray(fluid.global_scope().find_var("mp_table"))

    exe, loss = build(True)
    pe = ParallelExecutor(
        loss_name=loss.name, mesh=make_mesh({"dp": 2, "mp": 4}))
    dist = [
        float(np.ravel(np.asarray(
            pe.run(feed={"ids": idv, "label": lv}, fetch_list=[loss])[0]))[0])
        for _ in range(4)
    ]
    w_dist = np.asarray(fluid.global_scope().find_var("mp_table"))
    np.testing.assert_allclose(dist, serial, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(w_dist, w_serial, rtol=1e-5, atol=1e-6)


def test_deepfm_data_parallel_matches_serial():
    """dist loss == local loss for the CTR model (reference contract:
    test_dist_base.py check_with_place), on a 4-way dp mesh."""
    from paddle_tpu.parallel import ParallelExecutor, make_mesh

    def build():
        fluid.reset_default_env()
        spec = models.deepfm(num_fields=4, vocab_size=1000, embed_dim=4,
                             hidden_sizes=(16,))
        fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(spec.loss)
        return spec

    spec = build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    b = spec.synthetic_batch(16)
    serial = [
        float(np.ravel(np.asarray(
            exe.run(feed=b, fetch_list=[spec.loss])[0]))[0])
        for _ in range(3)
    ]

    spec = build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    mesh = make_mesh({"dp": 4}, devices=jax.devices()[:4])
    pe = ParallelExecutor(loss_name=spec.loss.name, mesh=mesh)
    b = spec.synthetic_batch(16)
    dist = [
        float(np.ravel(np.asarray(
            pe.run(feed=b, fetch_list=[spec.loss])[0]))[0])
        for _ in range(3)
    ]
    np.testing.assert_allclose(dist, serial, rtol=1e-5, atol=1e-6)
