"""Multi-level LoD (reference: framework/lod_tensor.h nested offset tables,
python/paddle/fluid/lod_tensor.py create_lod_tensor 2-level examples)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.core.lod import LoDValue, create_lod_tensor


def test_two_level_construction_and_lod():
    # 2 paragraphs: [2, 3] sentences; sentence word counts [2, 2, 1, 3, 2]
    flat = np.arange(10, dtype="float32").reshape(10, 1)
    v = create_lod_tensor(flat, [[2, 3], [2, 2, 1, 3, 2]])
    assert isinstance(v, LoDValue)
    assert v.lod_level == 2
    assert v.data.shape == (2, 3, 3, 1)  # N=2, L1=3, L2=3
    # reference offset convention
    assert v.lod() == [[0, 2, 5], [0, 2, 4, 5, 8, 10]]
    # padded placement: paragraph 1, sentence 2 holds tokens [8, 9]
    np.testing.assert_allclose(v.data[1, 2, :2, 0], [8.0, 9.0])
    assert v.data[0, 2].sum() == 0  # padding sentence in paragraph 0


def test_flatten_level_roundtrip():
    flat = np.arange(20, dtype="float32").reshape(10, 2)
    v = create_lod_tensor(flat, [[2, 3], [2, 2, 1, 3, 2]])
    inner = v.flatten_level()
    assert inner.lod_level == 1
    assert inner.data.shape == (6, 3, 2)  # N*L1 inner sequences
    np.testing.assert_array_equal(
        np.asarray(inner.lengths), [2, 2, 0, 1, 3, 2])  # pad slot len 0
    # inner sequence contents survive
    np.testing.assert_allclose(
        np.asarray(inner.data)[0, :2], flat[:2])
    np.testing.assert_allclose(
        np.asarray(inner.data)[4, :3], flat[5:8])


def test_two_level_feeds_through_executor():
    """A 2-level value flows through feed -> op -> fetch as a pytree."""
    fluid.reset_default_env()
    x = fluid.layers.data(name="x", shape=[1], dtype="float32", lod_level=2)
    y = fluid.layers.scale(x, scale=2.0)
    exe = fluid.Executor(fluid.CPUPlace())
    flat = np.arange(10, dtype="float32").reshape(10, 1)
    v = create_lod_tensor(flat, [[2, 3], [2, 2, 1, 3, 2]])
    (got,) = exe.run(feed={"x": v}, fetch_list=[y], return_numpy=False)
    np.testing.assert_allclose(np.asarray(got.data), np.asarray(v.data) * 2)
    assert got.lod_level == 2  # nested lengths survive the op
    assert got.lod() == v.lod()


def test_three_level_lod_offsets():
    """lod() is exact at depth 3 (review finding r2)."""
    # 2 tops with [2, 1] mids; mids have [2, 1, 2] bottoms;
    # bottoms have [1, 2, 3, 1, 1] tokens
    lengths = np.array([2, 1], dtype=np.int32)
    sub1 = np.zeros((2, 2), dtype=np.int32)
    sub1[0, 0], sub1[0, 1], sub1[1, 0] = 2, 1, 2
    sub2 = np.zeros((2, 2, 2), dtype=np.int32)
    sub2[0, 0, 0], sub2[0, 0, 1] = 1, 2
    sub2[0, 1, 0] = 3
    sub2[1, 0, 0], sub2[1, 0, 1] = 1, 1
    data = np.zeros((2, 2, 2, 3, 1), dtype="float32")
    v = LoDValue(data, lengths, (sub1, sub2))
    assert v.lod_level == 3
    assert v.lod() == [
        [0, 2, 3],
        [0, 2, 3, 5],
        [0, 1, 3, 6, 7, 8],
    ]


def test_numpy_fetch_keeps_levels():
    """return_numpy=True fetch preserves nested lengths (review finding)."""
    fluid.reset_default_env()
    x = fluid.layers.data(name="x", shape=[1], dtype="float32", lod_level=2)
    y = fluid.layers.scale(x, scale=3.0)
    exe = fluid.Executor(fluid.CPUPlace())
    flat = np.arange(10, dtype="float32").reshape(10, 1)
    v = create_lod_tensor(flat, [[2, 3], [2, 2, 1, 3, 2]])
    (got,) = exe.run(feed={"x": v}, fetch_list=[y])  # default return_numpy
    assert got.lod_level == 2
    assert got.lod() == v.lod()


def test_flatten_level_depth3():
    lengths = np.array([2, 1], dtype=np.int32)
    sub1 = np.zeros((2, 2), dtype=np.int32)
    sub1[0, 0], sub1[0, 1], sub1[1, 0] = 2, 1, 2
    sub2 = np.zeros((2, 2, 2), dtype=np.int32)
    sub2[0, 0, 0], sub2[0, 0, 1] = 1, 2
    sub2[0, 1, 0] = 3
    sub2[1, 0, 0], sub2[1, 0, 1] = 1, 1
    data = np.zeros((2, 2, 2, 3, 1), dtype="float32")
    v = LoDValue(data, lengths, (sub1, sub2))
    inner = v.flatten_level()
    assert inner.lod_level == 2
    # offsets of the flattened view drop the old outermost level; the
    # grid-ordered slots are (0,0)=2, (0,1)=1, (1,0)=2, (1,1)=pad 0
    assert inner.lod() == [[0, 2, 3, 5, 5], [0, 1, 3, 6, 7, 8]]
