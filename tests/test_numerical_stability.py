"""Numerical-stability checks at extreme inputs: the log-sum-exp family
must not overflow for large logits, normalizers must survive
zero-variance rows, and the CTC alpha scan must stay finite on long
sequences (reference analogues: the C++ kernels' max-subtraction in
softmax functors, math/cross_entropy.h TolerableValue clamping)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.core.backward import append_backward


def _finite(*arrays):
    for a in arrays:
        assert np.all(np.isfinite(np.asarray(a))), a


def test_softmax_ce_large_logits_shift_invariant():
    """softmax_with_cross_entropy at logits ~1e4 is finite and equals the
    shifted computation (max-subtraction invariance)."""
    rng = np.random.RandomState(0)
    # eighths are exactly representable even after the +1e4 shift, so the
    # shifted logits carry identical information (a raw randn would be
    # rounded at the 1e4 scale and change the task itself)
    base = (np.round(rng.randn(4, 6) * 8) / 8).astype("float32")
    yv = rng.randint(0, 6, (4, 1)).astype("int64")

    def run(logits):
        fluid.reset_default_env()
        x = layers.data("x", [6], dtype="float32")
        x.stop_gradient = False
        y = layers.data("y", [1], dtype="int64")
        loss = layers.softmax_with_cross_entropy(x, y)
        append_backward(layers.reduce_sum(loss))
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        out, g = exe.run(feed={"x": logits, "y": yv},
                         fetch_list=[loss, f"{x.name}@GRAD"])
        return np.asarray(out), np.asarray(g)

    small, gs = run(base)
    big, gb = run(base + 1e4)
    _finite(small, big, gs, gb)
    np.testing.assert_allclose(small, big, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(gs, gb, rtol=1e-3, atol=1e-5)


def test_sigmoid_ce_saturated_logits_finite():
    """sigmoid_cross_entropy_with_logits at +-50 must not produce inf
    (the naive log(sigmoid) would); grads saturate to 0/1 cleanly."""
    x = layers.data("x", [4], dtype="float32")
    x.stop_gradient = False
    lab = layers.data("lab", [4], dtype="float32")
    loss = layers.sigmoid_cross_entropy_with_logits(x, lab)
    append_backward(layers.reduce_sum(loss))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    xv = np.array([[50.0, -50.0, 30.0, -30.0]], dtype="float32")
    lv = np.array([[1.0, 0.0, 0.0, 1.0]], dtype="float32")
    out, g = exe.run(feed={"x": xv, "lab": lv},
                     fetch_list=[loss, f"{x.name}@GRAD"])
    _finite(out, g)
    # matched-sign entries have ~0 loss; mismatched ~|logit|
    np.testing.assert_allclose(np.asarray(out)[0, :2], [0.0, 0.0],
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(out)[0, 2:], [30.0, 30.0],
                               rtol=1e-5)


def test_norms_zero_variance_rows_finite():
    """layer_norm and batch_norm on constant inputs (zero variance) stay
    finite fwd and bwd (epsilon guards)."""
    x = layers.data("x", [5], dtype="float32")
    x.stop_gradient = False
    ln = layers.layer_norm(x, begin_norm_axis=1)
    loss = layers.reduce_sum(layers.square(ln))
    append_backward(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    xv = np.full((3, 5), 2.5, dtype="float32")
    out, g = exe.run(feed={"x": xv}, fetch_list=[ln, f"{x.name}@GRAD"])
    _finite(out, g)

    fluid.reset_default_env()
    x = layers.data("x", [2, 4, 4], dtype="float32")
    x.stop_gradient = False
    bn = layers.batch_norm(x)
    loss = layers.reduce_sum(layers.square(bn))
    append_backward(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    xv = np.zeros((2, 2, 4, 4), dtype="float32")
    out, g = exe.run(feed={"x": xv}, fetch_list=[bn, f"{x.name}@GRAD"])
    _finite(out, g)


def test_warpctc_long_sequence_finite():
    """CTC alpha scan over T=200 stays finite in log space (a prob-space
    DP would underflow at ~1e-308 long before this)."""
    from tests.op_test import OpTest

    rng = np.random.RandomState(1)
    T, C, L = 200, 8, 20
    logits = rng.randn(T, C).astype("float32")
    labels = rng.randint(1, C, (L, 1)).astype("int64")

    class Tst(OpTest):
        op_type = "warpctc"

    t = Tst()
    t.inputs = {"Logits": (logits, [T]), "Label": (labels, [L])}
    t.attrs = {"blank": 0, "norm_by_times": False}
    t.outputs = {"Loss": None}
    prog, startup, feed, in_names, out_names = t._build()
    with fluid.program_guard(prog, startup):
        exe = fluid.Executor(fluid.CPUPlace())
        (loss,) = exe.run(program=prog, feed=feed,
                          fetch_list=[out_names["Loss"][0]])
    _finite(loss)
    assert float(np.asarray(loss).ravel()[0]) > 0


def test_exp_overflow_activations_finite_grad():
    """softplus/sigmoid/tanh grads at +-80 are finite (naive exp(x)
    overflows fp32 at ~88)."""
    x = layers.data("x", [3], dtype="float32")
    x.stop_gradient = False
    out = layers.softplus(x) + layers.sigmoid(x) + layers.tanh(x)
    append_backward(layers.reduce_sum(out))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    xv = np.array([[80.0, -80.0, 0.0]], dtype="float32")
    o, g = exe.run(feed={"x": xv}, fetch_list=[out, f"{x.name}@GRAD"])
    _finite(o, g)
