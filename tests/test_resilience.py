"""Resilience chaos suite: every failure mode the fault-injection hooks
can produce must recover end-to-end (ISSUE 2 acceptance; reference
analogues: go/master recover tests + the pserver checkpoint/LoadCheckpoint
round-trip, service.go:346).

In-process tests (tier-1): manifest verification, corrupt/truncated shard
rejection naming the file, zero-coverage rejection, CheckpointManager
rotation/GC/auto-resume, NaN sentinel skip + raise, preemption drain,
RPC drop-once retry, master-restart backoff.  Subprocess tests: a writer
killed mid-shard-write (FAULT_CKPT_KILL_AFTER_BYTES); the SIGKILL+RPC-drop
ElasticTrainer run (marked slow+chaos — out of tier-1 by the
`-m 'not slow'` discipline)."""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.io import CheckpointCorruptError
from paddle_tpu.resilience import (
    CheckpointManager,
    NonFiniteStepError,
    PreemptionDrain,
    faultinject,
    retry_with_backoff,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _disarm_faults():
    """Every test starts and ends with no armed faults and default flags."""
    faultinject.reset()
    yield
    for k in ("FAULT_CKPT_KILL_AFTER_BYTES", "FAULT_CKPT_CORRUPT_SHARD",
              "FAULT_RPC_DROP_ONCE", "FAULT_NAN_AT_STEP"):
        os.environ.pop(k, None)
    faultinject.reset()
    fluid.set_flags({"FLAGS_check_numerics": False,
                     "FLAGS_check_numerics_max_consecutive": 3})


def _build_sgd(name="rw"):
    x = layers.data("x", [4], dtype="float32")
    y = layers.data("y", [1], dtype="float32")
    pred = layers.fc(x, size=1, param_attr=fluid.ParamAttr(name=name),
                     bias_attr=False)
    loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
    fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    return exe, loss


def _feed(seed=0):
    rng = np.random.RandomState(seed)
    return {"x": rng.randn(4, 4).astype("float32"),
            "y": rng.randn(4, 1).astype("float32")}


# -----------------------------------------------------------------------
# verified checkpoints
# -----------------------------------------------------------------------
def test_manifest_records_every_shard_file(tmp_path):
    _build_sgd()
    d = str(tmp_path / "ck")
    fluid.io.save_sharded(d, step=11, extra={"note": "hi"})
    meta = json.load(open(os.path.join(d, "meta.json")))
    m = meta["__manifest__"]
    assert m["process_count"] == 1 and m["step"] == 11
    assert m["extra"] == {"note": "hi"} and m["wall_time"] > 0
    assert set(m["files"]) == {"shard_0.npz", "index_0.json"}
    for fn, rec in m["files"].items():
        assert rec["bytes"] == os.path.getsize(os.path.join(d, fn))
    # the loader hands the manifest back
    got = fluid.io.load_sharded(d)
    assert got["step"] == 11 and got["extra"] == {"note": "hi"}


def test_corrupt_shard_raises_naming_file(tmp_path):
    """Acceptance: one flipped byte can never load silently."""
    exe, loss = _build_sgd()
    d = str(tmp_path / "ck")
    fluid.io.save_sharded(d)
    bad = faultinject.corrupt_shard(d)
    with pytest.raises(CheckpointCorruptError, match="shard_0.npz"):
        fluid.io.load_sharded(d)
    assert bad.endswith("shard_0.npz")


def test_truncated_shard_raises_naming_file(tmp_path):
    _build_sgd()
    d = str(tmp_path / "ck")
    fluid.io.save_sharded(d)
    p = os.path.join(d, "shard_0.npz")
    with open(p, "r+b") as f:
        f.truncate(os.path.getsize(p) // 2)
    with pytest.raises(CheckpointCorruptError, match="truncated"):
        fluid.io.load_sharded(d)


def test_missing_shard_file_raises(tmp_path):
    _build_sgd()
    d = str(tmp_path / "ck")
    fluid.io.save_sharded(d)
    os.remove(os.path.join(d, "shard_0.npz"))
    with pytest.raises(CheckpointCorruptError, match="missing"):
        fluid.io.load_sharded(d)


def test_missing_meta_is_incomplete(tmp_path):
    _build_sgd()
    d = str(tmp_path / "ck")
    fluid.io.save_sharded(d)
    os.remove(os.path.join(d, "meta.json"))
    with pytest.raises(CheckpointCorruptError, match="meta.json"):
        fluid.io.load_sharded(d)


def test_zero_coverage_raises_even_without_manifest(tmp_path):
    """Satellite: pre-manifest checkpoints (no __manifest__) must STILL
    refuse to zero-fill a var whose shard entries are absent — the seed
    behavior silently loaded np.zeros."""
    _build_sgd(name="zc_w")
    d = str(tmp_path / "ck")
    fluid.io.save_sharded(d)
    # strip the manifest (legacy checkpoint) and delete the var's index
    # entries so no shard covers it
    meta = json.load(open(os.path.join(d, "meta.json")))
    meta.pop("__manifest__")
    json.dump(meta, open(os.path.join(d, "meta.json"), "w"))
    idx_p = os.path.join(d, "index_0.json")
    index = json.load(open(idx_p))
    index = {k: v for k, v in index.items() if v["var"] != "zc_w"}
    json.dump(index, open(idx_p, "w"))
    with pytest.raises(CheckpointCorruptError, match="zc_w"):
        fluid.io.load_sharded(d)


def test_partial_coverage_raises(tmp_path):
    """An index slice covering only part of a tensor is corruption, not
    'the rest is zeros' — handcrafted legacy checkpoint whose one shard
    covers half of pc_w."""
    d = str(tmp_path / "ck")
    os.makedirs(d)
    np.savez(os.path.join(d, "shard_0.npz"),
             **{"pc_w@@0": np.ones((2, 1), "float32")})
    json.dump(
        {"pc_w@@0": {"var": "pc_w", "index": [[0, 2, None], [0, 1, None]]}},
        open(os.path.join(d, "index_0.json"), "w"))
    json.dump({"pc_w": {"shape": [4, 1], "dtype": "float32"}},
              open(os.path.join(d, "meta.json"), "w"))
    with pytest.raises(CheckpointCorruptError, match="partially covered"):
        fluid.io.load_sharded(d)


def test_multiproc_async_handle_is_precompleted():
    """Satellite: the multi-process fallback hands back a pre-completed
    handle, no dummy thread spawned just to join it."""
    from paddle_tpu.io import AsyncCheckpoint

    h = AsyncCheckpoint.completed()
    assert h.done()
    h.wait()  # no-op, no raise
    assert h._thread is None


# -----------------------------------------------------------------------
# CheckpointManager: rotation, LATEST, auto-resume
# -----------------------------------------------------------------------
def test_manager_rotation_and_latest(tmp_path):
    exe, loss = _build_sgd()
    mgr = CheckpointManager(str(tmp_path / "run"), keep_last=2)
    for s in (1, 2, 3, 4):
        exe.run(feed=_feed(s), fetch_list=[loss])
        mgr.save(s, extra={"s": s})
    steps = mgr.valid_steps()
    assert steps == [3, 4], steps  # keep-last-2 GC
    assert mgr.latest_step() == 4
    latest = json.load(open(str(tmp_path / "run" / "LATEST")))
    assert latest == {"step": 4, "dir": "step_4"}


def test_manager_restore_falls_back_past_corruption(tmp_path):
    """Acceptance: corrupt the newest checkpoint's shard; restore_or_init
    resumes from the previous valid one with bit-identical params."""
    exe, loss = _build_sgd(name="fb_w")
    scope = fluid.global_scope()
    mgr = CheckpointManager(str(tmp_path / "run"), keep_last=3)
    exe.run(feed=_feed(1), fetch_list=[loss])
    w_good = np.asarray(scope.find_var("fb_w")).copy()
    mgr.save(1)
    exe.run(feed=_feed(2), fetch_list=[loss])
    mgr.save(2)
    faultinject.corrupt_shard(mgr.step_dir(2))
    # clobber live params, then auto-resume
    scope.set_var("fb_w", np.full_like(w_good, 7.0))
    res = mgr.restore_or_init()
    assert res is not None and res.step == 1
    np.testing.assert_array_equal(
        np.asarray(scope.find_var("fb_w")), w_good)


def test_manager_never_gcs_newest_valid(tmp_path):
    """keep_last=1 with a torn NEWER directory must not delete the only
    valid checkpoint."""
    exe, loss = _build_sgd()
    mgr = CheckpointManager(str(tmp_path / "run"), keep_last=1)
    mgr.save(1)
    # a torn newer checkpoint: directory exists, no meta.json
    os.makedirs(mgr.step_dir(2), exist_ok=True)
    open(os.path.join(mgr.step_dir(2), "shard_0.npz"), "wb").write(b"torn")
    mgr.gc()
    assert mgr.valid_steps() == [1]
    res = mgr.restore_or_init()
    assert res is not None and res.step == 1


def test_manager_init_fn_when_nothing_restorable(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "empty"))
    called = []
    assert mgr.restore_or_init(init_fn=lambda: called.append(1)) is None
    assert called == [1]


def test_manager_async_save_flips_latest_after_write(tmp_path):
    exe, loss = _build_sgd(name="as_w")
    scope = fluid.global_scope()
    mgr = CheckpointManager(str(tmp_path / "run"), keep_last=2)
    snap = np.asarray(scope.find_var("as_w")).copy()
    h = mgr.save(5, asynchronous=True)
    assert h is not None
    # training continues while the write drains
    exe.run(feed=_feed(9), fetch_list=[loss])
    h.wait()
    assert mgr.latest_step() == 5
    scope.set_var("as_w", np.zeros_like(snap))
    res = mgr.restore_or_init()
    assert res.step == 5
    np.testing.assert_array_equal(np.asarray(scope.find_var("as_w")), snap)


# -----------------------------------------------------------------------
# crash during save (subprocess: the writer dies mid-shard-write)
# -----------------------------------------------------------------------
_KILLED_WRITER = '''
import os, sys
sys.path.insert(0, {repo!r})
import numpy as np
import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.resilience import CheckpointManager

x = layers.data("x", [4], dtype="float32")
pred = layers.fc(x, size=1, param_attr=fluid.ParamAttr(name="kw"),
                 bias_attr=False)
loss = layers.mean(pred)
fluid.optimizer.SGD(0.1).minimize(loss)
exe = fluid.Executor(fluid.CPUPlace())
exe.run(fluid.default_startup_program())
mgr = CheckpointManager({run_dir!r}, keep_last=3)
mgr.save(1)  # a good checkpoint first
np.save({w_out!r}, np.asarray(fluid.global_scope().find_var("kw")))
exe.run(feed={{"x": np.ones((2, 4), "float32")}}, fetch_list=[loss])
os.environ["FAULT_CKPT_KILL_AFTER_BYTES"] = "64"
mgr.save(2)  # writer dies mid-shard-write: os._exit(43)
print("UNREACHABLE", flush=True)
'''


def test_crash_during_save_recovers_to_previous(tmp_path):
    """Satellite: kill the writer mid-npz; the loader rejects the torn
    step_2 and restore_or_init falls back to step_1 bit-identically."""
    run_dir = str(tmp_path / "run")
    w_out = str(tmp_path / "w.npy")
    script = _KILLED_WRITER.format(repo=REPO, run_dir=run_dir, w_out=w_out)
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    p = subprocess.run([sys.executable, "-c", script], env=env,
                       capture_output=True, text=True, timeout=240)
    assert p.returncode == 43, p.stdout + p.stderr
    assert "UNREACHABLE" not in p.stdout
    # step_2 is torn: shard truncated, meta.json never written
    assert not os.path.exists(os.path.join(run_dir, "step_2", "meta.json"))
    with pytest.raises(CheckpointCorruptError):
        fluid.io.load_sharded(os.path.join(run_dir, "step_2"))

    # a fresh process restores the previous valid checkpoint
    _build_sgd(name="kw")
    mgr = CheckpointManager(run_dir, keep_last=3)
    res = mgr.restore_or_init()
    assert res is not None and res.step == 1
    np.testing.assert_array_equal(
        np.asarray(fluid.global_scope().find_var("kw")), np.load(w_out))


_OVERWRITE_WRITER = '''
import os, sys
sys.path.insert(0, {repo!r})
import numpy as np
import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.resilience import CheckpointManager

x = layers.data("x", [4], dtype="float32")
pred = layers.fc(x, size=1, param_attr=fluid.ParamAttr(name="ow"),
                 bias_attr=False)
loss = layers.mean(pred)
fluid.optimizer.SGD(0.1).minimize(loss)
exe = fluid.Executor(fluid.CPUPlace())
exe.run(fluid.default_startup_program())
mgr = CheckpointManager({run_dir!r}, keep_last=3)
mgr.save(1)
mgr.save(2)
exe.run(feed={{"x": np.ones((2, 4), "float32")}}, fetch_list=[loss])
os.environ["FAULT_CKPT_KILL_AFTER_BYTES"] = "64"
mgr.save(2)  # RE-save the same step (the preemption-drain shape): dies
print("UNREACHABLE", flush=True)
'''


def test_killed_overwrite_of_existing_step_cannot_masquerade(tmp_path):
    """Re-saving an existing step dir (preemption drain re-checkpoints
    the current cursor) invalidates the old meta.json BEFORE touching the
    shards: a kill mid-rewrite leaves a skippable torn dir, never the old
    manifest's digests over half-new shards."""
    run_dir = str(tmp_path / "run")
    script = _OVERWRITE_WRITER.format(repo=REPO, run_dir=run_dir)
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    p = subprocess.run([sys.executable, "-c", script], env=env,
                       capture_output=True, text=True, timeout=240)
    assert p.returncode == 43, p.stdout + p.stderr
    # step_2's stale meta.json is GONE (not lying about the torn shards)
    assert not os.path.exists(os.path.join(run_dir, "step_2", "meta.json"))
    # restore walks back to the intact step_1
    _build_sgd(name="ow")
    mgr = CheckpointManager(run_dir, keep_last=3)
    res = mgr.restore_or_init()
    assert res is not None and res.step == 1


# -----------------------------------------------------------------------
# NaN sentinel (FLAGS_check_numerics)
# -----------------------------------------------------------------------
def test_sentinel_skips_injected_step_and_recovers():
    """Acceptance: NaN at step K skips the step (params untouched, still
    finite) and training continues."""
    exe, loss = _build_sgd(name="nw")
    scope = fluid.global_scope()
    fluid.set_flags({"FLAGS_check_numerics": True})
    feed = _feed(3)
    exe.run(feed=feed, fetch_list=[loss])
    w_before = np.asarray(scope.find_var("nw")).copy()
    os.environ["FAULT_NAN_AT_STEP"] = "0"
    faultinject.reset()
    (bad,) = exe.run(feed=feed, fetch_list=[loss])
    assert np.isnan(np.asarray(bad)).all()  # the fetch reports the trip
    np.testing.assert_array_equal(
        np.asarray(scope.find_var("nw")), w_before)  # step skipped
    # next (clean) step updates params again and stays finite
    exe.run(feed=feed, fetch_list=[loss])
    w_after = np.asarray(scope.find_var("nw"))
    assert np.isfinite(w_after).all()
    assert not np.array_equal(w_after, w_before)


def test_sentinel_raises_after_n_consecutive_naming_fetch():
    """Acceptance: after N consecutive trips the executor raises with the
    offending fetch named; params stay finite and un-updated."""
    exe, loss = _build_sgd(name="nw2")
    scope = fluid.global_scope()
    fluid.set_flags({"FLAGS_check_numerics": True,
                     "FLAGS_check_numerics_max_consecutive": 3})
    feed = _feed(4)
    exe.run(feed=feed, fetch_list=[loss])
    w_before = np.asarray(scope.find_var("nw2")).copy()
    os.environ["FAULT_NAN_AT_STEP"] = "0+"
    faultinject.reset()
    with pytest.raises(NonFiniteStepError) as ei:
        for _ in range(10):
            exe.run(feed=feed, fetch_list=[loss])
    assert ei.value.var_name == loss.name
    assert ei.value.consecutive == 3
    np.testing.assert_array_equal(
        np.asarray(scope.find_var("nw2")), w_before)
    assert np.isfinite(np.asarray(scope.find_var("nw2"))).all()


def test_sentinel_catches_real_nan_state():
    """No injection: genuinely poisoned feeds trip on the first non-finite
    fetch/state var and never write it back."""
    exe, loss = _build_sgd(name="nw3")
    scope = fluid.global_scope()
    fluid.set_flags({"FLAGS_check_numerics": True,
                     "FLAGS_check_numerics_max_consecutive": 2})
    good = _feed(5)
    exe.run(feed=good, fetch_list=[loss])
    poison = {"x": np.full((4, 4), np.nan, "float32"), "y": good["y"]}
    with pytest.raises(NonFiniteStepError):
        for _ in range(3):
            exe.run(feed=poison, fetch_list=[loss])
    assert np.isfinite(np.asarray(scope.find_var("nw3"))).all()


def test_elastic_trainer_reports_nonfinite_task_failed(tmp_path):
    """The sentinel raise must reach the master as task_failed (lease
    re-queues) — not a published poisoned checkpoint."""
    from paddle_tpu.elastic import InMemStore, MasterService, ElasticTrainer

    fluid.reset_default_env()
    x = fluid.layers.data(name="x", shape=[1], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    pred = fluid.layers.fc(x, size=1, param_attr=fluid.ParamAttr(name="tf_w"))
    loss = fluid.layers.reduce_mean(
        fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(0.3).minimize(loss)

    np.save(str(tmp_path / "c0.npy"), np.linspace(-1, 1, 8, dtype="float32"))
    m = MasterService(InMemStore(), chunks_per_task=1, timeout_dur=60,
                      failure_max=3)
    m.set_dataset([str(tmp_path / "c0.npy")])

    def feed_fn(chunk):
        xs = np.load(chunk).reshape(-1, 1)
        yield {"x": np.full_like(xs, np.nan), "y": xs}

    fluid.set_flags({"FLAGS_check_numerics": True,
                     "FLAGS_check_numerics_max_consecutive": 1})
    exe = fluid.Executor(fluid.CPUPlace())
    t = ElasticTrainer(m, exe, feed_fn, [loss], str(tmp_path / "ck"),
                       num_passes=1)
    with pytest.raises(NonFiniteStepError):
        t.train()
    # the failure was REPORTED: the task went back to todo immediately
    c = m.counts()
    assert c["pending"] == 0 and c["todo"] == 1, c
    # and no checkpoint of the poisoned attempt was published
    assert t.ckpt.valid_steps() == []
    m.shutdown()


# -----------------------------------------------------------------------
# preemption drain
# -----------------------------------------------------------------------
def test_preemption_drain_checkpoints_and_exits_cleanly(tmp_path):
    """SIGTERM mid-run: the trainer finishes the in-flight step, drains an
    emergency checkpoint, returns cleanly; the leased task is NOT reported
    done and a successor worker finishes the job."""
    from paddle_tpu.elastic import InMemStore, MasterService, ElasticTrainer

    fluid.reset_default_env()
    x = fluid.layers.data(name="x", shape=[1], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    pred = fluid.layers.fc(x, size=1, param_attr=fluid.ParamAttr(name="pd_w"))
    loss = fluid.layers.reduce_mean(
        fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(0.3).minimize(loss)

    rng = np.random.RandomState(0)
    for i in range(4):
        np.save(str(tmp_path / f"c{i}.npy"),
                rng.uniform(-1, 1, 32).astype("float32"))
    m = MasterService(InMemStore(), chunks_per_task=1, timeout_dur=0.3,
                      failure_max=5)
    m.set_dataset([str(tmp_path / "c*.npy")])

    fired = [0]

    def feed_fn(chunk):
        xs = np.load(chunk).reshape(-1, 1)
        for i in range(0, len(xs), 8):
            fired[0] += 1
            if fired[0] == 3:
                # the preemption notice arrives DURING training
                os.kill(os.getpid(), signal.SIGTERM)
            xb = xs[i:i + 8]
            yield {"x": xb, "y": (2.0 * xb - 1.0).astype("float32")}

    exe = fluid.Executor(fluid.CPUPlace())
    with PreemptionDrain() as drain:
        t = ElasticTrainer(m, exe, feed_fn, [loss], str(tmp_path / "ck"),
                           num_passes=2, drain=drain)
        t.train()  # returns cleanly instead of dying mid-step
        assert drain.requested
    # the emergency checkpoint landed and is valid — in a FRESH step dir
    # (save seq > tasks_done cursor), so a kill during the drain write
    # could never have torn the previous valid checkpoint
    steps = t.ckpt.valid_steps()
    assert steps != []
    mf = json.load(open(os.path.join(
        t.ckpt.step_dir(steps[-1]), "meta.json")))["__manifest__"]
    assert mf["extra"]["tasks_done"] < steps[-1], (mf["extra"], steps)
    # the in-flight task was NOT reported finished; its lease re-queues
    time.sleep(0.5)
    assert m.counts()["pending"] == 0

    # a successor worker resumes from the drained checkpoint and finishes
    t2 = ElasticTrainer(m, exe, feed_fn, [loss], str(tmp_path / "ck"),
                        num_passes=2)
    t2.train()
    assert t2.pass_id == 2
    assert m.counts()["cur_pass"] == 2
    w = np.ravel(np.asarray(fluid.global_scope().find_var("pd_w")))[0]
    assert abs(w - 2.0) < 0.3, f"did not converge: w={w}"
    m.shutdown()


# -----------------------------------------------------------------------
# RPC retry / backoff
# -----------------------------------------------------------------------
def test_retry_with_backoff_bounds_and_jitter():
    calls = []
    delays = []

    def flaky():
        calls.append(1)
        if len(calls) < 4:
            raise ConnectionError("nope")
        return "ok"

    out = retry_with_backoff(flaky, retries=5, base_delay=0.01,
                             max_delay=0.04, sleep=delays.append)
    assert out == "ok" and len(calls) == 4
    # exponential, capped, jittered upward only
    assert len(delays) == 3
    for i, d in enumerate(delays):
        lo = min(0.04, 0.01 * (2 ** i))
        assert lo <= d <= lo * 1.5 + 1e-9

    def always_down():
        raise ConnectionError("always")

    with pytest.raises(ConnectionError):
        retry_with_backoff(always_down, retries=2, base_delay=0.001,
                           sleep=lambda _: None)


def test_rpc_drop_once_is_absorbed():
    """FAULT_RPC_DROP_ONCE: one dropped RPC costs a retry, not the run."""
    from paddle_tpu.elastic.master import InMemStore, MasterService
    from paddle_tpu.elastic.rpc import RemoteMaster, serve_master

    svc = MasterService(InMemStore(), failure_max=2)
    srv = serve_master(svc, port=0)
    try:
        m = RemoteMaster(srv.endpoint, max_retries=3,
                         retry_base_delay=0.01, retry_max_delay=0.05)
        os.environ["FAULT_RPC_DROP_ONCE"] = "counts"
        faultinject.reset()
        c = m.counts()
        assert c["cur_pass"] == 0
        assert "rpc_drop" in faultinject.fired  # the fault DID fire
        m.close()
    finally:
        srv.shutdown()
        srv.server_close()


def test_rpc_survives_master_restart():
    """Kill the master, restart it on the same port + store: in-flight
    worker calls ride the backoff across the outage."""
    import threading

    from paddle_tpu.elastic.master import InMemStore, MasterService
    from paddle_tpu.elastic.rpc import MasterServer, RemoteMaster

    store = InMemStore()
    svc = MasterService(store, failure_max=2)
    srv = MasterServer(svc, port=0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    host, port = srv.server_address
    m = RemoteMaster(f"{host}:{port}", max_retries=8,
                     retry_base_delay=0.02, retry_max_delay=0.2)
    assert m.counts()["cur_pass"] == 0

    srv.shutdown()
    srv.server_close()  # port freed (handler threads may linger...)
    m.close()  # ...so force the next call to reconnect through the outage

    def _restart():
        time.sleep(0.3)  # outage window: client must back off through it
        svc2 = MasterService(store, failure_max=2)
        srv2 = MasterServer(svc2, host=host, port=port)
        threading.Thread(target=srv2.serve_forever, daemon=True).start()
        _restart.srv = srv2

    t = threading.Thread(target=_restart)
    t.start()
    c = m.counts()  # spans the outage
    assert c["cur_pass"] == 0
    t.join()
    m.close()
    _restart.srv.shutdown()
    _restart.srv.server_close()


# -----------------------------------------------------------------------
# bench checkpoint cadence (BENCH_CKPT_DIR)
# -----------------------------------------------------------------------
def _run_bench(extra_env, timeout=560):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "BENCH_TUNE": "0",
        "BENCH_PREPROBE": "0",
        "BENCH_DEADLINE_S": "0",
        "BENCH_COMPILE_CACHE": "0",
        "PYTHONPATH": REPO,
    })
    env.update(extra_env)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=timeout, env=env,
    )
    line = next((ln for ln in out.stdout.splitlines()
                 if ln.strip().startswith("{")), None)
    assert line, f"no JSON line from bench.py:\n{out.stdout}\n{out.stderr}"
    return json.loads(line), out


def test_bench_ckpt_cadence_resumes(tmp_path):
    """BENCH_CKPT_DIR: the first run banks verified checkpoints on a
    cadence; a second run restores from the newest one instead of
    reinitializing."""
    ck = str(tmp_path / "bench_ck")
    env = {"BENCH_MODELS": "lenet", "BENCH_STEPS": "6", "BENCH_BS": "8",
           "BENCH_CKPT_DIR": ck, "BENCH_CKPT_EVERY": "2",
           "BENCH_CKPT_KEEP": "2"}
    res1, out1 = _run_bench(env)
    assert res1.get("metric") != "error", out1.stdout + out1.stderr
    assert res1["ckpt_every"] == 2
    mgr = CheckpointManager(os.path.join(ck, "lenet"))
    steps = mgr.valid_steps()
    assert steps and steps[-1] == 6, steps  # final sync save landed
    assert len(steps) <= 2  # BENCH_CKPT_KEEP rotation

    res2, out2 = _run_bench(env)
    assert res2.get("metric") != "error", out2.stdout + out2.stderr
    assert "resumed params from checkpoint step_6" in out2.stderr, (
        out2.stderr[-2000:])
    # the resumed segment numbers PAST the restored step (6 + 6), so its
    # checkpoints are not GC'd on arrival as older-than-newest-valid
    assert mgr.valid_steps()[-1] == 12, mgr.valid_steps()


# -----------------------------------------------------------------------
# end-to-end chaos: SIGKILL a trainer worker mid-task + drop one RPC
# (multiprocess; slow => out of tier-1 per the -m 'not slow' discipline)
# -----------------------------------------------------------------------
_CHAOS_SERVER = '''
import sys, time
sys.path.insert(0, {repo!r})
from paddle_tpu.elastic.master import FileStore, MasterService
from paddle_tpu.elastic.rpc import serve_master

svc = MasterService(FileStore(sys.argv[1]), chunks_per_task=1,
                    timeout_dur=3.0, failure_max=5)
svc.set_dataset([sys.argv[2]])
srv = serve_master(svc, port=0)
print("SERVING", srv.endpoint, flush=True)
while True:
    time.sleep(0.2)
'''

_CHAOS_WORKER = '''
import os, sys
sys.path.insert(0, {repo!r})
import numpy as np
import paddle_tpu as fluid
from paddle_tpu.elastic import ElasticTrainer
from paddle_tpu.elastic.rpc import RemoteMaster

endpoint, ckpt_dir, num_passes = sys.argv[1], sys.argv[2], int(sys.argv[3])

x = fluid.layers.data(name="x", shape=[1], dtype="float32")
y = fluid.layers.data(name="y", shape=[1], dtype="float32")
pred = fluid.layers.fc(x, size=1, param_attr=fluid.ParamAttr(name="cw"))
loss = fluid.layers.reduce_mean(fluid.layers.square_error_cost(pred, y))
fluid.optimizer.SGD(0.3).minimize(loss)

def feed_fn(chunk):
    xs = np.load(chunk).reshape(-1, 1)
    for i in range(0, len(xs), 8):
        xb = xs[i:i + 8]
        yield {{"x": xb, "y": (2.0 * xb - 1.0).astype("float32")}}

class Noisy:
    def __init__(self, m):
        self._m = m
    def __getattr__(self, n):
        return getattr(self._m, n)
    def task_finished(self, task_id):
        self._m.task_finished(task_id)
        print("TASK", task_id, flush=True)

m = RemoteMaster(endpoint, max_retries=8, retry_base_delay=0.05,
                 retry_max_delay=0.5)
exe = fluid.Executor(fluid.CPUPlace())
t = ElasticTrainer(Noisy(m), exe, feed_fn, [loss], ckpt_dir,
                   num_passes=num_passes, idle_wait=0.1)
t.train()
w = float(np.ravel(np.asarray(fluid.global_scope().find_var("cw")))[0])
print("DONE", t.pass_id, w, flush=True)
'''


@pytest.mark.slow
@pytest.mark.timeout(600)
def test_chaos_sigkill_worker_and_dropped_rpc_recover(tmp_path):
    """Acceptance e2e: a worker SIGKILLed mid-task AND one dropped master
    RPC both recover to a completed run with the same final pass count as
    the fault-free run."""
    rng = np.random.RandomState(0)
    for i in range(6):
        np.save(str(tmp_path / f"chunk{i}.npy"),
                rng.uniform(-1, 1, 32).astype("float32"))
    glob_pat = str(tmp_path / "chunk*.npy")
    num_passes = 2

    # ---- fault-free reference run (in-process master, same protocol)
    from paddle_tpu.elastic import ElasticTrainer, FileStore, MasterService

    fluid.reset_default_env()
    x = fluid.layers.data(name="x", shape=[1], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    pred = fluid.layers.fc(x, size=1, param_attr=fluid.ParamAttr(name="cw"))
    loss = fluid.layers.reduce_mean(
        fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(0.3).minimize(loss)

    def feed_fn(chunk):
        xs = np.load(chunk).reshape(-1, 1)
        for i in range(0, len(xs), 8):
            xb = xs[i:i + 8]
            yield {"x": xb, "y": (2.0 * xb - 1.0).astype("float32")}

    m0 = MasterService(FileStore(str(tmp_path / "ref.snap")),
                       chunks_per_task=1, timeout_dur=3.0, failure_max=5)
    m0.set_dataset([glob_pat])
    exe = fluid.Executor(fluid.CPUPlace())
    t0 = ElasticTrainer(m0, exe, feed_fn, [loss],
                        str(tmp_path / "ref_ck"), num_passes=num_passes)
    t0.train()
    faultfree_passes = m0.counts()["cur_pass"]
    assert faultfree_passes == num_passes
    m0.shutdown()

    # ---- chaos run: real subprocesses
    snap = str(tmp_path / "chaos.snap")
    server_py = str(tmp_path / "server.py")
    worker_py = str(tmp_path / "worker.py")
    open(server_py, "w").write(_CHAOS_SERVER.format(repo=REPO))
    open(worker_py, "w").write(_CHAOS_WORKER.format(repo=REPO))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("FAULT_RPC_DROP_ONCE", None)
    ckpt = str(tmp_path / "chaos_ck")

    server = subprocess.Popen(
        [sys.executable, server_py, snap, glob_pat], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        line = server.stdout.readline()
        assert "SERVING" in line, line
        endpoint = line.split()[1]

        # worker A: drops one RPC (absorbed by backoff), then gets
        # SIGKILLed the moment it reports its first finished task —
        # i.e. mid-run, holding a leased task it will never finish
        env_a = {**env, "FAULT_RPC_DROP_ONCE": "*"}
        wa = subprocess.Popen(
            [sys.executable, worker_py, endpoint, ckpt, str(num_passes)],
            env=env_a, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        saw_task = False
        for line in wa.stdout:
            if line.startswith("TASK"):
                saw_task = True
                os.kill(wa.pid, signal.SIGKILL)
                break
        assert saw_task, "worker A never finished a task"
        wa.wait(timeout=60)
        assert wa.returncode == -signal.SIGKILL

        # worker B: clean env, resumes from A's checkpoint + the master
        # queue; A's leased task re-dispatches on lease expiry
        wb = subprocess.Popen(
            [sys.executable, worker_py, endpoint, ckpt, str(num_passes)],
            env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        ob, _ = wb.communicate(timeout=480)
        assert wb.returncode == 0, ob[-3000:]
        done = [ln for ln in ob.splitlines() if ln.startswith("DONE")]
        assert done, ob[-3000:]
        _, passes, w = done[0].split()
        # same final pass count as the fault-free run, converged params
        assert int(passes) == faultfree_passes
        assert abs(float(w) - 2.0) < 0.3, w
    finally:
        server.kill()
        server.wait()
