"""Per-op sweep: tensor manipulation family (reference:
test_reshape_op.py, test_transpose_op.py, test_concat_op.py,
test_gather_op.py, test_pad_op.py, ... over operators/)."""

import numpy as np
import pytest

from op_test import OpTest


def _rand(shape, seed=0, lo=-2.0, hi=2.0):
    return np.random.RandomState(seed).uniform(lo, hi, shape).astype("float32")


def _case(op_type, inputs, attrs, outputs, grad=None, atol=1e-5, **gkw):
    class T(OpTest):
        pass

    T.op_type = op_type
    t = T()
    t.inputs = inputs
    t.attrs = attrs
    t.outputs = outputs
    t.check_output(atol=atol, rtol=1e-5)
    if grad:
        t.check_grad(grad, list(outputs.keys())[0],
                     max_relative_error=gkw.get("max_relative_error", 0.01))


def test_reshape():
    x = _rand((2, 3, 4), 1)
    _case("reshape", {"X": x}, {"shape": [2, 12]},
          {"Out": x.reshape(2, 12)}, grad=["X"])


def test_reshape_infer_dim():
    x = _rand((2, 3, 4), 2)
    _case("reshape", {"X": x}, {"shape": [-1, 6]},
          {"Out": x.reshape(4, 6)})


def test_transpose():
    x = _rand((2, 3, 4), 3)
    _case("transpose", {"X": x}, {"axis": [2, 0, 1]},
          {"Out": x.transpose(2, 0, 1)}, grad=["X"])


def test_concat():
    xs = [_rand((2, 3), 4), _rand((2, 5), 5), _rand((2, 1), 6)]
    _case("concat", {"X": xs}, {"axis": 1},
          {"Out": np.concatenate(xs, axis=1)})


def test_split():
    x = _rand((2, 9), 7)
    parts = np.split(x, 3, axis=1)
    _case("split", {"X": x}, {"num": 3, "axis": 1}, {"Out": parts})


def test_split_sections():
    x = _rand((2, 9), 8)
    parts = [x[:, :2], x[:, 2:5], x[:, 5:]]
    _case("split", {"X": x}, {"sections": [2, 3, 4], "axis": 1},
          {"Out": parts})


def test_stack():
    xs = [_rand((3, 4), i + 10) for i in range(3)]
    _case("stack", {"X": xs}, {"axis": 1}, {"Y": np.stack(xs, axis=1)})


def test_unstack():
    x = _rand((3, 4, 2), 13)
    _case("unstack", {"X": x}, {"axis": 1, "num": 4},
          {"Y": [x[:, i] for i in range(4)]})


def test_slice():
    x = _rand((4, 5, 6), 14)
    _case("slice", {"Input": x},
          {"axes": [0, 2], "starts": [1, 2], "ends": [3, 5]},
          {"Out": x[1:3, :, 2:5]}, grad=["Input"])


def test_gather():
    x = _rand((6, 4), 15)
    idx = np.array([0, 3, 5, 3], dtype="int64")
    _case("gather", {"X": x, "Index": idx}, {},
          {"Out": x[idx]}, grad=["X"])


def test_scatter_overwrite():
    x = _rand((6, 4), 16)
    idx = np.array([1, 4], dtype="int64")
    upd = _rand((2, 4), 17)
    want = x.copy()
    want[idx] = upd
    _case("scatter", {"X": x, "Ids": idx, "Updates": upd}, {},
          {"Out": want})


def test_pad():
    x = _rand((2, 3), 18)
    _case("pad", {"X": x},
          {"paddings": [0, 1, 2, 0], "pad_value": 0.5},
          {"Out": np.pad(x, [(0, 1), (2, 0)], constant_values=0.5)},
          grad=["X"])


def test_pad2d():
    x = _rand((2, 3, 4, 5), 19)
    _case("pad2d", {"X": x},
          {"paddings": [1, 0, 0, 2], "mode": "constant", "pad_value": 0.0},
          {"Out": np.pad(x, [(0, 0), (0, 0), (1, 0), (0, 2)])})


def test_pad_constant_like():
    x = _rand((4, 5), 20)
    y = _rand((2, 3), 21)
    want = np.zeros((4, 5), "float32")
    want[:2, :3] = y
    _case("pad_constant_like", {"X": x, "Y": y}, {"pad_value": 0.0},
          {"Out": want})


def test_expand():
    x = _rand((2, 1, 3), 22)
    _case("expand", {"X": x}, {"expand_times": [2, 3, 1]},
          {"Out": np.tile(x, (2, 3, 1))}, grad=["X"])


def test_reverse():
    x = _rand((3, 4), 23)
    _case("reverse", {"X": x}, {"axis": [1]}, {"Out": x[:, ::-1]})


def test_cast():
    x = _rand((3, 4), 24)
    _case("cast", {"X": x}, {"in_dtype": 5, "out_dtype": 2},  # fp32->int32
          {"Out": x.astype("int32")})


def test_one_hot():
    x = np.array([[1], [3], [0]], dtype="int64")
    want = np.eye(4, dtype="float32")[x.ravel()]
    _case("one_hot", {"X": x}, {"depth": 4}, {"Out": want})


def test_fill_zeros_like():
    x = _rand((2, 5), 25)
    _case("fill_zeros_like", {"X": x}, {}, {"Out": np.zeros_like(x)})


def test_squeeze():
    x = _rand((2, 1, 3, 1), 26)
    _case("squeeze", {"X": x}, {"axes": [1, 3]}, {"Out": x.reshape(2, 3)})


def test_unsqueeze():
    x = _rand((2, 3), 27)
    _case("unsqueeze", {"X": x}, {"axes": [1]}, {"Out": x.reshape(2, 1, 3)})


def test_flatten():
    x = _rand((2, 3, 4, 5), 28)
    _case("flatten", {"X": x}, {"axis": 2}, {"Out": x.reshape(6, 20)})


def test_multiplex():
    xs = [_rand((4, 5), 30 + i) for i in range(3)]
    ids = np.array([[2], [0], [1], [2]], dtype="int64")
    want = np.stack([xs[ids[i, 0]][i] for i in range(4)])
    _case("multiplex", {"X": xs, "Ids": ids}, {}, {"Out": want})


def test_crop():
    x = _rand((4, 6), 34)
    _case("crop", {"X": x}, {"offsets": [1, 2], "shape": [2, 3]},
          {"Out": x[1:3, 2:5]})


def test_space_to_depth():
    """Expectation emulates the reference reorg kernel index math
    (space_to_depth_op.h:40-56) element by element — NOT a
    reshape/transpose formula that could share a bias with the
    lowering.  C must divide blocksize^2 (space_to_depth_op.cc:41)."""
    bs = 2
    B, C, H, W = 1, 4, 4, 4
    x = _rand((B, C, H, W), 35)
    out_flat = np.zeros(B * C * H * W, dtype=x.dtype)
    out_c = C // (bs * bs)
    xf = x.ravel()
    for in_index in range(x.size):
        b = in_index // (C * H * W)
        k = (in_index % (C * H * W)) // (H * W)
        j = ((in_index % (C * H * W)) % (H * W)) // W
        i = ((in_index % (C * H * W)) % (H * W)) % W
        c2 = k % out_c
        off = k // out_c
        w2 = i * bs + off % bs
        h2 = j * bs + off // bs
        out_flat[w2 + W * bs * (h2 + H * bs * (c2 + out_c * b))] = xf[in_index]
    want = out_flat.reshape(B, C * bs * bs, H // bs, W // bs)

    class T(OpTest):
        op_type = "space_to_depth"

    t = T()
    t.inputs = {"X": x}
    t.attrs = {"blocksize": bs}
    t.outputs = {"Out": want}
    t.check_output()
    t.check_grad(["X"], "Out")


def test_range():
    # bounds must be compile-time constants (they set a static XLA shape);
    # feeds arrive as tracers, so use the const_* attr path layers.range
    # produces after fill_constant folding
    _case("range", {},
          {"const_start": 1.0, "const_end": 7.0, "const_step": 2.0,
           "dtype": 5},
          {"Out": np.arange(1.0, 7.0, 2.0, dtype="float32")})


def test_increment():
    x = np.array([3.0], dtype="float32")
    _case("increment", {"X": x}, {"step": 2.0},
          {"Out": np.array([5.0], "float32")})


def test_label_like_fills():
    x = _rand((3, 7), 36)
    _case("fill_constant_batch_size_like", {"Input": x},
          {"shape": [-1, 2], "value": 1.5, "dtype": 5},
          {"Out": np.full((3, 2), 1.5, "float32")})
