"""conv_bn_add_act: the whole-block one-op tier (conv2d + BN + residual +
act; reference counterpart operators/conv_fusion_op.cu.cc).

Contract: numerical identity with the conv2d -> batch_norm ->
elementwise_add -> relu chain for BOTH implementations —
FLAGS_conv_epilogue=reference (one lowering, XLA fuses) and =pallas
(kernels/conv_epilogue.py, interpret mode on CPU)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers


def _train(mode, steps=4, seed=7, with_residual=True):
    """mode: 'chain' | 'op-ref' | 'op-pallas'."""
    fluid.reset_default_env()
    fluid.set_flags({"FLAGS_conv_epilogue":
                     "pallas" if mode == "op-pallas" else "reference"})
    fluid.default_main_program().random_seed = seed
    fluid.default_startup_program().random_seed = seed
    x = layers.data("x", [4, 8, 8], dtype="float32")
    yv = layers.data("y", [1], dtype="int64")
    res = x if with_residual else None
    if mode == "chain":
        conv = layers.conv2d(x, 4, 3, padding=1, bias_attr=False,
                             param_attr=fluid.ParamAttr(name="w"))
        b = layers.batch_norm(conv, act=None,
                              param_attr=fluid.ParamAttr(name="s"),
                              bias_attr=fluid.ParamAttr(name="b"),
                              moving_mean_name="m", moving_variance_name="v")
        h = layers.relu(layers.elementwise_add(b, res)
                        if res is not None else b)
    else:
        h = layers.conv_bn_add_act(
            x, 4, 3, residual=res, padding=1, act="relu",
            param_attr=fluid.ParamAttr(name="w"),
            bn_param_attr=fluid.ParamAttr(name="s"),
            bn_bias_attr=fluid.ParamAttr(name="b"),
            moving_mean_name="m", moving_variance_name="v")
    pool = layers.pool2d(h, pool_size=8, pool_type="avg")
    pred = layers.fc(pool, size=3, act="softmax",
                     param_attr=fluid.ParamAttr(name="fc"))
    loss = layers.mean(layers.cross_entropy(pred, yv))
    fluid.optimizer.MomentumOptimizer(0.1, 0.9).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    r = np.random.RandomState(5)
    xa = r.randn(8, 4, 8, 8).astype("float32")
    ya = r.randint(0, 3, size=(8, 1)).astype("int64")
    ls = [float(np.ravel(np.asarray(exe.run(feed={"x": xa, "y": ya},
          fetch_list=[loss])[0]))[0]) for _ in range(steps)]
    sc = fluid.global_scope()
    st = {n: np.asarray(sc.find_var(n)).copy()
          for n in ("w", "s", "b", "m", "v", "fc")}
    fluid.set_flags({"FLAGS_conv_epilogue": "reference"})
    return ls, st


@pytest.mark.parametrize("with_residual", [True, False])
def test_one_op_matches_chain_both_impls(with_residual):
    l0, s0 = _train("chain", with_residual=with_residual)
    l1, s1 = _train("op-ref", with_residual=with_residual)
    l2, s2 = _train("op-pallas", with_residual=with_residual)
    assert l0[-1] < l0[0]  # training moved
    np.testing.assert_allclose(l0, l1, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(l0, l2, rtol=1e-4, atol=1e-5)
    for n in s0:
        np.testing.assert_allclose(s0[n], s1[n], rtol=1e-5, atol=1e-6,
                                   err_msg=n)
        np.testing.assert_allclose(s0[n], s2[n], rtol=1e-4, atol=1e-5,
                                   err_msg=n)


def test_test_mode_uses_moving_stats():
    """clone(for_test=True): the op normalizes with MOVING stats and does
    not update them (reference BN contract)."""
    _l, _s = None, None
    fluid.reset_default_env()
    fluid.default_startup_program().random_seed = 3
    x = layers.data("x", [4, 8, 8], dtype="float32")
    h = layers.conv_bn_add_act(x, 4, 3, residual=x, padding=1, act="relu",
                               moving_mean_name="tm",
                               moving_variance_name="tv")
    test_prog = fluid.default_main_program().clone(for_test=True)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    r = np.random.RandomState(0)
    xa = r.randn(2, 4, 8, 8).astype("float32")
    m0 = np.asarray(fluid.global_scope().find_var("tm")).copy()
    (y1,) = exe.run(program=test_prog, feed={"x": xa}, fetch_list=[h])
    (y2,) = exe.run(program=test_prog, feed={"x": xa}, fetch_list=[h])
    m1 = np.asarray(fluid.global_scope().find_var("tm"))
    np.testing.assert_array_equal(m0, m1)  # stats untouched
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))


def test_resnet_conv_tier_matches_unfused():
    from paddle_tpu import models

    def run(fuse_bn):
        fluid.reset_default_env()
        fluid.default_main_program().random_seed = 3
        fluid.default_startup_program().random_seed = 3
        spec = models.resnet_cifar10(depth=8, class_num=4, fuse_bn=fuse_bn)
        fluid.optimizer.MomentumOptimizer(0.05, 0.9).minimize(spec.loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        b = spec.synthetic_batch(8, seed=2)
        return [float(np.ravel(np.asarray(
            exe.run(feed=b, fetch_list=[spec.loss])[0]))[0])
            for _ in range(3)]

    base = run(False)
    conv_tier = run("conv")
    assert base[-1] < base[0]
    np.testing.assert_allclose(base, conv_tier, rtol=1e-5, atol=1e-6)


def test_mismatched_residual_raises():
    fluid.reset_default_env()
    x = layers.data("x", [4, 8, 8], dtype="float32")
    bad = layers.pool2d(x, pool_size=8, pool_type="avg")  # [N,4,1,1]
    with pytest.raises(ValueError, match="residual Z shape"):
        layers.conv_bn_add_act(x, 4, 3, residual=bad, padding=1)


def test_rectangular_stride_rejected():
    fluid.reset_default_env()
    x = layers.data("x", [4, 8, 8], dtype="float32")
    with pytest.raises(NotImplementedError, match="square"):
        h = layers.conv_bn_add_act(x, 4, 3, padding=1, stride=(1, 2))
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        exe.run(feed={"x": np.zeros((2, 4, 8, 8), "float32")},
                fetch_list=[h])


def test_grouped_conv_tier_matches_chain():
    """ResNeXt-style cardinality: conv_bn_add_act with groups>1 must
    match the grouped conv2d -> batch_norm chain (pallas impl falls back
    to the reference composition for groups>1)."""
    def run(mode):
        fluid.reset_default_env()
        fluid.set_flags({"FLAGS_conv_epilogue":
                         "pallas" if mode == "op-pallas" else "reference"})
        fluid.default_main_program().random_seed = 9
        fluid.default_startup_program().random_seed = 9
        x = layers.data("x", [8, 8, 8], dtype="float32")
        y = layers.data("y", [1], dtype="int64")
        if mode == "chain":
            conv = layers.conv2d(x, 8, 3, padding=1, groups=4,
                                 bias_attr=False,
                                 param_attr=fluid.ParamAttr(name="gw"))
            h = layers.batch_norm(conv, act="relu",
                                  param_attr=fluid.ParamAttr(name="gs"),
                                  bias_attr=fluid.ParamAttr(name="gb"),
                                  moving_mean_name="gm",
                                  moving_variance_name="gv")
        else:
            h = layers.conv_bn_add_act(
                x, 8, 3, padding=1, groups=4, act="relu",
                param_attr=fluid.ParamAttr(name="gw"),
                bn_param_attr=fluid.ParamAttr(name="gs"),
                bn_bias_attr=fluid.ParamAttr(name="gb"),
                moving_mean_name="gm", moving_variance_name="gv")
        pool = layers.pool2d(h, pool_size=8, pool_type="avg")
        pred = layers.fc(pool, size=3, act="softmax",
                         param_attr=fluid.ParamAttr(name="gfc"))
        loss = layers.mean(layers.cross_entropy(pred, y))
        fluid.optimizer.MomentumOptimizer(0.1, 0.9).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        r = np.random.RandomState(5)
        xa = r.randn(8, 8, 8, 8).astype("float32")
        ya = r.randint(0, 3, size=(8, 1)).astype("int64")
        ls = [float(np.ravel(np.asarray(exe.run(
            feed={"x": xa, "y": ya}, fetch_list=[loss])[0]))[0])
            for _ in range(3)]
        fluid.set_flags({"FLAGS_conv_epilogue": "reference"})
        return ls

    base = run("chain")
    np.testing.assert_allclose(base, run("op-ref"), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(base, run("op-pallas"), rtol=1e-5, atol=1e-6)


def test_se_resnext_conv_tier_builds_and_trains():
    from paddle_tpu import models

    fluid.reset_default_env()
    fluid.default_main_program().random_seed = 3
    fluid.default_startup_program().random_seed = 3
    spec = models.se_resnext(class_num=4, layers_cfg=(1,), cardinality=4,
                             reduction_ratio=4, img_shape=(3, 32, 32),
                             fuse_bn="conv")
    ops = [op.type for op in fluid.default_main_program().global_block().ops]
    assert "conv_bn_add_act" in ops
    fluid.optimizer.MomentumOptimizer(0.05, 0.9).minimize(spec.loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    b = spec.synthetic_batch(4, seed=2)
    ls = [float(np.ravel(np.asarray(exe.run(feed=b,
          fetch_list=[spec.loss])[0]))[0]) for _ in range(3)]
    assert ls[-1] < ls[0], ls
