"""Speculative decoding + the sampling contract (ISSUE 13).

Acceptance pinned here:
(a) greedy speculative decode (prompt-lookup drafting, multi-token
    paged verify, page-table rollback) is token-EXACT vs the
    ``full_decode`` oracle across overlapping ragged sequences WITH
    rollbacks occurring — the interpret-tier parity matrix spans
    d in {1, 2, 4} x H_kv in {8, 2} x {fp32, int8} pools x a
    prefix-cache-hit arm, each with zero leaked pages and
    ``check_invariants`` green after every truncation;
(b) the multi-token verify kernel: ragged ``q_lengths`` blocks under
    the interpret kernel match the dense reference row-for-row AND
    match stacked single-token steps (the in-block causal frontier is
    exact), quantized arm included; the byte model's KV stream is
    INVARIANT in q_tokens (only the query/output term grows);
(c) ``KVCachePool.truncate_seq`` rollback invariants: freeing only
    emptied refcount-zero pages, releasing (never freeing) shared
    prefix pages, clearing int8 scales with freed pages, and CoW-ing
    correctly on the next append after a rollback into a shared page;
(d) EOS / stop sequences / per-request max_new are honored INSIDE an
    accepted draft block: the sequence retires at the stop position
    and the surplus fed tokens leave both result.tokens and the page
    table;
(e) SamplingParams: temperature/top-k/top-p through the one jitted
    epilogue (deterministic per (seed, token-index), independent of
    batch composition), logit bias shifting greedy argmax, sampled
    rows drafting through the exact accept/resample epilogue
    (ISSUE 16), and Engine.submit threading the params in
    pass-through mode;
(i) ISSUE 16 exactness: the accept/resample epilogue's emitted-token
    distribution matches the plain sampler's over thousands of
    replayed draws (TV-distance bound across temp/top-k/top-p arms,
    chi-square sanity vs the exact filtered distribution), its
    accept/resample stream replays bit-identically per (seed, step),
    the spec_disabled counter surfaces a program without verify_step,
    and the corpus drafter (``PrefixCache.ngram_continuation``) follows
    the own-history-first decision rule — a corpus continuation only
    displaces the sequence's own draft when STRICTLY longer;
(f) serve_bench --speculate/--sampling scenarios on the 0/2/3 gate
    contract (usage errors exit 2) with acceptance_rate > 0 and
    tokens/s above the same invocation's d=0 arm — ISSUE 16 extends
    the matrix with sampled (topk), --mesh, and corpus-drafted
    --prefix-share speculation arms;
(g) the spec_verify zoo entry is banked under require_all coverage at
    < 2x the d=0 gqa_decode bytes/step, and the known-bad
    spec_verify_gather corpus arm trips the bytes gate; the SPMD
    mirror (spec_verify_spmd / spec_verify_spmd_gather) holds the
    same contract for the mesh verify step;
(h) observability: draft/verify/rollback flight events and the
    per-sequence accepted/rejected span annotation.
"""

import json
import os
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import observability as obs
from paddle_tpu.kernels.paged_attention import (
    attention_bytes_per_step,
    paged_decode_attention,
)
from paddle_tpu.serving import (
    ContinuousBatchingLoop,
    DecodeConfig,
    DecodeRequest,
    KVCachePool,
    PrefixCache,
    PromptLookupDrafter,
    SamplingParams,
    full_decode,
    init_decode_params,
    verify_step,
)
from paddle_tpu.serving.sampling import (
    apply_bias,
    sample_rows,
    spec_sample_rows,
    stop_hit,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# (b) kernel level: multi-token ragged verify


def _random_pool_state(rng, Hkv=2, P=16, ps=4, D=8, B=3, maxp=5):
    kp = rng.standard_normal((Hkv, P, ps, D)).astype(np.float32)
    vp = rng.standard_normal((Hkv, P, ps, D)).astype(np.float32)
    tables = rng.randint(0, P, size=(B, maxp)).astype(np.int32)
    return kp, vp, tables


def test_verify_kernel_interpret_matches_reference_ragged():
    rng = np.random.RandomState(0)
    kp, vp, tables = _random_pool_state(rng)
    lengths = np.array([18, 7, 13], np.int32)
    qlens = np.array([3, 1, 4], np.int32)
    q = rng.standard_normal((3, 4, 4, 8)).astype(np.float32)
    ref = paged_decode_attention(q, kp, vp, tables, lengths,
                                 impl="reference", q_lengths=qlens)
    it = paged_decode_attention(q, kp, vp, tables, lengths,
                                impl="interpret", q_lengths=qlens)
    for b in range(3):
        n = qlens[b]
        np.testing.assert_allclose(np.asarray(it)[b, :, :n],
                                   np.asarray(ref)[b, :, :n],
                                   rtol=2e-5, atol=2e-5)


def test_verify_block_rows_equal_stacked_single_token_steps():
    """The in-block causal frontier: row t of a verify block must equal
    a single-token decode at position lengths - q_lengths + t with the
    keys truncated there — speculation changes NOTHING about what each
    row attends to."""
    rng = np.random.RandomState(1)
    kp, vp, tables = _random_pool_state(rng)
    lengths = np.array([18, 7, 13], np.int32)
    qlens = np.array([3, 1, 4], np.int32)
    q = rng.standard_normal((3, 4, 4, 8)).astype(np.float32)
    blk = paged_decode_attention(q, kp, vp, tables, lengths,
                                 impl="reference", q_lengths=qlens)
    for b in range(3):
        for t in range(qlens[b]):
            ln_t = lengths.copy()
            ln_t[b] = lengths[b] - qlens[b] + t + 1
            single = paged_decode_attention(
                q[:, :, t:t + 1], kp, vp, tables, ln_t, impl="reference")
            np.testing.assert_allclose(np.asarray(blk)[b, :, t],
                                       np.asarray(single)[b, :, 0],
                                       rtol=2e-5, atol=2e-5)


def test_verify_kernel_int8_dequant_parity():
    rng = np.random.RandomState(2)
    Hkv, P, ps, D, B, maxp = 2, 16, 4, 8, 3, 5
    kf = rng.standard_normal((Hkv, P, ps, D)).astype(np.float32)
    vf = rng.standard_normal((Hkv, P, ps, D)).astype(np.float32)
    k_sc = np.abs(kf).max(axis=(0, 2, 3)) / 127.0
    v_sc = np.abs(vf).max(axis=(0, 2, 3)) / 127.0
    k8 = np.clip(np.round(kf / k_sc[None, :, None, None]),
                 -127, 127).astype(np.int8)
    v8 = np.clip(np.round(vf / v_sc[None, :, None, None]),
                 -127, 127).astype(np.int8)
    tables = rng.randint(0, P, size=(B, maxp)).astype(np.int32)
    lengths = np.array([15, 9, 20], np.int32)
    qlens = np.array([2, 4, 3], np.int32)
    q = rng.standard_normal((B, 4, 4, D)).astype(np.float32)
    ref = paged_decode_attention(q, k8, v8, tables, lengths,
                                 impl="reference", q_lengths=qlens,
                                 k_scales=k_sc, v_scales=v_sc)
    it = paged_decode_attention(q, k8, v8, tables, lengths,
                                impl="interpret", q_lengths=qlens,
                                k_scales=k_sc, v_scales=v_sc)
    for b in range(B):
        n = qlens[b]
        np.testing.assert_allclose(np.asarray(it)[b, :, :n],
                                   np.asarray(ref)[b, :, :n],
                                   rtol=1e-4, atol=1e-4)


def test_verify_query_validation():
    rng = np.random.RandomState(3)
    kp, vp, tables = _random_pool_state(rng)
    lengths = np.array([8, 8, 8], np.int32)
    q1 = rng.standard_normal((3, 4, 1, 8)).astype(np.float32)
    with pytest.raises(ValueError, match="q_lengths"):
        paged_decode_attention(q1, kp, vp, tables, lengths,
                               impl="reference",
                               q_lengths=np.ones(3, np.int32))
    with pytest.raises(ValueError, match=">= 1 token"):
        paged_decode_attention(q1[:, :, :0], kp, vp, tables, lengths,
                               impl="reference")


def test_bytes_model_kv_stream_invariant_in_q_tokens():
    """The whole amortization claim in one assertion: the pallas KV
    stream bytes do not change with the draft depth; only the (small)
    query/output term rides on top, so bytes/step at d=4 is far under
    2x the d=0 step."""
    kw = dict(batch=4, max_pages=32, page_size=16, num_heads=8,
              head_dim=128, num_layers=1, num_kv_heads=2)
    d0 = attention_bytes_per_step("pallas", **kw)
    d4 = attention_bytes_per_step("pallas", q_tokens=5, **kw)
    qo = 2 * 4 * 5 * 8 * 128 * 4  # query read + output write at fp32
    assert d4 == d0 + qo
    assert d4 < 2 * d0
    # at full acceptance the step commits 5 tokens: >= 2x (here ~4x)
    # effective bytes-per-token reduction
    assert d0 / (d4 / 5) > 2.0
    # q_tokens=1 is byte-identical to the pre-ISSUE-13 model (banked
    # zoo entries unchanged)
    assert attention_bytes_per_step("pallas", q_tokens=1, **kw) == d0


# ---------------------------------------------------------------------------
# (c) truncate_seq rollback invariants


def _pool(dtype="float32", pages=16, ps=4):
    return KVCachePool(num_pages=pages, page_size=ps, num_layers=2,
                       num_heads=2, head_dim=4, dtype=dtype)


def _fill(pool, seq_id, n, value=1.0):
    pages, slots = pool.append_tokens([seq_id], [n])
    rows = np.full((n, pool.num_kv_heads, pool.head_dim), value,
                   np.float32)
    for li in range(pool.num_layers):
        pool.write_kv(li, pages, slots, rows, rows)
    return pages, slots


def test_truncate_seq_frees_emptied_pages_only():
    pool = _pool()
    pool.allocate(0)
    _fill(pool, 0, 10)
    assert pool.used_pages == 3
    assert pool.truncate_seq(0, 5) == 1  # page 3 emptied
    assert pool.length(0) == 5 and pool.used_pages == 2
    assert pool.check_invariants()["ok"]
    assert pool.truncate_seq(0, 5) == 0  # no-op
    assert pool.truncate_seq(0, 0) == 2
    assert pool.used_pages == 0 and pool.check_invariants()["ok"]
    with pytest.raises(ValueError, match="truncate"):
        pool.truncate_seq(0, 1)  # growth is append's job
    pool.free_seq(0)


def test_truncate_seq_through_shared_prefix_releases_not_frees():
    """A rollback crossing a prefix-cache share drops only THIS
    sequence's hold: the share survives for its other readers and the
    audit stays green (the never-strand-a-share contract)."""
    pool = _pool(dtype="int8")
    pool.allocate(0)
    _fill(pool, 0, 8)
    shared, _ = pool.table_snapshot(0)
    pool.retain_pages(shared)  # the cache's entry hold
    holds = {p: 1 for p in shared}
    pool.register_owner(lambda: dict(holds))
    pool.allocate(1)
    pool.attach_prefix(1, shared, 8)
    _fill(pool, 1, 5, value=2.0)  # 2 own pages on top
    own = [p for p in pool.table_snapshot(1)[0] if p not in shared]
    assert pool.check_invariants()["ok"]
    # roll back 3 tokens: one own page frees, its int8 scales clear
    assert pool.truncate_seq(1, 10) == 1
    assert float(pool.k_scales[0, own[-1]]) == 0.0
    assert float(pool.k_scales[0, own[0]]) != 0.0
    assert pool.check_invariants()["ok"]
    # roll back INTO the shared region: shared pages drop this
    # sequence's hold but stay live (seq 0 + cache still read them)
    pool.truncate_seq(1, 3)
    assert all(pool.refcount(p) >= 2 for p in shared[:1])
    rep = pool.check_invariants()
    assert rep["ok"], rep
    assert pool.stats()["tokens_truncated"] == 3 + 7
    # cleanup leaves nothing behind (the "cache" drops its entry too)
    pool.free_seq(1)
    pool.free_seq(0)
    holds.clear()
    pool.release_pages(shared)
    assert pool.used_pages == 0 and pool.check_invariants()["ok"]


def test_append_after_rollback_into_shared_page_cows():
    """After truncating into a shared partially-filled page, the next
    append must copy-on-write it — rollback cannot turn a shared page
    writable."""
    pool = _pool()
    pool.allocate(0)
    _fill(pool, 0, 6)  # 2 pages, second partial
    shared, _ = pool.table_snapshot(0)
    pool.allocate(1)
    pool.attach_prefix(1, shared, 6)
    _fill(pool, 1, 4, value=2.0)  # CoWs the partial tail + 1 more page
    cows0 = pool.stats()["cow_copies"]
    assert cows0 == 1
    pool.truncate_seq(1, 5)  # back INSIDE the shared page-1 span? no:
    # 5 tokens = page0(4) + 1 token in seq1's CoW'd page — the shared
    # page-1 left the table, refcount back to seq0's
    tab1, _ = pool.table_snapshot(1)
    assert pool.check_invariants()["ok"]
    # appending again writes into seq 1's own (or fresh) pages — never
    # the shared ones
    _fill(pool, 1, 3, value=3.0)
    assert pool.check_invariants()["ok"]
    for p in pool.table_snapshot(0)[0]:
        assert pool.refcount(p) >= 1
    pool.free_seq(1)
    pool.free_seq(0)
    assert pool.used_pages == 0


# ---------------------------------------------------------------------------
# drafter unit behavior


def test_prompt_lookup_drafter():
    d = PromptLookupDrafter(max_draft=4, max_ngram=3)
    assert d.draft([5, 6, 7, 9, 5, 6, 7]) == [9, 5, 6, 7]
    assert d.draft([1, 2, 3]) == []
    assert d.draft([4, 4, 4, 4]) == [4, 4, 4]  # longest partial
    assert d.draft([5, 6, 7, 9, 5, 6, 7], max_draft=2) == [9, 5]
    assert d.draft([1, 2, 1, 2, 1, 2]) == [1, 2, 1, 2]
    assert d.draft([3]) == [] and d.draft([]) == []
    with pytest.raises(ValueError):
        PromptLookupDrafter(max_draft=0)
    with pytest.raises(ValueError):
        PromptLookupDrafter(min_ngram=3, max_ngram=2)


# ---------------------------------------------------------------------------
# (a) the interpret-tier parity matrix


@pytest.mark.parametrize("d", [1, 2, 4])
@pytest.mark.parametrize("h_kv", [8, 2])
@pytest.mark.parametrize("dtype", ["float32", "int8"])
def test_speculative_parity_matrix_vs_full_decode(d, h_kv, dtype):
    """Greedy speculative decode through the REAL multi-token kernel
    (interpret mode) is token-EXACT vs full_decode on overlapping
    ragged sequences, drafts genuinely fire, and every rollback leaves
    the audited pool clean with zero leaked pages."""
    cfg = DecodeConfig(vocab_size=61, d_model=32, n_head=8, n_layer=2,
                       d_inner=48, max_length=48, n_kv_head=h_kv)
    params = init_decode_params(cfg, seed=2)
    rng = np.random.RandomState(2)
    prompts = [rng.randint(1, cfg.vocab_size, size=n).tolist()
               for n in (6, 9, 4, 11)]
    pool = KVCachePool(num_pages=48, page_size=4, num_layers=cfg.n_layer,
                       num_heads=cfg.n_head, head_dim=cfg.head_dim,
                       num_kv_heads=h_kv, dtype=dtype)
    loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=3,
                                  paged_impl="interpret", speculate=d,
                                  check_every=1)
    results = loop.run([DecodeRequest(p, 10) for p in prompts])
    tol = 2e-2 if dtype == "int8" else 1e-4
    for p, res in zip(prompts, results):
        want_tokens, want_logits = full_decode(params, cfg, p, 10)
        assert res.tokens == want_tokens  # greedy tokens EXACT
        for got, want in zip(res.logits, want_logits):
            np.testing.assert_allclose(got, want, rtol=tol, atol=tol)
    assert loop.drafted_tokens > 0  # speculation actually ran
    assert loop.spec_steps > 0
    assert pool.free_pages == pool.num_pages  # zero leaked pages
    assert loop.invariant_violations == 0
    assert pool.check_invariants()["ok"]


def test_speculative_rollbacks_occur_and_stay_clean():
    """The acceptance wording is explicit: rollbacks must OCCUR.  At
    this seed the drafter over-proposes and the verifier rejects some
    tokens — truncations fire and the pool audit stays green after
    every one (check_every=1)."""
    cfg = DecodeConfig(vocab_size=61, d_model=16, n_head=2, n_layer=2,
                       d_inner=32, max_length=64)
    params = init_decode_params(cfg, seed=2)
    rng = np.random.RandomState(2)
    prompts = [rng.randint(1, cfg.vocab_size, size=n).tolist()
               for n in (6, 9, 4, 11)]
    pool = KVCachePool(num_pages=80, page_size=4, num_layers=cfg.n_layer,
                       num_heads=cfg.n_head, head_dim=cfg.head_dim)
    loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=4,
                                  speculate=3, check_every=1)
    results = loop.run([DecodeRequest(p, 14) for p in prompts])
    for p, res in zip(prompts, results):
        assert res.tokens == full_decode(params, cfg, p, 14)[0]
    assert loop.rolled_back_tokens > 0
    assert loop.accepted_tokens < loop.drafted_tokens
    assert 0.0 < loop.acceptance_rate() < 1.0
    assert pool.stats()["tokens_truncated"] == loop.rolled_back_tokens
    assert loop.invariant_violations == 0
    assert pool.free_pages == pool.num_pages
    # fewer model steps than unspeculated decode for the same tokens
    loop0 = ContinuousBatchingLoop(
        params, cfg,
        KVCachePool(num_pages=80, page_size=4, num_layers=cfg.n_layer,
                    num_heads=cfg.n_head, head_dim=cfg.head_dim),
        max_batch=4, speculate=0)
    loop0.run([DecodeRequest(p, 14) for p in prompts])
    assert loop.steps < loop0.steps


def test_speculation_composes_with_prefix_cache_hits():
    """Prefix-cache hits + speculation + rollback in one run: token
    parity holds, hits and drafts both fire, and truncation through
    refcounted tables never corrupts the audit."""
    cfg = DecodeConfig(vocab_size=61, d_model=32, n_head=8, n_layer=2,
                       d_inner=48, max_length=48, n_kv_head=2)
    params = init_decode_params(cfg, seed=2)
    rng = np.random.RandomState(2)
    shared = rng.randint(1, 61, size=9).tolist()
    prompts = [shared + rng.randint(1, 61, size=3).tolist()
               for _ in range(5)]
    pool = KVCachePool(num_pages=60, page_size=4, num_layers=cfg.n_layer,
                       num_heads=cfg.n_head, head_dim=cfg.head_dim,
                       num_kv_heads=2, dtype="int8")
    cache = PrefixCache(pool)
    loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=2,
                                  paged_impl="interpret", speculate=3,
                                  prefix_cache=cache, check_every=1)
    results = loop.run([DecodeRequest(p, 8) for p in prompts])
    for p, res in zip(prompts, results):
        assert res.tokens == full_decode(params, cfg, p, 8)[0]
    assert loop.prefix_hits > 0 and loop.drafted_tokens > 0
    cache.clear()
    assert pool.free_pages == pool.num_pages
    assert pool.check_invariants()["ok"]


# ---------------------------------------------------------------------------
# (d) stops inside an accepted block


class _OracleDrafter:
    """Proposes the exact greedy continuation — forces full acceptance
    so EOS/stop/max_new land INSIDE accepted blocks."""

    def __init__(self, prompt, tokens):
        self.seq = list(prompt) + list(tokens)

    def draft(self, context, max_draft=None):
        n = len(context)
        return self.seq[n:n + (max_draft or 4)]


def _oracle_setup(seed=0, max_new=14):
    cfg0 = DecodeConfig(vocab_size=61, d_model=16, n_head=2, n_layer=2,
                        d_inner=32, max_length=64)
    params = init_decode_params(cfg0, seed=seed)
    prompt = list(np.random.RandomState(seed).randint(1, 61, size=6))
    want, _ = full_decode(params, cfg0, prompt, max_new)
    return cfg0, params, prompt, want


def test_eos_inside_accepted_draft_block_truncates_both_sides():
    cfg0, params, prompt, want = _oracle_setup()
    eos = want[4]
    cfg = DecodeConfig(vocab_size=61, d_model=16, n_head=2, n_layer=2,
                       d_inner=32, max_length=64, eos_id=int(eos))
    want_e, _ = full_decode(params, cfg, prompt, 14)
    assert want_e[-1] == eos and len(want_e) < 14
    pool = KVCachePool(num_pages=32, page_size=4, num_layers=cfg.n_layer,
                       num_heads=cfg.n_head, head_dim=cfg.head_dim)
    loop = ContinuousBatchingLoop(
        params, cfg, pool, max_batch=2, speculate=4,
        drafter=_OracleDrafter(prompt, want))
    res = loop.run([DecodeRequest(prompt, 14)])[0]
    # retires AT the EOS position: no surplus tokens in the result...
    assert res.tokens == want_e
    # ...and none left in the page table: the fed-but-dead tail was
    # truncated before retirement freed the rest
    assert loop.rolled_back_tokens > 0
    assert pool.free_pages == pool.num_pages
    assert pool.check_invariants()["ok"]


def test_stop_sequence_and_max_new_inside_blocks():
    cfg0, params, prompt, want = _oracle_setup()
    pool = KVCachePool(num_pages=64, page_size=4, num_layers=cfg0.n_layer,
                       num_heads=cfg0.n_head, head_dim=cfg0.head_dim)
    loop = ContinuousBatchingLoop(
        params, cfg0, pool, max_batch=4, speculate=4,
        drafter=_OracleDrafter(prompt, want))
    stop = tuple(want[2:4])
    res = loop.run([
        DecodeRequest(prompt, 14),
        DecodeRequest(prompt, 14, sampling=SamplingParams(stop=[stop])),
        DecodeRequest(prompt, 14, sampling=SamplingParams(max_new=3)),
    ])
    assert res[0].tokens == want
    # the stop-seq arm ends the moment its generated tokens end with
    # the stop — the shortest such prefix of the oracle stream
    got = res[1].tokens
    assert tuple(got[-2:]) == stop
    assert got == want[:len(got)]
    assert all(tuple(got[i - 1:i + 1]) != stop
               for i in range(1, len(got) - 1))
    # per-request max_new caps below the request's own limit
    assert res[2].tokens == want[:3]
    assert pool.free_pages == pool.num_pages
    assert pool.check_invariants()["ok"]


# ---------------------------------------------------------------------------
# (e) the sampling contract


def test_sampling_params_validation_and_normalization():
    p = SamplingParams(stop=[[1, 2]], logit_bias={3: 2.0, 1: -1.0})
    assert p.greedy and p.stop == ((1, 2),)
    assert p.logit_bias == ((1, -1.0), (3, 2.0))
    assert p.max_bias_token() == 3 and SamplingParams().max_bias_token() == -1
    assert hash(p) is not None  # frozen + normalized: usable as a key
    for bad in (dict(temperature=-1), dict(top_k=-1), dict(top_p=0.0),
                dict(top_p=1.5), dict(max_new=0), dict(stop=[[]]),
                # a bad seed/bias must fail THIS request's construction,
                # never the shared batch mid-step
                dict(seed=-1), dict(seed=2 ** 32),
                dict(logit_bias={-2: 1.0})):
        with pytest.raises(ValueError):
            SamplingParams(**bad)
    assert stop_hit([9, 1, 2], p) and not stop_hit([1, 2, 9], p)
    row = np.zeros(8, np.float32)
    biased = apply_bias(row, p)
    assert biased[3] == 2.0 and biased[1] == -1.0 and row[3] == 0.0


def test_out_of_vocab_bias_rejected_at_admission():
    cfg = DecodeConfig(vocab_size=31, d_model=16, n_head=2, n_layer=1,
                       d_inner=16, max_length=32)
    pool = KVCachePool(num_pages=16, page_size=4, num_layers=1,
                       num_heads=2, head_dim=8)
    loop = ContinuousBatchingLoop(init_decode_params(cfg), cfg, pool)
    with pytest.raises(ValueError, match="vocab_size"):
        loop.run([DecodeRequest([1, 2], 2,
                  sampling=SamplingParams(logit_bias={99: 1.0}))])
    assert pool.free_pages == pool.num_pages  # before-any-work raise


def test_rogue_drafter_output_clamped_to_room():
    """A custom drafter ignoring max_draft must not breach the pad_to
    width or the admission page reservation — the loop clamps."""
    cfg = DecodeConfig(vocab_size=31, d_model=16, n_head=2, n_layer=1,
                       d_inner=16, max_length=32)
    params = init_decode_params(cfg)
    pool = KVCachePool(num_pages=16, page_size=4, num_layers=1,
                       num_heads=2, head_dim=8)

    class Rogue:
        def draft(self, context, max_draft=None):
            return [1, 2, 3, 4, 5, 6, 7]

    loop = ContinuousBatchingLoop(params, cfg, pool, speculate=2,
                                  drafter=Rogue())
    res = loop.run([DecodeRequest([1, 2, 3], 4)])
    assert res[0].tokens == full_decode(params, cfg, [1, 2, 3], 4)[0]
    assert pool.free_pages == pool.num_pages


def test_top_p_default_is_a_true_no_op():
    """The fp32 cumsum of sorted softmax probs often tops out below
    1.0; top_p=1.0 (the documented 'off') must still keep the whole
    vocab — hot-temperature draws stay genuinely random instead of
    collapsing to argmax."""
    rng = np.random.RandomState(0)
    logits = rng.standard_normal((120, 32)).astype(np.float32)
    ps = [SamplingParams(temperature=1.0, seed=i) for i in range(120)]
    toks = sample_rows(logits, ps, list(range(120)))
    assert float((toks == logits.argmax(-1)).mean()) < 0.5


def test_sample_rows_epilogue_semantics():
    rng = np.random.RandomState(0)
    logits = rng.standard_normal((4, 32)).astype(np.float32)
    ps = [SamplingParams(temperature=0.8, seed=i) for i in range(4)]
    t1 = sample_rows(logits, ps, [0] * 4)
    assert (t1 == sample_rows(logits, ps, [0] * 4)).all()  # deterministic
    assert (t1 != sample_rows(logits, ps, [1] * 4)).any()  # per-step keys
    # top_k=1 and a vanishing top_p both collapse to argmax even hot
    for collapse in (dict(top_k=1), dict(top_p=1e-7)):
        pc = [SamplingParams(temperature=5.0, seed=i, **collapse)
              for i in range(4)]
        assert (sample_rows(logits, pc, [0] * 4)
                == logits.argmax(-1)).all()
    # greedy rows are the host argmax path's job, never the epilogue's
    with pytest.raises(ValueError, match="greedy"):
        sample_rows(logits, [SamplingParams()] * 4, [0] * 4)


# ---------------------------------------------------------------------------
# (i) ISSUE 16: the exact accept/resample epilogue — distribution,
# replay, degrade surfacing, and the corpus drafter decision rule


def _exact_filtered_probs(row, p):
    """Host-side exact target: the SAME ``_filter_scaled`` both jitted
    epilogues trace, applied eagerly to one row, then softmax."""
    import jax.numpy as jnp

    from paddle_tpu.serving import sampling as _sampling

    x = np.asarray(_sampling._filter_scaled(
        jnp.asarray(row[None], jnp.float32),
        jnp.asarray([p.temperature], jnp.float32),
        jnp.asarray([p.top_k], jnp.int32),
        jnp.asarray([p.top_p], jnp.float32), row.shape[0]))[0]
    x = x - x[np.isfinite(x)].max()
    e = np.where(np.isfinite(x), np.exp(x), 0.0)
    return e / e.sum()


@pytest.mark.parametrize("kw", [
    dict(temperature=0.8),
    dict(temperature=0.9, top_k=8),
    dict(temperature=1.0, top_p=0.85),
], ids=["temp", "topk", "topp"])
def test_spec_epilogue_emitted_distribution_is_exact(kw):
    """The exactness theorem, empirically: with a fixed drafted token,
    the FIRST emitted token of the accept/resample walk (the draft when
    accepted, the masked residual resample otherwise) must be
    distributed exactly as the plain filtered sampler.  Checked three
    ways over thousands of independent seeds: TV distance against
    ``sample_rows``'s empirical histogram, chi-square against the exact
    filtered softmax, and the acceptance frequency against p(draft)
    itself — with both the accept and resample arms firing."""
    V, B = 32, 8192
    rng = np.random.RandomState(5)
    row = rng.standard_normal(V).astype(np.float32)
    ps = [SamplingParams(seed=i, **kw) for i in range(B)]
    steps = [0] * B
    p_exact = _exact_filtered_probs(row, ps[0])
    draft = int(np.argsort(p_exact)[-2])  # in-support, not the mode
    spec_logits = np.broadcast_to(row, (B, 2, V)).copy()
    acc, toks = spec_sample_rows(spec_logits, ps, steps, [[draft]] * B)
    emitted = toks[:, 0]
    accepted = acc >= 1
    assert 0 < accepted.sum() < B              # both arms exercised
    assert (emitted[accepted] == draft).all()  # accepts emit the draft
    assert (emitted[~accepted] != draft).all()  # residual masks it out
    # TV distance vs the plain epilogue's empirical distribution
    plain = sample_rows(np.broadcast_to(row, (B, V)).copy(), ps, steps)
    h_spec = np.bincount(emitted, minlength=V) / B
    h_plain = np.bincount(plain, minlength=V) / B
    assert 0.5 * np.abs(h_spec - h_plain).sum() < 0.05
    # chi-square vs the exact filtered softmax (loose bound — a wrong
    # residual, e.g. forgetting to mask the draft, misses it by miles)
    exp = p_exact * B
    keep = exp >= 5
    chi2 = float((((np.bincount(emitted, minlength=V) - exp) ** 2
                   / np.maximum(exp, 1e-9))[keep]).sum())
    dof = int(keep.sum()) - 1
    assert chi2 < dof + 6 * np.sqrt(2 * dof), (chi2, dof)
    # acceptance itself is a Bernoulli(p(draft)) draw per row
    p_d = float(p_exact[draft])
    assert abs(float(accepted.mean()) - p_d) \
        < 5 * np.sqrt(p_d * (1 - p_d) / B)
    # exact replay: the (seed, token-index) stream is bit-identical
    acc2, toks2 = spec_sample_rows(spec_logits, ps, steps,
                                   [[draft]] * B)
    assert (acc2 == acc).all() and (toks2 == toks).all()


def test_spec_epilogue_no_draft_row_is_exactly_sample_rows():
    """A row with an empty draft walks zero accepts and lands on the
    bonus draw — the UNSALTED Gumbel at key_g — so it must be
    byte-identical to the plain epilogue at the same (seed, step)."""
    rng = np.random.RandomState(7)
    B, V = 64, 32
    logits = rng.standard_normal((B, V)).astype(np.float32)
    ps = [SamplingParams(temperature=0.7 + 0.01 * i, seed=i)
          for i in range(B)]
    steps = list(range(B))
    acc, toks = spec_sample_rows(logits[:, None, :], ps, steps,
                                 [[]] * B)
    assert (acc == 0).all()
    assert (toks[:, 0] == sample_rows(logits, ps, steps)).all()


def test_spec_epilogue_rejects_greedy_rows_and_overfull_drafts():
    logits = np.zeros((2, 3, 8), np.float32)
    sp = SamplingParams(temperature=0.8, seed=0)
    with pytest.raises(ValueError, match="greedy"):
        spec_sample_rows(logits, [SamplingParams(), sp], [0, 0],
                         [[1], [1]])
    with pytest.raises(ValueError, match="at most"):
        spec_sample_rows(logits, [sp, sp], [0, 0], [[1, 2, 3], [1]])


def test_sampled_spec_arms_roll_back_and_leak_nothing():
    """Every sampling scenario speculates now: the epilogue rejects
    (rollbacks occur), the pool comes back fully free with invariants
    audited every step, and the replayed stream is identical."""
    cfg0, params, prompt, _ = _oracle_setup()
    prompt = prompt[:3] * 2  # a repeating prompt: drafting fires early
    for arm in (dict(temperature=1.0), dict(temperature=0.9, top_k=12),
                dict(temperature=0.9, top_p=0.9)):

        def run():
            pool = KVCachePool(num_pages=64, page_size=4,
                               num_layers=cfg0.n_layer,
                               num_heads=cfg0.n_head,
                               head_dim=cfg0.head_dim)
            loop = ContinuousBatchingLoop(params, cfg0, pool,
                                          max_batch=4, speculate=3,
                                          check_every=1)
            reqs = [DecodeRequest(prompt, 10,
                                  sampling=SamplingParams(seed=s,
                                                          **arm))
                    for s in range(3)]
            out = loop.run(reqs)
            assert pool.free_pages == pool.num_pages
            assert loop.invariant_violations == 0
            return loop, [o.tokens for o in out]

        loop, toks = run()
        assert loop.drafted_tokens > 0, arm
        assert loop.rolled_back_tokens > 0, arm  # rejections happened
        _, toks2 = run()
        assert toks2 == toks, arm


def test_program_without_verify_step_surfaces_spec_disabled(obs_on):
    """ISSUE 16 bugfix: a program that cannot verify used to degrade
    speculation to d=0 with only a log line — now it lands a
    {reason=}-labelled counter and a flight event."""
    cfg = DecodeConfig(vocab_size=17, d_model=16, n_head=2, n_layer=1,
                       d_inner=16, max_length=16)
    pool = KVCachePool(num_pages=4, page_size=4, num_layers=1,
                       num_heads=2, head_dim=8)

    class _NoVerify:
        def __init__(self, cfg):
            self.cfg = cfg

        def resolve_impl(self, pool):
            return "reference"

    loop = ContinuousBatchingLoop(None, None, pool,
                                  program=_NoVerify(cfg), speculate=2)
    assert loop._speculate == 0 and loop.drafter is None
    snap = obs.default_registry().to_prometheus()
    assert "paddle_tpu_serving_spec_disabled_total" in snap
    assert 'reason="program_no_verify"' in snap
    ev = [e for e in obs.default_flight().events()
          if e["kind"] == "spec_disabled"]
    assert ev and ev[0]["reason"] == "program_no_verify"
    assert ev[0]["program"] == "_NoVerify"


def _corpus_cache(chains):
    """A PrefixCache primed the production way: each chain is a
    finished prefill whose prompt pages were inserted into the trie."""
    pool = KVCachePool(num_pages=64, page_size=4, num_layers=1,
                       num_heads=2, head_dim=8)
    cache = PrefixCache(pool)
    for sid, chain in enumerate(chains):
        pool.allocate(sid)
        pool.append_tokens([sid], [len(chain)])
        cache.insert(sid, chain)
    return pool, cache


def test_ngram_continuation_decision_rule():
    pool, cache = _corpus_cache([
        [1, 2, 3, 4, 5, 6, 7, 8],   # older chain, longer follow-up
        [9, 9, 1, 2, 3, 7, 7, 7],   # newer chain, shorter follow-up
    ])
    # the longer continuation wins across chains
    assert cache.ngram_continuation([1, 2, 3], 4) == [4, 5, 6, 7]
    # at equal (full) length the more recently used chain wins the tie
    assert cache.ngram_continuation([1, 2, 3], 3) == [7, 7, 7]
    # a miss returns [] — the drafter falls back to own history
    assert cache.ngram_continuation([5, 9], 4) == []
    assert cache.ngram_continuation([], 4) == []
    assert cache.ngram_continuation([1, 2, 3], 0) == []
    # the corpus walk is pure host bookkeeping: no pool state moved
    assert pool.check_invariants()["ok"]


def test_ngram_continuation_newest_position_wins_within_chain():
    _, cache = _corpus_cache([[1, 2, 5, 1, 2, 6, 1, 2]])
    # [1, 2] occurs at 0, 3 and 6; the newest occurrence with a
    # full-length continuation (position 3) wins over the older one
    assert cache.ngram_continuation([1, 2], 2) == [6, 1]


def test_drafter_corpus_decision_rule_and_type_check():
    _, cache = _corpus_cache([[3, 4, 50, 51, 52, 53, 54, 55]])
    d = PromptLookupDrafter(max_draft=4, max_ngram=3, corpus=cache)
    ctx = [3, 4, 8, 3, 4]
    # own history fills the limit → the corpus is never consulted
    assert d.draft(ctx, 3) == [8, 3, 4]
    # own comes up short → a STRICTLY longer corpus continuation wins
    assert d.draft(ctx, 4) == [50, 51, 52, 53]
    # an equal-length corpus match does NOT displace own history
    d2 = PromptLookupDrafter(
        max_draft=4, max_ngram=3,
        corpus=_corpus_cache([[3, 4, 60, 61, 62]])[1])
    assert d2.draft(ctx, 4) == [8, 3, 4]
    with pytest.raises(TypeError, match="ngram_continuation"):
        PromptLookupDrafter(corpus=object())


def test_loop_wires_prefix_cache_as_drafter_corpus():
    cfg0, params, _, _ = _oracle_setup()

    def pool():
        return KVCachePool(num_pages=64, page_size=4,
                           num_layers=cfg0.n_layer,
                           num_heads=cfg0.n_head,
                           head_dim=cfg0.head_dim)

    p1 = pool()
    cache = PrefixCache(p1)
    loop = ContinuousBatchingLoop(params, cfg0, p1, speculate=3,
                                  prefix_cache=cache)
    assert loop.drafter is not None and loop.drafter.corpus is cache
    # no prefix cache → no corpus, plain own-history drafting
    loop2 = ContinuousBatchingLoop(params, cfg0, pool(), speculate=3)
    assert loop2.drafter is not None and loop2.drafter.corpus is None


def test_sampled_request_rides_spec_batch_and_replays_identically():
    """A non-greedy request decodes alongside speculating batch-mates
    without breaking the greedy mate's oracle parity, and an identical
    replay regenerates the identical stream (the (seed, token-index)
    RNG key contract; exact cross-composition identity is NOT promised
    — fp32 reduction order differs between step shapes).  ISSUE 16:
    the sampled row itself DRAFTS now — the accept/resample epilogue
    verifies it — so a purely sampled run speculates too."""
    cfg0, params, prompt, want = _oracle_setup()
    sp = SamplingParams(temperature=0.9, seed=3)

    def run(reqs):
        pool = KVCachePool(num_pages=64, page_size=4,
                           num_layers=cfg0.n_layer, num_heads=cfg0.n_head,
                           head_dim=cfg0.head_dim)
        loop = ContinuousBatchingLoop(params, cfg0, pool, max_batch=4,
                                      speculate=3)
        out = loop.run(reqs)
        assert pool.free_pages == pool.num_pages
        return loop, out

    loop, mixed = run([DecodeRequest(prompt, 14),
                       DecodeRequest(prompt, 14, sampling=sp)])
    assert mixed[0].tokens == want            # greedy mate: oracle-exact
    assert len(mixed[1].tokens) == 14
    assert mixed[1].tokens != want            # genuinely sampled
    assert loop.drafted_tokens > 0            # the greedy mate drafted
    _, replay = run([DecodeRequest(prompt, 14),
                     DecodeRequest(prompt, 14, sampling=sp)])
    assert replay[1].tokens == mixed[1].tokens  # identical replay
    # a different seed is a different stream
    _, other = run([DecodeRequest(prompt, 14),
                    DecodeRequest(prompt, 14,
                                  sampling=SamplingParams(
                                      temperature=0.9, seed=4))])
    assert other[1].tokens != mixed[1].tokens
    # a purely sampled run drafts too (ISSUE 16 — no per-sequence
    # auto-disable anymore) and its replay is still exact
    loop2, out2 = run([DecodeRequest(prompt, 6, sampling=sp),
                       DecodeRequest(prompt, 6,
                                     sampling=SamplingParams(
                                         temperature=0.5, seed=1))])
    assert loop2.drafted_tokens > 0 and loop2.spec_steps > 0
    _, out3 = run([DecodeRequest(prompt, 6, sampling=sp),
                   DecodeRequest(prompt, 6,
                                 sampling=SamplingParams(
                                     temperature=0.5, seed=1))])
    assert [o.tokens for o in out3] == [o.tokens for o in out2]


def test_logit_bias_shifts_greedy_argmax_and_keeps_speculation():
    cfg0, params, prompt, want = _oracle_setup()
    forced = (want[0] + 1) % 61 or 1
    sp = SamplingParams(logit_bias={forced: 1e3})
    assert sp.greedy  # biased greedy is deterministic: speculation on
    pool = KVCachePool(num_pages=64, page_size=4, num_layers=cfg0.n_layer,
                       num_heads=cfg0.n_head, head_dim=cfg0.head_dim)
    loop = ContinuousBatchingLoop(params, cfg0, pool, max_batch=2,
                                  speculate=3)
    res = loop.run([DecodeRequest(prompt, 5, sampling=sp)])[0]
    assert all(t == forced for t in res.tokens)  # the bias wins each step
    assert pool.free_pages == pool.num_pages


def test_engine_submit_threads_sampling_passthrough_only():
    from paddle_tpu import serving

    captured = {}

    class _Backend:
        feed_names = None

        def __call__(self, feed, **kw):
            captured.update(kw)
            return [np.zeros((1, 1), np.float32)]

    eng = serving.Engine(_Backend(),
                         config=serving.EngineConfig(buckets=()))
    sp = SamplingParams(temperature=0.5, seed=9)
    fut = eng.submit({"x": np.zeros((1, 2), np.float32)}, sampling=sp)
    fut.result(timeout=10)
    assert captured["sampling"] is sp
    with pytest.raises(TypeError, match="SamplingParams"):
        eng.submit({"x": np.zeros((1, 2), np.float32)},
                   sampling={"temperature": 1.0})
    eng.close()
    bucketed = serving.Engine(_Backend(),
                              config=serving.EngineConfig(buckets=(1, 2)))
    with pytest.raises(ValueError, match="pass-through"):
        bucketed.submit({"x": np.zeros((1, 2), np.float32)}, sampling=sp)
    bucketed.close()


def test_loop_rejects_bad_speculate_and_degrades_for_program():
    cfg = DecodeConfig(vocab_size=17, d_model=16, n_head=2, n_layer=1,
                       d_inner=16, max_length=16)
    pool = KVCachePool(num_pages=4, page_size=4, num_layers=1,
                       num_heads=2, head_dim=8)
    with pytest.raises(ValueError, match="speculate"):
        ContinuousBatchingLoop(init_decode_params(cfg), cfg, pool,
                               speculate=-1)
    # FLAGS default keeps speculation off
    loop = ContinuousBatchingLoop(init_decode_params(cfg), cfg, pool)
    assert loop._speculate == 0 and loop.drafter is None


# ---------------------------------------------------------------------------
# (h) observability: flight events + span annotations


@pytest.fixture
def obs_on(tmp_path):
    fluid.set_flags({"FLAGS_observability": True,
                     "FLAGS_flight_dir": str(tmp_path / "flight")})
    obs.reset()
    yield
    obs.reset()
    fluid.set_flags({"FLAGS_observability": False,
                     "FLAGS_flight_dir": ""})


def test_flight_events_and_span_annotations(obs_on):
    cfg0, params, prompt, want = _oracle_setup()
    pool = KVCachePool(num_pages=80, page_size=4, num_layers=cfg0.n_layer,
                       num_heads=cfg0.n_head, head_dim=cfg0.head_dim)
    rng = np.random.RandomState(2)
    prompts = [rng.randint(1, 61, size=n).tolist() for n in (6, 9, 4, 11)]
    loop = ContinuousBatchingLoop(params, cfg0, pool, max_batch=4,
                                  speculate=3)
    results = loop.run([DecodeRequest(p, 14) for p in prompts])
    assert loop.rolled_back_tokens > 0  # this seed rolls back (pinned)
    kinds = [e["kind"] for e in obs.default_flight().events()]
    for kind in ("draft", "verify", "rollback"):
        assert kind in kinds, kinds
    ev = [e for e in obs.default_flight().events() if e["kind"] == "verify"]
    assert all("accepted" in e and "rejected" in e and "trace_id" in e
               for e in ev)
    # the sequence span carries the accepted/rejected annotation
    spans = [s for s in obs.default_tracer().spans()
             if s.name == "sequence"]
    annotated = [s for s in spans if "drafted" in s.args]
    assert annotated
    for s in annotated:
        assert s.args["drafted"] == s.args["accepted"] + s.args["rejected"]
    # the spec counter landed
    snap = obs.default_registry().to_prometheus()
    assert "paddle_tpu_serving_spec_tokens_total" in snap
    # every sequence still oracle-exact with the flag on
    for p, r in zip(prompts, results):
        assert r.tokens == full_decode(params, cfg0, p, 14)[0]


# ---------------------------------------------------------------------------
# (f) serve_bench scenarios + gate contract


def _bench_main(argv):
    sys.path.insert(0, os.path.abspath(REPO))
    try:
        from tools.serve_bench import main

        return main(argv)
    finally:
        sys.path.pop(0)


def test_serve_bench_speculate_smoke_and_gate(tmp_path, capsys):
    rc = _bench_main([
        "--mode", "decode", "--sequences", "6", "--max-new", "16",
        "--speculate", "4", "--prompt-range", "6,12", "--pages", "64",
        "--json", str(tmp_path / "out.json")])
    assert rc == 0
    out = json.loads((tmp_path / "out.json").read_text())
    capsys.readouterr()
    assert out["speculate"] == 4 and out["sampling"] == "greedy"
    assert out["acceptance_rate"] > 0
    assert out["drafted_tokens"] >= out["accepted_tokens"] > 0
    assert out["tokens_per_step"] > 1.0
    # the headline: tokens/s above the SAME invocation's d=0 arm
    assert out["tokens_per_s"] > out["tokens_per_s_d0"]
    assert out["spec_speedup"] > 1.0
    assert out["pages_leaked"] == 0
    # bank it and re-gate: the win is now held by CI
    bank = {k: out[k] for k in ("acceptance_rate", "tokens_per_step",
                                "spec_speedup", "pages_leaked")}
    bank_path = tmp_path / "SPEC_BANK.json"
    bank_path.write_text(json.dumps(bank))
    assert _bench_main([
        "--mode", "decode", "--sequences", "6", "--max-new", "16",
        "--speculate", "4", "--prompt-range", "6,12", "--pages", "64",
        "--baseline", str(bank_path), "--tol", "0.5", "--gate"]) == 0
    capsys.readouterr()
    # a regressed bank (impossible speedup) must exit 3
    bank_path.write_text(json.dumps({"spec_speedup": 99.0}))
    assert _bench_main([
        "--mode", "decode", "--sequences", "6", "--max-new", "16",
        "--speculate", "4", "--prompt-range", "6,12", "--pages", "64",
        "--baseline", str(bank_path), "--gate"]) == 3
    capsys.readouterr()


def test_serve_bench_sampled_speculation_smoke(tmp_path, capsys):
    """ISSUE 16: --speculate composes with a non-greedy --sampling —
    the exit-2 refusal is gone, rollbacks occur, nothing leaks, and
    the d=0 comparison arm still runs (the in-process replay-identity
    check already passed or the run would have exited 2)."""
    rc = _bench_main([
        "--mode", "decode", "--sequences", "6", "--max-new", "16",
        "--speculate", "3", "--sampling", "topk", "--pages", "96",
        "--page-size", "8", "--max-len", "96",
        "--json", str(tmp_path / "out.json")])
    capsys.readouterr()
    assert rc == 0
    out = json.loads((tmp_path / "out.json").read_text())
    assert out["sampling"] == "topk" and out["speculate"] == 3
    assert out["acceptance_rate"] > 0
    assert out["rolled_back_tokens"] > 0   # the epilogue rejected
    assert out["pages_leaked"] == 0
    assert out["spec_speedup"] > 0 and out["tokens_per_s_d0"] > 0


def test_serve_bench_mesh_speculation_smoke(tmp_path, capsys):
    """--speculate composes with --mesh: the SPMD program's multi-token
    verify runs the draft blocks and the d=0 arm compares mesh against
    mesh (greedy, so the token-identity check held in-process)."""
    rc = _bench_main([
        "--mode", "decode", "--sequences", "4", "--max-new", "10",
        "--mesh", "2", "--speculate", "2", "--pages", "64",
        "--page-size", "4", "--max-len", "48",
        "--json", str(tmp_path / "out.json")])
    capsys.readouterr()
    assert rc == 0
    out = json.loads((tmp_path / "out.json").read_text())
    assert out["mesh"] == 2 and out["speculate"] == 2
    assert out["acceptance_rate"] > 0
    assert out["pages_leaked"] == 0
    assert out["tokens_per_s_d0"] > 0


def test_serve_bench_corpus_drafted_prefix_share_smoke(tmp_path,
                                                      capsys):
    """Shared-prefix traffic drafts from the prefix cache's corpus: the
    acceptance rate on a --prefix-share arm sits far above what own-
    history lookup alone reaches on random prompts."""
    rc = _bench_main([
        "--mode", "decode", "--sequences", "6", "--max-new", "12",
        "--speculate", "3", "--prefix-share", "0.6", "--pages", "128",
        "--page-size", "8", "--max-len", "96",
        "--json", str(tmp_path / "out.json")])
    capsys.readouterr()
    assert rc == 0
    out = json.loads((tmp_path / "out.json").read_text())
    assert out["prefix_hit_rate"] > 0
    assert out["acceptance_rate"] > 0.5   # corpus-fed drafts land
    assert out["pages_leaked"] == 0


def test_serve_bench_sampling_scenario_smoke(tmp_path, capsys):
    rc = _bench_main([
        "--mode", "decode", "--sequences", "4", "--max-new", "8",
        "--sampling", "topp", "--json", str(tmp_path / "out.json")])
    capsys.readouterr()
    assert rc == 0
    out = json.loads((tmp_path / "out.json").read_text())
    assert out["sampling"] == "topp" and out["pages_leaked"] == 0


def test_serve_bench_speculate_usage_errors_exit_2(capsys):
    cases = [
        ["--mode", "engine", "--speculate", "2"],
        ["--mode", "decode", "--speculate", "-1"],
        ["--mode", "decode", "--speculate", "2", "--chaos"],
        ["--mode", "engine", "--sampling", "topk"],
    ]
    for argv in cases:
        assert _bench_main(argv) == 2, argv
        capsys.readouterr()


# ---------------------------------------------------------------------------
# (g) the banked zoo entry + known-bad corpus arm


def test_spec_verify_banked_under_2x_gqa_decode_with_coverage():
    from paddle_tpu import analysis

    with open(analysis.default_baseline_path()) as f:
        progs = json.load(f)["programs"]
    assert "spec_verify" in progs  # require_all coverage from here on
    spec = progs["spec_verify"]["bytes_per_step"]
    gqa = progs["gqa_decode"]["bytes_per_step"]
    assert spec < 2 * gqa, (spec, gqa)
    q_tokens = progs["spec_verify"]["config"]["q_tokens"]
    assert q_tokens == 5  # d = 4
    # >= 2x effective bytes-per-token reduction at full acceptance
    assert gqa / (spec / q_tokens) >= 2.0
    assert progs["spec_verify"]["findings"] == {}


def test_spec_verify_gather_corpus_trips_bytes_gate():
    """The known-bad arm: a verify step re-materializing the full
    [B,H,S,D] gather prices far above the banked page stream — the
    bytes gate (not a detector) is its teeth, end to end through
    lint_programs --inject ... --gate exiting 3."""
    from paddle_tpu import analysis
    from paddle_tpu.analysis.corpus import build_corpus_program

    pytest.importorskip("jax")
    art = build_corpus_program("spec_verify_gather")
    if art.compile_error:
        pytest.skip(f"AOT topology unavailable: {art.compile_error}")
    assert art.name == "spec_verify"  # deliberately the zoo entry's slot
    bad = analysis.ZooResult(
        name=art.name, artifacts=art, findings=[],
        bytes_per_step=art.bytes_per_step, flops_per_step=0.0)
    verdicts, failed = analysis.gate(
        [bad], analysis.default_baseline_path())
    assert failed
    v = [x for x in verdicts
         if x["metric"] == "spec_verify_aot_bytes_per_step"]
    assert v and v[0]["verdict"] == "fail"


def test_spec_verify_spmd_banked_under_require_all():
    """The mesh mirror of the spec_verify entry: the SPMD multi-token
    verify step is banked (require_all coverage — dropping it fails
    the lint gate) at the same q_tokens = 1 + d width, findings
    clean, on the 4-shard v5e topology."""
    from paddle_tpu import analysis

    with open(analysis.default_baseline_path()) as f:
        progs = json.load(f)["programs"]
    assert "spec_verify_spmd" in progs
    e = progs["spec_verify_spmd"]
    assert e["config"]["q_tokens"] == 5       # d = 4, Sq = 1 + d
    assert e["config"]["n_shards"] == 4
    assert e["config"]["impl"] == "pallas"
    assert e["findings"] == {}
    assert e["bytes_per_step"] > 0 and e["flops_per_step"] > 0


def test_spec_verify_spmd_gather_corpus_trips_bytes_gate():
    """The known-bad mesh arm: swapping the verify step's paged kernel
    for the reference gather re-materializes [B, H, S, D] per chip —
    at the banked 1024-token context that prices above the tolerance
    band and the bytes gate fails it in spec_verify_spmd's slot."""
    from paddle_tpu import analysis
    from paddle_tpu.analysis.corpus import build_corpus_program

    pytest.importorskip("jax")
    art = build_corpus_program("spec_verify_spmd_gather")
    if art.compile_error:
        pytest.skip(f"AOT topology unavailable: {art.compile_error}")
    assert art.name == "spec_verify_spmd"  # the zoo entry's slot
    bad = analysis.ZooResult(
        name=art.name, artifacts=art, findings=[],
        bytes_per_step=art.bytes_per_step, flops_per_step=0.0)
    verdicts, failed = analysis.gate(
        [bad], analysis.default_baseline_path())
    assert failed
    v = [x for x in verdicts
         if x["metric"] == "spec_verify_spmd_aot_bytes_per_step"]
    assert v and v[0]["verdict"] == "fail"


# ---------------------------------------------------------------------------
# (f) the incremental n-gram index (ROADMAP speculative item 3)


def test_drafter_incremental_index_parity_over_random_histories():
    """The per-sequence suffix index must answer EXACTLY like the
    stateless reversed scan at every point of a random commit/rollback
    history — the index is an accelerator, never a different oracle."""
    rng = np.random.RandomState(7)
    for trial in range(8):
        d = PromptLookupDrafter(max_draft=4, max_ngram=3)
        oracle = PromptLookupDrafter(max_draft=4, max_ngram=3)
        ctx = rng.randint(0, 5, size=rng.randint(2, 8)).tolist()
        for step in range(60):
            op = rng.rand()
            if op < 0.2 and len(ctx) > 3:
                # rollback: a verify step rejected some draft tokens
                ctx = ctx[:rng.randint(2, len(ctx))]
            else:
                ctx = ctx + rng.randint(0, 5,
                                        size=rng.randint(1, 4)).tolist()
            limit = int(rng.randint(1, 5))
            got = d.draft(ctx, limit, seq_id=trial)
            want = oracle.draft(ctx, limit)  # stateless scan
            assert got == want, (trial, step, ctx, limit, got, want)
            # the index re-synced to exactly the visible context
            assert d._index[trial].tokens == ctx


def test_drafter_rollback_rewinds_index_exactly():
    """truncate_seq rollbacks reach the drafter as a shorter/diverged
    context: the index must pop every n-gram the dead tokens registered
    (a stale occurrence would propose continuations from rolled-back
    text)."""
    d = PromptLookupDrafter(max_draft=4, max_ngram=3)
    # commit a history whose tail will be rolled back
    full = [1, 2, 3, 9, 9, 9, 1, 2, 3]
    assert d.draft(full, 4, seq_id=0) == [9, 9, 9, 1]
    idx = d._index[0]
    n_keys_full = len(idx.occ)
    # the verifier rejected everything after position 4, then committed
    # a different token — the next call's context diverges at 4
    rolled = full[:4] + [7]
    assert d.draft(rolled, 4, seq_id=0) == \
        PromptLookupDrafter(max_draft=4, max_ngram=3).draft(rolled, 4)
    assert idx.tokens == rolled
    assert len(idx.occ) < n_keys_full
    # no surviving occurrence may end past the new length
    for key, positions in idx.occ.items():
        for i in positions:
            assert i + len(key) <= len(rolled)
    # growing again after the rewind stays consistent
    grown = rolled + [1, 2, 3]
    assert d.draft(grown, 4, seq_id=0) == \
        PromptLookupDrafter(max_draft=4, max_ngram=3).draft(grown, 4)


def test_drafter_release_and_lru_cap_bound_host_memory():
    d = PromptLookupDrafter(max_draft=2, max_sequences=2)
    assert d.stateful  # the loop's seq_id/release protocol marker
    for sid in (10, 11, 12):
        d.draft([1, 2, 1, 2], 2, seq_id=sid)
    assert d.tracked_sequences() == 2  # LRU evicted the oldest
    assert 10 not in d._index and 12 in d._index
    d.release(11)
    assert d.tracked_sequences() == 1
    d.release(99)  # releasing an untracked id is a no-op
    # stateless calls never touch the index
    d.draft([1, 2, 1, 2], 2)
    assert d.tracked_sequences() == 1


def test_loop_releases_drafter_index_on_retirement():
    """The serving loop passes seq_id (the incremental path) and drops
    the index when a sequence retires — a long-lived engine must not
    grow one suffix map per request forever."""
    cfg = DecodeConfig(vocab_size=61, d_model=16, n_head=2, n_layer=1,
                       d_inner=32, max_length=64)
    params = init_decode_params(cfg, seed=2)
    rng = np.random.RandomState(2)
    prompts = [rng.randint(1, cfg.vocab_size, size=n).tolist()
               for n in (6, 9, 4)]
    pool = KVCachePool(num_pages=80, page_size=4, num_layers=cfg.n_layer,
                       num_heads=cfg.n_head, head_dim=cfg.head_dim)
    loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=3,
                                  speculate=3, check_every=1)
    assert loop.drafter.stateful
    results = loop.run([DecodeRequest(p, 10) for p in prompts])
    for p, res in zip(prompts, results):
        assert res.tokens == full_decode(params, cfg, p, 10)[0]
    assert loop.drafted_tokens > 0  # the indexed path actually drafted
    assert loop.drafter.tracked_sequences() == 0  # released on retire
