"""Flash attention kernel + ring attention (sequence parallelism).

Flash kernel runs in Pallas interpret mode on CPU (real kernel on TPU);
ring attention runs on the 8-device virtual CPU mesh."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.kernels import flash_attention
from paddle_tpu.kernels.flash_attention import _reference_attention
from paddle_tpu.longcontext import ring_attention, sequence_parallel_attention


def _rand_qkv(rng, B=2, H=2, S=64, D=16, Sk=None):
    Sk = Sk or S
    q = rng.standard_normal((B, H, S, D)).astype("float32")
    k = rng.standard_normal((B, H, Sk, D)).astype("float32")
    v = rng.standard_normal((B, H, Sk, D)).astype("float32")
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


def test_flash_interpret_matches_reference():
    rng = np.random.default_rng(0)
    q, k, v = _rand_qkv(rng, S=80, D=16)  # non-multiple of block => padding
    want = _reference_attention(q, k, v, False, 1 / math.sqrt(16))
    got = flash_attention(q, k, v, force="interpret")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_flash_interpret_causal():
    rng = np.random.default_rng(1)
    q, k, v = _rand_qkv(rng, S=64, D=8)
    want = _reference_attention(q, k, v, True, 1 / math.sqrt(8))
    got = flash_attention(q, k, v, causal=True, force="interpret")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_flash_grads_flow():
    rng = np.random.default_rng(2)
    q, k, v = _rand_qkv(rng, S=32, D=8)

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, force="jax") ** 2)

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    ref = jax.grad(
        lambda q, k, v: jnp.sum(
            _reference_attention(q, k, v, True, 1 / math.sqrt(8)) ** 2
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g, ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(causal):
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, axis_names=("sp",))
    rng = np.random.default_rng(3)
    B, H, S, D = 2, 2, 32, 8  # S sharded 4-way -> 8 tokens/device
    q, k, v = _rand_qkv(rng, B=B, H=H, S=S, D=D)

    want = _reference_attention(q, k, v, causal, 1 / math.sqrt(D))
    with mesh:
        got = sequence_parallel_attention(
            mesh, q, k, v, axis="sp", causal=causal, batch_axis=None
        )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5
    )


def test_ring_attention_with_dp_axis():
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, axis_names=("dp", "sp"))
    rng = np.random.default_rng(4)
    q, k, v = _rand_qkv(rng, B=4, H=2, S=16, D=8)
    want = _reference_attention(q, k, v, True, 1 / math.sqrt(8))
    with mesh:
        got = sequence_parallel_attention(
            mesh, q, k, v, axis="sp", causal=True, batch_axis="dp"
        )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_ring_attention_grads():
    from jax import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, axis_names=("sp",))
    rng = np.random.default_rng(5)
    q, k, v = _rand_qkv(rng, B=1, H=1, S=16, D=4)
    spec = P(None, None, "sp", None)

    def loss(q, k, v):
        with mesh:
            out = shard_map(
                lambda a, b, c: ring_attention(a, b, c, "sp", causal=True),
                mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
                check_vma=False,
            )(q, k, v)
        return jnp.sum(out ** 2)

    g = jax.grad(loss)(q, k, v)
    ref = jax.grad(
        lambda q: jnp.sum(
            _reference_attention(q, k, v, True, 1 / math.sqrt(4)) ** 2
        )
    )(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(ref), atol=1e-4)


def test_transformer_flash_matches_unfused():
    """Flash-attention transformer must produce ~the same loss as the
    bias-tensor formulation (dropout off, same params by construction)."""
    import paddle_tpu as fluid
    from paddle_tpu import models

    def build(flash):
        from paddle_tpu.core import framework, scope as scope_mod

        framework.switch_main_program(fluid.Program())
        framework.switch_startup_program(fluid.Program())
        scope_mod._current_scope = scope_mod.Scope()
        cfg = models.TransformerConfig(
            src_vocab_size=64, trg_vocab_size=64, max_length=16,
            n_layer=1, n_head=2, d_model=16, d_inner=32, dropout=0.0,
            use_flash_attention=flash,
        )
        spec = models.transformer(cfg)
        exe = fluid.Executor(fluid.CPUPlace())
        fluid.default_startup_program().random_seed = 7
        exe.run(fluid.default_startup_program())
        batch = spec.synthetic_batch(4)
        (lv,) = exe.run(feed=batch, fetch_list=[spec.loss])
        return float(np.ravel(np.asarray(lv))[0])

    base = build(False)
    flash = build(True)
    assert abs(base - flash) / abs(base) < 1e-3


def test_flash_causal_cross_length():
    # Sq != Sk (cached-decode shape): bottom-right-aligned causal mask must
    # match the reference in kernel (interpret) mode
    rng = np.random.default_rng(6)
    q, k, v = _rand_qkv(rng, B=1, H=1, S=4, D=8, Sk=12)
    want = _reference_attention(q, k, v, True, 1 / math.sqrt(8))
    got = flash_attention(q, k, v, causal=True, force="interpret")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


@pytest.mark.parametrize("force", ["jax", "interpret"])
def test_flash_empty_sequence_is_zero(force):
    """Both backends must agree: a zero-length row attends to nothing and
    outputs zeros (the pallas kernel's running-max floor guards this — an
    m floor of NEG_INF would make masked p = exp(0) = 1 and average V)."""
    rng = np.random.default_rng(7)
    q, k, v = _rand_qkv(rng, B=2, H=1, S=8, D=4)
    out = flash_attention(q, k, v, k_lengths=jnp.asarray([0, 8]), force=force)
    np.testing.assert_allclose(np.asarray(out)[0], 0.0)
    assert np.abs(np.asarray(out)[1]).sum() > 0


# -- pallas backward kernels (round 3: dq/dkv kernels replace the dense
#    recompute backward) -------------------------------------------------

def _grad_pair(q, k, v, causal=False, k_lengths=None, Dh=None):
    """(pallas-interpret grads, jax-reference grads) for sum(out * w)."""
    Dh = Dh or q.shape[-1]
    scale = 1.0 / math.sqrt(Dh)
    w = jnp.asarray(
        np.random.default_rng(99).standard_normal(q.shape[:3] + (q.shape[-1],))
        .astype("float32"))

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, causal=causal, k_lengths=k_lengths,
                              force="interpret")
        return jnp.sum(out * w)

    def loss_ref(q, k, v):
        kl = (jnp.asarray(k_lengths, jnp.int32)
              if k_lengths is not None else None)
        out = _reference_attention(q, k, v, causal, scale, k_lengths=kl)
        return jnp.sum(out * w)

    return jax.grad(loss_flash, (0, 1, 2))(q, k, v), \
        jax.grad(loss_ref, (0, 1, 2))(q, k, v)


def _assert_grads_close(got, want, atol=2e-4):
    for g, r, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(r), atol=atol,
            err_msg=f"d{name} mismatch")


def test_flash_bwd_matches_reference():
    rng = np.random.default_rng(5)
    q, k, v = _rand_qkv(rng, S=64, D=16)
    _assert_grads_close(*_grad_pair(q, k, v))


def test_flash_bwd_causal_padded_seq():
    rng = np.random.default_rng(6)
    # S=80 is not a block multiple: exercises padded q rows (zero dO) and
    # padded k columns in the backward kernels
    q, k, v = _rand_qkv(rng, S=80, D=16)
    _assert_grads_close(*_grad_pair(q, k, v, causal=True))


def test_flash_bwd_key_padding():
    rng = np.random.default_rng(7)
    q, k, v = _rand_qkv(rng, B=3, S=64, D=8)
    lens = np.array([64, 17, 1], np.int32)
    got, want = _grad_pair(q, k, v, k_lengths=lens)
    _assert_grads_close(got, want)
    # keys past each row's length must receive exactly zero grad
    for b, n in enumerate(lens):
        if n < q.shape[2]:
            assert np.abs(np.asarray(got[1])[b, :, n:]).max() == 0
            assert np.abs(np.asarray(got[2])[b, :, n:]).max() == 0


def test_flash_bwd_cross_attention_lengths():
    rng = np.random.default_rng(8)
    q, k, v = _rand_qkv(rng, S=32, Sk=96, D=16)
    _assert_grads_close(*_grad_pair(q, k, v, causal=True))


def test_flash_bwd_bf16_inputs():
    rng = np.random.default_rng(9)
    q, k, v = _rand_qkv(rng, S=64, D=16)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    got, _ = _grad_pair(qb, kb, vb)
    _, want = _grad_pair(q, k, v)
    for g, r, name in zip(got, want, "qkv"):
        assert g.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(g, dtype=np.float32), np.asarray(r), atol=0.15,
            rtol=0.1, err_msg=f"d{name} bf16 drift")


# -- zigzag (load-balanced) causal context parallelism --------------------

def test_zigzag_permutation_roundtrip():
    from paddle_tpu.longcontext import zigzag_permutation

    perm, inv = zigzag_permutation(16, 4)
    x = np.arange(16)
    np.testing.assert_array_equal(x[perm][inv], x)
    # device 0 holds chunks 0 and 7, device 3 holds chunks 3 and 4
    np.testing.assert_array_equal(perm[:4], [0, 1, 14, 15])
    np.testing.assert_array_equal(perm[-4:], [6, 7, 8, 9])


def test_zigzag_ring_matches_full_causal():
    from paddle_tpu.longcontext import zigzag_sequence_parallel_attention
    from paddle_tpu.parallel import make_mesh

    rng = np.random.default_rng(3)
    q, k, v = _rand_qkv(rng, B=2, H=2, S=32, D=8)
    mesh = make_mesh({"sp": 4}, devices=jax.devices()[:4])
    got = zigzag_sequence_parallel_attention(mesh, q, k, v, batch_axis=None)
    want = _reference_attention(q, k, v, True, 1 / math.sqrt(8))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_zigzag_ring_grads():
    from paddle_tpu.longcontext import zigzag_sequence_parallel_attention
    from paddle_tpu.parallel import make_mesh

    rng = np.random.default_rng(4)
    q, k, v = _rand_qkv(rng, B=1, H=2, S=16, D=4)
    mesh = make_mesh({"sp": 4}, devices=jax.devices()[:4])
    w = jnp.asarray(rng.standard_normal(q.shape).astype("float32"))

    def loss_z(q, k, v):
        return jnp.sum(
            zigzag_sequence_parallel_attention(mesh, q, k, v,
                                               batch_axis=None) * w)

    def loss_ref(q, k, v):
        return jnp.sum(
            _reference_attention(q, k, v, True, 1 / math.sqrt(4)) * w)

    gz = jax.grad(loss_z, (0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, (0, 1, 2))(q, k, v)
    for a, b, name in zip(gz, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4,
                                   err_msg=f"d{name}")


# -- ulysses (all-to-all) sequence parallelism -------------------------------

@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_full(causal):
    from jax.sharding import Mesh

    from paddle_tpu.longcontext import ulysses_sequence_parallel_attention

    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, axis_names=("sp",))
    rng = np.random.default_rng(11)
    B, H, S, D = 2, 4, 32, 8  # H=4 divisible by the 4-way sp axis
    q, k, v = _rand_qkv(rng, B=B, H=H, S=S, D=D)

    want = _reference_attention(q, k, v, causal, 1 / math.sqrt(D))
    with mesh:
        got = ulysses_sequence_parallel_attention(
            mesh, q, k, v, axis="sp", causal=causal, batch_axis=None
        )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_ulysses_attention_with_dp_axis():
    from jax.sharding import Mesh

    from paddle_tpu.longcontext import ulysses_sequence_parallel_attention

    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, axis_names=("dp", "sp"))
    rng = np.random.default_rng(12)
    q, k, v = _rand_qkv(rng, B=4, H=4, S=16, D=8)
    want = _reference_attention(q, k, v, True, 1 / math.sqrt(8))
    with mesh:
        got = ulysses_sequence_parallel_attention(
            mesh, q, k, v, axis="sp", causal=True, batch_axis="dp"
        )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_ulysses_attention_grads():
    from jax.sharding import Mesh

    from paddle_tpu.longcontext import ulysses_sequence_parallel_attention

    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, axis_names=("sp",))
    rng = np.random.default_rng(13)
    q, k, v = _rand_qkv(rng, B=1, H=4, S=16, D=4)

    def loss(q, k, v):
        with mesh:
            out = ulysses_sequence_parallel_attention(
                mesh, q, k, v, axis="sp", causal=True, batch_axis=None
            )
        return jnp.sum(out ** 2)

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    ref = jax.grad(
        lambda q, k, v: jnp.sum(
            _reference_attention(q, k, v, True, 1 / math.sqrt(4)) ** 2
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g, ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_ulysses_attention_head_divisibility_error():
    from jax.sharding import Mesh

    from paddle_tpu.longcontext import ulysses_sequence_parallel_attention

    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, axis_names=("sp",))
    rng = np.random.default_rng(14)
    q, k, v = _rand_qkv(rng, B=1, H=3, S=16, D=4)  # 3 heads, 4-way axis
    with pytest.raises(ValueError, match="divisible"):
        ulysses_sequence_parallel_attention(mesh, q, k, v, axis="sp")


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_blockwise_key_blocks(causal):
    """block_k smaller than (and not dividing) the sequence exercises the
    online-softmax block loop and the padded final key block."""
    from jax.sharding import Mesh

    from paddle_tpu.longcontext import ulysses_sequence_parallel_attention

    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, axis_names=("sp",))
    rng = np.random.default_rng(14)
    B, H, S, D = 2, 4, 24, 8  # S=24 with block_k=7 -> 4 blocks, 4 pad slots
    q, k, v = _rand_qkv(rng, B=B, H=H, S=S, D=D)

    want = _reference_attention(q, k, v, causal, 1 / math.sqrt(D))
    with mesh:
        got = ulysses_sequence_parallel_attention(
            mesh, q, k, v, axis="sp", causal=causal, batch_axis=None,
            block_k=7,
        )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_ulysses_blockwise_grads():
    from jax.sharding import Mesh

    from paddle_tpu.longcontext import ulysses_sequence_parallel_attention

    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, axis_names=("sp",))
    rng = np.random.default_rng(15)
    q, k, v = _rand_qkv(rng, B=1, H=4, S=16, D=4)

    def loss(q, k, v):
        with mesh:
            out = ulysses_sequence_parallel_attention(
                mesh, q, k, v, axis="sp", causal=True, batch_axis=None,
                block_k=5,
            )
        return jnp.sum(out ** 2)

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    ref = jax.grad(
        lambda q, k, v: jnp.sum(
            _reference_attention(q, k, v, True, 1 / math.sqrt(4)) ** 2
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g, ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_longcontext_s512_sp8_all_variants():
    """Beyond-toy shape on the full 8-way sp mesh: S=512 (64 tokens per
    device), causal, all three sequence-parallel variants against the
    dense reference — plus gradient parity for the zigzag form (the
    load-balanced one the long-context bench uses)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.longcontext import (
        sequence_parallel_attention,
        ulysses_sequence_parallel_attention,
        zigzag_sequence_parallel_attention,
    )
    from paddle_tpu.parallel import make_mesh

    mesh = make_mesh({"sp": 8})
    B, H, S, D = 1, 8, 512, 16
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, H, S, D), jnp.float32) * 0.3
    k = jnp.asarray(rng.randn(B, H, S, D), jnp.float32) * 0.3
    v = jnp.asarray(rng.randn(B, H, S, D), jnp.float32)

    scale = 1.0 / np.sqrt(D)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    mask = np.tril(np.ones((S, S), bool))
    s = jnp.where(mask[None, None], s, -1e30)
    ref = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), v)

    ring = sequence_parallel_attention(mesh, q, k, v, causal=True,
                                       batch_axis=None)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)

    uly = ulysses_sequence_parallel_attention(mesh, q, k, v, causal=True,
                                              batch_axis=None)
    np.testing.assert_allclose(np.asarray(uly), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)

    # the zigzag wrapper permutes internally: global-view in, global-view out
    zig = zigzag_sequence_parallel_attention(mesh, q, k, v, batch_axis=None)
    np.testing.assert_allclose(np.asarray(zig), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)

    # gradient parity at the same scale for the zigzag form
    def loss_zig(q_, k_, v_):
        o = zigzag_sequence_parallel_attention(mesh, q_, k_, v_,
                                               batch_axis=None)
        return jnp.sum(o * o)

    def loss_ref(q_, k_, v_):
        s_ = jnp.einsum("bhqd,bhkd->bhqk", q_, k_) * scale
        s_ = jnp.where(mask[None, None], s_, -1e30)
        o = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s_, axis=-1), v_)
        return jnp.sum(o * o)

    gz = jax.grad(loss_zig, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gz, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-3, atol=3e-4)
