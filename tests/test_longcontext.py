"""Flash attention kernel + ring attention (sequence parallelism).

Flash kernel runs in Pallas interpret mode on CPU (real kernel on TPU);
ring attention runs on the 8-device virtual CPU mesh."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.kernels import flash_attention
from paddle_tpu.kernels.flash_attention import _reference_attention
from paddle_tpu.longcontext import ring_attention, sequence_parallel_attention


def _rand_qkv(rng, B=2, H=2, S=64, D=16, Sk=None):
    Sk = Sk or S
    q = rng.standard_normal((B, H, S, D)).astype("float32")
    k = rng.standard_normal((B, H, Sk, D)).astype("float32")
    v = rng.standard_normal((B, H, Sk, D)).astype("float32")
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


def test_flash_interpret_matches_reference():
    rng = np.random.default_rng(0)
    q, k, v = _rand_qkv(rng, S=80, D=16)  # non-multiple of block => padding
    want = _reference_attention(q, k, v, False, 1 / math.sqrt(16))
    got = flash_attention(q, k, v, force="interpret")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_flash_interpret_causal():
    rng = np.random.default_rng(1)
    q, k, v = _rand_qkv(rng, S=64, D=8)
    want = _reference_attention(q, k, v, True, 1 / math.sqrt(8))
    got = flash_attention(q, k, v, causal=True, force="interpret")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_flash_grads_flow():
    rng = np.random.default_rng(2)
    q, k, v = _rand_qkv(rng, S=32, D=8)

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, force="jax") ** 2)

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    ref = jax.grad(
        lambda q, k, v: jnp.sum(
            _reference_attention(q, k, v, True, 1 / math.sqrt(8)) ** 2
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g, ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(causal):
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, axis_names=("sp",))
    rng = np.random.default_rng(3)
    B, H, S, D = 2, 2, 32, 8  # S sharded 4-way -> 8 tokens/device
    q, k, v = _rand_qkv(rng, B=B, H=H, S=S, D=D)

    want = _reference_attention(q, k, v, causal, 1 / math.sqrt(D))
    with mesh:
        got = sequence_parallel_attention(
            mesh, q, k, v, axis="sp", causal=causal, batch_axis=None
        )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5
    )


def test_ring_attention_with_dp_axis():
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, axis_names=("dp", "sp"))
    rng = np.random.default_rng(4)
    q, k, v = _rand_qkv(rng, B=4, H=2, S=16, D=8)
    want = _reference_attention(q, k, v, True, 1 / math.sqrt(8))
    with mesh:
        got = sequence_parallel_attention(
            mesh, q, k, v, axis="sp", causal=True, batch_axis="dp"
        )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_ring_attention_grads():
    from jax import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, axis_names=("sp",))
    rng = np.random.default_rng(5)
    q, k, v = _rand_qkv(rng, B=1, H=1, S=16, D=4)
    spec = P(None, None, "sp", None)

    def loss(q, k, v):
        with mesh:
            out = shard_map(
                lambda a, b, c: ring_attention(a, b, c, "sp", causal=True),
                mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
                check_vma=False,
            )(q, k, v)
        return jnp.sum(out ** 2)

    g = jax.grad(loss)(q, k, v)
    ref = jax.grad(
        lambda q: jnp.sum(
            _reference_attention(q, k, v, True, 1 / math.sqrt(4)) ** 2
        )
    )(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(ref), atol=1e-4)


def test_transformer_flash_matches_unfused():
    """Flash-attention transformer must produce ~the same loss as the
    bias-tensor formulation (dropout off, same params by construction)."""
    import paddle_tpu as fluid
    from paddle_tpu import models

    def build(flash):
        from paddle_tpu.core import framework, scope as scope_mod

        framework.switch_main_program(fluid.Program())
        framework.switch_startup_program(fluid.Program())
        scope_mod._current_scope = scope_mod.Scope()
        cfg = models.TransformerConfig(
            src_vocab_size=64, trg_vocab_size=64, max_length=16,
            n_layer=1, n_head=2, d_model=16, d_inner=32, dropout=0.0,
            use_flash_attention=flash,
        )
        spec = models.transformer(cfg)
        exe = fluid.Executor(fluid.CPUPlace())
        fluid.default_startup_program().random_seed = 7
        exe.run(fluid.default_startup_program())
        batch = spec.synthetic_batch(4)
        (lv,) = exe.run(feed=batch, fetch_list=[spec.loss])
        return float(np.ravel(np.asarray(lv))[0])

    base = build(False)
    flash = build(True)
    assert abs(base - flash) / abs(base) < 1e-3


def test_flash_causal_cross_length():
    # Sq != Sk (cached-decode shape): bottom-right-aligned causal mask must
    # match the reference in kernel (interpret) mode
    rng = np.random.default_rng(6)
    q, k, v = _rand_qkv(rng, B=1, H=1, S=4, D=8, Sk=12)
    want = _reference_attention(q, k, v, True, 1 / math.sqrt(8))
    got = flash_attention(q, k, v, causal=True, force="interpret")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_flash_empty_sequence_is_zero():
    rng = np.random.default_rng(7)
    q, k, v = _rand_qkv(rng, B=2, H=1, S=8, D=4)
    out = flash_attention(q, k, v, k_lengths=jnp.asarray([0, 8]), force="jax")
    np.testing.assert_allclose(np.asarray(out)[0], 0.0)
    assert np.abs(np.asarray(out)[1]).sum() > 0
