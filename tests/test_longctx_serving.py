"""Long-context serving (ISSUE 20).

(a) sliding-window + attention-sink decode: the windowed paged loop is
    TOKEN-IDENTICAL to the full_decode oracle under the same
    page-granular mask, across GQA x two-level-tables x int8 x
    prefix-hit x speculation arms, with interior pages actually
    evicted and nothing leaked;
(b) the two-level page-table view round-trips every pool mutation the
    flat view does (eviction, CoW, defrag, truncate, export/import)
    — ``flatten()`` must equal ``page_tables_with_starts`` after each;
(c) eviction vs readers: a dropped interior page another holder still
    reads RELEASES this sequence's hold, never frees;
(d) tiered-KV spill staging (D2H copy) runs OUTSIDE the pool lock — a
    concurrent append must not serialize behind a parking export;
(e) compute-budgeted chunked prefill: ``plan_chunks`` prices a chunk
    by estimated attention work (quadratic in resident prefix), the
    head never starves, both budgets compose;
(f) the SMEM linter prices the flat ~1k-page table out of scalar
    memory and the two-level view back in — from the traced jaxpr,
    no chip, no AOT client;
(g) the acceptance arithmetic: under the same window+sinks, a 128k
    context's decode bytes/step (priced over WALKED post-eviction
    pages) stays within 1.15x of 8k's.
"""

import functools
import threading
import time

import numpy as np
import pytest

from paddle_tpu.kernels.paged_attention import (
    PAD_START,
    TwoLevelTables,
    attention_bytes_per_step,
    paged_decode_attention,
)
from paddle_tpu.serving.generate import (
    ContinuousBatchingLoop,
    DecodeConfig,
    DecodeRequest,
    chunk_prefill_step,
    full_decode,
    init_decode_params,
)
from paddle_tpu.serving.kvcache import KVCachePool
from paddle_tpu.serving.prefill_sched import plan_chunks

# -- (a) windowed decode parity matrix ----------------------------------

PS = 4
WIN, SNK = 8, 4
MAX_NEW = 16
CFG = DecodeConfig(vocab_size=64, d_model=32, n_head=4, n_kv_head=2,
                   n_layer=2, max_length=96, eos_id=None)
PARAMS = init_decode_params(CFG, seed=0)
_rng = np.random.default_rng(1)
PROMPTS = tuple(tuple(int(t) for t in _rng.integers(0, 64, n))
                for n in (12, 7, 20))


@functools.lru_cache(maxsize=None)
def _oracle(window, sinks):
    kw = ({"window": window, "sinks": sinks, "page_size": PS}
          if window else {})
    return tuple(tuple(full_decode(PARAMS, CFG, list(p), MAX_NEW, **kw)[0])
                 for p in PROMPTS)


@functools.lru_cache(maxsize=None)
def _arm(window=None, sinks=0, dtype="float32", speculate=0,
         table_block=None):
    """One loop replay; returns (tokens, pages_evicted, drafted)."""
    pool = KVCachePool(num_pages=256, page_size=PS, num_layers=CFG.n_layer,
                       num_heads=CFG.n_head, head_dim=CFG.head_dim,
                       num_kv_heads=CFG.n_kv_head, dtype=dtype)
    loop = ContinuousBatchingLoop(PARAMS, CFG, pool, max_batch=3,
                                  speculate=speculate,
                                  table_block=table_block, check_every=1)
    res = loop.run([DecodeRequest(list(p), MAX_NEW, window=window,
                                  sinks=sinks) for p in PROMPTS])
    rep = pool.check_invariants()
    assert rep["ok"], rep
    assert rep["used_pages"] == 0, rep
    return (tuple(tuple(r.tokens) for r in res), loop.pages_evicted,
            loop.drafted_tokens)


def test_unwindowed_decode_matches_oracle():
    toks, evicted, _ = _arm()
    assert toks == _oracle(None, 0)
    assert evicted == 0


def test_windowed_decode_matches_masked_oracle_and_evicts():
    toks, evicted, _ = _arm(window=WIN, sinks=SNK)
    assert toks == _oracle(WIN, SNK)
    assert evicted > 0


def test_windowed_two_level_tables_token_identical():
    toks, evicted, _ = _arm(window=WIN, sinks=SNK, table_block=2)
    assert toks == _arm(window=WIN, sinks=SNK)[0]
    assert evicted == _arm(window=WIN, sinks=SNK)[1]


def test_windowed_speculation_token_identical():
    toks, _, drafted = _arm(window=WIN, sinks=SNK, speculate=3)
    assert toks == _arm(window=WIN, sinks=SNK)[0]
    assert drafted > 0  # speculation really ran under the window


def test_windowed_int8_flat_equals_two_level():
    # int8 re-quantizes per page so the fp32 oracle is only close; the
    # flat and two-level views of the SAME quantized pool must still be
    # bit-identical — they gather identical pages
    assert (_arm(window=WIN, sinks=SNK, dtype="int8")[0]
            == _arm(window=WIN, sinks=SNK, dtype="int8", table_block=4)[0])


def test_windowed_prefix_hit_token_identical():
    from paddle_tpu.serving.prefixcache import PrefixCache

    pool = KVCachePool(num_pages=256, page_size=PS, num_layers=CFG.n_layer,
                       num_heads=CFG.n_head, head_dim=CFG.head_dim,
                       num_kv_heads=CFG.n_kv_head)
    loop = ContinuousBatchingLoop(PARAMS, CFG, pool, max_batch=2,
                                  prefix_cache=PrefixCache(pool),
                                  check_every=1)
    base = list(PROMPTS[2])
    r1 = loop.run([DecodeRequest(base, 10, window=WIN, sinks=SNK)])
    r2 = loop.run([DecodeRequest(base, 10, window=WIN, sinks=SNK)])
    assert loop.prefix_hits >= 1
    oracle, _ = full_decode(PARAMS, CFG, base, 10, window=WIN, sinks=SNK,
                            page_size=PS)
    assert r1[0].tokens == oracle and r2[0].tokens == oracle
    assert pool.check_invariants()["ok"]


# -- (b) two-level table view round-trips pool mutations ----------------

def _mk_pool(n=64, name="t"):
    return KVCachePool(num_pages=n, page_size=PS, num_layers=2,
                       num_heads=2, head_dim=8, name=name)


def _views_agree(pool, seq_ids, block_size=2):
    """flatten() of the two-level view must equal the flat view."""
    t, st, ln = pool.page_tables_with_starts(seq_ids)
    tl, ln2 = pool.two_level_tables(seq_ids, block_size=block_size)
    ft, fs = (np.asarray(a) for a in tl.flatten())
    np.testing.assert_array_equal(np.asarray(ln), np.asarray(ln2))
    for i, s in enumerate(seq_ids):
        live = len(pool._tables[s].pages)
        np.testing.assert_array_equal(ft[i, :live], np.asarray(t)[i, :live])
        np.testing.assert_array_equal(fs[i, :live], np.asarray(st)[i, :live])
        assert (fs[i, live:] == PAD_START).all()


def test_two_level_view_tracks_eviction_append_truncate():
    pool = _mk_pool()
    pool.allocate(0)
    pool.append_tokens([0], [30])
    pool.evict_interior(0, window=6, sinks=4)
    pool.append_tokens([0], [2])
    pool.append_tokens([0], [5])
    pool.truncate_seq(0, 34)
    pool.allocate(1)
    pool.append_tokens([1], [5])  # short row: pads with the shared block
    t, st, ln = pool.page_tables_with_starts([0, 1])
    assert list(st[0]) == [0, 24, 28, 32]
    assert list(st[1]) == [0, 4, PAD_START, PAD_START]
    _views_agree(pool, [0, 1])
    assert pool.check_invariants()["ok"]


def test_two_level_view_tracks_cow_and_defrag():
    pool = _mk_pool(n=16)
    pool.allocate(0)
    pg, sl = pool.append_tokens([0], [6])  # page 2 half-filled
    k = np.arange(6 * 2 * 8, dtype=np.float32).reshape(6, 2, 8)
    pool.write_kv(0, pg, sl, k, k)
    # share all of 0's pages into 1, then diverge: the shared
    # partially-filled tail page must copy-on-write
    pool.allocate(1)
    pool.attach_prefix(1, pool._tables[0].pages, 6)
    _views_agree(pool, [0, 1])
    tail_before = pool._tables[1].pages[-1]
    pool.append_tokens([1], [3])
    assert pool._tables[1].pages[-1] != tail_before  # CoW happened
    assert pool._tables[0].pages[-1] == tail_before
    _views_agree(pool, [0, 1])
    # punch a hole and defrag: pages remap, both views must follow
    pool.allocate(2)
    pool.append_tokens([2], [8])
    pool.free_seq(0)
    assert pool.defrag() > 0
    _views_agree(pool, [1, 2])
    assert pool.check_invariants()["ok"]


def test_export_import_preserves_evicted_starts():
    pool = _mk_pool()
    pool.allocate(0)
    pool.append_tokens([0], [30])
    pool.evict_interior(0, window=6, sinks=4)
    pool.append_tokens([0], [7])
    pool.truncate_seq(0, 34)
    exp = pool.export_seq(0)
    assert exp.starts == [0, 24, 28, 32]
    dst = _mk_pool(n=32, name="dst")
    dst.allocate(7)
    dst.import_seq(exp, 7)
    h = dst._tables[7]
    assert h.starts == [0, 24, 28, 32] and h.length == 34
    # appends on the imported, evicted table keep extending starts
    dst.append_tokens([7], [3])
    assert h.length == 37 and h.starts == [0, 24, 28, 32, 36]
    _views_agree(dst, [7])
    assert dst.check_invariants()["ok"]


# -- (c) eviction vs readers --------------------------------------------

def test_evicted_shared_page_releases_never_frees():
    pool = _mk_pool()
    pool.allocate(0)
    pool.append_tokens([0], [30])
    h = pool._tables[0]
    pool.evict_interior(0, window=6, sinks=4)
    pool.append_tokens([0], [7])
    pool.truncate_seq(0, 34)
    # pin one kept page like the prefix cache would (hold + owner hook
    # so check_invariants can explain the extra refcount)
    pinned = h.pages[1]  # starts at 24: a tighter window drops it
    pins = {pinned: 1}
    pool.register_owner(lambda: pins)
    pool.retain_pages([pinned])
    pool.evict_interior(0, window=2, sinks=0)
    assert pinned not in h.pages  # dropped from THIS table...
    assert pool.refcount(pinned) == 1  # ...but the reader keeps it live
    assert pinned not in pool._free
    assert pool.check_invariants()["ok"]
    pins.clear()
    pool.release_pages([pinned])
    assert pool.refcount(pinned) == 0
    pool.free_seq(0)
    rep = pool.check_invariants()
    assert rep["ok"] and rep["used_pages"] == 0, rep


def test_int8_eviction_clears_dropped_scales():
    pool = KVCachePool(num_pages=16, page_size=PS, num_layers=1,
                       num_heads=2, head_dim=8, dtype="int8", name="q")
    pool.allocate(0)
    pg, sl = pool.append_tokens([0], [16])
    rng = np.random.default_rng(0)
    pool.write_kv(0, pg, sl, rng.standard_normal((16, 2, 8), np.float32),
                  rng.standard_normal((16, 2, 8), np.float32))
    h = pool._tables[0]
    dropped = [p for p, st in zip(h.pages, range(0, 16, PS))
               if st >= PS and st + PS <= 16 - 2]
    assert dropped
    pool.evict_interior(0, window=2, sinks=4)
    for p in dropped:  # freed pages must not leave stale scales behind
        assert pool.k_scales[0, p] == 0.0 and pool.v_scales[0, p] == 0.0
    assert pool.check_invariants()["ok"]


# -- (d) spill staging off the pool lock --------------------------------

def test_export_d2h_stage_does_not_block_appends():
    pool = KVCachePool(num_pages=64, page_size=PS, num_layers=1,
                       num_heads=2, head_dim=8, num_kv_heads=2)
    pool.allocate(1)
    pool.append_tokens([1], [12])
    pool.allocate(2)
    pool.append_tokens([2], [4])
    gate, entered = threading.Event(), threading.Event()
    orig = pool._stage_d2h

    def slow(k_src, v_src, idx):
        entered.set()
        assert gate.wait(10), "gate never opened"
        return orig(k_src, v_src, idx)

    pool._stage_d2h = slow
    out = {}
    t = threading.Thread(target=lambda: out.update(e=pool.export_seq(1)))
    t.start()
    try:
        assert entered.wait(10)
        # export is parked mid-D2H: an append on ANOTHER sequence must
        # not serialize behind it
        t0 = time.perf_counter()
        pool.append_tokens([2], [4])
        dt = time.perf_counter() - t0
        assert dt < 1.0, f"append serialized behind export: {dt}s"
    finally:
        gate.set()
        t.join(10)
    assert out["e"].length == 12  # the parked export still lands whole
    assert pool.check_invariants()["ok"]


# -- (e) compute-budgeted chunk planning --------------------------------

def test_plan_chunks_flop_budget_arithmetic():
    # pos 0, budget 50: n*(0 + n/2) <= 50 -> n = 10
    _, ch, _ = plan_chunks([[1] * 100], [0], 0, flop_budget=50.0)
    assert len(ch[0]) == 10
    # deep prefix: the quadratic term shrinks the chunk, head gets >= 1
    _, ch, _ = plan_chunks([[1] * 100], [90], 0, flop_budget=5.0)
    assert len(ch[0]) == 1
    # the token cap composes and binds where tighter
    _, ch, _ = plan_chunks([[1] * 50, [2] * 50], [0, 0], 8, flop_budget=1e9)
    assert [len(c) for c in ch] == [8]
    with pytest.raises(ValueError):
        plan_chunks([[1]], [0], 0, flop_budget=0)


def test_prefill_flops_loop_parity_with_and_without_window():
    cfg = DecodeConfig(vocab_size=64, d_model=32, n_head=4, n_kv_head=2,
                       n_layer=2, max_length=128, eos_id=None)
    params = init_decode_params(cfg, seed=0)
    rng = np.random.default_rng(2)
    prompts = [list(rng.integers(0, 64, 40)), list(rng.integers(0, 64, 25))]

    def run(**req_kw):
        pool = KVCachePool(num_pages=256, page_size=PS,
                           num_layers=cfg.n_layer, num_heads=cfg.n_head,
                           head_dim=cfg.head_dim,
                           num_kv_heads=cfg.n_kv_head)
        loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=2,
                                      prefill_chunk=16, prefill_flops=200.0,
                                      check_every=1)
        return loop, loop.run([DecodeRequest(p, 12, **req_kw)
                               for p in prompts])

    loop, res = run()
    for p, r in zip(prompts, res):
        assert r.tokens == full_decode(params, cfg, p, 12)[0]
    assert loop.decode_step_p99_during_prefill_s() >= 0.0
    loop, res = run(window=WIN, sinks=SNK)
    for p, r in zip(prompts, res):
        assert r.tokens == full_decode(params, cfg, p, 12, window=WIN,
                                       sinks=SNK, page_size=PS)[0]
    assert loop.pages_evicted > 0


def test_longctx_validation_errors():
    pool = _mk_pool()
    sid = 7
    pool.allocate(sid)
    pool.append_tokens([sid], [24])
    pool.evict_interior(sid, window=6, sinks=4)
    cfg = DecodeConfig(vocab_size=64, d_model=16, n_head=2, n_layer=2,
                       d_inner=32, max_length=64)
    params = init_decode_params(cfg, seed=0)
    # chunk-prefill can never extend a window-evicted table: the chunk's
    # queries would attend a prefix that is no longer resident
    with pytest.raises(ValueError, match="window-evicted"):
        chunk_prefill_step(params, cfg, pool, [sid], [[1, 2, 3]], [24])
    # a FLOP budget without chunked prefill has nothing to budget
    with pytest.raises(ValueError, match="prefill_chunk"):
        ContinuousBatchingLoop(params, cfg, pool, prefill_flops=100.0)
    for bad in (DecodeRequest([1, 2, 3], 4, window=0),
                DecodeRequest([1, 2, 3], 4, sinks=2)):  # sinks w/o window
        with pytest.raises(ValueError):
            ContinuousBatchingLoop(params, cfg, pool,
                                   max_batch=1).run([bad])


# -- (f) SMEM pricing: flat ~1k-page tables out, two-level in -----------

def _smem_art(two_level):
    """Trace the longctx decode shape (B=4, 1024 pages/seq, int8) into a
    bare ProgramArtifacts — jaxpr-only, so the detector needs no AOT
    client and no chip."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.analysis.capture import ProgramArtifacts

    B, Hq, Hkv, D, ps, maxp = 4, 8, 2, 128, 32, 1024
    P = 16384  # POOL pages — the flat path's [P] scale rows ride SMEM
    q = jax.ShapeDtypeStruct((B, Hq, 1, D), jnp.float32)
    kp = jax.ShapeDtypeStruct((Hkv, P, ps, D), jnp.int8)
    ln = jax.ShapeDtypeStruct((B,), jnp.int32)
    sc = jax.ShapeDtypeStruct((P,), jnp.float32)
    if two_level:
        bs = 128
        n_blocks = B * (maxp // bs) + 1
        l1 = jax.ShapeDtypeStruct((B, maxp // bs), jnp.int32)
        blk = jax.ShapeDtypeStruct((n_blocks, bs), jnp.int32)
        jaxpr = jax.make_jaxpr(
            lambda q, k, v, l1, l2, st, l, w, s, ks, vs:
                paged_decode_attention(
                    q, k, v, TwoLevelTables(l1, l2, st, bs), l,
                    impl="pallas", windows=w, sinks=s,
                    k_scales=ks, v_scales=vs))(
            q, kp, kp, l1, blk, blk, ln, ln, ln, sc, sc)
    else:
        tb = jax.ShapeDtypeStruct((B, maxp), jnp.int32)
        jaxpr = jax.make_jaxpr(
            lambda q, k, v, t, st, l, w, s, ks, vs: paged_decode_attention(
                q, k, v, t, l, impl="pallas", page_starts=st,
                windows=w, sinks=s, k_scales=ks, v_scales=vs))(
            q, kp, kp, tb, tb, ln, ln, ln, sc, sc)
    return ProgramArtifacts(name="longctx_smem", jaxpr=jaxpr, stablehlo="",
                            hlo="", cost={})


def test_smem_linter_flat_overflows_two_level_fits():
    from paddle_tpu.analysis.pallas import (
        default_smem_budget,
        detect_smem_overflow,
        iter_pallas_calls,
        kernel_smem_bytes,
    )

    flat = detect_smem_overflow(_smem_art(two_level=False))
    assert len(flat) == 1 and flat[0].detector == "smem-overflow"
    # the [P] scale rows and the [B, max_pages] table are what blew it
    assert "float32[16384]" in flat[0].message
    assert detect_smem_overflow(_smem_art(two_level=True)) == []
    # the two-level walk prices by LIVE blocks: under budget, and well
    # under the flat arm's pool-sized scalar footprint
    (flat_eqn,) = iter_pallas_calls(_smem_art(two_level=False).jaxpr)
    (tl_eqn,) = iter_pallas_calls(_smem_art(two_level=True).jaxpr)
    assert kernel_smem_bytes(tl_eqn) < default_smem_budget()
    assert kernel_smem_bytes(tl_eqn) < kernel_smem_bytes(flat_eqn) // 2


# -- (g) the acceptance arithmetic: 128k within 1.15x of 8k -------------

def test_128k_decode_bytes_within_1p15x_of_8k_under_window():
    ps, win, snk = 32, 1024, 128
    nl, hq, hkv, d = 1, 8, 2, 128

    def walked_pages(ctx):
        pool = KVCachePool(num_pages=ctx // ps + 8, page_size=ps,
                           num_layers=nl, num_heads=hq, head_dim=8,
                           num_kv_heads=hkv)
        pool.allocate(0)
        pool.append_tokens([0], [ctx])
        pool.evict_interior(0, window=win, sinks=snk)
        assert pool.check_invariants()["ok"]
        return len(pool._tables[0].pages)

    def bytes_per_step(pages):
        return attention_bytes_per_step(
            "pallas", 1, pages, ps, hq, d, num_layers=nl,
            num_kv_heads=hkv, dtype="int8")

    p8k, p128k = walked_pages(8 << 10), walked_pages(128 << 10)
    # residency is window + sinks + the in-progress tail page — NOT
    # context: 16x more context costs at most one boundary page
    assert p128k <= p8k + 1
    assert bytes_per_step(p128k) <= 1.15 * bytes_per_step(p8k)
