"""Grouped-query paged attention + int8 KV pages (ISSUE 12).

Acceptance pinned here:
(a) interpret-tier parity: continuous-batching decode with H_q=8 over
    H_kv in {8, 4, 2, 1}, at fp32 AND int8 pages, is token-identical to
    the ``full_decode`` oracle on >= 3 overlapping ragged sequences
    (logits at fp32 tolerance; int8 at the stated 2e-2 tolerance), with
    zero leaked pages;
(b) the grouped pallas kernel (interpret mode) matches the reference
    gather token-for-token over a ragged multi-step decode, grouped and
    quantized arms both;
(c) the per-page scale table stays consistent through copy-on-write,
    defrag, scrub, free, and reclaim_orphans — ``check_invariants``
    audits it (live written pages have entries, freed pages must not) —
    and FAULT_SERVE_PREFIX_CORRUPT against an INT8 pool quarantines the
    poisoned-prefix reader while batch-mates survive oracle-identical;
(d) envelope/typing: H_q % H_kv != 0 raises the typed
    ``GroupedHeadsError`` everywhere (kernel, pool, config); int8 joins
    the Mosaic envelope at sublane 32; an out-of-envelope explicit
    ``pallas`` falls back to reference with a ``fallback_count()``
    increment; the analytic byte model prices H_kv and dtype arms;
(e) serving observability: the attention-bytes gauge carries
    ``kv_dtype=`` next to ``impl=``, and the disabled path stays
    zero-work (no metrics recorded with FLAGS_observability off);
(f) serve_bench decode mode banks kv_heads / kv_dtype /
    kv_bytes_per_token on the shared 0/2/3 gate contract.
"""

import json
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.kernels.paged_attention import (
    GroupedHeadsError,
    attention_bytes_per_step,
    fallback_count,
    gather_kv_pages,
    paged_decode_attention,
    pallas_paged_viable,
    resolve_paged_impl,
)
from paddle_tpu.serving import (
    ContinuousBatchingLoop,
    DecodeConfig,
    DecodeRequest,
    KVCachePool,
    PrefixCache,
    full_decode,
    init_decode_params,
)
from paddle_tpu.serving.generate import NonFiniteSequenceError


def _write_random(pool, rng, seq_ids, layers=1):
    """Append one token per sequence and write random K/V rows on every
    layer; returns the per-layer K rows for layer 0."""
    B = len(seq_ids)
    pages, slots = pool.append_token(seq_ids)
    rows = None
    for li in range(layers):
        k = rng.standard_normal(
            (B, pool.num_kv_heads, pool.head_dim)).astype(np.float32)
        v = rng.standard_normal(
            (B, pool.num_kv_heads, pool.head_dim)).astype(np.float32)
        pool.write_kv(li, pages, slots, k, v)
        if li == 0:
            rows = k
    return rows


# -- (a) the acceptance matrix: loop vs oracle ---------------------------

@pytest.mark.parametrize("h_kv", [8, 4, 2, 1])
@pytest.mark.parametrize("dtype", ["float32", "int8"])
def test_loop_parity_matrix_vs_full_decode(h_kv, dtype):
    """H_q=8 over every banked H_kv, fp32 and int8 pages, through the
    REAL grouped pallas kernel (interpret mode): tokens exactly match
    the full-recompute oracle on overlapping ragged sequences, logits
    within tolerance (int8: the stated 2e-2 — amax per-page quant), and
    every page returns to the pool."""
    cfg = DecodeConfig(vocab_size=61, d_model=32, n_head=8, n_layer=2,
                       d_inner=48, max_length=40, n_kv_head=h_kv)
    assert cfg.num_kv_heads == h_kv and cfg.group_size == 8 // h_kv
    params = init_decode_params(cfg, seed=h_kv)
    rng = np.random.RandomState(h_kv)
    prompts = [rng.randint(1, cfg.vocab_size, size=n).tolist()
               for n in (5, 2, 7, 3)]
    pool = KVCachePool(num_pages=36, page_size=4, num_layers=cfg.n_layer,
                       num_heads=cfg.n_head, head_dim=cfg.head_dim,
                       num_kv_heads=h_kv, dtype=dtype)
    assert pool.quantized == (dtype == "int8")
    loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=3,
                                  paged_impl="interpret", check_every=1)
    results = loop.run([DecodeRequest(p, 5) for p in prompts])
    tol = 2e-2 if dtype == "int8" else 1e-4
    for p, res in zip(prompts, results):
        want_tokens, want_logits = full_decode(params, cfg, p, 5)
        assert res.tokens == want_tokens  # greedy tokens EXACT
        for got, want in zip(res.logits, want_logits):
            np.testing.assert_allclose(got, want, rtol=tol, atol=tol)
    assert pool.free_pages == pool.num_pages
    assert loop.invariant_violations == 0
    assert pool.check_invariants()["ok"]


# -- (b) kernel-level grouped/quantized parity ---------------------------

@pytest.mark.parametrize("h_kv,dtype", [(2, "float32"), (1, "float32"),
                                        (2, "int8")])
def test_grouped_kernel_interpret_matches_reference_multistep(h_kv, dtype):
    """The grouped page-walk kernel vs the reference gather+repeat over
    a ragged multi-step simulated decode — the ISSUE 5 parity contract,
    grouped and int8 arms."""
    Hq, Dh, page_size = 4, 8, 3  # odd page size: deliberately unaligned
    pool = KVCachePool(num_pages=32, page_size=page_size, num_layers=1,
                       num_heads=Hq, head_dim=Dh, num_kv_heads=h_kv,
                       dtype=dtype)
    rng = np.random.RandomState(12)
    seq_ids = [0, 1, 2, 3]
    for s in seq_ids:
        pool.allocate(s)
    for s, prefix in zip(seq_ids, (5, 1, 9, 3)):
        for _ in range(prefix):
            _write_random(pool, rng, [s])
    tol = dict(rtol=2e-5, atol=2e-6)
    for step in range(10):
        _write_random(pool, rng, seq_ids)
        tables, lengths = pool.page_table_batch(seq_ids)
        ks, vs = pool.layer_scales(0)
        q = rng.standard_normal((4, Hq, 1, Dh)).astype(np.float32)
        want = np.asarray(paged_decode_attention(
            q, pool.k_pages[0], pool.v_pages[0], tables, lengths,
            impl="reference", k_scales=ks, v_scales=vs))
        got = np.asarray(paged_decode_attention(
            q, pool.k_pages[0], pool.v_pages[0], tables, lengths,
            impl="interpret", k_scales=ks, v_scales=vs))
        np.testing.assert_allclose(got, want, err_msg=f"step {step}",
                                   **tol)


def test_int8_dequant_error_bounded_by_page_amax():
    """amax per-page quantization: every dequantized value sits within
    half an int8 LSB of its page's largest magnitude — including after
    later writes GREW the page's amax (the requantize arm)."""
    pool = KVCachePool(num_pages=4, page_size=4, num_layers=1,
                       num_heads=2, head_dim=4, dtype="int8")
    pool.allocate(0)
    rng = np.random.RandomState(3)
    written = []
    for step in range(4):
        pages, slots = pool.append_token([0])
        # growing magnitudes force scale growth + requantization
        k = (rng.standard_normal((1, 2, 4)) * (1 + 3 * step)).astype(
            np.float32)
        pool.write_kv(0, pages, slots, k, k)
        written.append(k[0])
    tables, _ = pool.page_table_batch([0])
    ks, _ = pool.layer_scales(0)
    got = np.asarray(gather_kv_pages(pool.k_pages[0], tables, scales=ks))
    want = np.stack(written, axis=1)  # [H, S, D]
    amax = np.abs(want).max()
    # one page here: bound is half an LSB of the page amax
    assert np.abs(got[0, :, :4] - want).max() <= amax / 127.0


# -- (c) scale-table consistency -----------------------------------------

def test_scale_audit_live_and_freed_pages():
    pool = KVCachePool(num_pages=6, page_size=2, num_layers=2,
                       num_heads=2, head_dim=4, dtype="int8")
    pool.allocate(0)
    rng = np.random.RandomState(5)
    _write_random(pool, rng, [0], layers=2)
    assert pool.check_invariants()["ok"]
    page = pool.table_snapshot(0)[0][0]
    # a live written page missing its scale entry is flagged
    saved = pool.k_scales[1, page]
    pool.k_scales[1, page] = 0.0
    rep = pool.check_invariants()
    assert not rep["ok"] and page in rep["scale_errors"]
    pool.k_scales[1, page] = saved
    # scrubbing a LIVE sequence (the pre-quarantine path) zeroes scales
    # WITH the content — all-zero is consistent, not corruption
    pool.scrub_seq_pages(0)
    assert pool.check_invariants()["ok"]
    _write_random(pool, rng, [0], layers=2)
    # a freed page keeping a stale entry is flagged...
    pool.free_seq(0)
    rep = pool.check_invariants()
    assert rep["ok"] and rep["scale_errors"] == []
    pool.k_scales[0, page] = 0.25
    rep = pool.check_invariants()
    assert not rep["ok"] and page in rep["scale_errors"]
    # ...and reclaim_orphans re-trues it with the refcounts
    pool.reclaim_orphans()
    assert pool.check_invariants()["ok"]


def test_scales_travel_through_cow_defrag_scrub():
    """CoW copies the shared tail's scales to the fresh page; defrag
    permutes scale columns with their pages (gather parity holds); the
    quarantine scrub zeroes content AND scales."""
    pool = KVCachePool(num_pages=8, page_size=4, num_layers=1,
                       num_heads=2, head_dim=4, dtype="int8")
    rng = np.random.RandomState(9)
    for s in (0, 1):
        pool.allocate(s)
    pages, slots = pool.append_tokens([0], [2])  # partial tail page
    k = rng.standard_normal((2, 2, 4)).astype(np.float32)
    pool.write_kv(0, pages, slots, k, k)
    tail = pool.table_snapshot(0)[0][-1]
    # share the tail read-only, then diverge: append_tokens must CoW
    pool.attach_prefix(1, [tail], 2)
    p2, s2 = pool.append_token([0])
    pool.write_kv(0, p2, s2, np.ones((1, 2, 4), np.float32),
                  np.ones((1, 2, 4), np.float32))
    new_tail = pool.table_snapshot(0)[0][-1]
    assert new_tail != tail and pool.stats()["cow_copies"] == 1
    np.testing.assert_array_equal(pool.k_scales[:, new_tail],
                                  pool.k_scales[:, tail])
    assert pool.check_invariants()["ok"]
    # defrag: punch a hole, compact, dequantized gather identical
    pool.free_seq(1)
    tables, _ = pool.page_table_batch([0])
    ks, _ = pool.layer_scales(0)
    before = np.asarray(gather_kv_pages(pool.k_pages[0], tables,
                                        scales=ks))
    pool.defrag()
    tables2, _ = pool.page_table_batch([0])
    ks2, _ = pool.layer_scales(0)
    after = np.asarray(gather_kv_pages(pool.k_pages[0], tables2,
                                       scales=ks2))
    np.testing.assert_array_equal(before, after)
    assert pool.check_invariants()["ok"]
    # scrub zeroes scales with the content
    own = pool.table_snapshot(0)[0]
    pool.scrub_seq_pages(0)
    assert pool.k_scales[:, own].sum() == 0
    pool.free_seq(0)
    assert pool.check_invariants()["ok"]


def test_prefix_corrupt_chaos_against_int8_pool():
    """FAULT_SERVE_PREFIX_CORRUPT with int8 pages: the poison lands on
    the cached page's K SCALE (int8 content cannot hold NaN), the hit
    sequence quarantines, batch-mates survive oracle-identical, the
    chain is invalidated + scrubbed, and the scale audit stays green
    with zero leaked pages."""
    cfg = DecodeConfig(vocab_size=41, d_model=16, n_head=4, n_layer=2,
                       d_inner=32, max_length=48, n_kv_head=2)
    params = init_decode_params(cfg, seed=21)
    rng = np.random.RandomState(21)
    shared = rng.randint(1, cfg.vocab_size, size=12).tolist()
    owner = shared + rng.randint(1, cfg.vocab_size, size=2).tolist()
    victim = shared + rng.randint(1, cfg.vocab_size, size=3).tolist()
    bystander = rng.randint(1, cfg.vocab_size, size=5).tolist()
    pool = KVCachePool(num_pages=48, page_size=4, num_layers=cfg.n_layer,
                       num_heads=cfg.n_head, head_dim=cfg.head_dim,
                       num_kv_heads=2, dtype="int8")
    cache = PrefixCache(pool)
    loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=2,
                                  prefix_cache=cache, check_every=1)
    assert loop.run([DecodeRequest(owner, 3)])[0].error is None
    os.environ["FAULT_SERVE_PREFIX_CORRUPT"] = "1"
    try:
        res = loop.run([DecodeRequest(victim, 3),
                        DecodeRequest(bystander, 3)])
    finally:
        os.environ.pop("FAULT_SERVE_PREFIX_CORRUPT", None)
        from paddle_tpu.resilience import faultinject

        faultinject.reset()
    assert loop.quarantined == 1
    assert isinstance(res[0].error, NonFiniteSequenceError)
    want_b, _ = full_decode(params, cfg, bystander, 3)
    assert res[1].error is None and res[1].tokens == want_b
    assert cache.stats()["invalidations"] >= 1
    # re-request re-prefills clean and matches the oracle (NaN scale
    # was scrubbed with the invalidated chain, not recycled)
    res3 = loop.run([DecodeRequest(list(victim), 3)])
    want_v, _ = full_decode(params, cfg, victim, 3)
    assert res3[0].error is None and res3[0].tokens == want_v
    cache.clear()
    assert pool.used_pages == 0
    rep = pool.check_invariants()
    assert rep["ok"] and rep["scale_errors"] == []
    assert np.isfinite(pool.k_scales).all()


# -- prefix sharing + GQA + int8 compose ---------------------------------

def test_prefix_cache_hits_compose_with_gqa_int8():
    """The ISSUE 11 prefix cache over an int8 GQA pool: second
    same-prefix request HITS, attaches quantized pages read-only, and
    both generations match the oracle exactly."""
    cfg = DecodeConfig(vocab_size=53, d_model=32, n_head=8, n_layer=2,
                       d_inner=48, max_length=48, n_kv_head=2)
    params = init_decode_params(cfg, seed=4)
    rng = np.random.RandomState(4)
    shared = rng.randint(1, cfg.vocab_size, size=9).tolist()
    a = shared + rng.randint(1, cfg.vocab_size, size=3).tolist()
    b = shared + rng.randint(1, cfg.vocab_size, size=2).tolist()
    pool = KVCachePool(num_pages=32, page_size=4, num_layers=cfg.n_layer,
                       num_heads=cfg.n_head, head_dim=cfg.head_dim,
                       num_kv_heads=2, dtype="int8")
    cache = PrefixCache(pool)
    loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=2,
                                  prefix_cache=cache, check_every=1)
    res = loop.run([DecodeRequest(a, 4)])
    res2 = loop.run([DecodeRequest(b, 4)])
    assert loop.prefix_hits == 1 and loop.cached_prefill_tokens >= 8
    for prompt, r in ((a, res[0]), (b, res2[0])):
        want_tokens, want_logits = full_decode(params, cfg, prompt, 4)
        assert r.tokens == want_tokens
        for got, want in zip(r.logits, want_logits):
            np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)
    cache.clear()
    assert pool.used_pages == 0 and pool.check_invariants()["ok"]
    assert loop.invariant_violations == 0


# -- (d) envelope, typed errors, byte model ------------------------------

def test_grouped_envelope_typed_errors_and_fallback_count():
    # int8 joins the envelope at sublane 32
    assert pallas_paged_viable(32, 128, "int8")
    assert not pallas_paged_viable(16, 128, "int8")
    assert pallas_paged_viable(16, 128)  # fp32 arms unchanged
    assert not pallas_paged_viable(16, 128, "float64")
    # H_q % H_kv != 0: the TYPED error, never a silent fallback
    rng = np.random.RandomState(0)
    kp = rng.standard_normal((3, 4, 4, 8)).astype(np.float32)
    q = rng.standard_normal((1, 4, 1, 8)).astype(np.float32)
    tb = np.zeros((1, 2), np.int32)
    ln = np.ones((1,), np.int32)
    with pytest.raises(GroupedHeadsError):
        paged_decode_attention(q, kp, kp, tb, ln, impl="reference")
    with pytest.raises(GroupedHeadsError):
        KVCachePool(4, 4, 1, num_heads=4, head_dim=8, num_kv_heads=3)
    with pytest.raises(GroupedHeadsError):
        DecodeConfig(n_head=4, n_kv_head=3).num_kv_heads
    with pytest.raises(GroupedHeadsError):
        attention_bytes_per_step("pallas", 1, 2, 4, 4, 8, num_kv_heads=3)
    # int8 pool content without its scales is meaningless: rejected
    with pytest.raises(ValueError, match="scales"):
        paged_decode_attention(
            q[:, :3], kp.astype(np.int8), kp.astype(np.int8), tb, ln,
            impl="reference")
    # out-of-envelope explicit pallas on an int8 geometry: reference
    # fallback with the counter increment (the gate's signal)
    before = fallback_count()
    assert resolve_paged_impl("pallas", 16, 128, "int8") == "reference"
    assert fallback_count() == before + 1
    # in-envelope int8 passes through untouched
    assert resolve_paged_impl("pallas", 32, 128, "int8") == "pallas"
    assert resolve_paged_impl("interpret", 16, 128, "int8") == "interpret"
    assert fallback_count() == before + 1


def test_attention_bytes_model_gqa_and_dtype_arms():
    """The fixed byte model: explicit dtype overrides the fp32-itemsize
    default, KV traffic scales with num_kv_heads, int8 charges the
    per-page scale reads, and the reference arm prices its dequantized
    fp32 copy."""
    kw = dict(batch=4, max_pages=32, page_size=16, num_heads=8,
              head_dim=128, num_layers=2)
    elems = 4 * 32 * 16 * 8 * 128
    # legacy arms unchanged (itemsize default 4)
    assert attention_bytes_per_step("pallas", **kw) == 2 * elems * 4 * 2
    assert attention_bytes_per_step("reference", **kw) == 6 * elems * 4 * 2
    # explicit dtype wins over the itemsize default
    assert attention_bytes_per_step("pallas", dtype="bfloat16", **kw) \
        == 2 * elems * 2 * 2
    # GQA: H_kv/H_q x on the page stream (the pallas arm)
    full = attention_bytes_per_step("pallas", **kw)
    quarter = attention_bytes_per_step("pallas", num_kv_heads=2, **kw)
    assert quarter == full // 4
    # the reference arm under GQA pays its materialized group
    # broadcast: pages + gather copy at H_kv, repeat write + attention
    # read at H_q — NOT the naive H_kv-scaled 6x
    e_kv, e_q = elems // 4, elems
    assert attention_bytes_per_step("reference", num_kv_heads=2, **kw) \
        == 2 * 2 * (e_kv * 4 + e_kv * 4 + e_q * 4 + e_q * 4)
    # int8: elements at 1 byte + 2 fp32 scales per page walked; the
    # reference arm's materialized copy is the DEQUANTIZED fp32 one
    scale_bytes = 2 * 4 * 32 * 4 * 2  # 2 scales * B * maxp * 4B * L
    assert attention_bytes_per_step("pallas", dtype="int8", **kw) \
        == 2 * elems * 1 * 2 + scale_bytes
    assert attention_bytes_per_step("reference", dtype="int8", **kw) \
        == (2 * elems * 1 + 4 * elems * 4) * 2 + scale_bytes


# -- (e) observability: kv_dtype label + zero-work disabled path ---------

def test_attention_bytes_gauge_labeled_with_kv_dtype():
    from paddle_tpu import observability as obs

    cfg = DecodeConfig(vocab_size=17, d_model=16, n_head=4, n_layer=1,
                       d_inner=16, max_length=16, n_kv_head=2)
    params = init_decode_params(cfg, seed=0)

    def run_once():
        pool = KVCachePool(num_pages=8, page_size=4, num_layers=1,
                           num_heads=4, head_dim=4, num_kv_heads=2,
                           dtype="int8")
        ContinuousBatchingLoop(params, cfg, pool, max_batch=2).run(
            [DecodeRequest([1, 2], 2)])

    # disabled path first: ZERO series recorded (the zero-work contract)
    obs.reset()
    assert not fluid.flags.flag("FLAGS_observability")
    run_once()
    assert obs.default_registry().snapshot()["metrics"] == []
    # enabled: the gauge carries impl AND kv_dtype
    fluid.set_flags({"FLAGS_observability": True})
    try:
        run_once()
        snap = obs.default_registry().snapshot()["metrics"]
        by_name = {m["name"]: m for m in snap}
        series = by_name[
            "paddle_tpu_serving_attention_bytes_per_step"]["series"]
        assert series and all(
            s["labels"] == {"impl": "reference", "kv_dtype": "int8"}
            and s["value"] > 0 for s in series)
    finally:
        fluid.set_flags({"FLAGS_observability": False})
        obs.reset()


# -- (f) serve_bench kv knobs -------------------------------------------

def test_serve_bench_kv_knobs_bank_and_gate(tmp_path, capsys):
    from tools.serve_bench import main as bench_main

    out = tmp_path / "gqa.json"
    argv = ["--mode", "decode", "--sequences", "3", "--max-new", "4",
            "--d-model", "32", "--n-head", "8", "--kv-heads", "2",
            "--kv-dtype", "int8", "--vocab", "31", "--max-len", "32",
            "--pages", "32", "--page-size", "4"]
    rc = bench_main(argv + ["--json", str(out)])
    assert rc == 0
    r = json.loads(out.read_text())
    assert r["kv_heads"] == 2 and r["kv_dtype"] == "int8"
    assert r["pages_leaked"] == 0 and r["paged_fallbacks"] == 0
    # kv_bytes_per_token = bytes_per_page / page_size: H_kv heads at 1
    # byte + amortized fp32 scales — 2*2L*4ps*2H*4D*1B/4 + 2*2L*4B/4
    assert r["kv_bytes_per_token"] == (2 * 2 * 4 * 2 * 4 * 1
                                       + 2 * 2 * 4) / 4.0
    # bank the capacity numbers, re-gate: kv_bytes_per_token gates
    # lower-is-better, so an fp32 full-head run against the int8 GQA
    # bank must FAIL (16x the bytes/token)
    bank = tmp_path / "bank.json"
    bank.write_text(json.dumps({
        "kv_bytes_per_token": r["kv_bytes_per_token"],
        "pages_leaked": 0, "paged_fallbacks": 0}))
    assert bench_main(argv + ["--baseline", str(bank), "--gate"]) == 0
    rc = bench_main([
        "--mode", "decode", "--sequences", "3", "--max-new", "4",
        "--d-model", "32", "--n-head", "8", "--vocab", "31",
        "--max-len", "32", "--pages", "32", "--page-size", "4",
        "--baseline", str(bank), "--gate"])
    assert rc == 3
    capsys.readouterr()


def test_serve_bench_kv_usage_errors(capsys):
    from tools.serve_bench import main as bench_main

    # engine mode: exit 2
    assert bench_main(["--kv-dtype", "int8"]) == 2
    assert bench_main(["--mode", "engine", "--kv-heads", "2"]) == 2
    # non-divisor kv-heads: exit 2
    assert bench_main(["--mode", "decode", "--n-head", "4",
                       "--kv-heads", "3"]) == 2
    # int8 / non-mesh-dividing KV heads cannot shard: exit 2, not a
    # ValueError traceback (the shared 0/2/3 gate contract)
    assert bench_main(["--mode", "decode", "--mesh", "2",
                       "--kv-dtype", "int8"]) == 2
    assert bench_main(["--mode", "decode", "--mesh", "4",
                       "--n-head", "8", "--kv-heads", "2"]) == 2
    capsys.readouterr()
