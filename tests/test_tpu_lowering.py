"""Relay-independent TPU lowering gate for every pallas kernel.

Round-5 chip lesson: pallas interpret-mode tests validate numerics but
NEVER see the real TPU's Mosaic constraints — the first healthy chip
window in five rounds was half-lost to a (1, block_q) lse block that
violates the (8, 128) tile rule, and the staged conv-epilogue probe
would have burned a second window on a strided-slice lowering failure.
Both fail CLIENT-SIDE at lowering time, which means `jax.export` with
platforms=["tpu"] reproduces them on a CPU host with no TPU attached.

Every pallas kernel in the repo must TPU-lower here, at realistic
shapes (the flagship bench configs), including the shapes that caught
the two bugs above.
"""

import jax
import jax.numpy as jnp
import pytest

import importlib

# the kernels package re-exports the flash_attention FUNCTION under the
# same name as its module; go through importlib for the module itself
fa = importlib.import_module("paddle_tpu.kernels.flash_attention")
from paddle_tpu.kernels.conv_epilogue import conv_bn_act


def _tpu_lowers(fn, *args):
    """Assert fn TPU-lowers via jax.export (Mosaic runs client-side)."""
    jax.export.export(jax.jit(fn), platforms=["tpu"])(*args)


class TestFlashLowering:
    # (B, H, Sq, Sk, D): the transformer bench (256-seq), the longctx
    # bench (2048-seq), a cached-decode shape (Sq < Sk), and a ragged
    # shape exercising the padding path
    SHAPES = [(16, 16, 256, 256, 64), (4, 16, 2048, 2048, 64),
              (8, 8, 128, 384, 64), (2, 4, 200, 200, 64)]

    @pytest.mark.parametrize("shape", SHAPES)
    def test_forward_with_lse(self, shape):
        B, H, Sq, Sk, D = shape
        q = jax.ShapeDtypeStruct((B, H, Sq, D), jnp.bfloat16)
        k = jax.ShapeDtypeStruct((B, H, Sk, D), jnp.bfloat16)

        def f(q, k):
            klen = jnp.full((B,), Sk, jnp.float32)
            return fa._pallas_flash(q, k, k, klen, causal=True,
                                    scale=0.125)

        _tpu_lowers(f, q, k)

    def test_forward_no_lse(self):
        B, H, S, D = 16, 16, 256, 64
        q = jax.ShapeDtypeStruct((B, H, S, D), jnp.bfloat16)

        def f(q):
            klen = jnp.full((B,), S, jnp.float32)
            return fa._pallas_flash(q, q, q, klen, causal=False,
                                    scale=0.125, need_lse=False)[0]

        _tpu_lowers(f, q)

    @pytest.mark.parametrize("shape", [(16, 16, 256, 256, 64),
                                       (4, 16, 2048, 2048, 64)])
    def test_backward_pair(self, shape):
        B, H, Sq, Sk, D = shape
        q = jax.ShapeDtypeStruct((B, H, Sq, D), jnp.bfloat16)

        def f(q):
            klen = jnp.full((B,), Sk, jnp.float32)
            out, lse = fa._pallas_flash(q, q, q, klen, causal=True,
                                        scale=0.125)
            return fa._pallas_flash_bwd(q, q, q, klen, out, lse, out,
                                        causal=True, scale=0.125)

        _tpu_lowers(f, q)

    def test_packed_residuals_no_lane_broadcast(self):
        """lse/dvec ride the packed [B*H, nqb, bq] layout: the lowered
        module must contain NO [B*H, Sqp, 128] fp32 operand (the round-5
        layout broadcast every per-row scalar across 128 lanes —
        ~67 MB/tensor at this longcontext shape, 128x the payload)."""
        B, H, Sq, Sk, D = 4, 16, 2048, 2048, 64
        q = jax.ShapeDtypeStruct((B, H, Sq, D), jnp.bfloat16)

        def f(q):
            klen = jnp.full((B,), Sk, jnp.float32)
            out, lse = fa._pallas_flash(q, q, q, klen, causal=True,
                                        scale=0.125)
            return fa._pallas_flash_bwd(q, q, q, klen, out, lse, out,
                                        causal=True, scale=0.125)

        exp = jax.export.export(jax.jit(f), platforms=["tpu"])(q)
        txt = exp.mlir_module()
        assert f"tensor<{B * H}x{Sq}x128xf32>" not in txt
        # the packed residual layout is what flows instead
        assert f"tensor<{B * H}x{Sq // 128}x128xf32>" in txt


class TestConvEpilogueLowering:
    # ResNet-50 block shapes (NHWC), incl. the stride-2 stage
    # transitions that Mosaic's strided-slice limitation used to kill
    CASES = [
        (8, 56, 56, 64, 64, 1, 1, False),
        (8, 56, 56, 64, 64, 3, 1, True),
        (8, 56, 56, 128, 128, 3, 2, False),
        (8, 28, 28, 256, 256, 3, 2, False),
        (8, 7, 7, 512, 512, 3, 1, True),
    ]

    @pytest.mark.parametrize("case", CASES)
    def test_conv_bn_act(self, case):
        N, H, W, C, F, K, s, res = case
        x = jax.ShapeDtypeStruct((N, H, W, C), jnp.bfloat16)
        w = jax.ShapeDtypeStruct((K, K, C, F), jnp.bfloat16)
        g = jax.ShapeDtypeStruct((F,), jnp.float32)
        Ho = -(-H // s)
        args = (x, w, g, g)
        if res:
            args += (jax.ShapeDtypeStruct((N, Ho, Ho, F), jnp.bfloat16),)

        def f(x, w, gamma, beta, z=None):
            return conv_bn_act(x, w, gamma, beta, z, stride=s,
                               padding="SAME")

        _tpu_lowers(f, *args)
