"""Learning-rate schedules (reference: test_learning_rate_scheduler.py):
run N steps, compare the in-graph LR against a python reference."""

import math

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers


def _run_schedule(lr_var, steps):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    got = []
    for _ in range(steps):
        (v,) = exe.run(feed={}, fetch_list=[lr_var])
        got.append(float(np.ravel(np.asarray(v))[0]))
    return got


def test_exponential_decay():
    base, dsteps, rate = 1.0, 5, 0.5
    lr = layers.exponential_decay(base, dsteps, rate, staircase=True)
    got = _run_schedule(lr, 12)
    want = [base * rate ** int(i // dsteps) for i in range(12)]
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_natural_exp_decay():
    base, dsteps, rate = 0.5, 4, 0.3
    lr = layers.natural_exp_decay(base, dsteps, rate)
    got = _run_schedule(lr, 8)
    want = [base * math.exp(-rate * i / dsteps) for i in range(8)]
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_inverse_time_decay():
    base, dsteps, rate = 1.0, 2, 0.5
    lr = layers.inverse_time_decay(base, dsteps, rate)
    got = _run_schedule(lr, 6)
    want = [base / (1 + rate * i / dsteps) for i in range(6)]
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_polynomial_decay():
    base, dsteps, end, p = 1.0, 10, 0.1, 2.0
    lr = layers.polynomial_decay(base, dsteps, end, p)
    got = _run_schedule(lr, 14)
    want = [
        (base - end) * (1 - min(i, dsteps) / dsteps) ** p + end
        for i in range(14)
    ]
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_piecewise_decay():
    lr = layers.piecewise_decay([3, 6], [1.0, 0.5, 0.1])
    got = _run_schedule(lr, 9)
    want = [1.0 if i < 3 else 0.5 if i < 6 else 0.1 for i in range(9)]
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_noam_decay():
    d_model, warmup = 64, 4
    lr = layers.noam_decay(d_model, warmup)
    got = _run_schedule(lr, 8)
    want = [
        d_model ** -0.5 * min((i + 1) ** -0.5, (i + 1) * warmup ** -1.5)
        for i in range(8)
    ]
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_cosine_decay():
    base, per_epoch, epochs = 1.0, 3, 4
    lr = layers.cosine_decay(base, per_epoch, epochs)
    got = _run_schedule(lr, 9)
    want = [
        0.5 * base * (1 + math.cos(math.pi * (i // per_epoch) / epochs))
        for i in range(9)
    ]
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_warmup_then_constant():
    lr = layers.linear_lr_warmup(0.8, 4, 0.0, 0.4)
    got = _run_schedule(lr, 7)
    want = [0.0 + (0.4 - 0.0) / 4 * i if i < 4 else 0.8 for i in range(7)]
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_schedule_drives_optimizer():
    x = layers.data("x", [4], dtype="float32")
    y = layers.fc(x, size=1)
    loss = layers.mean(y)
    lr = layers.exponential_decay(0.1, 10, 0.5)
    fluid.optimizer.SGDOptimizer(learning_rate=lr).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    xv = np.ones((2, 4), dtype="float32")
    vals = [
        float(np.ravel(np.asarray(exe.run(feed={"x": xv}, fetch_list=[loss])[0]))[0])
        for _ in range(3)
    ]
    assert vals[0] != vals[1]  # training moved the params
