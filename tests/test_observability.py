"""Unified telemetry subsystem (paddle_tpu/observability/): metrics
registry, trace spans, step stats, regression gates, executor wiring, and
the zero-overhead-when-disabled contract."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers, observability as obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def obs_on():
    """FLAGS_observability on with clean registry/tracer/stats, restored
    after the test."""
    fluid.set_flags({"FLAGS_observability": True})
    obs.reset()
    yield
    obs.reset()
    fluid.set_flags({"FLAGS_observability": False})


def _build_step(name="obs_w"):
    x = layers.data("x", [4], dtype="float32")
    y = layers.fc(x, size=2, param_attr=fluid.ParamAttr(name=name))
    loss = layers.reduce_mean(y)
    fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    return exe, loss


def _feed(seed=0, bad=False):
    rng = np.random.RandomState(seed)
    x = rng.randn(2, 4).astype("float32")
    if bad:
        x[0, 0] = np.nan
    return {"x": x}


# -----------------------------------------------------------------------
# metrics registry
# -----------------------------------------------------------------------
def test_counter_gauge_histogram_with_labels(obs_on):
    reg = obs.MetricsRegistry()
    c = reg.counter("requests", "requests served")
    c.inc(model="resnet50")
    c.inc(2.0, model="resnet50")
    c.inc(model="transformer")
    assert c.value(model="resnet50") == 3.0
    assert c.value(model="transformer") == 1.0
    assert c.value(model="absent") == 0.0

    g = reg.gauge("capacity", "")
    g.set(5.0, host="a")
    g.inc(2.0, host="a")
    g.dec(1.0, host="a")
    assert g.value(host="a") == 6.0
    assert g.value(host="b") is None
    # monotonic watermark: set_max never moves backwards
    g.set_max(10.0, host="a")
    g.set_max(3.0, host="a")
    assert g.value(host="a") == 10.0

    h = reg.histogram("lat", "", buckets=[0.01, 0.1, 1.0])
    for v in (0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    s = h.series_summary()
    assert s["count"] == 4
    assert s["min"] == 0.005 and s["max"] == 5.0
    # non-cumulative per-bucket counts: one obs each in 0.01/0.1/1.0/+Inf
    assert [c for _, c in s["buckets"]] == [1, 1, 1, 1]


def test_metric_type_conflict_raises(obs_on):
    reg = obs.MetricsRegistry()
    reg.counter("m", "")
    with pytest.raises(TypeError, match="already registered"):
        reg.gauge("m", "")


def test_prometheus_text_format(obs_on):
    reg = obs.MetricsRegistry()
    reg.counter("steps", "steps run").inc(3, model="lenet")
    reg.gauge("hbm_bytes", "").set(1024)
    reg.histogram("step_s", "", buckets=[0.1, 1.0]).observe(0.05)
    text = reg.to_prometheus()
    assert "# TYPE steps_total counter" in text
    assert 'steps_total{model="lenet"} 3' in text
    assert "# TYPE hbm_bytes gauge" in text
    assert "hbm_bytes 1024" in text
    # histogram: cumulative buckets + sum + count
    assert 'step_s_bucket{le="0.1"} 1' in text
    assert 'step_s_bucket{le="1"} 1' in text
    assert 'step_s_bucket{le="+Inf"} 1' in text
    assert "step_s_count 1" in text


def test_snapshot_merge_adds_counters_and_histograms(obs_on):
    a, b = obs.MetricsRegistry(), obs.MetricsRegistry()
    a.counter("c", "").inc(2, k="x")
    b.counter("c", "").inc(3, k="x")
    a.histogram("h", "", buckets=[1.0]).observe(0.5)
    b.histogram("h", "", buckets=[1.0]).observe(2.0)
    a.gauge("g", "").set(1.0)
    time.sleep(0.01)
    b.gauge("g", "").set(9.0)  # newer write wins on merge

    merged = obs.MetricsRegistry()
    merged.merge(a.snapshot())
    merged.merge(b.snapshot())
    assert merged.counter("c", "").value(k="x") == 5.0
    hs = merged.histogram("h", "").series_summary()
    assert hs["count"] == 2 and hs["min"] == 0.5 and hs["max"] == 2.0
    assert merged.gauge("g", "").value() == 9.0


def test_process_dump_and_aggregate_dir(obs_on, tmp_path):
    """The multi-host story: one atomic snapshot file per process, any
    host merges the directory."""
    for p in (0, 1):
        reg = obs.MetricsRegistry()
        reg.counter("paddle_tpu_steps", "").inc(10, process=str(p))
        reg.counter("shared", "").inc(1)
        reg.dump(str(tmp_path / f"metrics_{p}.json"))
    agg = obs.MetricsRegistry.aggregate_dir(str(tmp_path))
    assert agg.counter("shared", "").value() == 2.0
    assert agg.counter("paddle_tpu_steps", "").value(process="0") == 10.0
    assert agg.counter("paddle_tpu_steps", "").value(process="1") == 10.0


def test_metrics_noop_when_disabled():
    assert not obs.enabled()
    reg = obs.MetricsRegistry()
    reg.counter("dead", "").inc(5)
    reg.gauge("dead_g", "").set(1)
    reg.histogram("dead_h", "").observe(1)
    assert reg.counter("dead", "").value() == 0.0
    assert reg.gauge("dead_g", "").value() is None
    assert reg.histogram("dead_h", "").series_summary() is None


# -----------------------------------------------------------------------
# spans + chrome trace
# -----------------------------------------------------------------------
def test_spans_nest_on_one_thread(obs_on):
    with obs.span("step", step=7):
        with obs.span("forward"):
            pass
        with obs.span("backward"):
            pass
    spans = {s.name: s for s in obs.default_tracer().spans()}
    assert set(spans) == {"step", "forward", "backward"}
    assert spans["forward"].parent == "step"
    assert spans["backward"].parent == "step"
    assert spans["step"].parent is None
    assert spans["step"].args == {"step": 7}
    # time containment
    assert spans["step"].t0 <= spans["forward"].t0
    assert spans["forward"].t1 <= spans["step"].t1


def test_spans_nest_independently_across_threads(obs_on):
    """A worker thread's spans must not adopt the main thread's open span
    as parent (per-thread stacks)."""
    def worker():
        with obs.span("io.write"):
            time.sleep(0.002)

    with obs.span("step"):
        t = threading.Thread(target=worker, name="ckpt-writer")
        t.start()
        t.join()
    spans = {s.name: s for s in obs.default_tracer().spans()}
    assert spans["io.write"].parent is None
    assert spans["io.write"].thread_name == "ckpt-writer"
    assert spans["io.write"].tid != spans["step"].tid


def test_chrome_trace_named_threads_stable_tids(obs_on, tmp_path):
    def worker(i):
        with obs.span(f"w{i}"):
            time.sleep(0.002)

    with obs.span("main_span"):
        ts = [threading.Thread(target=worker, args=(i,), name=f"worker-{i}")
              for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    path = str(tmp_path / "trace.json")
    n = obs.write_chrome_trace(path, obs.default_tracer().spans())
    assert n == 3
    with open(path) as f:
        doc = json.load(f)
    evs = doc["traceEvents"]
    xs = [e for e in evs if e["ph"] == "X"]
    metas = [e for e in evs if e["ph"] == "M" and e["name"] == "thread_name"]
    assert len(xs) == 3
    for e in xs:
        assert e["dur"] >= 0
    # main thread pinned to tid 0; workers named
    by_name = {e["name"]: e for e in xs}
    assert by_name["main_span"]["tid"] == 0
    tid_names = {e["tid"]: e["args"]["name"] for e in metas}
    assert tid_names[0] == threading.main_thread().name
    assert {"worker-0", "worker-1"} <= set(tid_names.values())
    assert by_name["w0"]["tid"] != by_name["w1"]["tid"] != 0


def test_chrome_trace_separates_reused_thread_idents(obs_on, tmp_path):
    """CPython reuses thread idents after join; rows are keyed on
    (ident, name) so a stream of short-lived writer threads doesn't
    collapse onto one mislabeled row."""
    spans = [obs.Span("save1", 0.0, 1.0, 12345, "ckpt_finalize_1"),
             obs.Span("save2", 2.0, 3.0, 12345, "ckpt_finalize_2")]
    path = str(tmp_path / "t.json")
    obs.write_chrome_trace(path, spans)
    doc = json.load(open(path))
    xs = {e["name"]: e for e in doc["traceEvents"] if e["ph"] == "X"}
    metas = {e["tid"]: e["args"]["name"] for e in doc["traceEvents"]
             if e["ph"] == "M" and e["name"] == "thread_name"}
    assert xs["save1"]["tid"] != xs["save2"]["tid"]
    assert metas[xs["save1"]["tid"]] == "ckpt_finalize_1"
    assert metas[xs["save2"]["tid"]] == "ckpt_finalize_2"


def test_histogram_merge_rejects_mismatched_buckets(obs_on):
    a, b = obs.MetricsRegistry(), obs.MetricsRegistry()
    a.histogram("h", "", buckets=[0.1, 1.0]).observe(0.05)
    b.histogram("h", "", buckets=[1.0, 10.0]).observe(5.0)
    merged = obs.MetricsRegistry()
    merged.merge(a.snapshot())
    with pytest.raises(ValueError, match="buckets"):
        merged.merge(b.snapshot())


def test_span_disabled_records_nothing():
    assert not obs.enabled()
    with obs.span("ghost"):
        pass
    assert obs.default_tracer().spans() == []


# -----------------------------------------------------------------------
# step stats + regression gate
# -----------------------------------------------------------------------
def test_stepstats_ring_and_percentiles():
    st = obs.StepStats(capacity=100)
    for v in range(1, 101):
        st.record(v / 1000.0)
    assert st.count == 100
    assert st.p50() == pytest.approx(0.050)
    assert st.p99() == pytest.approx(0.099)
    # rollover: 50 more samples push the window past capacity
    for v in range(101, 151):
        st.record(v / 1000.0)
    w = st.window()
    assert len(w) == 100 and st.count == 150
    assert min(w) == pytest.approx(0.051)  # oldest 50 rotated out
    s = st.summary()
    assert s["count"] == 150 and s["window"] == 100
    assert s["max_s"] == pytest.approx(0.150)
    assert s["last_s"] == pytest.approx(0.150)


def test_regression_verdicts():
    v = obs.regression_verdict("m", baseline=100.0, current=99.0)
    assert v["verdict"] == "pass"  # within 5%
    v = obs.regression_verdict("m", baseline=100.0, current=90.0)
    assert v["verdict"] == "fail" and v["delta_pct"] == pytest.approx(-10.0)
    # lower-is-better (step time): +10% is a fail
    v = obs.regression_verdict("t", 1.0, 1.1, higher_is_better=False,
                               tolerance=0.05)
    assert v["verdict"] == "fail"
    v = obs.regression_verdict("t", 1.0, 1.02, higher_is_better=False)
    assert v["verdict"] == "pass"
    assert obs.regression_verdict("m", None, 1.0)["verdict"] == "no_baseline"


def test_gate_results_direction_follows_metric_name(tmp_path):
    """bytes/step (BENCH_COST_ONLY) and duration metrics gate on RISING
    above baseline, not falling below it."""
    p = str(tmp_path / "base.json")
    json.dump({"resnet50_bytes_per_step": 100.0}, open(p, "w"))
    worse = obs.gate_results(
        [{"metric": "resnet50_bytes_per_step", "value": 120.0}], p)
    better = obs.gate_results(
        [{"metric": "resnet50_bytes_per_step", "value": 80.0}], p)
    assert worse[0]["verdict"] == "fail"
    assert better[0]["verdict"] == "pass"


def test_tracer_is_bounded(obs_on):
    t = obs.Tracer(capacity=4)
    for i in range(6):
        with t.span(f"s{i}"):
            pass
    spans = t.spans()
    assert len(spans) == 4 and t.dropped == 2
    assert [s.name for s in spans] == ["s2", "s3", "s4", "s5"]  # newest kept
    t.clear()
    assert t.spans() == [] and t.dropped == 0


def test_gate_results_against_bench_artifact(tmp_path):
    baseline = {
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": 2000.0, "unit": "images/sec",
        "extra_metrics": [
            {"metric": "transformer_train_tokens_per_sec_per_chip",
             "value": 100000.0}],
    }
    p = str(tmp_path / "base.json")
    json.dump(baseline, open(p, "w"))
    results = [
        {"metric": "resnet50_train_images_per_sec_per_chip", "value": 2100.0},
        {"metric": "transformer_train_tokens_per_sec_per_chip",
         "value": 80000.0},
        {"metric": "unbaselined_metric", "value": 1.0},
    ]
    verdicts = obs.gate_results(results, p)
    by = {v["metric"]: v for v in verdicts}
    assert len(verdicts) == 2
    assert by["resnet50_train_images_per_sec_per_chip"]["verdict"] == "pass"
    assert by["transformer_train_tokens_per_sec_per_chip"]["verdict"] == "fail"


# -----------------------------------------------------------------------
# executor wiring
# -----------------------------------------------------------------------
def test_executor_step_telemetry(obs_on):
    exe, loss = _build_step()
    obs.reset()  # drop the startup-program run's records
    for i in range(3):
        exe.run(feed=_feed(i), fetch_list=[loss])
    reg = obs.default_registry()
    h = reg.histogram("paddle_tpu_executor_step_seconds", "")
    assert h.series_summary()["count"] == 3
    # first post-reset run compiled fresh (miss), then cache hits
    cc = reg.counter("paddle_tpu_compile_cache", "")
    assert cc.value(result="miss") == 1
    assert cc.value(result="hit") == 2
    # donation is the serial executor default
    assert reg.counter("paddle_tpu_executor_steps", "").value(
        donated="1") == 3
    assert obs.step_stats().count == 3
    assert obs.step_stats().p50() > 0
    names = [s.name for s in obs.default_tracer().spans()]
    assert names.count("executor.step") == 3
    assert "compile" in names  # the fresh compile rode in a span


def test_executor_sentinel_skip_metrics(obs_on):
    exe, loss = _build_step(name="obs_nan_w")
    fluid.set_flags({"FLAGS_check_numerics": True,
                     "FLAGS_check_numerics_max_consecutive": 5})
    try:
        obs.reset()
        exe.run(feed=_feed(0), fetch_list=[loss])
        exe.run(feed=_feed(1, bad=True), fetch_list=[loss])  # skipped
        exe.run(feed=_feed(2), fetch_list=[loss])
    finally:
        fluid.set_flags({"FLAGS_check_numerics": False,
                         "FLAGS_check_numerics_max_consecutive": 3})
    reg = obs.default_registry()
    assert reg.counter("paddle_tpu_executor_skipped_steps", "").value() == 1
    assert reg.counter("paddle_tpu_sentinel_trips", "").value(
        var="loss_mean") >= 0  # labeled by offending var; total below
    total = sum(
        s["value"] for s in reg.counter(
            "paddle_tpu_sentinel_trips", "").snapshot()["series"])
    assert total == 1
    # the skipped step still landed in the step histogram
    assert reg.histogram("paddle_tpu_executor_step_seconds",
                         "").series_summary()["count"] == 3


def test_executor_cost_attribution_native(obs_on):
    exe, loss = _build_step(name="obs_cost_w")
    fluid.set_flags({"FLAGS_observability_cost": "native"})
    try:
        obs.reset()
        exe.run(feed=_feed(0), fetch_list=[loss])
        exe.run(feed=_feed(1), fetch_list=[loss])  # same entry: no re-cost
    finally:
        fluid.set_flags({"FLAGS_observability_cost": "off"})
    g = obs.default_registry().gauge("paddle_tpu_cost_bytes_per_step", "")
    series = g.snapshot()["series"]
    assert len(series) == 1  # once per compiled entry
    assert series[0]["value"] > 0
    assert series[0]["labels"]["platform"] == "native"
    assert series[0]["labels"]["fused_regions"] == "0"


def test_device_memory_watermarks(obs_on):
    class FakeDev:
        id = 3

        def __init__(self):
            self.stats = {"bytes_in_use": 100.0}

        def memory_stats(self):
            return self.stats

    dev = FakeDev()
    obs.record_device_memory(dev)
    reg = obs.default_registry()
    in_use = reg.gauge("paddle_tpu_device_bytes_in_use", "")
    peak = reg.gauge("paddle_tpu_device_peak_bytes_in_use", "")
    assert in_use.value(device="3") == 100.0
    # no allocator peak -> monotonic max of samples
    assert peak.value(device="3") == 100.0
    dev.stats = {"bytes_in_use": 60.0}
    obs.record_device_memory(dev)
    assert in_use.value(device="3") == 60.0
    assert peak.value(device="3") == 100.0  # watermark holds
    # allocator-reported peak wins when present (TPU backends)
    dev.stats = {"bytes_in_use": 80.0, "peak_bytes_in_use": 500.0}
    obs.record_device_memory(dev)
    assert peak.value(device="3") == 500.0
    # stats-less backends (CPU jax) are silently skipped
    class NoStats:
        def memory_stats(self):
            return None

    obs.record_device_memory(NoStats())


def test_histogram_rejects_conflicting_buckets(obs_on):
    reg = obs.MetricsRegistry()
    reg.histogram("h", "", buckets=[1.0, 10.0]).observe(5.0)
    reg.histogram("h", "")  # no buckets requested: fine
    with pytest.raises(ValueError, match="already registered"):
        reg.histogram("h", "", buckets=[0.1, 1.0])


def test_disabled_path_zero_observability_overhead(monkeypatch):
    """Acceptance: with the flag off the per-step path is one flag check
    — no observability calls, and NO allocations attributed to the
    observability package (tracemalloc filename filter)."""
    import tracemalloc

    assert not obs.enabled()
    exe, loss = _build_step(name="obs_cold_w")
    for i in range(2):  # warm the compile + caches
        exe.run(feed=_feed(i), fetch_list=[loss])

    calls = []
    monkeypatch.setattr(obs, "record_executor_step",
                        lambda *a, **k: calls.append(1))
    monkeypatch.setattr(obs, "record_compile_cache",
                        lambda *a, **k: calls.append(1))
    obs_pkg_dir = os.path.dirname(os.path.abspath(obs.__file__))
    tracemalloc.start()
    try:
        for i in range(3):
            exe.run(feed=_feed(i), fetch_list=[loss])
        snap = tracemalloc.take_snapshot()
    finally:
        tracemalloc.stop()
    assert calls == []  # no instrument reached
    hits = snap.filter_traces(
        [tracemalloc.Filter(True, os.path.join(obs_pkg_dir, "*"))]
    ).statistics("filename")
    assert hits == [], f"observability allocated while disabled: {hits}"
    # control: the SAME steps with the flag on do reach the instruments
    fluid.set_flags({"FLAGS_observability": True})
    try:
        exe.run(feed=_feed(0), fetch_list=[loss])
    finally:
        fluid.set_flags({"FLAGS_observability": False})
    assert calls


# -----------------------------------------------------------------------
# resilience / elastic accounting (satellite: surfaced, not dropped)
# -----------------------------------------------------------------------
def test_retry_stats_filled_on_success_and_exhaustion(obs_on):
    from paddle_tpu.resilience import retry_with_backoff

    attempts = []

    def flaky():
        attempts.append(1)
        if len(attempts) < 3:
            raise ConnectionError("down")
        return "ok"

    stats = {}
    out = retry_with_backoff(flaky, retries=5, base_delay=0.001,
                             sleep=lambda s: None, stats=stats,
                             label="test")
    assert out == "ok"
    assert stats["attempts"] == 3 and stats["retries"] == 2
    assert stats["backoff_s"] > 0
    assert obs.default_registry().counter(
        "paddle_tpu_resilience_retries", "").value(
            label="test", error="ConnectionError") == 2

    stats2 = {}
    with pytest.raises(TimeoutError):
        retry_with_backoff(lambda: (_ for _ in ()).throw(TimeoutError()),
                           retries=2, base_delay=0.001,
                           sleep=lambda s: None, stats=stats2)
    assert stats2["attempts"] == 3 and stats2["retries"] == 2

    # third path: a NON-retryable error after transient retries still
    # fills stats (the retried attempts must not be undercounted)
    attempts3 = []

    def then_fatal():
        attempts3.append(1)
        if len(attempts3) < 3:
            raise ConnectionError("transient")
        raise ValueError("application error")

    stats3 = {}
    with pytest.raises(ValueError):
        retry_with_backoff(then_fatal, retries=5, base_delay=0.001,
                           sleep=lambda s: None, stats=stats3)
    assert stats3["attempts"] == 3 and stats3["retries"] == 2
    assert stats3["backoff_s"] > 0


def test_checkpoint_manager_save_durations(obs_on, tmp_path):
    from paddle_tpu.resilience import CheckpointManager

    exe, loss = _build_step(name="obs_ck_w")
    exe.run(feed=_feed(0), fetch_list=[loss])
    mgr = CheckpointManager(str(tmp_path / "run"), keep_last=2)
    h = mgr.save(1)
    assert h is not None and h.done()
    assert h.stats["step"] == 1
    assert h.stats["save_seconds"] > 0
    assert h.stats["gc_seconds"] >= 0
    assert h.stats["total_seconds"] >= h.stats["save_seconds"]
    # async: stats complete after wait()
    h2 = mgr.save(2, asynchronous=True)
    h2.wait()
    assert h2.stats["save_seconds"] > 0
    reg = obs.default_registry()
    assert reg.counter("paddle_tpu_checkpoint_saves", "").value(
        result="ok") == 2
    assert reg.histogram("paddle_tpu_checkpoint_save_seconds",
                         "").series_summary()["count"] == 2
    assert "ckpt.save" in [s.name for s in obs.default_tracer().spans()]


def test_remote_master_retry_stats_accumulate(obs_on, monkeypatch):
    from paddle_tpu.elastic.rpc import RemoteMaster

    rm = RemoteMaster("127.0.0.1:1")  # nothing listens; no connect yet
    calls = []

    def call_once(req):
        calls.append(1)
        if len(calls) < 2:
            raise ConnectionError("transient")
        return {"ok": True, "counts": {"cur_pass": 0}}

    monkeypatch.setattr(rm, "_call_once", call_once)
    monkeypatch.setattr(rm, "_retry_base_delay", 0.0)
    assert rm.counts() == {"cur_pass": 0}
    assert rm.retry_stats["calls"] == 1
    assert rm.retry_stats["retries"] == 1
    assert rm.last_call_retries == 1


# -----------------------------------------------------------------------
# run artifacts + obsdump + bench integration
# -----------------------------------------------------------------------
def test_export_run_artifacts_and_obsdump(obs_on, tmp_path):
    exe, loss = _build_step(name="obs_art_w")
    obs.reset()
    for i in range(4):
        exe.run(feed=_feed(i), fetch_list=[loss])
    base = str(tmp_path / "base.json")
    json.dump({"toy_metric": 100.0}, open(base, "w"))
    d = str(tmp_path / "run")
    report = obs.export_run(
        d, results=[{"metric": "toy_metric", "value": 99.0}],
        baseline_path=base)
    assert sorted(os.listdir(d)) == [
        "metrics.json", "metrics.prom", "report.json", "trace.json"]
    assert report["step_time"]["count"] == 4
    assert report["regression"][0]["verdict"] == "pass"
    prom = open(os.path.join(d, "metrics.prom")).read()
    assert "paddle_tpu_executor_step_seconds_bucket" in prom
    assert "paddle_tpu_compile_cache_total" in prom
    with open(os.path.join(d, "trace.json")) as f:
        doc = json.load(f)
    assert any(e["ph"] == "M" and e["name"] == "thread_name"
               for e in doc["traceEvents"])

    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "obsdump.py"), d],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, out.stderr[-500:]
    assert "p50" in out.stdout
    assert "paddle_tpu_executor_step_seconds" in out.stdout
    assert "[PASS]" in out.stdout
    # --gate turns a fail verdict into a nonzero exit
    json.dump({"toy_metric": 1000.0}, open(base, "w"))
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "obsdump.py"), d,
         "--baseline", base, "--gate"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 3
    assert "[FAIL]" in out.stdout


def _bench_obs_env(monkeypatch, tmp_path, model, bs):
    monkeypatch.setenv("BENCH_MODELS", model)
    monkeypatch.setenv("BENCH_BS", bs)
    monkeypatch.setenv("BENCH_STEPS", "2")
    monkeypatch.setenv("BENCH_TUNE", "0")
    monkeypatch.setenv("BENCH_AMP", "0")
    monkeypatch.setenv("BENCH_SMOKE", "1")
    monkeypatch.setenv("BENCH_DEADLINE_S", "0")
    monkeypatch.setenv("BENCH_PREPROBE", "0")
    monkeypatch.setenv("BENCH_CKPT_DIR", "")
    monkeypatch.setenv("BENCH_OBS_DIR", str(tmp_path / "obs"))
    monkeypatch.setenv("BENCH_BASELINE", str(tmp_path / "base.json"))


def _assert_bench_obs_artifacts(rec, tmp_path, metric):
    # (c) report with p50/p99 + baseline delta verdict
    assert rec["observability"]["steps_recorded"] >= 2
    assert rec["observability"]["step_time_p50_s"] > 0
    assert rec["regression"][0]["metric"] == metric
    assert rec["regression"][0]["verdict"] == "pass"
    d = str(tmp_path / "obs")
    report = json.load(open(os.path.join(d, "report.json")))
    assert report["step_time"]["p99_s"] > 0
    assert report["regression"][0]["verdict"] == "pass"
    # (a) Prometheus snapshot with step-time histogram + compile-cache
    # counters
    prom = open(os.path.join(d, "metrics.prom")).read()
    assert "paddle_tpu_executor_step_seconds_bucket" in prom
    assert 'paddle_tpu_compile_cache_total{result="miss"}' in prom
    # (b) merged chrome trace with named threads
    doc = json.load(open(os.path.join(d, "trace.json")))
    metas = [e for e in doc["traceEvents"]
             if e["ph"] == "M" and e["name"] == "thread_name"]
    assert metas
    xs = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert "executor.step" in xs and "bench.model" in xs


def _run_bench_obs(monkeypatch, capsys, tmp_path, model, bs, metric):
    import bench

    _bench_obs_env(monkeypatch, tmp_path, model, bs)
    json.dump({metric: 0.001}, open(str(tmp_path / "base.json"), "w"))
    fluid.set_flags({"FLAGS_observability": True})
    obs.reset()
    try:
        bench.main()
    finally:
        fluid.set_flags({"FLAGS_observability": False})
        fluid.disable_amp()
        line = capsys.readouterr().out.strip().splitlines()[-1]
        obs.reset()  # artifacts are on disk; keep later tests clean
    rec = json.loads(line)
    assert rec["metric"] == metric, rec
    _assert_bench_obs_artifacts(rec, tmp_path, metric)


def test_bench_observability_smoke_lenet(monkeypatch, capsys, tmp_path):
    """Tier-1 shape of the acceptance run: FLAGS_observability on, a
    bench smoke produces (a) Prometheus metrics with the step-time
    histogram + compile-cache counters, (b) a merged named-thread chrome
    trace, (c) a report with p50/p99 + baseline verdict."""
    _run_bench_obs(monkeypatch, capsys, tmp_path, "lenet", "4",
                   "mnist_train_images_per_sec_per_chip")


@pytest.mark.slow
def test_bench_observability_smoke_resnet50(monkeypatch, capsys, tmp_path):
    """The literal acceptance criterion (ResNet-50), CPU-sized; slow —
    tier-1 proves the same path on lenet."""
    _run_bench_obs(monkeypatch, capsys, tmp_path, "resnet50", "2",
                   "resnet50_train_images_per_sec_per_chip")
