"""Per-op sweep: loss family (reference: test_cross_entropy_op.py,
test_sigmoid_cross_entropy_with_logits_op.py, test_huber_loss_op.py, ... over
operators/*_loss_op.cc and cross-entropy kernels)."""

import numpy as np
import pytest

from op_test import OpTest


def _softmax(x):
    e = np.exp(x - x.max(axis=-1, keepdims=True))
    return e / e.sum(axis=-1, keepdims=True)


def _rand(shape, seed=0, lo=-2.0, hi=2.0):
    return np.random.RandomState(seed).uniform(lo, hi, shape).astype("float32")


def test_cross_entropy_hard_label():
    probs = _softmax(_rand((4, 6), seed=1)).astype("float32")
    label = np.array([[1], [0], [5], [2]], dtype="int64")
    want = -np.log(np.take_along_axis(probs.astype(np.float64), label, axis=1) + 1e-12)

    class T(OpTest):
        op_type = "cross_entropy"

    t = T()
    t.inputs = {"X": probs, "Label": label}
    t.outputs = {"Y": want.astype("float32")}
    t.check_output(atol=2e-5, rtol=2e-5)
    t.check_grad(["X"], "Y", max_relative_error=0.01)


def test_cross_entropy_soft_label():
    probs = _softmax(_rand((4, 6), seed=2)).astype("float32")
    soft = _softmax(_rand((4, 6), seed=3)).astype("float32")
    want = -(soft.astype(np.float64) * np.log(probs.astype(np.float64) + 1e-12)).sum(
        axis=1, keepdims=True)

    class T(OpTest):
        op_type = "cross_entropy"

    t = T()
    t.inputs = {"X": probs, "Label": soft}
    t.attrs = {"soft_label": True}
    t.outputs = {"Y": want.astype("float32")}
    t.check_output(atol=2e-5, rtol=2e-5)


def test_softmax_with_cross_entropy():
    logits = _rand((5, 7), seed=4)
    label = np.array([[0], [3], [6], [2], [2]], dtype="int64")
    sm = _softmax(logits.astype(np.float64))
    want = -np.log(np.take_along_axis(sm, label, axis=1))

    class T(OpTest):
        op_type = "softmax_with_cross_entropy"

    t = T()
    t.inputs = {"Logits": logits, "Label": label}
    t.outputs = {"Softmax": sm.astype("float32"), "Loss": want.astype("float32")}
    t.check_output(atol=2e-5, rtol=2e-5)
    t.check_grad(["Logits"], "Loss", max_relative_error=0.01)


def test_sigmoid_cross_entropy_with_logits():
    x = _rand((4, 5), seed=5)
    label = np.random.RandomState(6).randint(0, 2, (4, 5)).astype("float32")
    xd = x.astype(np.float64)
    want = np.maximum(xd, 0) - xd * label + np.log1p(np.exp(-np.abs(xd)))

    class T(OpTest):
        op_type = "sigmoid_cross_entropy_with_logits"

    t = T()
    t.inputs = {"X": x, "Label": label}
    t.outputs = {"Out": want.astype("float32")}
    t.check_output(atol=2e-5, rtol=2e-5)
    t.check_grad(["X"], "Out", max_relative_error=0.01)


def test_bpr_loss():
    x = _rand((4, 6), seed=7)
    label = np.array([[1], [0], [5], [2]], dtype="int64")
    xd = x.astype(np.float64)
    pos = np.take_along_axis(xd, label, axis=1)
    want = np.mean(np.log1p(np.exp(xd - pos)), axis=1, keepdims=True)

    class T(OpTest):
        op_type = "bpr_loss"

    t = T()
    t.inputs = {"X": x, "Label": label}
    t.outputs = {"Y": want.astype("float32")}
    t.check_output(atol=2e-5, rtol=2e-5)
    t.check_grad(["X"], "Y", max_relative_error=0.01)


def test_hinge_loss():
    logits = _rand((6, 1), seed=8)
    labels = np.random.RandomState(9).randint(0, 2, (6, 1)).astype("float32")
    want = np.maximum(0.0, 1.0 - (2 * labels - 1) * logits.astype(np.float64))

    class T(OpTest):
        op_type = "hinge_loss"

    t = T()
    t.inputs = {"Logits": logits, "Labels": labels}
    t.outputs = {"Loss": want.astype("float32")}
    t.check_output(atol=2e-5, rtol=2e-5)


def test_huber_loss():
    x = _rand((8, 1), seed=10)
    y = _rand((8, 1), seed=11)
    delta = 0.8
    r = (y - x).astype(np.float64)
    want = np.where(np.abs(r) <= delta, 0.5 * r * r,
                    delta * (np.abs(r) - 0.5 * delta))

    class T(OpTest):
        op_type = "huber_loss"

    t = T()
    t.inputs = {"X": x, "Y": y}
    t.attrs = {"delta": delta}
    t.outputs = {"Out": want.astype("float32"), "Residual": r.astype("float32")}
    t.check_output(atol=2e-5, rtol=2e-5)
    t.check_grad(["X", "Y"], "Out", max_relative_error=0.01)


def test_log_loss():
    p = _rand((6, 1), seed=12, lo=0.1, hi=0.9)
    label = np.random.RandomState(13).randint(0, 2, (6, 1)).astype("float32")
    eps = 1e-4
    pd = p.astype(np.float64)
    want = -label * np.log(pd + eps) - (1 - label) * np.log(1 - pd + eps)

    class T(OpTest):
        op_type = "log_loss"

    t = T()
    t.inputs = {"Predicted": p, "Labels": label}
    t.attrs = {"epsilon": eps}
    t.outputs = {"Loss": want.astype("float32")}
    t.check_output(atol=2e-5, rtol=2e-5)
    t.check_grad(["Predicted"], "Loss", max_relative_error=0.01)


def test_rank_loss():
    left = _rand((5, 1), seed=14)
    right = _rand((5, 1), seed=15)
    label = np.random.RandomState(16).randint(0, 2, (5, 1)).astype("float32")
    d = (left - right).astype(np.float64)
    want = np.log1p(np.exp(d)) - label * d

    class T(OpTest):
        op_type = "rank_loss"

    t = T()
    t.inputs = {"Left": left, "Right": right, "Label": label}
    t.outputs = {"Out": want.astype("float32")}
    t.check_output(atol=2e-5, rtol=2e-5)
    t.check_grad(["Left", "Right"], "Out", max_relative_error=0.01)


def test_margin_rank_loss():
    x1 = _rand((5, 1), seed=17)
    x2 = _rand((5, 1), seed=18)
    label = (np.random.RandomState(19).randint(0, 2, (5, 1)) * 2 - 1).astype("float32")
    margin = 0.1
    want = np.maximum(0.0, -label * (x1 - x2).astype(np.float64) + margin)

    class T(OpTest):
        op_type = "margin_rank_loss"

    t = T()
    t.inputs = {"X1": x1, "X2": x2, "Label": label}
    t.attrs = {"margin": margin}
    t.outputs = {"Out": want.astype("float32"),
                 "Activated": (want > 0).astype("float32")}
    t.check_output(atol=2e-5, rtol=2e-5)


def test_smooth_l1_loss():
    x = _rand((4, 6), seed=20)
    y = _rand((4, 6), seed=21)
    sigma = 1.5
    s2 = sigma * sigma
    d = (x - y).astype(np.float64)
    elem = np.where(np.abs(d) < 1.0 / s2, 0.5 * s2 * d * d,
                    np.abs(d) - 0.5 / s2)
    want = elem.sum(axis=1, keepdims=True)

    class T(OpTest):
        op_type = "smooth_l1_loss"

    t = T()
    t.inputs = {"X": x, "Y": y}
    t.attrs = {"sigma": sigma}
    t.outputs = {"Out": want.astype("float32"), "Diff": d.astype("float32")}
    t.check_output(atol=2e-5, rtol=2e-5)
    t.check_grad(["X", "Y"], "Out", max_relative_error=0.01)


def test_squared_l2_distance():
    x = _rand((4, 6), seed=22)
    y = _rand((4, 6), seed=23)
    sub = (x - y).astype(np.float64)
    want = (sub ** 2).sum(axis=1, keepdims=True)

    class T(OpTest):
        op_type = "squared_l2_distance"

    t = T()
    t.inputs = {"X": x, "Y": y}
    t.outputs = {"Out": want.astype("float32"),
                 "sub_result": sub.astype("float32")}
    t.check_output(atol=2e-5, rtol=2e-5)
    t.check_grad(["X", "Y"], "Out", max_relative_error=0.01)


def test_label_smooth():
    x = _softmax(_rand((4, 5), seed=24)).astype("float32")
    eps = 0.1
    want = (1 - eps) * x.astype(np.float64) + eps / 5

    class T(OpTest):
        op_type = "label_smooth"

    t = T()
    t.inputs = {"X": x}
    t.attrs = {"epsilon": eps}
    t.outputs = {"Out": want.astype("float32")}
    t.check_output(atol=2e-5, rtol=2e-5)


def test_softmax_with_cross_entropy_smooth_eps():
    """smooth_eps folds uniform label smoothing analytically: must equal
    one_hot -> label_smooth -> soft-label CE bit-for-near-bit, including
    zeroed loss at ignore_index positions, and reject soft_label+smooth."""
    import numpy as np
    import pytest

    import paddle_tpu as fluid
    from paddle_tpu import layers

    rng = np.random.RandomState(0)
    B, V, eps_s = 6, 12, 0.1
    logits_v = rng.randn(B, V).astype("float32")
    label_v = rng.randint(0, V, size=(B, 1)).astype("int64")
    label_v[2, 0] = -100  # ignore_index sentinel position

    fluid.reset_default_env()
    logits = layers.data("logits", [V], dtype="float32")
    label = layers.data("label", [1], dtype="int64")
    fused = layers.softmax_with_cross_entropy(
        logits, label, smooth_eps=eps_s, ignore_index=-100)

    # reference-shaped chain (clamp the sentinel to a valid id for one_hot;
    # its loss row is checked as zero on the fused side separately)
    lab_c = layers.elementwise_max(
        label, layers.fill_constant([1], "int64", 0))
    one_hot = layers.one_hot(layers.reshape(lab_c, [-1]), depth=V)
    smooth = layers.label_smooth(one_hot, epsilon=eps_s)
    soft = layers.softmax_with_cross_entropy(
        logits, smooth, soft_label=True)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    fv, sv = exe.run(feed={"logits": logits_v, "label": label_v},
                     fetch_list=[fused, soft])
    fv, sv = np.asarray(fv), np.asarray(sv)
    keep = np.arange(B) != 2
    np.testing.assert_allclose(fv[keep], sv[keep], rtol=1e-5, atol=1e-6)
    assert fv[2] == 0.0  # ignored position contributes nothing

    with pytest.raises(ValueError, match="smooth_eps"):
        layers.softmax_with_cross_entropy(
            logits, smooth, soft_label=True, smooth_eps=0.1)
