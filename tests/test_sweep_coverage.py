"""Sweep-coverage manifest (VERDICT r2 task 6 done-criterion): every
registered non-grad op either appears in a direct numeric harness entry
somewhere under tests/, or is listed in EXERCISED_VIA below — a mapping to
the public layer surface that emits it, which this module then BUILDS and
RUNS so the mapping can't go stale."""

import glob
import os
import re

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.core.lod import create_lod_tensor

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))


# op -> builder(returning fetchable var(s) + feed dict); the test asserts
# the op type materializes in the program and the program executes
def _via_dynamic_gru():
    x = layers.data("x", [9], dtype="float32", lod_level=1)
    h = layers.dynamic_gru(x, size=3)
    feed = {"x": create_lod_tensor(
        np.random.RandomState(0).rand(4, 9).astype("float32"), [[2, 2]])}
    return h, feed


def _via_fusion_gru():
    # no layer wrapper in the reference either — drive the op directly
    hid, m = 3, 5
    x = layers.data("x", [m], dtype="float32", lod_level=1)
    r = np.random.RandomState(0)
    block = fluid.default_main_program().global_block()
    for name, shape in (("fg_wx", [m, 3 * hid]), ("fg_wh", [hid, 3 * hid])):
        v = block.create_var(name=name, shape=shape, dtype="float32")
        fluid.default_startup_program().global_block().create_var(
            name=name, shape=shape, dtype="float32", persistable=True)
    block.vars["fg_wx"].persistable = True
    block.vars["fg_wh"].persistable = True
    for slot in ("fg_hidden", "fg_xx"):
        block.create_var(name=slot, shape=[-1, hid], dtype="float32",
                         lod_level=1)
    block.append_op(type="fusion_gru",
                    inputs={"X": [x.name], "WeightX": ["fg_wx"],
                            "WeightH": ["fg_wh"]},
                    outputs={"Hidden": ["fg_hidden"], "XX": ["fg_xx"]},
                    attrs={})
    fluid.global_scope().set_var(
        "fg_wx", r.rand(m, 3 * hid).astype("float32"))
    fluid.global_scope().set_var(
        "fg_wh", r.rand(hid, 3 * hid).astype("float32"))
    feed = {"x": create_lod_tensor(
        r.rand(4, m).astype("float32"), [[2, 2]])}
    return "fg_hidden", feed


def _via_fused_attention():
    # [batch, heads, seq, head_dim]
    q = layers.data("q", [2, 4, 8], dtype="float32")
    out = layers.fused_attention(q, q, q)
    feed = {"q": np.random.RandomState(0).rand(
        1, 2, 4, 8).astype("float32")}
    return out, feed


def _via_ifelse():
    # IfElse emits split_lod_tensor / conditional_block / merge_lod_tensor
    x = layers.data("x", [1], dtype="float32")
    limit = layers.fill_constant([1], "float32", 0.0)
    cond = layers.less_than(x, limit)
    ie = layers.IfElse(cond)
    with ie.true_block():
        ie.output(layers.scale(ie.input(x), scale=-1.0))
    with ie.false_block():
        ie.output(layers.scale(ie.input(x), scale=1.0))
    (out,) = ie()
    feed = {"x": np.array([[-2.0], [3.0]], "float32")}
    return out, feed


def _via_dynamic_rnn():
    # DynamicRNN emits lod_rank_table / lod_tensor_to_array /
    # array_to_lod_tensor / while / shrink_rnn_memory / array ops
    x = layers.data("x", [4], dtype="float32", lod_level=1)
    drnn = layers.DynamicRNN()
    with drnn.block():
        step = drnn.step_input(x)
        mem = drnn.memory(shape=[4], value=0.0)
        new = layers.elementwise_add(step, mem)
        drnn.update_memory(mem, new)
        drnn.output(new)
    out = drnn()
    feed = {"x": create_lod_tensor(
        np.random.RandomState(0).rand(5, 4).astype("float32"), [[3, 2]])}
    return out, feed


def _via_array_ops():
    # create_array / write_to_array / read_from_array / lod_array_length /
    # stack_from_array via the layers array API
    x = layers.data("x", [3], dtype="float32")
    i = layers.fill_constant([1], "int64", 0)
    arr = layers.array_write(x, i)
    n = layers.array_length(arr)
    y = layers.array_read(arr, i)
    feed = {"x": np.ones((2, 3), "float32")}
    return [y, n], feed


def _via_is_empty():
    x = layers.data("x", [3], dtype="float32")
    e = layers.is_empty(x)
    return e, {"x": np.ones((2, 3), "float32")}


def _via_switch():
    # Switch emits conditional_block sub-blocks
    x = layers.data("x", [1], dtype="float32")
    zero = layers.fill_constant([1], "float32", 0.0)
    out = layers.create_global_var([1], 0.0, "float32",
                                   persistable=True, name="sw_out")
    with layers.Switch() as switch:
        with switch.case(layers.less_than(x, zero)):
            layers.assign(layers.fill_constant([1], "float32", -1.0), out)
        with switch.default():
            layers.assign(layers.fill_constant([1], "float32", 1.0), out)
    return out, {"x": np.array([[2.0]], "float32")}


def _via_static_rnn():
    # StaticRNN emits unstack_into_array (step_input) and
    # stack_from_array (output collection)
    x = layers.data("x", [3, 2, 4], dtype="float32",
                    append_batch_size=False)
    rnn = layers.StaticRNN()
    with rnn.step():
        step = rnn.step_input(x)
        mem = rnn.memory(shape=[-1, 4], batch_ref=step, value=0.0)
        new = layers.elementwise_add(step, mem)
        rnn.update_memory(mem, new)
        rnn.step_output(new)
    out = rnn()
    return out, {"x": np.random.RandomState(0).rand(
        3, 2, 4).astype("float32")}


def _via_shrink_memory():
    xl = layers.data("xl", [2], dtype="float32", lod_level=1)
    x = layers.data("x", [2], dtype="float32")
    table = layers.lod_rank_table(xl)
    i = layers.fill_constant([1], "int64", 0)
    out = layers.shrink_memory(x, i, table)
    feed = {"xl": create_lod_tensor(
        np.ones((5, 2), "float32"), [[3, 2]]),
        "x": np.ones((2, 2), "float32")}
    return out, feed


def _via_distribute_transpiler():
    # split_ids / merge_ids / split_selected_rows appear in transpiled
    # pserver programs; here just materialize them directly through the
    # block API (their numeric behavior is in test_framework_ops.py)
    block = fluid.default_main_program().global_block()
    ids = layers.data("ids", [1], dtype="int64")
    for i in range(2):
        block.create_var(name=f"shard_{i}", shape=[-1, 1], dtype="int64")
    block.append_op(type="split_ids", inputs={"Ids": [ids.name]},
                    outputs={"Out": ["shard_0", "shard_1"]}, attrs={})
    block.create_var(name="merged", shape=[-1, 1], dtype="int64")
    block.append_op(type="merge_ids",
                    inputs={"Ids": [ids.name],
                            "Rows": ["shard_0", "shard_1"],
                            "X": ["shard_0", "shard_1"]},
                    outputs={"Out": ["merged"]}, attrs={})
    return "shard_0", {"ids": np.array([[2], [5]], "int64")}


def _via_delete_var():
    x = layers.data("x", [3], dtype="float32")
    y = layers.scale(x, scale=2.0)
    block = fluid.default_main_program().global_block()
    block.append_op(type="delete_var", inputs={"X": [x.name]},
                    outputs={}, attrs={})
    return y, {"x": np.ones((2, 3), "float32")}


def _via_print():
    x = layers.data("x", [3], dtype="float32")
    y = layers.Print(x, message="sweep-coverage")
    return y, {"x": np.ones((2, 3), "float32")}


EXERCISED_VIA = {
    "gru": _via_dynamic_gru,
    "fusion_gru": _via_fusion_gru,
    "fused_attention": _via_fused_attention,
    "split_lod_tensor": _via_ifelse,
    "merge_lod_tensor": _via_ifelse,
    "conditional_block": _via_switch,
    "lod_rank_table": _via_dynamic_rnn,
    "lod_tensor_to_array": _via_dynamic_rnn,
    "array_to_lod_tensor": _via_dynamic_rnn,
    "max_sequence_len": _via_dynamic_rnn,
    "shrink_rnn_memory": _via_shrink_memory,
    "while": _via_dynamic_rnn,
    "write_to_array": _via_array_ops,
    "read_from_array": _via_array_ops,
    "create_array": _via_array_ops,
    "lod_array_length": _via_array_ops,
    "stack_from_array": _via_static_rnn,
    "unstack_into_array": _via_static_rnn,
    "is_empty": _via_is_empty,
    "split_ids": _via_distribute_transpiler,
    "merge_ids": _via_distribute_transpiler,
    "delete_var": _via_delete_var,
    "print": _via_print,
}

# ops whose direct numeric coverage lives under a spelling the scanner
# can't see, with the file that covers them
# patterns that indicate a REAL harness invocation (no catch-all
# quoted-string pattern: {"shape": ...} attrs would otherwise "cover" the
# shape op and make this gate vacuous)
_DIRECT_PATTERNS = (
    r'op_type\s*=\s*[\'"]([a-z0-9_]+)[\'"]',      # OpTest subclasses
    r'_t\(\s*[\'"]([a-z0-9_]+)[\'"]',             # _t("op", ...) helper
    r'_run\(\s*[\'"]([a-z0-9_]+)[\'"]',           # _run("op", ...)
    r'_run_op\(\s*[\'"]([a-z0-9_]+)[\'"]',        # _run_op("op", ...)
    r'_case\(\s*[\'"]([a-z0-9_]+)[\'"]',          # _case("op", ...)
    r'^\s{4}[\'"]([a-z0-9_]+)[\'"]\s*:\s*\(',     # CASES dict keys
    r'type\s*=\s*[\'"]([a-z0-9_]+)[\'"]',         # block.append_op(type=)
    r'layers\.([a-z0-9_]+)\(',                    # public layer calls
    r'\._([a-z0-9_]+)\(',  # direct-lowering calls, e.g. F._merge_selected_rows
)

# registered op -> the public surface whose harness tests it under another
# spelling (each verified manually; the layer emits the op on its program)
ALIASED_COVERAGE = {
    "lookup_table": "layers.embedding",
    "arg_max": "layers.argmax",
    "arg_min": "layers.argmin",
    "equal": "layers.less_than-family comparisons (test_op_harness)",
    "greater_equal": "comparison sweep",
    "less_equal": "comparison sweep",
    "not_equal": "comparison sweep",
    "logical_and": "logical sweep (test_metrics/test_op_harness)",
    "logical_or": "logical sweep",
    "logical_xor": "logical sweep",
    "conv2d_int8": "tests/test_inference_quant.py freeze path",
    "mul_int8": "tests/test_inference_quant.py freeze path",
    "detection_map": "tests/test_proposal_ops.py _run_op",
    "generate_proposals": "tests/test_proposal_ops.py _run_op",
    "generate_proposal_labels": "tests/test_proposal_ops.py _run_op",
    "rpn_target_assign": "tests/test_proposal_ops.py _run_op",
    "psroi_pool": "tests/test_proposal_ops.py _run_op",
    "roi_perspective_transform": "tests/test_proposal_ops.py _run_op",
    "polygon_box_transform": "tests/test_proposal_ops.py _run_op",
    "lookup_sparse_table": "tests/test_framework_ops.py",
    "expand": "tests/test_op_sweep_tensor.py _case",
    "flatten": "tensor sweep",
    "fill_zeros_like": "tensor sweep",
    "fill_constant_batch_size_like": "model tests (transformer decode)",
    "gaussian_random_batch_size_like": "tests/test_op_sweep_tail2.py",
    "uniform_random_batch_size_like": "tests/test_op_sweep_tail2.py",
    "multiplex": "tensor sweep",
    "one_hot": "tensor sweep",
    "pad": "tensor sweep",
    "pad2d": "tensor sweep",
    "pad_constant_like": "tensor sweep",
    "range": "tensor sweep",
    "reduce_all": "reduce sweep",
    "reduce_any": "reduce sweep",
    "reverse": "tensor sweep",
    "scatter": "tensor sweep",
    "shape": "tensor sweep",
    "slice": "tensor sweep",
    "split": "tensor sweep",
    "squeeze": "tensor sweep",
    "stack": "tensor sweep",
    "unsqueeze": "tensor sweep",
    "unstack": "tensor sweep",
}


def _scanned_coverage():
    covered = set()
    for f in glob.glob(os.path.join(TESTS_DIR, "**", "*.py"),
                       recursive=True):
        if os.path.basename(f) == os.path.basename(__file__):
            continue  # don't let this manifest cover anything by itself
        txt = open(f).read()
        for pat in _DIRECT_PATTERNS:
            covered |= set(re.findall(pat, txt, re.M))
    return covered


def test_every_op_covered_or_mapped():
    from paddle_tpu.core.registry import OpRegistry

    nond = {m for m in OpRegistry._ops if not m.endswith("_grad")}
    covered = _scanned_coverage()
    missing = sorted(nond - covered - set(EXERCISED_VIA)
                     - set(ALIASED_COVERAGE))
    assert missing == [], (
        f"ops with neither a test-harness mention nor an EXERCISED_VIA "
        f"mapping: {missing}")


@pytest.mark.parametrize("op_name", sorted(EXERCISED_VIA),
                         ids=sorted(EXERCISED_VIA))
def test_exercised_via_mapping_is_live(op_name):
    """The mapped layer surface really emits the op and really runs."""
    fluid.reset_default_env()
    fetch, feed = EXERCISED_VIA[op_name]()
    prog = fluid.default_main_program()
    types = set()
    for b in prog.blocks:
        types |= {op.type for op in b.desc.ops}
    assert op_name in types, (
        f"{op_name} not emitted by its mapped builder (got {sorted(types)})")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    fetches = fetch if isinstance(fetch, list) else [fetch]
    exe.run(feed=feed, fetch_list=fetches, return_numpy=False)
