"""Imperative (dygraph) mode (reference: test_imperative.py)."""

import jax.numpy as jnp
import numpy as np

import paddle_tpu as fluid
from paddle_tpu.imperative import PyLayer, guard, to_variable


def test_to_variable_and_numpy():
    with guard():
        v = to_variable(np.ones((2, 3), dtype="float32"))
        np.testing.assert_array_equal(v.numpy(), np.ones((2, 3)))
        assert fluid.imperative.enabled()
    assert not fluid.imperative.enabled()


class MyLayer(PyLayer):
    """reference: test_imperative.py MyLayer (relu -> elementwise_mul -> sum)."""

    def forward(self, x):
        x = jnp.maximum(x, 0.0)
        return jnp.sum(x * x)


def test_pylayer_forward_backward():
    npx = np.array([[1.0, -1.0], [2.0, 3.0]], dtype="float32")
    with guard():
        layer = MyLayer()
        x = to_variable(npx)
        out = layer(x)
        out.backward()
        g = x.gradient
    relu = np.maximum(npx, 0)
    want = 2 * relu * (npx > 0)
    np.testing.assert_allclose(np.asarray(out.numpy()), np.sum(relu * relu))
    np.testing.assert_allclose(g, want)


class Linear(PyLayer):
    def __init__(self, d_in, d_out):
        super().__init__()
        self.w = self.create_parameter([d_in, d_out])
        self.b = self.create_parameter([d_out], init=np.zeros(d_out, "float32"))

    def forward(self, x):
        return x @ self.w._value + self.b._value


def test_pylayer_sgd_training():
    rng = np.random.RandomState(0)
    xv = rng.randn(16, 4).astype("float32")
    yv = (xv @ np.array([[1.0], [2.0], [-1.0], [0.5]], "float32"))
    with guard():
        model = Linear(4, 1)
        losses = []
        for _ in range(50):
            x = to_variable(xv)
            pred = model(x)

            def loss_of(p):
                return jnp.mean((p - yv) ** 2)

            from paddle_tpu.imperative import _record

            loss = _record(loss_of, pred)
            loss.backward()
            for p in model.parameters():
                p._value = p._value - 0.1 * p._grad
                p.clear_gradient()
            losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0] * 0.05
