"""Disaggregated prefill/decode fleet (paddle_tpu/serving/fleet/):
KV handoff, elastic autoscaling, rolling upgrades, chaos degradation.

Acceptance criteria pinned here (ISSUE 15):
(a) disaggregated prefill→handoff→decode output is TOKEN-IDENTICAL to
    the monolithic ContinuousBatchingLoop oracle across the
    H_kv∈{8,2} × {fp32,int8} × prefix-cache hit/miss matrix, with zero
    leaked pages and check_invariants green on BOTH pools;
(b) prefix-cache composition ships only the unshared tail (the
    destination re-attaches shared pages from its own cache, pinned by
    a transfer reservation);
(c) the autoscaler scales each class between min/max on queue/shed
    signals read from heartbeat payloads (in-process AND over the
    RemoteMaster RPC plane), with scale decisions visible in flight
    events;
(d) replica kill mid-traffic and a rolling weight upgrade both finish
    with lost_requests=0 (failover / zero-loss drain handoff);
(e) ghost leases are fixed: ReplicaDirectory.deregister (wired into
    Router.remove_replica and Fleet.remove_replica) stops a removed
    replica from haunting every later expired() poll;
(f) Router routing tables survive a concurrent submit-vs-membership
    storm with no request lost, misrouted, or double-dispatched.
"""

import os
import threading
import time

import numpy as np
import pytest

from paddle_tpu import flags as pflags
from paddle_tpu import observability as obs
from paddle_tpu import serving
from paddle_tpu.elastic.master import InMemStore, MasterService
from paddle_tpu.elastic.rpc import RemoteMaster, serve_master
from paddle_tpu.resilience import faultinject
from paddle_tpu.serving import (
    ContinuousBatchingLoop,
    DecodeConfig,
    DecodeRequest,
    Engine,
    EngineConfig,
    KVCachePool,
)
from paddle_tpu.serving.distributed import (
    ReplicaDirectory,
    ReplicaUnavailableError,
    Router,
)
from paddle_tpu.serving.fleet import (
    AutoscalePolicy,
    DecodeReplica,
    Fleet,
    FleetController,
    FleetReplica,
    PrefillReplica,
    ReplicaKilledError,
)


def _cfg(**kw):
    base = dict(vocab_size=64, d_model=32, n_head=4, n_layer=2,
                d_inner=64, max_length=48)
    base.update(kw)
    return DecodeConfig(**base)


def _mk_fleet(params, cfg, n_prefill=1, n_decode=1, dtype="float32",
              pages=64, page_size=4, max_batch=4, directory=None,
              prefix_cache=True, beat_every_s=0.05, **fleet_kw):
    return Fleet(
        lambda n: PrefillReplica(
            n, params, cfg, num_pages=pages, page_size=page_size,
            dtype=dtype, max_batch=max_batch,
            prefix_cache=prefix_cache, beat_every_s=beat_every_s),
        lambda n: DecodeReplica(
            n, params, cfg, num_pages=pages, page_size=page_size,
            dtype=dtype, max_batch=max_batch,
            prefix_cache=prefix_cache, beat_every_s=beat_every_s),
        n_prefill=n_prefill, n_decode=n_decode, directory=directory,
        **fleet_kw)


# ---------------------------------------------------------------------------
# export_seq / import_seq: the KV handoff substrate


def _write_random(pool, seq_id, tokens, seed=0):
    rng = np.random.RandomState(seed)
    pages, slots = pool.append_tokens([seq_id], [tokens])
    for li in range(pool.num_layers):
        pool.write_kv(
            li, pages, slots,
            rng.rand(tokens, pool.num_kv_heads,
                     pool.head_dim).astype(np.float32),
            rng.rand(tokens, pool.num_kv_heads,
                     pool.head_dim).astype(np.float32))


def _gathered(pool, seq_id):
    tables, lengths = pool.page_table_batch([seq_id])
    return (np.asarray(pool.k_pages[:, :, tables[0]]),
            np.asarray(pool.v_pages[:, :, tables[0]]), int(lengths[0]))


def test_export_import_roundtrip_fp32():
    a = KVCachePool(16, 4, 2, 4, 8, name="src")
    b = KVCachePool(16, 4, 2, 4, 8, name="dst")
    a.allocate(0)
    _write_random(a, 0, 10)
    ex = a.export_seq(0)
    assert ex.length == 10 and ex.skip_tokens == 0
    assert ex.k.shape == (2, 4, 3, 4, 8)
    assert ex.nbytes() == 2 * ex.k.nbytes
    b.allocate(7)
    pages, tokens = b.import_seq(ex, 7)
    assert (pages, tokens) == (3, 10)
    ka, va, la = _gathered(a, 0)
    kb, vb, lb = _gathered(b, 7)
    assert la == lb == 10
    np.testing.assert_array_equal(ka, kb)
    np.testing.assert_array_equal(va, vb)
    # export leaves the source untouched; both pools audit green
    assert a.check_invariants()["ok"] and b.check_invariants()["ok"]
    assert a.stats()["seqs_exported"] == 1
    assert b.stats()["seqs_imported"] == 1
    a.free_seq(0)
    b.free_seq(7)
    assert a.used_pages == 0 and b.used_pages == 0


def test_export_import_int8_scales_travel():
    a = KVCachePool(16, 4, 2, 4, 8, dtype="int8", name="src8")
    b = KVCachePool(16, 4, 2, 4, 8, dtype="int8", name="dst8")
    a.allocate(0)
    _write_random(a, 0, 9, seed=3)
    ex = a.export_seq(0)
    assert ex.k_scales is not None and ex.k_scales.shape == (2, 3)
    b.allocate(1)
    b.import_seq(ex, 1)
    ka, va, _ = _gathered(a, 0)
    kb, vb, _ = _gathered(b, 1)
    np.testing.assert_array_equal(ka, kb)  # int8 content verbatim
    ta, _ = a.page_table_batch([0])
    tb, _ = b.page_table_batch([1])
    np.testing.assert_array_equal(a.k_scales[:, ta[0]],
                                  b.k_scales[:, tb[0]])
    # the freed-pages-carry-no-scale / live-pages-have-scales audit
    assert b.check_invariants()["scale_errors"] == []
    assert b.check_invariants()["ok"]
    a.free_seq(0)
    b.free_seq(1)
    assert b.check_invariants()["ok"]


def test_export_import_validation_and_atomicity():
    a = KVCachePool(16, 4, 2, 4, 8)
    a.allocate(0)
    _write_random(a, 0, 10)
    with pytest.raises(ValueError, match="page boundary|multiple"):
        a.export_seq(0, skip_tokens=3)  # not page-aligned
    with pytest.raises(ValueError, match="multiple|page boundary"):
        a.export_seq(0, skip_tokens=12)  # >= length
    ex = a.export_seq(0)
    # geometry mismatches are loud
    wrong = KVCachePool(16, 8, 2, 4, 8)
    wrong.allocate(0)
    with pytest.raises(ValueError, match="page_size"):
        wrong.import_seq(ex, 0)
    wrong_dtype = KVCachePool(16, 4, 2, 4, 8, dtype="int8")
    wrong_dtype.allocate(0)
    with pytest.raises(ValueError, match="dtype"):
        wrong_dtype.import_seq(ex, 0)
    # the destination must hold exactly the skipped prefix
    b = KVCachePool(16, 4, 2, 4, 8)
    b.allocate(5)
    b.append_tokens([5], [2])
    with pytest.raises(ValueError, match="re-attach"):
        b.import_seq(ex, 5)
    # exhaustion raises BEFORE any table mutates (atomic claim)
    tiny = KVCachePool(2, 4, 2, 4, 8)
    tiny.allocate(9)
    from paddle_tpu.serving import PagePoolExhausted

    with pytest.raises(PagePoolExhausted):
        tiny.import_seq(ex, 9)
    assert tiny.length(9) == 0 and tiny.used_pages == 0
    assert tiny.check_invariants()["ok"]


def test_export_skip_tokens_ships_only_tail():
    a = KVCachePool(16, 4, 2, 4, 8)
    a.allocate(0)
    _write_random(a, 0, 10)
    full = a.export_seq(0)
    tail = a.export_seq(0, skip_tokens=8)
    assert tail.skip_tokens == 8 and tail.k.shape[2] == 1
    assert tail.nbytes() < full.nbytes()
    np.testing.assert_array_equal(tail.k, full.k[:, :, 2:])


# ---------------------------------------------------------------------------
# (a) disaggregated output == monolithic oracle, across the matrix


@pytest.mark.parametrize("n_kv_head", [8, 2])
@pytest.mark.parametrize("dtype", ["float32", "int8"])
@pytest.mark.parametrize("prefix", ["hit", "miss"])
def test_disagg_token_identical_to_monolithic(n_kv_head, dtype, prefix):
    cfg = _cfg(n_head=8, n_kv_head=n_kv_head, n_layer=1)
    params = serving.init_decode_params(cfg, seed=11)
    rng = np.random.RandomState(11)
    if prefix == "hit":
        shared = rng.randint(1, cfg.vocab_size, size=13).tolist()
        prompts = [shared + rng.randint(1, cfg.vocab_size,
                                        size=3).tolist()
                   for _ in range(4)]
    else:
        prompts = [rng.randint(1, cfg.vocab_size, size=n).tolist()
                   for n in (5, 9, 4, 7)]

    def reqs():
        return [DecodeRequest(prompt=list(p), max_new_tokens=5)
                for p in prompts]

    # monolithic oracle, SAME submission discipline (first request
    # warms its prefix cache, the rest hit)
    mpool = KVCachePool(64, 4, cfg.n_layer, cfg.n_head, cfg.head_dim,
                        num_kv_heads=cfg.num_kv_heads, dtype=dtype)
    mcache = serving.PrefixCache(mpool)
    mono = ContinuousBatchingLoop(params, cfg, mpool, max_batch=4,
                                  prefix_cache=mcache)
    want = mono.run(reqs()[:1]) + mono.run(reqs()[1:])

    fleet = _mk_fleet(params, cfg, dtype=dtype)
    try:
        r = reqs()
        first = fleet.submit(r[0]).result(120)
        rest = [f.result(120) for f in
                [fleet.submit(q) for q in r[1:]]]
        got = [first] + rest
        for w, g in zip(want, got):
            assert g.error is None
            assert g.tokens == w.tokens
        st = fleet.stats()
        assert st["handoffs"] == 4 and st["lost_requests"] == 0
        if prefix == "hit":
            # both sides actually shared: the oracle hit its cache and
            # the handoffs shipped only the unshared tail
            assert mono.prefix_hits >= 1
            assert st["skipped_tokens"] > 0
        audit = fleet.audit()
        assert audit["pages_leaked"] == 0 and audit["invariants_ok"]
    finally:
        fleet.close()
    mcache.clear()
    assert mpool.used_pages == 0 and mpool.check_invariants()["ok"]


def test_handoff_prefix_reuse_shrinks_payload():
    cfg = _cfg()
    params = serving.init_decode_params(cfg, seed=2)
    rng = np.random.RandomState(2)
    shared = rng.randint(1, cfg.vocab_size, size=12).tolist()
    fleet = _mk_fleet(params, cfg)
    try:
        sizes = []
        orig = Fleet._dispatch_decode

        def spy(self, hd, *a, **kw):
            sizes.append((hd.payload.skip_tokens, hd.nbytes()))
            return orig(self, hd, *a, **kw)

        Fleet._dispatch_decode = spy
        try:
            for k in range(3):
                tail = rng.randint(1, cfg.vocab_size, size=3).tolist()
                fleet.infer(DecodeRequest(prompt=shared + tail,
                                          max_new_tokens=4),
                            timeout=120)
        finally:
            Fleet._dispatch_decode = orig
        # first handoff ships everything; later ones skip the shared
        # full pages and ship strictly less
        assert sizes[0][0] == 0
        assert sizes[1][0] >= 8 and sizes[2][0] >= 8
        assert sizes[1][1] < sizes[0][1]
        audit = fleet.audit()
        assert audit["pages_leaked"] == 0 and audit["invariants_ok"]
    finally:
        fleet.close()


# ---------------------------------------------------------------------------
# chaos: quarantine-not-crash degradation


def test_prefill_quarantine_not_crash():
    cfg = _cfg()
    params = serving.init_decode_params(cfg, seed=5)
    rng = np.random.RandomState(5)
    prompts = [rng.randint(1, cfg.vocab_size, size=n).tolist()
               for n in (4, 6, 5)]
    fleet = _mk_fleet(params, cfg)
    os.environ["FAULT_SERVE_NAN_SEQ"] = "0@0"  # first prefill step
    try:
        futs = [fleet.submit(DecodeRequest(prompt=list(p),
                                           max_new_tokens=4))
                for p in prompts]
        results = [f.result(120) for f in futs]
    finally:
        os.environ.pop("FAULT_SERVE_NAN_SEQ", None)
        faultinject.reset()
    errs = [r for r in results if r.error is not None]
    assert len(errs) == 1
    assert isinstance(errs[0].error, serving.NonFiniteSequenceError)
    ok = [r for r in results if r.error is None]
    assert all(len(r.tokens) == 4 for r in ok)
    pre = fleet.replicas("prefill")["prefill0"]
    assert pre.alive and pre.quarantined == 1
    st = fleet.stats()
    assert st["lost_requests"] == 0
    audit = fleet.audit()
    assert audit["pages_leaked"] == 0 and audit["invariants_ok"]
    fleet.close()


def test_replica_kill_failover_zero_lost():
    cfg = _cfg()
    params = serving.init_decode_params(cfg, seed=7)
    rng = np.random.RandomState(7)
    prompts = [rng.randint(1, cfg.vocab_size, size=4 + n % 4).tolist()
               for n in range(10)]
    fleet = _mk_fleet(params, cfg, n_decode=2)
    ctl = FleetController(fleet, min_replicas={"decode": 2})
    os.environ["FAULT_SERVE_REPLICA_KILL"] = "decode0"
    try:
        futs = [fleet.submit(DecodeRequest(prompt=list(p),
                                           max_new_tokens=4))
                for p in prompts]
        results = [f.result(120) for f in futs]
        assert all(r.error is None for r in results)
        # the victim is dead; the controller quarantines and replaces
        deadline = time.perf_counter() + 5.0
        while fleet.replicas("decode")["decode0"].alive \
                and time.perf_counter() < deadline:
            time.sleep(0.02)
        assert not fleet.replicas("decode")["decode0"].alive
        ctl.step()
        st = fleet.stats()
        assert st["lost_requests"] == 0
        assert st["replica_deaths"] == 1
        assert "decode2" in fleet.replicas("decode")  # replacement
        assert any(d["action"] == "replica_dead"
                   for d in ctl.decisions)
    finally:
        os.environ.pop("FAULT_SERVE_REPLICA_KILL", None)
        faultinject.reset()
        fleet.close()


def test_handoff_drop_requeues_zero_lost():
    cfg = _cfg()
    params = serving.init_decode_params(cfg, seed=9)
    rng = np.random.RandomState(9)
    prompts = [rng.randint(1, cfg.vocab_size, size=n).tolist()
               for n in (5, 7, 4)]
    want = [serving.full_decode(params, cfg, p, 4)[0] for p in prompts]
    fleet = _mk_fleet(params, cfg, prefix_cache=False)
    os.environ["FAULT_SERVE_HANDOFF_DROP"] = "1"
    try:
        futs = [fleet.submit(DecodeRequest(prompt=list(p),
                                           max_new_tokens=4))
                for p in prompts]
        results = [f.result(120) for f in futs]
        for w, g in zip(want, results):
            assert g.error is None and g.tokens == w
        st = fleet.stats()
        assert st["handoff_drops"] == 1
        assert st["re_prefills"] == 1
        assert st["lost_requests"] == 0
    finally:
        os.environ.pop("FAULT_SERVE_HANDOFF_DROP", None)
        faultinject.reset()
        fleet.close()


def test_engine_replica_kill_goes_broken_without_restart():
    """The Engine-level arm of FAULT_SERVE_REPLICA_KILL (serve_bench
    --chaos --replicas): the dispatcher dies WITHOUT supervisor
    restart, queued futures fail typed, health goes BROKEN."""

    class _Slow:
        feed_names = ["x"]
        fetch_names = ["y"]
        meta: dict = {}

        def __call__(self, feed):
            time.sleep(0.05)
            return [np.asarray(feed["x"]) * 2.0]

    eng = Engine(_Slow(), config=EngineConfig(
        buckets=(1,), max_wait_s=0.0), name="victim")
    try:
        eng.infer({"x": np.ones((1, 2), np.float32)})
        os.environ["FAULT_SERVE_REPLICA_KILL"] = "victim"
        futs = [eng.submit({"x": np.ones((1, 2), np.float32)})
                for _ in range(4)]
        failed = 0
        for f in futs:
            try:
                f.result(timeout=10)
            except Exception:
                failed += 1
        assert failed >= 1  # queued requests failed typed, none hang
        deadline = time.perf_counter() + 5.0
        while eng.health()["state"] != "BROKEN" \
                and time.perf_counter() < deadline:
            time.sleep(0.02)
        assert eng.health()["state"] == "BROKEN"
        st = eng.stats()
        assert st["replica_killed"] is True
        assert st["dispatcher_restarts"] == 0
        # a dead engine must REJECT new submits typed, not strand them
        # in a queue nothing drains (the router falls over on this even
        # when its cached health snapshot predates the kill)
        with pytest.raises(serving.EngineClosedError):
            eng.submit({"x": np.ones((1, 2), np.float32)})
    finally:
        os.environ.pop("FAULT_SERVE_REPLICA_KILL", None)
        faultinject.reset()


# ---------------------------------------------------------------------------
# (c) autoscaler: policy units + e2e with flight events


class _StubFleet:
    directory = None
    name = "stub"

    def replicas(self, role=None):
        return {}


def _sig(replicas=1, queue=0, shed=0):
    return {"replicas": replicas, "queue_depth": queue, "shed": shed,
            "dead": []}


def test_autoscale_policy_units():
    ctl = FleetController(
        _StubFleet(),
        policy=AutoscalePolicy(queue_high=4, sustain=2, idle_sustain=3,
                               cooldown=1),
        min_replicas={"decode": 1}, max_replicas={"decode": 3})
    # queue pressure must SUSTAIN before scale-up
    assert ctl._decide("decode", _sig(queue=10)) is None  # streak 1
    assert ctl._decide("decode", _sig(queue=10)) == "scale_up"
    # cooldown holds the very next step even under pressure
    assert ctl._decide("decode", _sig(replicas=2, queue=20)) is None
    # shed delta alone is pressure (queue empty): streak reaches 2
    assert ctl._decide("decode", _sig(replicas=2,
                                      shed=3)) == "scale_up"
    # cooldown again, then the MAX clamp: pressured at max never
    # scales up
    assert ctl._decide("decode", _sig(replicas=3, queue=99,
                                      shed=3)) is None  # cooldown
    assert ctl._decide("decode", _sig(replicas=3, queue=99,
                                      shed=3)) is None  # at max
    assert ctl._decide("decode", _sig(replicas=3, queue=99,
                                      shed=3)) is None  # still at max
    # idleness must sustain before scale-down (queue 0, no new shed)
    assert ctl._decide("decode", _sig(replicas=3, shed=3)) is None
    assert ctl._decide("decode", _sig(replicas=3, shed=3)) is None
    assert ctl._decide("decode", _sig(replicas=3,
                                      shed=3)) == "scale_down"
    # min clamp: idle at min never scales down
    ctl2 = FleetController(
        _StubFleet(),
        policy=AutoscalePolicy(idle_sustain=1, cooldown=0))
    for _ in range(4):
        assert ctl2._decide("decode", _sig(replicas=1)) is None


def test_controller_scale_up_down_e2e_flight_events():
    pflags.set_flags({"FLAGS_observability": True})
    obs.reset()
    cfg = _cfg()
    params = serving.init_decode_params(cfg, seed=13)
    rng = np.random.RandomState(13)
    fleet = _mk_fleet(params, cfg)
    ctl = FleetController(
        fleet,
        policy=AutoscalePolicy(queue_high=2, sustain=2, idle_sustain=2,
                               cooldown=0),
        max_replicas={"prefill": 2, "decode": 2})
    try:
        futs = [fleet.submit(DecodeRequest(
            prompt=rng.randint(1, cfg.vocab_size, size=5).tolist(),
            max_new_tokens=4)) for _ in range(10)]
        # burst: back-to-back steps see the sustained queue
        ctl.step()
        ctl.step()
        assert fleet.stats()["scale_ups"] >= 1
        [f.result(120) for f in futs]
        for _ in range(3):
            ctl.step()
        st = fleet.stats()
        assert st["scale_downs"] >= 1
        assert st["lost_requests"] == 0
        kinds = [e["kind"] for e in obs.default_flight().events()]
        assert "scale_up" in kinds and "scale_down" in kinds
        assert "handoff" in kinds
    finally:
        fleet.close()
        pflags.set_flags({"FLAGS_observability": False})
        obs.reset()


# ---------------------------------------------------------------------------
# (d) rolling upgrade under live traffic


def test_rolling_upgrade_zero_lost_and_new_params_serve():
    cfg = _cfg()
    p_old = serving.init_decode_params(cfg, seed=1)
    p_new = serving.init_decode_params(cfg, seed=2)
    rng = np.random.RandomState(1)
    prompts = [rng.randint(1, cfg.vocab_size, size=n).tolist()
               for n in (4, 6, 3, 5)]
    fleet = _mk_fleet(p_old, cfg, n_decode=2)
    ctl = FleetController(fleet, min_replicas={"decode": 2})
    try:
        # warm every step shape so drains are fast
        [f.result(120) for f in
         [fleet.submit(DecodeRequest(prompt=list(p), max_new_tokens=4))
          for p in prompts]]
        stop = threading.Event()
        futs, lock = [], threading.Lock()

        def traffic():
            i = 0
            while not stop.is_set():
                f = fleet.submit(DecodeRequest(
                    prompt=list(prompts[i % len(prompts)]),
                    max_new_tokens=4))
                with lock:
                    futs.append(f)
                i += 1
                time.sleep(0.02)

        t = threading.Thread(target=traffic)
        t.start()
        time.sleep(0.1)
        upgraded = ctl.rolling_upgrade(p_new, timeout=60.0)
        stop.set()
        t.join()
        assert upgraded == ["prefill0", "decode0", "decode1"]
        results = [f.result(120) for f in futs]
        assert all(r.error is None for r in results)
        st = fleet.stats()
        # zero lost, zero duplicated: every submit resolved exactly
        # once and nothing failed
        assert st["lost_requests"] == 0 and st["failed"] == 0
        assert st["upgrades"] == 3
        # the upgraded fleet serves the NEW weights
        want, _ = serving.full_decode(p_new, cfg, prompts[0], 4)
        got = fleet.infer(DecodeRequest(prompt=list(prompts[0]),
                                        max_new_tokens=4), timeout=120)
        assert got.tokens == want
        audit = fleet.audit()
        assert audit["pages_leaked"] == 0 and audit["invariants_ok"]
    finally:
        fleet.close()


# ---------------------------------------------------------------------------
# heartbeat payloads + controller signals over the RPC plane


def test_heartbeat_payloads_and_signals_over_remote_master():
    master = MasterService(InMemStore(), timeout_dur=60.0)
    server = serve_master(master)
    remote = RemoteMaster(server.endpoint)
    directory = ReplicaDirectory(remote, max_silence_s=2.0)
    cfg = _cfg()
    params = serving.init_decode_params(cfg, seed=3)
    fleet = _mk_fleet(params, cfg, n_decode=2, directory=directory)
    ctl = FleetController(fleet)
    try:
        deadline = time.perf_counter() + 5.0
        while time.perf_counter() < deadline:
            st = directory.status()
            if set(st) == {"prefill0", "decode0", "decode1"} and all(
                    v["payload"] for v in st.values()):
                break
            time.sleep(0.05)
        st = directory.status()
        assert set(st) == {"prefill0", "decode0", "decode1"}
        assert st["decode0"]["payload"]["role"] == "decode"
        assert st["prefill0"]["payload"]["state"] == "SERVING"
        assert "queue_depth" in st["decode1"]["payload"]
        # the controller reads the SAME signals through the RPC plane
        sigs = ctl.signals()
        assert sigs["decode"]["replicas"] == 2
        assert sigs["prefill"]["replicas"] == 1
        # deregistration over RPC: no ghost lease after removal
        fleet.drain_replica("decode1", timeout=30)
        fleet.remove_replica("decode1")
        assert "decode1" not in directory.status()
        time.sleep(0.1)
        assert "decode1" not in directory.expired()
    finally:
        fleet.close()
        remote.shutdown_server()


# ---------------------------------------------------------------------------
# (e) ghost leases: deregister on removal


def test_replica_directory_deregister_fixes_ghost_lease():
    master = MasterService(InMemStore(), timeout_dur=60.0)
    directory = ReplicaDirectory(master, max_silence_s=0.1)
    directory.register("gone")
    directory.register("alive")
    time.sleep(0.15)
    directory.beat("alive")
    # without deregistration the silent replica haunts every poll
    assert "gone" in directory.expired()
    directory.deregister("gone")
    assert "gone" not in directory.expired()
    assert "gone" not in directory.status()
    time.sleep(0.15)
    assert directory.expired() == ["alive"]  # real expiry still works


def test_router_remove_replica_deregisters_lease():
    class _Noop:
        feed_names = ["x"]
        fetch_names = ["y"]
        meta: dict = {}

        def __call__(self, feed):
            return [np.asarray(feed["x"])]

    master = MasterService(InMemStore(), timeout_dur=60.0)
    directory = ReplicaDirectory(master, max_silence_s=0.1)
    e0 = Engine(_Noop(), config=EngineConfig(buckets=(1,)), name="r0")
    e1 = Engine(_Noop(), config=EngineConfig(buckets=(1,)), name="r1")
    router = Router([e0, e1], directory=directory,
                    health_cache_s=0.0)
    router.drain_replica("r0", timeout=10)
    router.remove_replica("r0")
    time.sleep(0.15)
    directory.beat("r1")
    # the REGRESSION: before deregister-on-removal, r0 reported
    # lease-expired in every later poll forever
    assert "r0" not in directory.expired()
    router.close()
    e0.close()


def test_prefill_batch_failure_frees_pages_and_replica_recovers():
    """A mid-group prefill raise (pool exhausted under pressure) must
    fail the batch's futures typed and free every allocated sequence —
    leaked pages would shrink the pool forever and wedge swap_params."""
    cfg = _cfg()
    params = serving.init_decode_params(cfg, seed=0)
    rep = PrefillReplica("p0", params, cfg, num_pages=8, page_size=4,
                         prefix_cache=False)
    try:
        # eat most of the pool so the head request passes submit's
        # whole-pool check but cannot claim its pages at process time
        rep.pool.allocate(999)
        rep.pool.append_tokens([999], [24])  # 6 of 8 pages
        req = DecodeRequest(prompt=list(range(1, 17)),
                            max_new_tokens=2)  # needs 4 pages, 2 free
        with pytest.raises(Exception) as ei:
            rep.submit(req).result(timeout=30)
        assert "pool" in str(ei.value).lower()
        # the REGRESSION: the failed group's sequence stayed allocated
        assert rep.pool.used_pages == 6  # only the blocker remains
        rep.pool.free_seq(999)
        assert rep.pool.used_pages == 0
        assert rep.pool.check_invariants()["ok"]
        # and the replica still serves: same request now prefills fine
        hd = rep.submit(req).result(timeout=30)
        assert hd.payload.length == 16
        assert rep.pool.used_pages == 0  # exported then freed
    finally:
        rep.close(timeout=10)


def test_quarantine_silences_flapping_replica_and_fails_over_queue():
    """Quarantining an ALIVE-but-flapping replica (lease lapsed while
    its worker lives on) must stop its heartbeats for good — a
    quarantined worker that kept beating re-registered the ghost lease
    the controller just deregistered, was counted live forever with
    routing off, and the class never got its replacement."""

    class _Slow(FleetReplica):
        role = "decode"

        def _process(self, batch):
            time.sleep(0.2)
            for item, fut in batch:
                fut.set_result(item)

    master = MasterService(InMemStore(), timeout_dur=60.0)
    directory = ReplicaDirectory(master, max_silence_s=10.0)
    rep = _Slow("flappy", max_batch=1, beat_every_s=0.01)
    rep.join_directory(directory)
    f1 = rep._submit_item("a")
    f2 = rep._submit_item("b")
    time.sleep(0.05)  # worker is mid-batch on "a"; "b" still queued
    rep.quarantine()
    directory.deregister("flappy")
    # queued work fails over typed; the in-flight batch still resolves
    with pytest.raises(ReplicaKilledError):
        f2.result(timeout=5)
    assert f1.result(timeout=5) == "a"
    assert not rep.alive and not rep.routing
    rep._thread.join(5.0)
    assert not rep._thread.is_alive()
    # the REGRESSION: no post-quarantine beat resurrected the lease
    time.sleep(0.1)
    assert "flappy" not in directory.status()
    assert "flappy" not in directory.expired()


# ---------------------------------------------------------------------------
# (f) routing-table races: submit vs drain/remove/add storm


def test_router_membership_storm_no_lost_misrouted_or_doubled():
    class _Echo:
        feed_names = ["x"]
        fetch_names = ["y"]
        meta: dict = {}

        def __call__(self, feed):
            time.sleep(0.001)
            return [np.asarray(feed["x"]) * 2.0]

    def _mk(name):
        return Engine(_Echo(), config=EngineConfig(
            buckets=(1, 2, 4), max_wait_s=0.001, queue_depth=512),
            name=name)

    router = Router([_mk("churn0"), _mk("stable")])
    n = 120
    feeds = [np.full((1, 4), i, np.float32) for i in range(n)]
    results: dict = {}
    lock = threading.Lock()
    errors: list = []
    stop_churn = threading.Event()

    def submitter(lo, hi):
        for i in range(lo, hi):
            for _ in range(200):
                try:
                    out = router.submit({"x": feeds[i]}).result(30)
                    break
                except ReplicaUnavailableError:
                    time.sleep(0.002)  # membership mid-swap
            else:
                errors.append(f"request {i} never placed")
                continue
            with lock:
                if i in results:
                    errors.append(f"request {i} resolved twice")
                results[i] = out[0]

    def churner():
        gen = 0
        while not stop_churn.is_set():
            name = f"churn{gen}"
            try:
                # zero-loss removal discipline: drain fully first
                router.drain_replica(name, timeout=10)
                old = router.remove_replica(name)
                old.close()
                gen += 1
                router.add_replica(_mk(f"churn{gen}"))
            except KeyError:
                break
            time.sleep(0.005)

    threads = [threading.Thread(target=submitter,
                                args=(k * 30, (k + 1) * 30))
               for k in range(4)]
    ct = threading.Thread(target=churner)
    [t.start() for t in threads]
    ct.start()
    [t.join(60) for t in threads]
    stop_churn.set()
    ct.join(30)
    assert not errors, errors
    # no lost: every request resolved; no misrouted/cross-wired: each
    # got ITS OWN payload back exactly
    assert len(results) == n
    for i in range(n):
        np.testing.assert_array_equal(results[i], feeds[i] * 2.0)
    st = router.stats()
    # counters consistent after the storm: the surviving members'
    # routed counts are sane and nothing negative/corrupt
    assert st["routed"] >= 1
    assert all(v["routed"] >= 0 and v["skipped"] >= 0
               for v in st["replicas"].values())
    assert "stable" in st["replicas"]
    router.close()


# ---------------------------------------------------------------------------
# serve_bench wiring: --disagg / --fleet / --chaos --replicas


def test_serve_bench_disagg_gate_roundtrip(tmp_path, capsys):
    import json

    from tools.serve_bench import main as bench_main

    bank = tmp_path / "bank.json"
    bank.write_text(json.dumps({
        "lost_requests": 0, "pages_leaked": 0, "invariants_ok": 1,
        "handoff_drops": 0,
    }))
    out_json = tmp_path / "out.json"
    rc = bench_main([
        "--mode", "decode", "--disagg", "--sequences", "5",
        "--max-new", "5", "--pages", "64", "--page-size", "4",
        "--d-model", "32", "--max-len", "48", "--json", str(out_json),
        "--baseline", str(bank), "--gate",
    ])
    capsys.readouterr()
    assert rc == 0
    result = json.loads(out_json.read_text())
    assert result["mode"] == "disagg"
    assert result["handoffs"] == 5
    assert result["handoff_bytes_per_seq"] > 0
    assert result["lost_requests"] == 0
    assert result["pages_leaked"] == 0
    assert result["ttft_p50_ms"] is not None


def test_serve_bench_disagg_gate_teeth_on_handoff_drop(tmp_path,
                                                       capsys):
    """The fleet gate's teeth: an armed FAULT_SERVE_HANDOFF_DROP is
    absorbed (lost_requests still 0) but the banked handoff_drops=0
    regresses — the gate must exit 3."""
    import json

    from tools.serve_bench import main as bench_main

    bank = tmp_path / "bank.json"
    bank.write_text(json.dumps({"lost_requests": 0,
                                "handoff_drops": 0}))
    os.environ["FAULT_SERVE_HANDOFF_DROP"] = "1"
    try:
        rc = bench_main([
            "--mode", "decode", "--disagg", "--sequences", "4",
            "--max-new", "4", "--pages", "64", "--page-size", "4",
            "--d-model", "32", "--max-len", "48",
            "--baseline", str(bank), "--gate",
        ])
    finally:
        os.environ.pop("FAULT_SERVE_HANDOFF_DROP", None)
        faultinject.reset()
    capsys.readouterr()
    assert rc == 3


def test_serve_bench_fleet_elastic_smoke(tmp_path, capsys):
    import json

    from tools.serve_bench import main as bench_main

    out_json = tmp_path / "out.json"
    rc = bench_main([
        "--mode", "decode", "--fleet", "--sequences", "8",
        "--max-new", "5", "--pages", "64", "--page-size", "4",
        "--d-model", "32", "--max-len", "48", "--json", str(out_json),
    ])
    capsys.readouterr()
    assert rc == 0
    result = json.loads(out_json.read_text())
    assert result["mode"] == "fleet"
    assert result["scale_ups"] >= 1
    assert result["scale_downs"] >= 1
    assert result["lost_requests"] == 0
    assert result["invariants_ok"] == 1


def test_serve_bench_chaos_replicas_failover(tmp_path, capsys):
    import json

    from tools.serve_bench import main as bench_main

    bank = tmp_path / "bank.json"
    bank.write_text(json.dumps({"lost_requests": 0,
                                "replica_kills": 1}))
    out_json = tmp_path / "out.json"
    rc = bench_main([
        "--replicas", "2", "--model", "tiny", "--requests", "18",
        "--rate", "400", "--no-warmup", "--chaos",
        "--json", str(out_json), "--baseline", str(bank), "--gate",
    ])
    capsys.readouterr()
    assert rc == 0
    result = json.loads(out_json.read_text())
    assert result["killed_replica"] == "replica1"
    assert result["replica_kills"] == 1
    assert result["lost_requests"] == 0


def test_serve_bench_fleet_usage_errors(capsys):
    from tools.serve_bench import main as bench_main

    # --disagg/--fleet need decode mode and exclude mesh/spec/chaos
    assert bench_main(["--disagg"]) == 2
    assert bench_main(["--fleet"]) == 2
    assert bench_main(["--mode", "decode", "--disagg",
                       "--mesh", "4"]) == 2
    assert bench_main(["--mode", "decode", "--fleet",
                       "--chaos"]) == 2
    assert bench_main(["--mode", "decode", "--disagg",
                       "--sampling", "temp"]) == 2
    capsys.readouterr()
