"""RNN op numerics: masked-scan lowerings vs per-sequence numpy recurrences
(reference: unittests/test_lstm_op.py, test_gru_op.py — same equations,
ragged layout)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.core.lod import create_lod_tensor

RNG = np.random.RandomState(3)
LENS = [4, 2, 5]


def sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def np_lstm_seq(x4h, w, b4, peep, h0, c0):
    """Reference recurrence (math/detail/lstm_kernel.h forward::lstm):
    gate order [c-cand, i, f, o]."""
    hid = w.shape[0]
    ci, cf, co = peep if peep is not None else (None, None, None)
    h, c = h0.copy(), c0.copy()
    hs = []
    for t in range(x4h.shape[0]):
        g = x4h[t] + h @ w + b4
        g_in, g_i, g_f, g_o = np.split(g, 4)
        cand = np.tanh(g_in)
        i = sigmoid(g_i + (c * ci if ci is not None else 0))
        f = sigmoid(g_f + (c * cf if cf is not None else 0))
        c = cand * i + c * f
        o = sigmoid(g_o + (c * co if co is not None else 0))
        h = o * np.tanh(c)
        hs.append(h.copy())
    return np.stack(hs)


@pytest.mark.parametrize("use_peepholes", [True, False])
def test_dynamic_lstm_matches_numpy(use_peepholes):
    hid = 8
    seqs = [RNG.randn(l, 4 * hid).astype(np.float32) * 0.5 for l in LENS]
    x = fluid.layers.data("x", [4 * hid], dtype="float32", lod_level=1)
    h, _c = fluid.layers.dynamic_lstm(
        input=x, size=4 * hid, use_peepholes=use_peepholes,
        param_attr=fluid.ParamAttr(name="lstm_w"),
        bias_attr=fluid.ParamAttr(name="lstm_b"),
    )
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    (res,) = exe.run(feed={"x": create_lod_tensor(seqs)}, fetch_list=[h])
    w = np.asarray(fluid.global_scope().find_var("lstm_w"))
    b = np.asarray(fluid.global_scope().find_var("lstm_b")).ravel()
    b4 = b[: 4 * hid]
    peep = (b[4 * hid:5 * hid], b[5 * hid:6 * hid], b[6 * hid:7 * hid]) if use_peepholes else None
    for i, s in enumerate(seqs):
        expect = np_lstm_seq(s, w, b4, peep, np.zeros(hid, np.float32), np.zeros(hid, np.float32))
        np.testing.assert_allclose(res.data[i, : len(s)], expect, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(res.data[i, len(s):], 0.0, atol=1e-6)


def test_dynamic_lstm_reverse():
    hid = 4
    seqs = [RNG.randn(l, 4 * hid).astype(np.float32) * 0.5 for l in [3, 5]]
    x = fluid.layers.data("x", [4 * hid], dtype="float32", lod_level=1)
    h, _ = fluid.layers.dynamic_lstm(
        input=x, size=4 * hid, use_peepholes=False, is_reverse=True,
        param_attr=fluid.ParamAttr(name="w"), bias_attr=fluid.ParamAttr(name="b"),
    )
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    (res,) = exe.run(feed={"x": create_lod_tensor(seqs)}, fetch_list=[h])
    w = np.asarray(fluid.global_scope().find_var("w"))
    b4 = np.asarray(fluid.global_scope().find_var("b")).ravel()[: 4 * hid]
    for i, s in enumerate(seqs):
        fwd = np_lstm_seq(s[::-1], w, b4, None, np.zeros(hid, np.float32), np.zeros(hid, np.float32))
        np.testing.assert_allclose(res.data[i, : len(s)], fwd[::-1], rtol=1e-4, atol=1e-5)


def np_gru_seq(x3h, w, b, h0):
    hid = w.shape[0]
    h = h0.copy()
    hs = []
    for t in range(x3h.shape[0]):
        g = x3h[t] + b
        ur = g[: 2 * hid] + h @ w[:, : 2 * hid]
        u, r = sigmoid(ur[:hid]), sigmoid(ur[hid:])
        c = np.tanh(g[2 * hid:] + (r * h) @ w[:, 2 * hid:])
        h = h - u * h + u * c
        hs.append(h.copy())
    return np.stack(hs)


def test_dynamic_gru_matches_numpy():
    hid = 6
    seqs = [RNG.randn(l, 3 * hid).astype(np.float32) * 0.5 for l in LENS]
    x = fluid.layers.data("x", [3 * hid], dtype="float32", lod_level=1)
    h = fluid.layers.dynamic_gru(
        input=x, size=hid,
        param_attr=fluid.ParamAttr(name="gru_w"),
        bias_attr=fluid.ParamAttr(name="gru_b"),
    )
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    (res,) = exe.run(feed={"x": create_lod_tensor(seqs)}, fetch_list=[h])
    w = np.asarray(fluid.global_scope().find_var("gru_w"))
    b = np.asarray(fluid.global_scope().find_var("gru_b")).ravel()
    for i, s in enumerate(seqs):
        expect = np_gru_seq(s, w, b, np.zeros(hid, np.float32))
        np.testing.assert_allclose(res.data[i, : len(s)], expect, rtol=1e-4, atol=1e-5)


def test_gru_unit_step():
    hid = 5
    x = fluid.layers.data("x", [3 * hid], dtype="float32")
    hprev = fluid.layers.data("h", [hid], dtype="float32")
    hnew, _rh, _g = fluid.layers.gru_unit(
        input=x, hidden=hprev, size=3 * hid,
        param_attr=fluid.ParamAttr(name="w"), bias_attr=fluid.ParamAttr(name="b"),
    )
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    xv = RNG.randn(3, 3 * hid).astype(np.float32)
    hv = RNG.randn(3, hid).astype(np.float32)
    (res,) = exe.run(feed={"x": xv, "h": hv}, fetch_list=[hnew])
    w = np.asarray(fluid.global_scope().find_var("w"))
    b = np.asarray(fluid.global_scope().find_var("b")).ravel()
    for row in range(3):
        g = xv[row] + b
        ur = g[: 2 * hid] + hv[row] @ w[:, : 2 * hid]
        u, r = sigmoid(ur[:hid]), sigmoid(ur[hid:])
        c = np.tanh(g[2 * hid:] + (r * hv[row]) @ w[:, 2 * hid:])
        expect = hv[row] - u * hv[row] + u * c
        np.testing.assert_allclose(res[row], expect, rtol=1e-4, atol=1e-5)


def test_lstm_unit_step():
    hid = 4
    x = fluid.layers.data("x", [8], dtype="float32")
    hprev = fluid.layers.data("hp", [hid], dtype="float32")
    cprev = fluid.layers.data("cp", [hid], dtype="float32")
    h, c = fluid.layers.lstm_unit(x_t=x, hidden_t_prev=hprev, cell_t_prev=cprev,
                                  forget_bias=1.0)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    feeds = {
        "x": RNG.randn(2, 8).astype(np.float32),
        "hp": RNG.randn(2, hid).astype(np.float32),
        "cp": RNG.randn(2, hid).astype(np.float32),
    }
    hv, cv = exe.run(feed=feeds, fetch_list=[h, c])
    assert hv.shape == (2, hid) and cv.shape == (2, hid)
    assert np.isfinite(hv).all() and np.isfinite(cv).all()


def test_cudnn_lstm_layer():
    t, n, d, hid = 6, 3, 5, 7
    # dense [T, N, D] input: build with explicit shape
    prog = fluid.default_main_program()
    xv = prog.global_block().create_var(name="seq_in", shape=[t, n, d], dtype="float32",
                                        stop_gradient=True)
    init_h = prog.global_block().create_var(name="init_h", shape=[1, n, hid], dtype="float32",
                                            stop_gradient=True)
    init_c = prog.global_block().create_var(name="init_c", shape=[1, n, hid], dtype="float32",
                                            stop_gradient=True)
    out, lh, lc = fluid.layers.lstm(xv, init_h, init_c, max_len=t,
                                    hidden_size=hid, num_layers=2)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    feeds = {
        "seq_in": RNG.randn(t, n, d).astype(np.float32),
        "init_h": np.zeros((2, n, hid), np.float32),
        "init_c": np.zeros((2, n, hid), np.float32),
    }
    o, h_last, c_last = exe.run(feed=feeds, fetch_list=[out, lh, lc])
    assert o.shape == (t, n, hid)
    assert h_last.shape == (2, n, hid)


def test_stacked_dynamic_lstm_trains():
    """Milestone: the stacked_dynamic_lstm benchmark model trains
    (reference: benchmark/fluid/models/stacked_dynamic_lstm.py)."""
    from paddle_tpu import models

    spec = models.stacked_dynamic_lstm(
        vocab_size=100, emb_dim=16, lstm_size=16, max_len=12
    )
    fluid.optimizer.Adam(learning_rate=1e-2).minimize(spec.loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    batch = spec.synthetic_batch(8)
    losses = []
    for _ in range(15):
        (l,) = exe.run(feed=batch, fetch_list=[spec.loss])
        losses.append(float(np.ravel(l)[0]))
    assert losses[-1] < losses[0]


def test_fusion_lstm_matches_fc_plus_lstm():
    """fusion_lstm == (x @ WeightX) fed to the lstm op
    (reference: fused/fusion_lstm_op.cc)."""
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu.core.lod import create_lod_tensor

    rng = np.random.RandomState(0)
    M, H = 5, 4
    lens = [3, 2]
    flat = rng.randn(sum(lens), M).astype("float32") * 0.5
    wx = rng.randn(M, 4 * H).astype("float32") * 0.3
    wh = rng.randn(H, 4 * H).astype("float32") * 0.3
    bias = rng.randn(1, 4 * H).astype("float32") * 0.1

    def run(op_type):
        fluid.reset_default_env()
        prog = fluid.Program()
        with fluid.program_guard(prog, fluid.Program()):
            block = prog.global_block()
            names = {}
            for n, v in [("x", flat), ("wx", wx), ("wh", wh), ("b", bias)]:
                shape = [-1] + list(np.shape(v)[1:]) if n == "x" else list(np.shape(v))
                block.create_var(name=n, shape=shape, dtype="float32",
                                 lod_level=1 if n == "x" else 0)
                names[n] = n
            for slot in ("hidden", "cell", "xx", "bg", "pre"):
                block.create_var(name=slot, shape=[-1, H], dtype="float32",
                                 lod_level=1)
            if op_type == "fusion_lstm":
                block.append_op(
                    type="fusion_lstm",
                    inputs={"X": ["x"], "WeightX": ["wx"],
                            "WeightH": ["wh"], "Bias": ["b"]},
                    outputs={"Hidden": ["hidden"], "Cell": ["cell"],
                             "XX": ["xx"]},
                    attrs={"use_peepholes": False},
                )
            else:
                block.create_var(name="xin", shape=[-1, 4 * H],
                                 dtype="float32", lod_level=1)
                block.append_op(type="mul", inputs={"X": ["x"], "Y": ["wx"]},
                                outputs={"Out": ["xin"]},
                                attrs={"x_num_col_dims": 1,
                                       "y_num_col_dims": 1})
                block.append_op(
                    type="lstm",
                    inputs={"Input": ["xin"], "Weight": ["wh"],
                            "Bias": ["b"]},
                    outputs={"Hidden": ["hidden"], "Cell": ["cell"],
                             "BatchGate": ["bg"], "BatchCellPreAct": ["pre"]},
                    attrs={"use_peepholes": False},
                )
        exe = fluid.Executor(fluid.CPUPlace())
        lod = create_lod_tensor(flat, [lens])
        (h,) = exe.run(program=prog,
                       feed={"x": lod, "wx": wx, "wh": wh, "b": bias},
                       fetch_list=["hidden"], return_numpy=False)
        return np.asarray(h.data)

    np.testing.assert_allclose(run("fusion_lstm"), run("lstm"),
                               rtol=1e-5, atol=1e-6)
