"""Per-op numeric sweep: the RNN tail VERDICT r2 weak #4 named — lstmp,
cudnn_lstm, lstm_unit — plus a full numpy reference for yolov3_loss.
References below are written independently from the reference kernels'
documented math (operators/lstmp_op.cc, cudnn_lstm_op.cu.cc,
lstm_unit_op.h:63-66, yolov3_loss_op.h)."""

import numpy as np

from op_test import OpTest


def _sig(x):
    return 1.0 / (1.0 + np.exp(-x))


def _rand(shape, seed, lo=-1.0, hi=1.0):
    return np.random.RandomState(seed).uniform(lo, hi, shape).astype(
        "float32")


# ---------------------------------------------------------------------------
# lstmp: LSTM with recurrent projection.  Input is pre-projected [sum(T), 4P4H]
# gate layout [c-candidate, input, forget, output]; recurrence runs from the
# PROJECTED state (math/detail/lstm_cpu_kernel.h + lstmp_op.cc).
# ---------------------------------------------------------------------------
def _lstmp_ref(seqs, w, pw, b, use_peepholes):
    hid = w.shape[1] // 4
    proj = pw.shape[1]
    b4 = b[:4 * hid]
    ci = b[4 * hid:5 * hid] if use_peepholes else 0.0
    cf = b[5 * hid:6 * hid] if use_peepholes else 0.0
    co = b[6 * hid:7 * hid] if use_peepholes else 0.0
    outs_p, outs_c = [], []
    for s in seqs:
        h = np.zeros(proj, "float64")
        c = np.zeros(hid, "float64")
        for x_t in s.astype("float64"):
            g = x_t + h @ w + b4
            g_cand, g_i, g_f, g_o = np.split(g, 4)
            cand = np.tanh(g_cand)
            i = _sig(g_i + c * ci)
            f = _sig(g_f + c * cf)
            c = cand * i + c * f
            o = _sig(g_o + c * co)
            h_raw = o * np.tanh(c)
            h = np.tanh(h_raw @ pw)
            outs_p.append(h.copy())
            outs_c.append(c.copy())
    return (np.asarray(outs_p, "float32"), np.asarray(outs_c, "float32"))


def test_lstmp_numeric():
    hid, proj = 4, 3
    lens = [3, 2]
    seqs = [_rand((t, 4 * hid), seed=20 + k) for k, t in enumerate(lens)]
    flat = np.concatenate(seqs, axis=0)
    w = _rand((proj, 4 * hid), seed=30)
    pw = _rand((hid, proj), seed=31)
    b = _rand((1, 7 * hid), seed=32)
    want_p, want_c = _lstmp_ref(seqs, w.astype("float64"),
                                pw.astype("float64"),
                                b.reshape(-1).astype("float64"), True)

    class T(OpTest):
        op_type = "lstmp"

    t = T()
    t.inputs = {"Input": (flat, lens), "Weight": w, "ProjWeight": pw,
                "Bias": b}
    t.attrs = {"use_peepholes": True, "proj_activation": "tanh"}
    t.outputs = {"Projection": (want_p, lens), "Cell": (want_c, lens)}
    t.check_output(atol=2e-5, rtol=2e-5)


def test_lstmp_no_peephole_grad():
    hid, proj = 3, 2
    lens = [2, 3]
    seqs = [_rand((t, 4 * hid), seed=40 + k) for k, t in enumerate(lens)]
    flat = np.concatenate(seqs, axis=0)
    w = _rand((proj, 4 * hid), seed=41)
    pw = _rand((hid, proj), seed=42)
    b = _rand((1, 4 * hid), seed=43)
    want_p, want_c = _lstmp_ref(seqs, w.astype("float64"),
                                pw.astype("float64"),
                                b.reshape(-1).astype("float64"), False)

    class T(OpTest):
        op_type = "lstmp"

    t = T()
    t.inputs = {"Input": (flat, lens), "Weight": w, "ProjWeight": pw,
                "Bias": b}
    t.attrs = {"use_peepholes": False, "proj_activation": "tanh"}
    t.outputs = {"Projection": (want_p, lens), "Cell": (want_c, lens)}
    t.check_output(atol=2e-5, rtol=2e-5)
    t.check_grad(["Input", "Weight", "ProjWeight"], "Projection",
                 max_relative_error=0.02)


# ---------------------------------------------------------------------------
# cudnn_lstm: dense multi-layer (bi)LSTM, flat weight
# [Wx, Wh, b] per layer+direction, cuDNN gate order [i, f, g, o]
# ---------------------------------------------------------------------------
def _cudnn_ref(x, w_flat, hid, layers, bidi):
    ndir = 2 if bidi else 1
    t, n, _ = x.shape
    off = 0

    def take(shape):
        nonlocal off
        size = int(np.prod(shape))
        out = w_flat[off:off + size].reshape(shape)
        off += size
        return out

    inp = x.astype("float64")
    last_h, last_c = [], []
    for _l in range(layers):
        d_in = inp.shape[-1]
        outs = []
        for direction in range(ndir):
            wx = take((d_in, 4 * hid))
            wh = take((hid, 4 * hid))
            b = take((4 * hid,))
            seq = inp[::-1] if direction == 1 else inp
            h = np.zeros((n, hid), "float64")
            c = np.zeros((n, hid), "float64")
            hs = []
            for x_t in seq:
                g = x_t @ wx + h @ wh + b
                i, f, gg, o = np.split(g, 4, axis=-1)
                c = _sig(f) * c + _sig(i) * np.tanh(gg)
                h = _sig(o) * np.tanh(c)
                hs.append(h.copy())
            hs = np.asarray(hs)
            if direction == 1:
                hs = hs[::-1]
            outs.append(hs)
            last_h.append(h.copy())
            last_c.append(c.copy())
        inp = np.concatenate(outs, axis=-1) if ndir == 2 else outs[0]
    return (inp.astype("float32"), np.asarray(last_h, "float32"),
            np.asarray(last_c, "float32"))


def test_cudnn_lstm_numeric_2layer_bidi():
    t, n, d, hid, layers = 4, 2, 3, 5, 2
    x = _rand((t, n, d), seed=50)
    sz = 0
    d_in = d
    for _l in range(layers):
        sz += 2 * (d_in * 4 * hid + hid * 4 * hid + 4 * hid)
        d_in = 2 * hid
    w = _rand((sz,), seed=51, lo=-0.5, hi=0.5)
    want_o, want_h, want_c = _cudnn_ref(x, w.astype("float64"), hid,
                                        layers, True)

    class T(OpTest):
        op_type = "cudnn_lstm"

    t_ = T()
    t_.inputs = {"Input": x, "W": w}
    t_.attrs = {"hidden_size": hid, "num_layers": layers,
                "is_bidirec": True, "dropout_prob": 0.0}
    t_.outputs = {"Out": want_o, "last_h": want_h, "last_c": want_c}
    t_.check_output(atol=2e-5, rtol=2e-5)


def test_cudnn_lstm_numeric_grad():
    t, n, d, hid = 3, 2, 3, 3
    x = _rand((t, n, d), seed=60)
    sz = d * 4 * hid + hid * 4 * hid + 4 * hid
    w = _rand((sz,), seed=61, lo=-0.5, hi=0.5)
    want_o, want_h, want_c = _cudnn_ref(x, w.astype("float64"), hid, 1,
                                        False)

    class T(OpTest):
        op_type = "cudnn_lstm"

    t_ = T()
    t_.inputs = {"Input": x, "W": w}
    t_.attrs = {"hidden_size": hid, "num_layers": 1, "is_bidirec": False,
                "dropout_prob": 0.0}
    t_.outputs = {"Out": want_o, "last_h": want_h, "last_c": want_c}
    t_.check_output(atol=2e-5, rtol=2e-5)
    t_.check_grad(["Input", "W"], "Out", max_relative_error=0.02)


# ---------------------------------------------------------------------------
# lstm_unit: one fused step, gate order [i, f, o, g], forget_bias on f
# (lstm_unit_op.h:63-66)
# ---------------------------------------------------------------------------
def test_lstm_unit_numeric():
    n, hid = 3, 4
    x = _rand((n, 4 * hid), seed=70)
    c_prev = _rand((n, hid), seed=71)
    forget_bias = 1.0
    xd = x.astype("float64")
    i, f, o, g = np.split(xd, 4, axis=-1)
    c = _sig(f + forget_bias) * c_prev + _sig(i) * np.tanh(g)
    h = _sig(o) * np.tanh(c)

    class T(OpTest):
        op_type = "lstm_unit"

    t = T()
    t.inputs = {"X": x, "C_prev": c_prev}
    t.attrs = {"forget_bias": forget_bias}
    t.outputs = {"C": c.astype("float32"), "H": h.astype("float32")}
    t.check_output(atol=2e-5, rtol=2e-5)
    t.check_grad(["X", "C_prev"], "H", max_relative_error=0.02)


# ---------------------------------------------------------------------------
# yolov3_loss: full numpy reference (yolov3_loss_op.h CalcYolov3Loss)
# ---------------------------------------------------------------------------
def _bce(p, t):
    p = np.clip(p, 1e-7, 1 - 1e-7)
    return -(t * np.log(p) + (1 - t) * np.log(1 - p))


def _yolo_ref(x, gt_box, gt_label, anchors, class_num, ignore_thresh,
              downsample):
    N, _, H, W = x.shape
    A = len(anchors) // 2
    anc = np.asarray(anchors, "float64").reshape(A, 2)
    input_size = downsample * H
    x = x.reshape(N, A, 5 + class_num, H, W).astype("float64")
    px, py = _sig(x[:, :, 0]), _sig(x[:, :, 1])
    pw, ph = x[:, :, 2], x[:, :, 3]
    pobj, pcls = x[:, :, 4], x[:, :, 5:]
    loss = np.zeros(N, "float64")
    for nidx in range(N):
        obj_target = np.zeros((A, H, W))
        for bidx in range(gt_box.shape[1]):
            cx, cy, bw, bh = gt_box[nidx, bidx].astype("float64")
            if bw <= 0 or bh <= 0:
                continue
            gx, gy = cx * W, cy * H
            gw, gh = bw * input_size, bh * input_size
            gi = min(max(int(gx), 0), W - 1)
            gj = min(max(int(gy), 0), H - 1)
            ious = [
                (min(gw, aw) * min(gh, ah))
                / (gw * gh + aw * ah - min(gw, aw) * min(gh, ah))
                for aw, ah in anc
            ]
            a = int(np.argmax(ious))
            tx, ty = gx - np.floor(gx), gy - np.floor(gy)
            tw = np.log(max(gw / anc[a, 0], 1e-10))
            th = np.log(max(gh / anc[a, 1], 1e-10))
            scale = 2.0 - bw * bh
            loss[nidx] += (_bce(px[nidx, a, gj, gi], tx)
                           + _bce(py[nidx, a, gj, gi], ty)) * scale
            loss[nidx] += ((pw[nidx, a, gj, gi] - tw) ** 2
                           + (ph[nidx, a, gj, gi] - th) ** 2) * 0.5 * scale
            obj_target[a, gj, gi] = 1.0
            onehot = np.zeros(class_num)
            onehot[int(gt_label[nidx, bidx])] = 1.0
            loss[nidx] += _bce(_sig(pcls[nidx, a, :, gj, gi]), onehot).sum()
        # objectness with ignore mask
        for a in range(A):
            for j in range(H):
                for i in range(W):
                    p_cx = (px[nidx, a, j, i] + i) / W
                    p_cy = (py[nidx, a, j, i] + j) / H
                    p_w = np.exp(pw[nidx, a, j, i]) * anc[a, 0] / input_size
                    p_h = np.exp(ph[nidx, a, j, i]) * anc[a, 1] / input_size
                    best = 0.0
                    for bidx in range(gt_box.shape[1]):
                        cx, cy, bw, bh = gt_box[nidx, bidx].astype("float64")
                        if bw <= 0 or bh <= 0:
                            continue
                        iw = max(min(p_cx + p_w / 2, cx + bw / 2)
                                 - max(p_cx - p_w / 2, cx - bw / 2), 0.0)
                        ih = max(min(p_cy + p_h / 2, cy + bh / 2)
                                 - max(p_cy - p_h / 2, cy - bh / 2), 0.0)
                        inter = iw * ih
                        u = p_w * p_h + bw * bh - inter
                        best = max(best, inter / max(u, 1e-10))
                    tgt = obj_target[a, j, i]
                    w_obj = tgt + (1 - tgt) * (best <= ignore_thresh)
                    loss[nidx] += _bce(_sig(pobj[nidx, a, j, i]), tgt) * w_obj
    return loss.astype("float32")


def test_yolov3_loss_numeric():
    N, A, H, W, cls = 2, 2, 4, 4, 3
    anchors = [10.0, 14.0, 23.0, 27.0]
    x = _rand((N, A * (5 + cls), H, W), seed=80)
    r = np.random.RandomState(81)
    gt_box = r.uniform(0.2, 0.6, (N, 3, 4)).astype("float32")
    gt_box[1, 2] = 0.0  # an invalid (zero-size) gt slot
    gt_label = r.randint(0, cls, (N, 3)).astype("int32")
    want = _yolo_ref(x, gt_box, gt_label, anchors, cls, 0.7, 32)

    class T(OpTest):
        op_type = "yolov3_loss"

    t = T()
    t.inputs = {"X": x, "GTBox": gt_box, "GTLabel": gt_label}
    t.attrs = {"anchors": anchors, "class_num": cls, "ignore_thresh": 0.7,
               "downsample_ratio": 32}
    t.outputs = {"Loss": want}
    t.check_output(atol=3e-4, rtol=3e-4)
