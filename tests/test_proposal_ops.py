"""Proposal/RPN family tests (reference: test_generate_proposals.py,
test_rpn_target_assign_op.py, test_generate_proposal_labels.py,
test_psroi_pool_op.py, test_polygon_box_transform.py,
test_roi_perspective_transform_op.py, test_detection_map_op.py)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.core.lod import LoDValue, create_lod_tensor


def _run_op(op_type, inputs, attrs, out_slots):
    prog = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(prog, startup):
        block = prog.global_block()
        feed = {}
        in_names = {}
        for slot, v in inputs.items():
            name = slot.lower()
            if isinstance(v, LoDValue):
                shape = list(np.shape(v.data))
                dtype = v.data.dtype
                lod_level = 1
            else:
                v = np.asarray(v)
                shape = list(v.shape)
                dtype = v.dtype
                lod_level = 0
            block.create_var(name=name, shape=shape, dtype=dtype,
                             lod_level=lod_level)
            feed[name] = v
            in_names[slot] = [name]
        out_names = {s: [f"out_{s.lower()}"] for s in out_slots}
        block.append_op(type=op_type, inputs=in_names, outputs=out_names,
                        attrs=attrs)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.program_guard(prog, startup):
        fetch = [n for ns in out_names.values() for n in ns]
        got = exe.run(program=prog, feed=feed, fetch_list=fetch,
                      return_numpy=False)
    return dict(zip(out_slots, got))


def test_polygon_box_transform():
    x = np.random.RandomState(0).randn(2, 8, 3, 4).astype("float32")
    res = _run_op("polygon_box_transform", {"Input": x}, {}, ["Output"])
    out = np.asarray(res["Output"])
    want = np.zeros_like(x)
    for c in range(8):
        for h in range(3):
            for w in range(4):
                if c % 2 == 0:
                    want[:, c, h, w] = 4.0 * w - x[:, c, h, w]
                else:
                    want[:, c, h, w] = 4.0 * h - x[:, c, h, w]
    np.testing.assert_allclose(out, want, rtol=1e-5)


def test_generate_proposals_basic():
    """Two anchors on a 1x1 map: zero deltas keep the anchors; NMS keeps the
    higher-score one when they overlap fully."""
    H = W = 1
    A = 2
    scores = np.array([[[[0.9]], [[0.8]]]], dtype="float32")  # [1, A, 1, 1]
    deltas = np.zeros((1, 4 * A, H, W), dtype="float32")
    anchors = np.array(
        [[[[0, 0, 9, 9], [0, 0, 9, 9]]]], dtype="float32"
    )  # [H, W, A, 4] identical -> IoU 1
    variances = np.ones((H, W, A, 4), dtype="float32")
    im_info = np.array([[20.0, 20.0, 1.0]], dtype="float32")
    res = _run_op(
        "generate_proposals",
        {"Scores": scores, "BboxDeltas": deltas, "ImInfo": im_info,
         "Anchors": anchors, "Variances": variances},
        {"pre_nms_topN": 10, "post_nms_topN": 5, "nms_thresh": 0.5,
         "min_size": 1.0, "eta": 1.0},
        ["RpnRois", "RpnRoiProbs"],
    )
    rois = res["RpnRois"]
    assert isinstance(rois, LoDValue)
    counts = np.asarray(rois.lengths)
    assert counts[0] == 1, f"NMS should keep 1 of 2 identical boxes, {counts}"
    np.testing.assert_allclose(
        np.asarray(rois.data)[0, 0], [0, 0, 9, 9], atol=1e-4)
    probs = np.asarray(res["RpnRoiProbs"].data)
    np.testing.assert_allclose(probs[0, 0, 0], 0.9, atol=1e-5)


def test_generate_proposals_min_size_filter():
    """A degenerate (tiny) anchor is filtered by min_size."""
    H = W = 1
    A = 2
    scores = np.array([[[[0.9]], [[0.95]]]], dtype="float32")
    deltas = np.zeros((1, 4 * A, H, W), dtype="float32")
    anchors = np.array(
        [[[[0, 0, 9, 9], [5, 5, 5.5, 5.5]]]], dtype="float32"
    )
    variances = np.ones((H, W, A, 4), dtype="float32")
    im_info = np.array([[20.0, 20.0, 1.0]], dtype="float32")
    res = _run_op(
        "generate_proposals",
        {"Scores": scores, "BboxDeltas": deltas, "ImInfo": im_info,
         "Anchors": anchors, "Variances": variances},
        {"pre_nms_topN": 10, "post_nms_topN": 5, "nms_thresh": 0.5,
         "min_size": 3.0, "eta": 1.0},
        ["RpnRois", "RpnRoiProbs"],
    )
    counts = np.asarray(res["RpnRois"].lengths)
    assert counts[0] == 1
    np.testing.assert_allclose(
        np.asarray(res["RpnRois"].data)[0, 0], [0, 0, 9, 9], atol=1e-4)


def test_rpn_target_assign_static():
    """4 anchors, 1 gt: the overlapping anchor goes fg, others bg; output is
    exactly S rows with fg first."""
    anchors = np.array(
        [[0, 0, 9, 9], [20, 20, 29, 29], [40, 40, 49, 49], [0, 20, 9, 29]],
        dtype="float32",
    )
    gt = create_lod_tensor(
        np.array([[0, 0, 9, 9]], dtype="float32"), [[1]])
    crowd = create_lod_tensor(np.zeros((1, 1), dtype="float32"), [[1]])
    im_info = np.array([[60.0, 60.0, 1.0]], dtype="float32")
    res = _run_op(
        "rpn_target_assign",
        {"Anchor": anchors, "GtBoxes": gt, "IsCrowd": crowd,
         "ImInfo": im_info},
        {"rpn_batch_size_per_im": 4, "rpn_straddle_thresh": 0.0,
         "rpn_positive_overlap": 0.7, "rpn_negative_overlap": 0.3,
         "rpn_fg_fraction": 0.5, "use_random": False},
        ["LocationIndex", "ScoreIndex", "TargetLabel", "TargetBBox",
         "BBoxInsideWeight"],
    )
    loc = np.asarray(res["LocationIndex"])
    label = np.asarray(res["TargetLabel"]).ravel()
    w = np.asarray(res["BBoxInsideWeight"])
    tgt = np.asarray(res["TargetBBox"])
    assert loc.shape == (4,)
    assert label[0] == 1 and label[1:].sum() == 0
    assert loc[0] == 0  # anchor 0 is the only fg
    np.testing.assert_allclose(w[0], 1.0)
    np.testing.assert_allclose(w[1:], 0.0)
    # perfect overlap -> zero regression target
    np.testing.assert_allclose(tgt[0], 0.0, atol=1e-5)


def test_generate_proposal_labels_static():
    rois = create_lod_tensor(
        np.array([[0, 0, 9, 9], [30, 30, 39, 39], [0, 0, 8, 8]],
                 dtype="float32"),
        [[3]],
    )
    gt_classes = create_lod_tensor(
        np.array([[3]], dtype="float32"), [[1]])
    crowd = create_lod_tensor(np.zeros((1, 1), dtype="float32"), [[1]])
    gt_boxes = create_lod_tensor(
        np.array([[0, 0, 9, 9]], dtype="float32"), [[1]])
    im_info = np.array([[60.0, 60.0, 1.0]], dtype="float32")
    S = 4
    res = _run_op(
        "generate_proposal_labels",
        {"RpnRois": rois, "GtClasses": gt_classes, "IsCrowd": crowd,
         "GtBoxes": gt_boxes, "ImInfo": im_info},
        {"batch_size_per_im": S, "fg_fraction": 0.5, "fg_thresh": 0.5,
         "bg_thresh_hi": 0.5, "bg_thresh_lo": 0.0,
         "bbox_reg_weights": [0.1, 0.1, 0.2, 0.2], "class_nums": 5,
         "use_random": False},
        ["Rois", "LabelsInt32", "BboxTargets", "BboxInsideWeights",
         "BboxOutsideWeights"],
    )
    out_rois = np.asarray(res["Rois"].data)
    labels = np.asarray(res["LabelsInt32"]).ravel()
    win = np.asarray(res["BboxInsideWeights"])
    assert out_rois.shape == (1, S, 4)
    # fg candidates: roi0 (IoU 1), roi2 (IoU ~0.81), gt itself (IoU 1) ->
    # capped at fg_fraction*S = 2
    assert (labels == 3).sum() == 2
    # fg rows carry per-class weights at class-3 slot
    fg_rows = np.where(labels == 3)[0]
    for r in fg_rows:
        assert win[r, 12:16].sum() == 4.0
        assert win[r, :12].sum() == 0.0 and win[r, 16:].sum() == 0.0


def test_psroi_pool():
    oc, ph, pw = 2, 2, 2
    x = np.arange(1 * oc * ph * pw * 4 * 4, dtype="float32").reshape(
        1, oc * ph * pw, 4, 4)
    rois = create_lod_tensor(
        np.array([[0, 0, 3, 3]], dtype="float32"), [[1]])
    res = _run_op(
        "psroi_pool", {"X": x, "ROIs": rois},
        {"output_channels": oc, "pooled_height": ph, "pooled_width": pw,
         "spatial_scale": 1.0},
        ["Out"],
    )
    out = np.asarray(res["Out"])
    assert out.shape == (1, oc, ph, pw)
    # bin (i,j) of output channel c averages channel (c*ph+i)*pw+j over the
    # bin region: roi = whole 4x4 map -> bins are 2x2 quadrants
    for c in range(oc):
        for i in range(ph):
            for j in range(pw):
                chan = (c * ph + i) * pw + j
                patch = x[0, chan, i * 2:(i + 1) * 2, j * 2:(j + 1) * 2]
                np.testing.assert_allclose(out[0, c, i, j], patch.mean(),
                                           rtol=1e-5)


def test_roi_perspective_transform_identity():
    """An axis-aligned square RoI warps to itself (identity homography)."""
    H = W = 6
    x = np.random.RandomState(1).rand(1, 1, H, W).astype("float32")
    th = tw = 4
    # square quad covering [1, 4] x [1, 4], corners clockwise from top-left
    rois = create_lod_tensor(
        np.array([[1, 1, 4, 1, 4, 4, 1, 4]], dtype="float32"), [[1]])
    res = _run_op(
        "roi_perspective_transform", {"X": x, "ROIs": rois},
        {"transformed_height": th, "transformed_width": tw,
         "spatial_scale": 1.0},
        ["Out"],
    )
    out = np.asarray(res["Out"])
    assert out.shape == (1, 1, th, tw)
    # output grid maps linearly onto [1,4]^2: out[i,j] = x[1+i, 1+j]
    np.testing.assert_allclose(out[0, 0], x[0, 0, 1:5, 1:5], atol=1e-4)


def test_detection_map_perfect_and_half():
    # image 0: one gt of class 1, one perfect detection -> AP 1
    # image 1: one gt of class 1, detection misses -> adds a FP + missed gt
    det = create_lod_tensor(
        np.array([
            [1, 0.9, 10, 10, 20, 20],
            [1, 0.8, 50, 50, 60, 60],
        ], dtype="float32"),
        [[1, 1]],
    )
    gt = create_lod_tensor(
        np.array([
            [1, 0, 10, 10, 20, 20],
            [1, 0, 0, 0, 5, 5],
        ], dtype="float32"),
        [[1, 1]],
    )
    res = _run_op(
        "detection_map", {"DetectRes": det, "Label": gt},
        {"overlap_threshold": 0.5, "class_num": 2, "background_label": 0,
         "ap_type": "integral", "evaluate_difficult": True},
        ["MAP", "AccumPosCount", "AccumTruePos", "AccumFalsePos"],
    )
    m = float(np.asarray(res["MAP"])[0])
    # integral AP: dets sorted (0.9 tp, 0.8 fp), npos=2:
    # rec 0.5 @ prec 1, then prec 0.5 no rec gain -> AP = 0.5
    np.testing.assert_allclose(m, 0.5, atol=1e-5)


def test_detection_map_11point():
    det = create_lod_tensor(
        np.array([[1, 0.9, 10, 10, 20, 20]], dtype="float32"), [[1]])
    gt = create_lod_tensor(
        np.array([[1, 0, 10, 10, 20, 20]], dtype="float32"), [[1]])
    res = _run_op(
        "detection_map", {"DetectRes": det, "Label": gt},
        {"overlap_threshold": 0.5, "class_num": 2, "background_label": 0,
         "ap_type": "11point", "evaluate_difficult": True},
        ["MAP", "AccumPosCount", "AccumTruePos", "AccumFalsePos"],
    )
    np.testing.assert_allclose(float(np.asarray(res["MAP"])[0]), 1.0,
                               atol=1e-5)


def test_detection_map_difficult_ignored():
    """A detection matching a difficult gt is neither tp nor fp when
    evaluate_difficult=False; the difficult gt doesn't count toward npos."""
    det = create_lod_tensor(
        np.array([
            [1, 0.9, 10, 10, 20, 20],   # matches the difficult gt
            [1, 0.8, 50, 50, 60, 60],   # matches the normal gt
        ], dtype="float32"),
        [[2]],
    )
    gt = create_lod_tensor(
        np.array([
            [1, 1, 10, 10, 20, 20],     # difficult
            [1, 0, 50, 50, 60, 60],     # normal
        ], dtype="float32"),
        [[2]],
    )
    res = _run_op(
        "detection_map", {"DetectRes": det, "Label": gt},
        {"overlap_threshold": 0.5, "class_num": 2, "background_label": 0,
         "ap_type": "integral", "evaluate_difficult": False},
        ["MAP", "AccumPosCount", "AccumTruePos", "AccumFalsePos"],
    )
    # npos=1 (difficult excluded); det0 ignored, det1 tp -> AP = 1
    np.testing.assert_allclose(float(np.asarray(res["MAP"])[0]), 1.0,
                               atol=1e-5)


def test_generate_proposal_labels_im_scale():
    """RoIs in scaled coords, gt in original coords: with im_scale=2 the
    roi [0,0,18,18] maps onto gt [0,0,9,9]; output rois return scaled."""
    rois = create_lod_tensor(
        np.array([[0, 0, 18, 18], [60, 60, 78, 78]], dtype="float32"),
        [[2]],
    )
    gt_classes = create_lod_tensor(np.array([[2]], dtype="float32"), [[1]])
    crowd = create_lod_tensor(np.zeros((1, 1), dtype="float32"), [[1]])
    gt_boxes = create_lod_tensor(
        np.array([[0, 0, 9, 9]], dtype="float32"), [[1]])
    im_info = np.array([[120.0, 120.0, 2.0]], dtype="float32")
    res = _run_op(
        "generate_proposal_labels",
        {"RpnRois": rois, "GtClasses": gt_classes, "IsCrowd": crowd,
         "GtBoxes": gt_boxes, "ImInfo": im_info},
        {"batch_size_per_im": 4, "fg_fraction": 0.5, "fg_thresh": 0.5,
         "bg_thresh_hi": 0.5, "bg_thresh_lo": 0.0,
         "bbox_reg_weights": [0.1, 0.1, 0.2, 0.2], "class_nums": 5,
         "use_random": False},
        ["Rois", "LabelsInt32", "BboxTargets", "BboxInsideWeights",
         "BboxOutsideWeights"],
    )
    labels = np.asarray(res["LabelsInt32"]).ravel()
    # roi0/im_scale == [0,0,9,9] == gt (IoU 1) and the gt itself -> 2 fg
    assert (labels == 2).sum() == 2
    out_rois = np.asarray(res["Rois"].data)[0]
    fg_rows = np.where(labels == 2)[0]
    for r in fg_rows:
        np.testing.assert_allclose(out_rois[r], [0, 0, 18, 18], atol=1e-4)
