"""ProgramPipeline: GPipe stages derived from a fluid Program (VERDICT r5
item 9 — the pp phase must go through the Program path, not just the raw
pipeline_apply primitive).

Parity contract: streaming micro-batches through the program-derived
stages over a pp mesh equals running the SAME program serially through
fluid.Executor, micro-batch by micro-batch."""

import jax
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.parallel import ProgramPipeline, make_mesh


def _chain_program(n_stages=2, d=8, act="tanh"):
    """x -> [fc(d)+act] * n_stages, one fc per stage, named boundaries."""
    fluid.reset_default_env()
    x = layers.data("x", [d], dtype="float32")
    h = x
    bounds = [x]
    for s in range(n_stages):
        h = layers.fc(h, size=d, act=act,
                      param_attr=fluid.ParamAttr(name=f"w{s}"),
                      bias_attr=fluid.ParamAttr(name=f"b{s}"))
        bounds.append(h)
    return x, bounds


def _init(seed=3):
    fluid.default_startup_program().random_seed = seed
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    return exe


def test_program_pipeline_matches_serial():
    x, bounds = _chain_program(n_stages=2)
    exe = _init()
    test_prog = fluid.default_main_program().clone(for_test=True)

    M, B, D = 4, 2, 8
    rng = np.random.RandomState(0)
    xmb = rng.randn(M, B, D).astype("float32")

    want = np.stack([
        np.asarray(exe.run(program=test_prog, feed={"x": xmb[m]},
                           fetch_list=[bounds[-1]])[0])
        for m in range(M)
    ])

    pp = ProgramPipeline(bounds, make_mesh({"pp": 2}, devices=jax.devices()[:2]),
                         main_program=test_prog)
    got = pp.run(xmb)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_program_pipeline_four_stages():
    x, bounds = _chain_program(n_stages=4)
    exe = _init(seed=11)
    test_prog = fluid.default_main_program().clone(for_test=True)

    M, B, D = 6, 2, 8
    rng = np.random.RandomState(1)
    xmb = rng.randn(M, B, D).astype("float32")
    want = np.stack([
        np.asarray(exe.run(program=test_prog, feed={"x": xmb[m]},
                           fetch_list=[bounds[-1]])[0])
        for m in range(M)
    ])
    pp = ProgramPipeline(bounds, make_mesh({"pp": 4}, devices=jax.devices()[:4]),
                         main_program=test_prog)
    got = pp.run(xmb)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_program_pipeline_rejects_heterogeneous_stages():
    fluid.reset_default_env()
    x = layers.data("x", [8], dtype="float32")
    h1 = layers.fc(x, size=8, act="tanh")
    h2 = layers.fc(h1, size=8, act="relu")  # different act attr
    _init()
    test_prog = fluid.default_main_program().clone(for_test=True)
    with pytest.raises(ValueError, match="not structurally identical"):
        ProgramPipeline([x, h1, h2], make_mesh({"pp": 2}, devices=jax.devices()[:2]),
                        main_program=test_prog)


def test_program_pipeline_rejects_shape_change():
    fluid.reset_default_env()
    x = layers.data("x", [8], dtype="float32")
    h1 = layers.fc(x, size=4, act="tanh")  # narrows the activation
    h2 = layers.fc(h1, size=8, act="tanh")
    _init()
    test_prog = fluid.default_main_program().clone(for_test=True)
    with pytest.raises(ValueError, match="shape/dtype"):
        ProgramPipeline([x, h1, h2], make_mesh({"pp": 2}, devices=jax.devices()[:2]),
                        main_program=test_prog)


def test_program_pipeline_rejects_training_mode_ops():
    fluid.reset_default_env()
    x = layers.data("x", [8], dtype="float32")
    h1 = layers.fc(x, size=8, act="tanh")
    d1 = layers.dropout(h1, dropout_prob=0.5)
    h2 = layers.fc(d1, size=8, act="tanh")
    d2 = layers.dropout(h2, dropout_prob=0.5)
    _init()
    # NOT cloned for test: dropout stays a random op -> must be rejected
    with pytest.raises(ValueError, match="purity|training mode"):
        ProgramPipeline([x, d1, d2], make_mesh({"pp": 2}, devices=jax.devices()[:2]))


def test_program_pipeline_rejects_persistable_writes():
    """A stage op that WRITES persistable state (LR counter, moving stats)
    must raise — the serial Executor updates it, the pipeline would drop
    the update silently (review r5)."""
    fluid.reset_default_env()
    x = layers.data("x", [8], dtype="float32")
    h1 = layers.fc(x, size=8, act="tanh")
    h2 = layers.fc(h1, size=8, act="tanh")
    _init()
    test_prog = fluid.default_main_program().clone(for_test=True)
    # hand-plant an increment on a persistable counter inside stage 1
    bdesc = test_prog.desc.block(0)
    from paddle_tpu.core.proto import OpDesc, VarDesc

    bdesc.vars["ctr"] = VarDesc(name="ctr", shape=[1], persistable=True)
    prod = {n: i for i, op in enumerate(bdesc.ops)
            for n in op.output_arg_names()}
    bdesc.ops.insert(prod[h2.name], OpDesc(
        type="increment", inputs={"X": ["ctr"]}, outputs={"Out": ["ctr"]},
        attrs={"step": 1.0}))
    with pytest.raises(ValueError, match="writes persistable"):
        ProgramPipeline([x, h1, h2],
                        make_mesh({"pp": 2}, devices=jax.devices()[:2]),
                        main_program=test_prog)


def test_program_pipeline_ignores_name_scopes():
    """Per-layer fluid.name_scope annotations are cosmetic; isomorphism
    must not be rejected over op_namescope attrs (review r5)."""
    fluid.reset_default_env()
    x = layers.data("x", [8], dtype="float32")
    h = x
    bounds = [x]
    for s in range(2):
        with fluid.name_scope(f"layer{s}"):
            h = layers.fc(h, size=8, act="tanh")
        bounds.append(h)
    _init()
    test_prog = fluid.default_main_program().clone(for_test=True)
    pp = ProgramPipeline(bounds,
                         make_mesh({"pp": 2}, devices=jax.devices()[:2]),
                         main_program=test_prog)
    rng = np.random.RandomState(9)
    xmb = rng.randn(4, 2, 8).astype("float32")
    want = np.stack([
        np.asarray(exe_out) for exe_out in (
            fluid.Executor(fluid.CPUPlace()).run(
                program=test_prog, feed={"x": xmb[m]},
                fetch_list=[bounds[-1]])[0]
            for m in range(4))
    ])
    got = pp.run(xmb)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_program_pipeline_mesh_without_pp_axis():
    fluid.reset_default_env()
    x = layers.data("x", [8], dtype="float32")
    h1 = layers.fc(x, size=8, act="tanh")
    h2 = layers.fc(h1, size=8, act="tanh")
    _init()
    test_prog = fluid.default_main_program().clone(for_test=True)
    with pytest.raises(ValueError, match="no 'pp' axis"):
        ProgramPipeline([x, h1, h2],
                        make_mesh({"dp": 2}, devices=jax.devices()[:2]),
                        main_program=test_prog)


def test_program_pipeline_train_step_matches_serial_sgd():
    """Pipelined GPipe training == serial per-microbatch SGD on the same
    Program: losses and updated weights must agree (the backward flows
    through the reverse ppermute schedule inside one XLA program)."""
    import jax.numpy as jnp

    x, bounds = _chain_program(n_stages=2)
    _init(seed=23)
    test_prog = fluid.default_main_program().clone(for_test=True)
    rng = np.random.RandomState(3)
    M, B, D = 4, 2, 8
    xmb = rng.randn(M, B, D).astype("float32")
    ymb = rng.randn(M, B, D).astype("float32")

    def loss_fn(out, y):
        return jnp.mean((out - y) ** 2)

    # serial reference: same mean-over-microbatch SGD step in numpy/jax
    import jax

    names = [f"w{s}" for s in range(2)] + [f"b{s}" for s in range(2)]
    w0 = {n: np.asarray(fluid.global_scope().find_var(n)).copy()
          for n in names}

    def serial_objective(params):
        total = 0.0
        for m in range(M):
            h = jnp.asarray(xmb[m])
            for s in range(2):
                h = jnp.tanh(h @ params[f"w{s}"] + params[f"b{s}"])
            total = total + jnp.mean((h - ymb[m]) ** 2)
        return total / M

    jparams = {n: jnp.asarray(v) for n, v in w0.items()}
    ref_loss, ref_grads = jax.value_and_grad(serial_objective)(jparams)
    ref_new = {n: np.asarray(jparams[n] - 0.1 * ref_grads[n])
               for n in names}

    pp = ProgramPipeline(bounds,
                         make_mesh({"pp": 2}, devices=jax.devices()[:2]),
                         main_program=test_prog)
    got_loss = pp.train_step(xmb, ymb, loss_fn, lr=0.1)
    pp.sync_to_scope()  # publish trained slices (deferred out of the step)
    np.testing.assert_allclose(got_loss, float(ref_loss), rtol=1e-5)
    for n in names:
        got = np.asarray(fluid.global_scope().find_var(n))
        np.testing.assert_allclose(got, ref_new[n], rtol=1e-4, atol=1e-5,
                                   err_msg=n)

    # a second step keeps improving (momentum path)
    l2 = pp.train_step(xmb, ymb, loss_fn, lr=0.1, momentum=0.9)
    l3 = pp.train_step(xmb, ymb, loss_fn, lr=0.1, momentum=0.9)
    assert l3 < l2 < got_loss


def test_program_pipeline_tied_weights_serve_but_reject_training():
    """Tied weights stack the same value per stage — fine for forward
    serving (run parity vs serial), but train_step must reject them:
    per-slice updates would silently diverge the copies (review r5)."""
    import jax.numpy as jnp

    fluid.reset_default_env()
    x = layers.data("x", [8], dtype="float32")
    shared = fluid.ParamAttr(name="wshared")
    h1 = layers.fc(x, size=8, act="tanh", param_attr=shared,
                   bias_attr=fluid.ParamAttr(name="b0"))
    h2 = layers.fc(h1, size=8, act="tanh", param_attr=shared,
                   bias_attr=fluid.ParamAttr(name="b1"))
    exe = _init()
    test_prog = fluid.default_main_program().clone(for_test=True)
    pp = ProgramPipeline([x, h1, h2],
                         make_mesh({"pp": 2}, devices=jax.devices()[:2]),
                         main_program=test_prog)
    rng = np.random.RandomState(5)
    xmb = rng.randn(4, 2, 8).astype("float32")
    want = np.stack([
        np.asarray(exe.run(program=test_prog, feed={"x": xmb[m]},
                           fetch_list=[h2])[0]) for m in range(4)])
    got = pp.run(xmb)   # forward with tied weights still works
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    with pytest.raises(ValueError, match="tied weights"):
        pp.train_step(xmb, xmb, lambda o, t: jnp.mean((o - t) ** 2))


def test_refresh_params_clears_momentum():
    import jax.numpy as jnp

    x, bounds = _chain_program(n_stages=2)
    _init(seed=29)
    test_prog = fluid.default_main_program().clone(for_test=True)
    pp = ProgramPipeline(bounds,
                         make_mesh({"pp": 2}, devices=jax.devices()[:2]),
                         main_program=test_prog)
    rng = np.random.RandomState(3)
    xmb = rng.randn(4, 2, 8).astype("float32")
    ymb = rng.randn(4, 2, 8).astype("float32")
    lf = lambda o, t: jnp.mean((o - t) ** 2)
    pp.train_step(xmb, ymb, lf, lr=0.1, momentum=0.9)
    assert hasattr(pp, "_vel")
    pp.refresh_params()  # checkpoint-load contract: velocity must reset
    assert not hasattr(pp, "_vel")


def test_program_pipeline_carried_mask_input():
    """Attention-stack shape: every stage reads the SAME feed var (a
    mask) besides the hidden chain — streamed alongside the activation
    through the schedule, with serial-Executor parity for both serving
    and a training step."""
    import jax.numpy as jnp

    fluid.reset_default_env()
    x = layers.data("x", [8], dtype="float32")
    mask = layers.data("mask", [8], dtype="float32")
    h = x
    bounds = [x]
    for s in range(2):
        fc = layers.fc(h, size=8, act="tanh",
                       param_attr=fluid.ParamAttr(name=f"cw{s}"),
                       bias_attr=fluid.ParamAttr(name=f"cb{s}"))
        h = layers.elementwise_mul(fc, mask)   # stage reads the mask
        bounds.append(h)
    _init(seed=31)
    test_prog = fluid.default_main_program().clone(for_test=True)
    exe = fluid.Executor(fluid.CPUPlace())

    M, B, D = 4, 2, 8
    rng = np.random.RandomState(7)
    xmb = rng.randn(M, B, D).astype("float32")
    mmb = (rng.rand(M, B, D) > 0.3).astype("float32")
    want = np.stack([
        np.asarray(exe.run(program=test_prog,
                           feed={"x": xmb[m], "mask": mmb[m]},
                           fetch_list=[bounds[-1]])[0])
        for m in range(M)
    ])
    pp = ProgramPipeline(bounds,
                         make_mesh({"pp": 2}, devices=jax.devices()[:2]),
                         main_program=test_prog)
    got = pp.run(xmb, carried={"mask": mmb})
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    # missing carried input is a clear error
    with pytest.raises(ValueError, match="side inputs"):
        pp.run(xmb)

    # training with the mask carried: loss decreases
    ymb = rng.randn(M, B, D).astype("float32")
    lf = lambda o, t: jnp.mean((o - t) ** 2)
    l1 = pp.train_step(xmb, ymb, lf, lr=0.1, carried={"mask": mmb})
    l2 = pp.train_step(xmb, ymb, lf, lr=0.1, carried={"mask": mmb})
    assert np.isfinite(l1) and l2 < l1


def test_program_pipeline_rejects_unknown_carried_key():
    fluid.reset_default_env()
    x = layers.data("x", [8], dtype="float32")
    mask = layers.data("mask", [8], dtype="float32")
    h1 = layers.elementwise_mul(layers.fc(x, size=8, act="tanh"), mask)
    h2 = layers.elementwise_mul(layers.fc(h1, size=8, act="tanh"), mask)
    _init()
    test_prog = fluid.default_main_program().clone(for_test=True)
    pp = ProgramPipeline([x, h1, h2],
                         make_mesh({"pp": 2}, devices=jax.devices()[:2]),
                         main_program=test_prog)
    rng = np.random.RandomState(0)
    xmb = rng.randn(4, 2, 8).astype("float32")
    mmb = np.ones((4, 2, 8), "float32")
    with pytest.raises(ValueError, match="not read by any stage"):
        pp.run(xmb, carried={"mask": mmb, "pos_ids": mmb})


def test_pipeline_apply_preserves_leaf_dtypes():
    """int/bool leaves in the streamed pytree must come back with their
    dtypes intact (review r5: a float literal in the final broadcast
    silently promoted them)."""
    import jax.numpy as jnp
    from paddle_tpu.parallel import pipeline_apply

    r = np.random.RandomState(0)
    S, M, B, D = 2, 4, 2, 8
    ws = jnp.asarray(r.randn(S, D, D).astype("float32") * 0.3)
    xmb = jnp.asarray(r.randn(M, B, D).astype("float32"))
    imb = jnp.asarray(r.randint(0, 5, size=(M, B, D)).astype("int32"))
    bmb = jnp.asarray(r.rand(M, B, D) > 0.5)

    def stage(w, tree):
        h, i, b = tree
        return (jnp.tanh(h @ w), i, b)

    got_h, got_i, got_b = pipeline_apply(
        stage, ws, (xmb, imb, bmb),
        make_mesh({"pp": S}, devices=jax.devices()[:S]))
    assert got_i.dtype == jnp.int32
    assert got_b.dtype == jnp.bool_
    np.testing.assert_array_equal(np.asarray(got_i), np.asarray(imb))
    np.testing.assert_array_equal(np.asarray(got_b), np.asarray(bmb))


@pytest.mark.parametrize("flash", [False, True])
@pytest.mark.parametrize("which,feed_names", [
    ("enc_boundaries", ["src_word"]),
    ("dec_boundaries", ["src_word", "trg_word"]),
])
def test_transformer_stack_pipeline(flash, which, feed_names):
    """The REAL transformer stacks pipeline from raw token feeds with
    serial-Executor parity.  Encoder: embedding+bias prefix, carried
    bias/length side inputs.  Decoder: the WHOLE encoder runs in the
    vmapped prefix and `enc` rides as a carried side input into every
    stage's cross-attention."""
    from paddle_tpu import models

    fluid.reset_default_env()
    spec = models.transformer(models.TransformerConfig(
        src_vocab_size=64, trg_vocab_size=64, max_length=16,
        n_layer=2, n_head=4, d_model=32, d_inner=64, dropout=0.0,
        use_flash_attention=flash))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    test_prog = fluid.default_main_program().clone(for_test=True)
    bounds = spec.extras[which]
    M, B = 4, 2
    batches = [spec.synthetic_batch(B, seed=i) for i in range(M)]
    want = np.stack([
        np.asarray(exe.run(program=test_prog, feed=batches[m],
                           fetch_list=[bounds[-1]])[0]) for m in range(M)])
    pp = ProgramPipeline(bounds,
                         make_mesh({"pp": 2}, devices=jax.devices()[:2]),
                         main_program=test_prog)
    feeds = {n: np.stack([b[n] for b in batches]) for n in feed_names}
    got = pp.run_feeds(feeds)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_transformer_encoder_pipeline_pretrains_from_tokens():
    """End-to-end pipelined training from raw tokens: gradients flow
    through the GPipe schedule AND the vmapped embedding prefix — the
    embedding table and the stage-stacked layer params both move, the
    loss decreases, and sync_to_scope publishes both parameter sets."""
    import jax.numpy as jnp
    from paddle_tpu import models

    fluid.reset_default_env()
    spec = models.transformer(models.TransformerConfig(
        src_vocab_size=64, trg_vocab_size=64, max_length=16,
        n_layer=2, n_head=4, d_model=32, d_inner=64, dropout=0.0,
        use_flash_attention=True))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    test_prog = fluid.default_main_program().clone(for_test=True)
    bounds = spec.extras["enc_boundaries"]
    pp = ProgramPipeline(bounds,
                         make_mesh({"pp": 2}, devices=jax.devices()[:2]),
                         main_program=test_prog)
    M, B = 4, 2
    batches = [spec.synthetic_batch(B, seed=i) for i in range(M)]
    feeds = {"src_word": np.stack([b["src_word"] for b in batches])}
    rng = np.random.RandomState(3)
    ymb = rng.randn(M, B, 16, 32).astype("float32")
    lf = lambda o, t: jnp.mean((o - t) ** 2)

    losses = [pp.train_step_feeds(feeds, ymb, lf, lr=0.05, momentum=0.9)
              for _ in range(4)]
    assert losses[-1] < losses[0], losses

    # the embedding table (a prefix param) actually moved
    emb0 = {n: np.asarray(fluid.global_scope().find_var(n)).copy()
            for n in pp._prefix_param_names}
    pp.sync_to_scope()
    moved = [n for n in pp._prefix_param_names
             if not np.allclose(emb0[n],
                                np.asarray(fluid.global_scope().find_var(n)))]
    assert moved, "no prefix parameter changed"
    # pipelined forward with the trained weights still runs
    out = pp.run_feeds(feeds)
    assert np.isfinite(out).all()
