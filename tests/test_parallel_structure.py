"""Structural assertions on the COMPILED parallelism artifacts.

The numerical parity suites (test_longcontext.py, test_parallel_executor.py)
prove these configs compute the right numbers; this file asserts the
*structural* claims the design makes, by compiling (never running) on the
virtual CPU mesh and inspecting the lowered module text:

- ulysses re-shards with a CONSTANT number of all_to_all collectives (4:
  q/k/v head-scatter + one output gather), independent of the axis size,
  and no ring permutes;
- ring attention rotates K/V with collective_permutes whose source-target
  pairs form the full P-device cycle (the per-step hop count is what
  scales with P, not the instruction count — the scan reuses one permute);
- zigzag ownership balances visible causal work exactly across devices
  (contiguous ownership provably does not);
- ReduceStrategy.Reduce really pins dim-0 sharded optimizer/param state in
  the compiled module's argument shardings (ZeRO-style), replicating only
  the indivisible leftovers.

Reference analogue: the SSA-graph op-handle structure tests
(paddle/fluid/framework/details/broadcast_op_handle_test.cc:1), which
assert on the built graph rather than on executed values.
"""

import re

import numpy as np
import pytest

import paddle_tpu as fluid


def _mesh(n, name="sp"):
    import jax
    from jax.sharding import Mesh

    devs = np.array(jax.devices())
    if len(devs) < n:
        pytest.skip(f"needs {n} devices")
    return Mesh(devs[:n].reshape(n), (name,))


def _count(pattern, text):
    return len(re.findall(pattern, text))


def _count_dp_sharded(text):
    """Count dp-dim-0-sharded jit arguments in lowered text, across the
    two spellings jax emits: the Shardy dialect (newer jax) and GSPMD
    mhlo.sharding device assignments (0.4.x)."""
    return (_count(r'sdy\.sharding = #sdy\.sharding<@mesh, \[\{"dp"\}', text)
            + _count(r'mhlo\.sharding = "\{devices=\[8[,\]]', text))


def _lower_attention(kind, mesh, causal=True):
    import jax

    from paddle_tpu import longcontext as lc

    q = np.zeros((2, 4, 32, 8), np.float32)
    wrappers = {
        "ring": lambda a, b, c: lc.sequence_parallel_attention(
            mesh, a, b, c, axis="sp", causal=causal, batch_axis=None),
        "ulysses": lambda a, b, c: lc.ulysses_sequence_parallel_attention(
            mesh, a, b, c, axis="sp", causal=causal, batch_axis=None),
        "zigzag": lambda a, b, c: lc.zigzag_sequence_parallel_attention(
            mesh, a, b, c, axis="sp"),
    }
    return jax.jit(wrappers[kind]).lower(q, q, q).as_text()


def test_ulysses_collective_count_constant_in_axis_size():
    """DeepSpeed-Ulysses' headline property: the collective cost is a
    fixed number of all_to_alls (here 4 — q, k, v to head-sharding plus
    one back to sequence-sharding), NOT a P-step ring."""
    counts = {}
    for p in (2, 4):
        text = _lower_attention("ulysses", _mesh(p))
        assert _count(r"collective_permute", text) == 0
        counts[p] = _count(r"stablehlo\.all_to_all", text)
    assert counts[2] == counts[4] == 4, counts


@pytest.mark.parametrize("kind", ["ring", "zigzag"])
def test_ring_permute_forms_full_cycle(kind):
    """Both ring variants rotate K and V one hop per scan step; the permute
    pairs must form the complete P-device cycle (a dropped pair would
    silently skip a device's K/V block) and no all_to_all may appear."""
    p = 4
    text = _lower_attention(kind, _mesh(p))
    assert _count(r"stablehlo\.all_to_all", text) == 0
    pair_attrs = re.findall(
        r"collective_permute.*?source_target_pairs = dense<\[(.*?)\]>", text)
    assert len(pair_attrs) == 2  # one rotating K, one rotating V
    for attr in pair_attrs:
        pairs = {
            (int(a), int(b))
            for a, b in re.findall(r"\[(\d+), (\d+)\]", attr)
        }
        assert pairs == {(j, (j + 1) % p) for j in range(p)}


def test_zigzag_ownership_balances_causal_work():
    """Zigzag gives device d chunks (d, 2P-1-d): its visible causal
    sub-blocks total 2P+1 for EVERY d, while contiguous ownership loads
    device P-1 with ~4x device 0's work (the imbalance the zigzag layout
    exists to fix)."""
    from paddle_tpu.longcontext import zigzag_permutation

    for p in (2, 4, 8):
        # a chunk with global id g sees g earlier chunks + its diagonal
        visible = lambda g: g + 1  # noqa: E731
        zig = [visible(d) + visible(2 * p - 1 - d) for d in range(p)]
        assert len(set(zig)) == 1, f"zigzag imbalanced at p={p}: {zig}"
        cont = [visible(2 * d) + visible(2 * d + 1) for d in range(p)]
        assert max(cont) > 2 * min(cont), cont  # contiguous is lopsided

        # and the layout permutation actually implements that ownership
        s = 8 * p
        perm, inv = zigzag_permutation(s, p)
        np.testing.assert_array_equal(perm[inv], np.arange(s))
        c = s // (2 * p)
        shards = perm.reshape(p, 2 * c)
        for d in range(p):
            got = {int(x) // c for x in shards[d]}
            assert got == {d, 2 * p - 1 - d}


def test_reduce_strategy_shards_state_in_compiled_module():
    """BuildStrategy.Reduce must show up in the ARTIFACT: the compiled
    module's state arguments carry dim-0 'dp' shardings for every state
    whose dim 0 divides the axis (params, momentum), and replicated
    shardings for indivisible ones (the size-1 biases)."""
    import jax

    from paddle_tpu.core.executor import _RunPlan
    from paddle_tpu.parallel import make_mesh
    from paddle_tpu.parallel.strategy import BuildStrategy, ReduceStrategy

    fluid.reset_default_env()
    x = fluid.layers.data("x", [8], dtype="float32")
    label = fluid.layers.data("label", [1], dtype="float32")
    h = fluid.layers.fc(x, size=16, act="relu",
                        param_attr=fluid.ParamAttr(name="w1"),
                        bias_attr=fluid.ParamAttr(name="b1"))
    pred = fluid.layers.fc(h, size=1,
                           param_attr=fluid.ParamAttr(name="w2"),
                           bias_attr=fluid.ParamAttr(name="b2"))
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, label))
    fluid.optimizer.MomentumOptimizer(
        learning_rate=0.1, momentum=0.9).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    bs = BuildStrategy()
    bs.reduce_strategy = ReduceStrategy.Reduce
    pe = fluid.ParallelExecutor(loss_name=loss.name, build_strategy=bs,
                                mesh=make_mesh({"dp": 8}))
    plan = _RunPlan(pe.program, ["label", "x"], [loss.name])
    compiled = pe._compile(plan)

    feed = (np.zeros((8, 1), np.float32), np.zeros((8, 8), np.float32))
    block0 = pe.program.desc.block(0)
    # host copies: the serial startup run commits its outputs to one
    # device, and lower() (unlike PE._run_scoped) does no explicit
    # resharding — the structural assertion is about the jit's OWN
    # sharding annotations, so feed uncommitted arrays
    states = tuple(np.asarray(v) for v in
                   plan.state_values(fluid.global_scope(), block0))
    rng = jax.random.PRNGKey(0)
    with pe.mesh.mesh:
        text = compiled.fn.lower(feed, states, rng).as_text()

    # w1 is [8,16]: 8 % 8 == 0 -> dp-sharded dim 0.  Momentum state
    # follows its param's shape, so it shards identically.  b1 is [16]:
    # 16 % 8 == 0 -> sharded too.  b2/w2's dim 0 (1) stays replicated.
    sharded = _count_dp_sharded(text)
    dp_states = sum(
        1 for n in plan.state_names
        if (block0.vars[n].shape or [0])[0] % 8 == 0
        and (block0.vars[n].shape or [0])[0] > 0
    )
    assert dp_states >= 4  # w1,b1 + their momentum at minimum
    assert sharded >= dp_states, (
        f"expected >= {dp_states} dp-sharded args, found {sharded}")

    # AllReduce (default) must NOT shard state: replicated everywhere
    pe2 = fluid.ParallelExecutor(loss_name=loss.name,
                                 mesh=make_mesh({"dp": 8}))
    compiled2 = pe2._compile(plan)
    with pe2.mesh.mesh:
        text2 = compiled2.fn.lower(feed, states, rng).as_text()
    assert _count_dp_sharded(text2) <= len(plan.feed_names)
