"""End-to-end coverage for bench.py's relay-independent gates: the
BENCH_LOWER_ONLY per-model TPU lowering check must run on a CPU host
without ever touching a (possibly wedged) backend, a reader thread, or
device staging — VERDICT r5's unverified path, now exercised the way the
driver would invoke it."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_bench(extra_env, timeout=560):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "BENCH_TUNE": "0",
        "BENCH_PREPROBE": "0",
        "BENCH_DEADLINE_S": "0",
        "BENCH_COMPILE_CACHE": "0",
        "PYTHONPATH": REPO,
    })
    env.update(extra_env)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=timeout, env=env,
    )
    line = next((ln for ln in out.stdout.splitlines()
                 if ln.strip().startswith("{")), None)
    assert line, f"no JSON line from bench.py:\n{out.stdout}\n{out.stderr}"
    return json.loads(line), out


def test_lower_only_gate_covers_flagship_models():
    """BENCH_LOWER_ONLY=1 over the north-star models: each returns a
    `<model>_tpu_lowering` ok record with a nonzero exported module.
    BENCH_DATA=pyreader is set deliberately: the hoisted early-return
    (bench.py regression) must come back BEFORE the reader thread or any
    device staging would start — pre-hoist, this returned with the
    worker still running and a wedged backend already touched."""
    rec, out = _run_bench({
        "BENCH_LOWER_ONLY": "1",
        "BENCH_MODELS": "resnet50,transformer",
        # small shapes: the gate's value is the lowering path, not scale
        "BENCH_BS": "4",
        "BENCH_TRANSFORMER_BS": "2",
        "BENCH_DATA": "pyreader",
    })
    results = [rec] + rec.get("extra_metrics", [])
    assert rec.get("model_errors") is None, rec.get("model_errors")
    by_metric = {r["metric"]: r for r in results}
    for model in ("resnet50", "transformer"):
        r = by_metric[f"{model}_tpu_lowering"]
        assert r["value"] == 1 and r["unit"] == "ok"
        assert r["module_bytes"] > 0
    # clean exit == no stray reader thread kept the process alive
    assert out.returncode == 0, out.stderr[-2000:]
