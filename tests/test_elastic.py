"""Elastic master + checkpoint-restart trainer
(reference semantics: go/master/service.go task leases with timeout
re-dispatch, failureMax discard, pass rollover, snapshot/recover;
go/master/service_internal_test.go + client tests)."""

import os
import threading
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.elastic import (
    AllTasksFailedError,
    ElasticTrainer,
    FileStore,
    InMemStore,
    MasterService,
    NoMoreAvailableError,
    partition,
)


def _touch(tmp_path, names):
    paths = []
    for n in names:
        p = tmp_path / n
        p.write_text("x")
        paths.append(str(p))
    return paths


def test_partition_groups_chunks():
    entries = partition(["a", "b", "c", "d", "e"], 2)
    assert [e.task.chunks for e in entries] == [["a", "b"], ["c", "d"], ["e"]]
    assert partition(["a"], 0)[0].task.chunks == ["a"]  # <=0 -> 1


def test_lease_timeout_redispatches(tmp_path):
    """A worker that dies mid-task never reports; the lease expires and
    the task returns to todo with num_failure bumped (processFailedTask)."""
    _touch(tmp_path, ["f0", "f1"])
    m = MasterService(InMemStore(), chunks_per_task=1,
                      timeout_dur=0.1, failure_max=3)
    m.set_dataset([str(tmp_path / "f*")])
    t = m.get_task(0)
    assert m.counts()["pending"] == 1
    time.sleep(0.3)  # lease expires; no finish report
    c = m.counts()
    assert c["pending"] == 0 and c["todo"] == 2
    # the timed-out task is dispatchable again with a new epoch
    seen = {m.get_task(0).id, m.get_task(0).id}
    assert t.id in seen
    m.shutdown()


def test_failure_max_discards_then_all_failed(tmp_path):
    _touch(tmp_path, ["f0"])
    m = MasterService(InMemStore(), timeout_dur=60, failure_max=1)
    m.set_dataset([str(tmp_path / "f0")])
    for _ in range(2):  # failure_max=1 -> second failure discards
        t = m.get_task(0)
        m.task_failed(t.id, t.epoch)
    assert m.counts()["failed"] == 1 and m.counts()["todo"] == 0
    with pytest.raises(AllTasksFailedError):
        m.get_task(0)
    m.shutdown()


def test_stale_failure_report_ignored(tmp_path):
    """A failure report carrying an old epoch (the task was already
    re-dispatched) must not double-punish (service.go epoch check)."""
    _touch(tmp_path, ["f0"])
    m = MasterService(InMemStore(), timeout_dur=60, failure_max=3)
    m.set_dataset([str(tmp_path / "f0")])
    t1 = m.get_task(0)
    m.task_failed(t1.id, t1.epoch)  # re-queued, failure=1
    t2 = m.get_task(0)  # epoch bumped
    m.task_failed(t1.id, t1.epoch)  # stale: epoch mismatch -> ignored
    assert m.counts()["pending"] == 1
    m.task_finished(t2.id)
    m.shutdown()


def test_pass_rollover_and_skew(tmp_path):
    from paddle_tpu.elastic import PassAfterError, PassBeforeError

    _touch(tmp_path, ["f0", "f1"])
    m = MasterService(InMemStore(), timeout_dur=60)
    m.set_dataset([str(tmp_path / "f*")])
    with pytest.raises(PassAfterError):
        m.get_task(1)  # client ahead
    for _ in range(2):
        t = m.get_task(0)
        m.task_finished(t.id)
    assert m.counts() == {"todo": 2, "pending": 0, "done": 0, "failed": 0,
                          "cur_pass": 1}
    with pytest.raises(PassBeforeError):
        m.get_task(0)  # client behind after rollover
    assert m.get_task(1).id in (0, 1)
    m.shutdown()


def test_rollover_when_last_task_fails_permanently(tmp_path):
    """A permanent failure of the pass's last outstanding task must roll
    the pass (with the failed task re-queued for the next one) — not
    strand workers in NoMoreAvailable forever."""
    _touch(tmp_path, ["f0", "f1"])
    m = MasterService(InMemStore(), timeout_dur=60, failure_max=0)
    m.set_dataset([str(tmp_path / "f*")])
    tA = m.get_task(0)
    m.task_finished(tA.id)
    tB = m.get_task(0)
    m.task_failed(tB.id, tB.epoch)  # failure_max=0 -> discarded
    c = m.counts()
    assert c["cur_pass"] == 1 and c["todo"] == 2 and c["failed"] == 0
    m.shutdown()


def test_snapshot_recover_rearms_pending(tmp_path):
    """Kill the master mid-lease; a new master over the same store
    recovers the queue and the leased task times out back to todo
    (service.go recover :196)."""
    _touch(tmp_path, ["f0", "f1"])
    store = FileStore(str(tmp_path / "snap.bin"))
    m1 = MasterService(store, timeout_dur=0.15, failure_max=3)
    m1.set_dataset([str(tmp_path / "f*")])
    t = m1.get_task(0)
    m1.shutdown()  # "crash": cancels timers, state only in the store
    del m1

    m2 = MasterService(store, timeout_dur=0.15, failure_max=3)
    c = m2.counts()
    assert c["pending"] == 1 and c["todo"] == 1  # recovered mid-lease
    time.sleep(0.4)  # recovered lease expires
    assert m2.counts()["todo"] == 2
    ids = {m2.get_task(0).id, m2.get_task(0).id}
    assert t.id in ids
    m2.shutdown()


def test_heartbeat_dead_worker_detection(tmp_path):
    m = MasterService(InMemStore(), timeout_dur=60)
    m.heartbeat("w0")
    m.heartbeat("w1")
    time.sleep(0.12)
    m.heartbeat("w1")
    assert m.dead_workers(max_silence=0.1) == ["w0"]
    m.shutdown()


def _linreg_program():
    """y = 2x - 1 regression; returns (loss, w_name)."""
    x = fluid.layers.data(name="x", shape=[1], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    pred = fluid.layers.fc(x, size=1, param_attr=fluid.ParamAttr(name="ew"))
    loss = fluid.layers.reduce_mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(learning_rate=0.3).minimize(loss)
    return loss


def _write_linreg_chunks(tmp_path, n_files=4, rows=64):
    rng = np.random.RandomState(0)
    for i in range(n_files):
        xs = rng.uniform(-1, 1, size=rows).astype(np.float32)
        np.save(str(tmp_path / f"chunk{i}.npy"), xs)
    return str(tmp_path / "chunk*.npy")


def _feed_fn(chunk):
    xs = np.load(chunk)
    for i in range(0, len(xs), 16):
        xb = xs[i:i + 16].reshape(-1, 1)
        yield {"x": xb, "y": (2.0 * xb - 1.0).astype(np.float32)}


def test_elastic_trainer_crash_resume(tmp_path):
    """Worker crashes mid-pass; a fresh worker (new process in real life)
    resumes from the checkpoint + master snapshot and finishes all passes
    with a converged model.  This is the checkpoint-restart elasticity
    SURVEY §5 maps the Go stack to."""
    fluid.reset_default_env()
    loss = _linreg_program()
    glob_pat = _write_linreg_chunks(tmp_path)
    store = FileStore(str(tmp_path / "master.snap"))
    ckpt = str(tmp_path / "ckpt")
    os.makedirs(ckpt, exist_ok=True)

    m = MasterService(store, chunks_per_task=1, timeout_dur=0.2,
                      failure_max=5)
    m.set_dataset([glob_pat])

    crash_after = [2]  # tasks before the simulated crash

    def crashing_feed(chunk):
        if crash_after[0] == 0:
            raise RuntimeError("simulated worker crash")
        crash_after[0] -= 1
        yield from _feed_fn(chunk)

    exe = fluid.Executor(fluid.CPUPlace())
    t1 = ElasticTrainer(m, exe, crashing_feed, [loss], ckpt, num_passes=3)
    with pytest.raises(RuntimeError, match="simulated"):
        t1.train()
    assert t1.tasks_done == 2
    m.shutdown()

    # restart: new master over the same snapshot store, new trainer over
    # the same checkpoint dir (same process here; same protocol anyway)
    m2 = MasterService(store, chunks_per_task=1, timeout_dur=0.2,
                       failure_max=5)
    t2 = ElasticTrainer(m2, exe, _feed_fn, [loss], ckpt, num_passes=3)
    t2.train()
    assert t2.pass_id == 3
    assert m2.counts()["cur_pass"] == 3
    w = np.ravel(np.asarray(fluid.global_scope().find_var("ew")))[0]
    assert abs(w - 2.0) < 0.2, f"did not converge: w={w}"
    m2.shutdown()


def test_elastic_two_workers_share_queue(tmp_path):
    """Two worker threads drain one master; every task runs exactly once
    per pass (the Go client pattern, one shared service)."""
    fluid.reset_default_env()
    loss = _linreg_program()
    glob_pat = _write_linreg_chunks(tmp_path, n_files=6)
    m = MasterService(InMemStore(), chunks_per_task=1, timeout_dur=5.0)
    m.set_dataset([glob_pat])
    fluid.Executor(fluid.CPUPlace()).run(fluid.default_startup_program())

    done = []
    lock = threading.Lock()

    def worker(wid):
        # Hogwild rule (async_executor.py worker): each thread gets its own
        # Executor with donation off — donated state buffers would be
        # freed under the other thread's feet
        exe = fluid.Executor(fluid.CPUPlace(), donate_states=False)
        my_pass = 0
        while True:
            try:
                task = m.get_task(my_pass)
            except NoMoreAvailableError:
                if m.counts()["cur_pass"] > my_pass:
                    return
                time.sleep(0.01)
                continue
            except Exception:
                return
            for chunk in task.chunks:
                for feed in _feed_fn(chunk):
                    exe.run(program=fluid.default_main_program(), feed=feed,
                            fetch_list=[loss])
            m.task_finished(task.id)
            with lock:
                done.append((wid, task.id))

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    assert sorted(t_id for _, t_id in done) == list(range(6))
    assert m.counts()["cur_pass"] == 1
    m.shutdown()
