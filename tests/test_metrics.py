"""Host metric accumulators (reference: test_metrics.py + metric op tests)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.metrics import (
    Accuracy,
    Auc,
    ChunkEvaluator,
    CompositeMetric,
    EditDistance,
    Precision,
    Recall,
)


def test_precision_recall():
    p, r = Precision(), Recall()
    preds = np.array([1, 1, 0, 1, 0])
    labels = np.array([1, 0, 0, 1, 1])
    p.update(preds, labels)
    r.update(preds, labels)
    assert p.eval() == pytest.approx(2 / 3)
    assert r.eval() == pytest.approx(2 / 3)


def test_accuracy_weighted():
    m = Accuracy()
    m.update(0.5, 10)
    m.update(1.0, 30)
    assert m.eval() == pytest.approx((0.5 * 10 + 1.0 * 30) / 40)


def test_chunk_evaluator():
    m = ChunkEvaluator()
    m.update(10, 8, 6)
    precision, recall, f1 = m.eval()
    assert precision == pytest.approx(0.6)
    assert recall == pytest.approx(0.75)
    assert f1 == pytest.approx(2 * 0.6 * 0.75 / (0.6 + 0.75))


def test_edit_distance():
    m = EditDistance()
    m.update(np.array([0.0, 2.0, 1.0]), 3)
    avg, err = m.eval()
    assert avg == pytest.approx(1.0)
    assert err == pytest.approx(2 / 3)


def test_auc_perfect_classifier():
    m = Auc()
    preds = np.array([[0.9, 0.1], [0.8, 0.2], [0.2, 0.8], [0.1, 0.9]])
    labels = np.array([0, 0, 1, 1])
    m.update(preds, labels)
    assert m.eval() == pytest.approx(1.0)


def test_composite():
    c = CompositeMetric()
    c.add_metric(Precision())
    c.add_metric(Recall())
    preds = np.array([1, 0, 1])
    labels = np.array([1, 0, 0])
    c.update(preds, labels)
    prec, rec = c.eval()
    assert prec == pytest.approx(0.5)
    assert rec == pytest.approx(1.0)


def test_weighted_average():
    from paddle_tpu.average import WeightedAverage

    w = WeightedAverage()
    w.add(2.0, 1.0)
    w.add(4.0, 3.0)
    assert w.eval() == pytest.approx((2 + 12) / 4)


def test_record_event_and_summary(capsys):
    # host-side annotation aggregation works without starting a device trace
    from paddle_tpu import profiler

    profiler.reset_profiler()
    with profiler.record_event("step"):
        np.dot(np.ones((64, 64)), np.ones((64, 64)))
    assert "step" in profiler._events
