"""The honest int64 contract (core/dtypes.py).

The reference's default integer dtype is int64 (lookup_table ids at
operators/lookup_table_op.cc:80, labels everywhere).  paddle_tpu narrows
INT64 descs to int32 on device by default (TPU-native) behind a checked
feed boundary, and honors true int64 end-to-end under enable_x64 — never
jax's silent warn-and-truncate."""
import warnings

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers


def _embedding_program(vocab, dim):
    ids = layers.data("ids", [1], dtype="int64", lod_level=0)
    emb = layers.embedding(ids, size=[vocab, dim],
                           param_attr=fluid.ParamAttr(name="i64_emb"))
    return ids, emb


def test_int64_feed_in_range_is_silent_and_correct():
    _, emb = _embedding_program(100, 4)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    table = np.asarray(fluid.global_scope().find_var("i64_emb"))
    ids = np.array([[3], [77], [0]], dtype=np.int64)
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # any truncation warning fails
        out, = exe.run(feed={"ids": ids}, fetch_list=[emb])
    np.testing.assert_allclose(np.asarray(out), table[ids.reshape(-1)],
                               rtol=1e-6)


def test_int64_feed_out_of_range_raises():
    _, emb = _embedding_program(100, 4)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    big = np.array([[2 ** 31 + 5]], dtype=np.int64)
    with pytest.raises(OverflowError, match="ids.*enable_x64"):
        exe.run(feed={"ids": big}, fetch_list=[emb])


def test_int64_fetch_restores_declared_dtype():
    x = layers.data("x", [8], dtype="float32")
    idx = layers.argmax(x, axis=1)
    exe = fluid.Executor(fluid.CPUPlace())
    out, = exe.run(feed={"x": np.random.rand(2, 8).astype("float32")},
                   fetch_list=[idx])
    assert np.asarray(out).dtype == np.int64


def test_x64_lookup_and_hash_past_2_31():
    """Under enable_x64, ids past 2**31 flow through hash -> lookup_table
    and land on the correct rows (VERDICT r2 done-criterion)."""
    with fluid.x64_scope(True):
        fluid.reset_default_env()
        vocab = 50
        ids = layers.data("ids", [1], dtype="int64")
        # hash folds the 64-bit id space into [0, vocab)
        hashed = layers.hash(ids, hash_size=vocab)
        emb = layers.embedding(hashed, size=[vocab, 3],
                               param_attr=fluid.ParamAttr(name="x64_emb"))
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        table = np.asarray(fluid.global_scope().find_var("x64_emb"))

        big = np.array([[2 ** 31 + 12345], [2 ** 40 + 7], [3]],
                       dtype=np.int64)
        h, out = exe.run(feed={"ids": big}, fetch_list=[hashed, emb])
        h = np.asarray(h).reshape(-1)
        assert h.dtype == np.int64
        assert ((0 <= h) & (h < vocab)).all()
        np.testing.assert_allclose(
            np.asarray(out).reshape(3, 3), table[h], rtol=1e-6)
        # high bits matter: two ids differing only in the high 32 bits
        # must mix differently (the hash folds both halves)
        a = np.array([[5]], dtype=np.int64)
        b = np.array([[5 + 2 ** 32]], dtype=np.int64)
        ha, = exe.run(feed={"ids": a}, fetch_list=[hashed])
        hb, = exe.run(feed={"ids": b}, fetch_list=[hashed])
        assert int(np.asarray(ha).reshape(())) != int(
            np.asarray(hb).reshape(()))


def test_x64_sgd_training_step_still_converges():
    """x64 mode must not break the float path (stays fp32 per desc)."""
    with fluid.x64_scope(True):
        fluid.reset_default_env()
        x = layers.data("x", [4], dtype="float32")
        y = layers.data("y", [1], dtype="float32")
        pred = layers.fc(x, size=1)
        loss = layers.mean(layers.square(pred - y))
        fluid.optimizer.SGD(0.1).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        rng = np.random.RandomState(0)
        w = rng.randn(4, 1).astype("float32")
        first = last = None
        for _ in range(20):
            xb = rng.randn(8, 4).astype("float32")
            lv, = exe.run(feed={"x": xb, "y": xb @ w}, fetch_list=[loss])
            lv = float(np.asarray(lv).reshape(()))
            first = lv if first is None else first
            last = lv
        assert last < first


def test_training_step_emits_no_truncation_warnings():
    """An int64-label classification step runs warning-free (the r2
    dryrun/suite tail was full of jax truncation warnings)."""
    fluid.reset_default_env()
    img = layers.data("img", [16], dtype="float32")
    label = layers.data("label", [1], dtype="int64")
    logits = layers.fc(img, size=5)
    loss = layers.mean(
        layers.softmax_with_cross_entropy(logits, label))
    fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    with warnings.catch_warnings():
        warnings.simplefilter("error", UserWarning)
        exe.run(feed={"img": np.random.rand(4, 16).astype("float32"),
                      "label": np.array([[0], [1], [2], [3]], np.int64)},
                fetch_list=[loss])


def test_lod_fetch_restores_declared_dtype():
    """LoD-carrying outputs also restore the declared INT64 at fetch
    (e.g. crf_decoding's ViterbiPath materializes int32 on device)."""
    from paddle_tpu.core.lod import create_lod_tensor

    fluid.reset_default_env()
    x = layers.data("x", [1], dtype="int64", lod_level=1)
    out = layers.sequence_reverse(x)
    exe = fluid.Executor(fluid.CPUPlace())
    feed = create_lod_tensor(np.array([[1], [2], [3]], np.int64), [[2, 1]])
    (res,) = exe.run(feed={"x": feed}, fetch_list=[out],
                     return_numpy=True)
    assert np.asarray(res.data).dtype == np.int64


def test_uint64_feed_uses_uint32_bounds():
    """A uint64 feed narrows to uint32: values in [2**31, 2**32) pass."""
    from paddle_tpu.core.dtypes import checked_feed_cast

    ok = checked_feed_cast(np.array([3_000_000_000], np.uint64),
                           np.uint64, "slot")
    assert ok.dtype == np.uint32 and int(ok[0]) == 3_000_000_000
    with pytest.raises(OverflowError, match="uint32"):
        checked_feed_cast(np.array([2 ** 33], np.uint64), np.uint64, "slot")
