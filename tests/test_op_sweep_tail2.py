"""Per-op numeric sweep, round 3: the remaining untested tail — detection
(anchor_generator, density_prior_box, box_clip, target_assign,
mine_hard_examples, roi_pool, affine_grid), conv3d, auc, nce, the
sequence_slice/scatter/expand_as/unpad window ops, and statistical checks
for the random generators.  All numpy references written independently
from the reference kernels' documented semantics."""

import numpy as np

import paddle_tpu as fluid
from op_test import OpTest


def _rand(shape, seed, lo=-1.0, hi=1.0):
    return np.random.RandomState(seed).uniform(lo, hi, shape).astype(
        "float32")


def _t(op_type, inputs, outputs, attrs=None):
    class T(OpTest):
        pass

    T.op_type = op_type
    t = T()
    t.inputs = inputs
    t.outputs = outputs
    t.attrs = attrs or {}
    return t


# ---------------------------------------------------------------------------
# anchor_generator (detection/anchor_generator_op.h)
# ---------------------------------------------------------------------------
def test_anchor_generator():
    H, W = 3, 4
    x = _rand((1, 8, H, W), seed=1)
    sizes, ratios = [32.0, 64.0], [0.5, 1.0]
    stride, offset = [16.0, 16.0], 0.5
    whs = []
    for r in ratios:
        for s in sizes:
            w = np.sqrt(s * s / r)
            whs.append((w, w * r))
    want = np.zeros((H, W, len(whs), 4), "float32")
    for j in range(H):
        for i in range(W):
            cx, cy = (i + offset) * stride[0], (j + offset) * stride[1]
            for p, (bw, bh) in enumerate(whs):
                want[j, i, p] = [cx - bw / 2, cy - bh / 2,
                                 cx + bw / 2, cy + bh / 2]
    var = np.tile(np.asarray([0.1, 0.1, 0.2, 0.2], "float32"),
                  (H, W, len(whs), 1))
    t = _t("anchor_generator", {"Input": x},
           {"Anchors": want, "Variances": var},
           {"anchor_sizes": sizes, "aspect_ratios": ratios,
            "stride": stride, "offset": offset,
            "variances": [0.1, 0.1, 0.2, 0.2]})
    t.check_output(atol=1e-4, rtol=1e-5)


# ---------------------------------------------------------------------------
# density_prior_box (detection/density_prior_box_op.h)
# ---------------------------------------------------------------------------
def test_density_prior_box():
    H, W, IH, IW = 2, 2, 32, 32
    x = _rand((1, 4, H, W), seed=2)
    img = _rand((1, 3, IH, IW), seed=3)
    fixed_sizes, densities = [8.0], [2]
    fixed_ratios = [1.0]
    step = IW / W
    boxes = []
    for j in range(H):
        for i in range(W):
            cx, cy = (i + 0.5) * step, (j + 0.5) * step
            for size, density in zip(fixed_sizes, densities):
                for ratio in fixed_ratios:
                    bw, bh = size * np.sqrt(ratio), size / np.sqrt(ratio)
                    shift = size / density
                    for dy in range(density):
                        for dx in range(density):
                            ccx = cx - size / 2 + shift / 2 + dx * shift
                            ccy = cy - size / 2 + shift / 2 + dy * shift
                            boxes.append([
                                (ccx - bw / 2) / IW, (ccy - bh / 2) / IH,
                                (ccx + bw / 2) / IW, (ccy + bh / 2) / IH])
    P = len(boxes) // (H * W)
    want = np.clip(np.asarray(boxes, "float32").reshape(H, W, P, 4), 0, 1)
    var = np.tile(np.asarray([0.1, 0.1, 0.2, 0.2], "float32"), (H, W, P, 1))
    t = _t("density_prior_box", {"Input": x, "Image": img},
           {"Boxes": want, "Variances": var},
           {"fixed_sizes": fixed_sizes, "fixed_ratios": fixed_ratios,
            "densities": densities, "clip": True,
            "variances": [0.1, 0.1, 0.2, 0.2]})
    t.check_output(atol=1e-5, rtol=1e-5)


# ---------------------------------------------------------------------------
# box_clip (detection/box_clip_op.h): clip to [0, im-1]
# ---------------------------------------------------------------------------
def test_box_clip():
    boxes = np.array([[[-5.0, 2.0, 40.0, 50.0], [1.0, -3.0, 10.0, 12.0]]],
                     "float32")  # [1, 2, 4]
    im_info = np.array([[20.0, 30.0, 1.0]], "float32")  # h=20, w=30
    want = np.array([[[0.0, 2.0, 29.0, 19.0], [1.0, 0.0, 10.0, 12.0]]],
                    "float32")
    t = _t("box_clip", {"Input": boxes, "ImInfo": im_info},
           {"Output": want})
    t.check_output(atol=1e-5, rtol=1e-5)


# ---------------------------------------------------------------------------
# target_assign (detection/target_assign_op.h)
# ---------------------------------------------------------------------------
def test_target_assign():
    # per-image gt rows, padded [N=2, M=3, K=4]
    x = _rand((2, 3, 4), seed=4)
    mi = np.array([[0, -1, 2, 1], [1, 1, -1, 0]], "int32")  # [N, P=4]
    want = np.zeros((2, 4, 4), "float32")
    wt = np.zeros((2, 4, 1), "float32")
    for n in range(2):
        for p in range(4):
            if mi[n, p] >= 0:
                want[n, p] = x[n, mi[n, p]]
                wt[n, p, 0] = 1.0
    t = _t("target_assign", {"X": x, "MatchIndices": mi},
           {"Out": want, "OutWeight": wt}, {"mismatch_value": 0})
    t.check_output(atol=1e-6, rtol=1e-6)


# ---------------------------------------------------------------------------
# mine_hard_examples (detection/mine_hard_examples_op.cc, max_negative)
# ---------------------------------------------------------------------------
def test_mine_hard_examples():
    cls_loss = np.array([[0.9, 0.1, 0.8, 0.3, 0.5]], "float32")
    match = np.array([[2, -1, -1, -1, -1]], "int32")  # 1 positive
    # neg_pos_ratio=3 -> keep 3 hardest negatives: losses 0.8, 0.5, 0.3
    want_mask = np.array([[0, 0, 1, 1, 1]], "float32")[..., None]
    want_match = np.array([[2, -1, -1, -1, -1]], "int32")
    t = _t("mine_hard_examples",
           {"ClsLoss": cls_loss, "MatchIndices": match},
           {"NegMask": want_mask, "UpdatedMatchIndices": want_match},
           {"neg_pos_ratio": 3.0, "mining_type": "max_negative"})
    t.check_output(atol=1e-6, rtol=1e-6)


# ---------------------------------------------------------------------------
# roi_pool (roi_pool_op.h) — integer-aligned RoI so the sample grid hits
# every cell and max matches the exact bin walk
# ---------------------------------------------------------------------------
def test_roi_pool_aligned():
    H = W = 8
    feat = np.arange(H * W, dtype="float32").reshape(1, 1, H, W)
    rois = (np.array([[0.0, 0.0, 3.0, 3.0]], "float32"), [1])  # LoD rois
    # roi 0..3 inclusive -> 4x4 region, pooled 2x2 -> bins of 2x2 px
    region = feat[0, 0, :4, :4]
    want = np.array([[[[region[:2, :2].max(), region[:2, 2:].max()],
                       [region[2:, :2].max(), region[2:, 2:].max()]]]],
                    "float32")
    t = _t("roi_pool", {"X": feat, "ROIs": rois}, {"Out": want},
           {"pooled_height": 2, "pooled_width": 2, "spatial_scale": 1.0})
    t.check_output(atol=1e-5, rtol=1e-5)


# ---------------------------------------------------------------------------
# affine_grid (affine_grid_op.h): theta [N,2,3] -> sampling grid [N,H,W,2]
# ---------------------------------------------------------------------------
def test_affine_grid_identity():
    N, H, W = 1, 3, 4
    theta = np.array([[[1.0, 0.0, 0.0], [0.0, 1.0, 0.0]]], "float32")
    xs = np.linspace(-1, 1, W)
    ys = np.linspace(-1, 1, H)
    want = np.zeros((N, H, W, 2), "float32")
    for j in range(H):
        for i in range(W):
            want[0, j, i] = [xs[i], ys[j]]
    t = _t("affine_grid", {"Theta": theta}, {"Output": want},
           {"output_shape": [N, 1, H, W]})
    t.check_output(atol=1e-5, rtol=1e-5)


# ---------------------------------------------------------------------------
# conv3d: direct numpy loop reference
# ---------------------------------------------------------------------------
def test_conv3d_numeric():
    x = _rand((1, 2, 4, 4, 4), seed=6)
    f = _rand((3, 2, 2, 2, 2), seed=7)
    xd, fd = x.astype("float64"), f.astype("float64")
    want = np.zeros((1, 3, 3, 3, 3))
    for oc in range(3):
        for d in range(3):
            for i in range(3):
                for j in range(3):
                    want[0, oc, d, i, j] = np.sum(
                        xd[0, :, d:d + 2, i:i + 2, j:j + 2] * fd[oc])
    t = _t("conv3d", {"Input": x, "Filter": f},
           {"Output": want.astype("float32")},
           {"strides": [1, 1, 1], "paddings": [0, 0, 0],
            "dilations": [1, 1, 1]})
    t.check_output(atol=2e-5, rtol=2e-5)
    t.check_grad(["Input", "Filter"], "Output", max_relative_error=0.02)


# ---------------------------------------------------------------------------
# auc op (metrics/auc_op.cc): histogram AUC vs exact rank AUC
# ---------------------------------------------------------------------------
def test_auc_op_numeric():
    r = np.random.RandomState(8)
    n = 200
    scores = r.uniform(0, 1, n).astype("float32")
    labels = (scores + r.normal(0, 0.3, n) > 0.5).astype("int64")
    preds = np.stack([1 - scores, scores], axis=1).astype("float32")
    buckets = 4095
    stat = np.zeros(buckets + 1, "int64")

    # exact AUC over the histogram discretization
    pos_h = np.zeros(buckets + 1)
    neg_h = np.zeros(buckets + 1)
    for s, l in zip(scores, labels):
        b = min(int(s * buckets), buckets)
        (pos_h if l else neg_h)[b] += 1
    pos_cum = np.cumsum(pos_h[::-1])
    neg_cum = np.cumsum(neg_h[::-1])
    tpr = pos_cum / max(pos_cum[-1], 1)
    fpr = neg_cum / max(neg_cum[-1], 1)
    want_auc = np.trapezoid(tpr, fpr)

    t = _t("auc",
           {"Predict": preds, "Label": labels.reshape(-1, 1),
            "StatPos": stat, "StatNeg": stat.copy()},
           {"AUC": np.array([want_auc], "float32"),
            "StatPosOut": None, "StatNegOut": None},
           {"num_thresholds": buckets})
    t.check_output(atol=1e-4, rtol=1e-4)


# ---------------------------------------------------------------------------
# nce (nce_op.cc): recompute the cost from the op's OWN sampled labels
# ---------------------------------------------------------------------------
def test_nce_consistent_with_samples():
    from paddle_tpu import layers

    fluid.reset_default_env()
    n, d, v, k = 4, 6, 20, 5
    x = layers.data("x", [d])
    lbl = layers.data("lbl", [1], dtype="int64")
    cost = layers.nce(input=x, label=lbl, num_total_classes=v,
                      num_neg_samples=k,
                      param_attr=fluid.ParamAttr(name="nce_w"),
                      bias_attr=fluid.ParamAttr(name="nce_b"))
    prog = fluid.default_main_program()
    op = [o for o in prog.global_block().ops if o.type == "nce"][0]
    logits_name = op.output("SampleLogits")[0]
    samples_name = op.output("SampleLabels")[0]
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    xv = _rand((n, d), seed=9)
    lv = np.random.RandomState(10).randint(0, v, (n, 1)).astype("int64")
    c, lg, smp = exe.run(feed={"x": xv, "lbl": lv},
                         fetch_list=[cost, logits_name, samples_name])
    w = np.asarray(fluid.global_scope().find_var("nce_w"))
    b = np.asarray(fluid.global_scope().find_var("nce_b")).reshape(-1)
    smp = np.asarray(smp)
    want_logits = np.einsum("nd,ntd->nt", xv, w[smp]) + b[smp]
    np.testing.assert_allclose(np.asarray(lg), want_logits, rtol=1e-4,
                               atol=1e-4)
    p = 1 / (1 + np.exp(-(want_logits - np.log(k / v))))
    lab01 = np.concatenate([np.ones((n, 1)), np.zeros((n, k))], axis=1)
    want_cost = -(lab01 * np.log(p + 1e-12)
                  + (1 - lab01) * np.log(1 - p + 1e-12)).sum(
        axis=1, keepdims=True)
    np.testing.assert_allclose(np.asarray(c), want_cost, rtol=1e-4,
                               atol=1e-4)
    assert smp.shape == (n, 1 + k) and (smp[:, 0:1] == lv).all()


# ---------------------------------------------------------------------------
# sequence window tail: slice / scatter / expand_as / unpad
# ---------------------------------------------------------------------------
def test_sequence_slice_numeric():
    from paddle_tpu import layers
    from paddle_tpu.core.lod import create_lod_tensor

    fluid.reset_default_env()
    seqs = [np.arange(10, dtype="float32").reshape(5, 2),
            np.arange(100, 108, dtype="float32").reshape(4, 2)]
    x = layers.data("x", [2], dtype="float32", lod_level=1)
    off = layers.data("off", [1], dtype="int64")
    length = layers.data("length", [1], dtype="int64")
    out = layers.sequence_slice(x, off, length)
    exe = fluid.Executor(fluid.CPUPlace())
    (res,) = exe.run(
        feed={"x": create_lod_tensor(np.concatenate(seqs), [[5, 4]]),
              "off": np.array([[1], [2]], "int64"),
              "length": np.array([[3], [2]], "int64")},
        fetch_list=[out], return_numpy=False)
    np.testing.assert_allclose(np.asarray(res.data)[0, :3], seqs[0][1:4],
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(res.data)[1, :2], seqs[1][2:4],
                               rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(res.lengths), [3, 2])


def test_sequence_scatter_numeric():
    x = np.zeros((2, 6), "float32")
    ids = (np.array([[1], [4], [0], [5]], "int64"), [2, 2])
    upd = (np.array([2.0, 3.0, 5.0, 7.0], "float32"), [2, 2])
    want = np.zeros((2, 6), "float32")
    want[0, 1], want[0, 4] = 2.0, 3.0
    want[1, 0], want[1, 5] = 5.0, 7.0
    t = _t("sequence_scatter", {"X": x, "Ids": ids, "Updates": upd},
           {"Out": want})
    t.check_output(atol=1e-6, rtol=1e-6)


def test_sequence_expand_as_numeric():
    from paddle_tpu import layers
    from paddle_tpu.core.lod import create_lod_tensor

    fluid.reset_default_env()
    x = layers.data("x", [3], dtype="float32")
    y = layers.data("y", [1], dtype="float32", lod_level=1)
    out = layers.sequence_expand_as(x, y)
    exe = fluid.Executor(fluid.CPUPlace())
    xv = _rand((2, 3), seed=11)
    yv = create_lod_tensor(np.zeros((5, 1), "float32"), [[3, 2]])
    (res,) = exe.run(feed={"x": xv, "y": yv}, fetch_list=[out],
                     return_numpy=False)
    # row i of x repeats len(y_i) times
    np.testing.assert_allclose(np.asarray(res.data)[0, :3],
                               np.tile(xv[0], (3, 1)), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(res.data)[1, :2],
                               np.tile(xv[1], (2, 1)), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(res.lengths), [3, 2])


def test_sequence_unpad_numeric():
    from paddle_tpu import layers

    fluid.reset_default_env()
    x = layers.data("x", [4, 3], dtype="float32", append_batch_size=False)
    length = layers.data("len", [1], dtype="int64")
    out = layers.sequence_unpad(x, length)
    exe = fluid.Executor(fluid.CPUPlace())
    xv = _rand((2, 4, 3), seed=12)
    lv = np.array([[3], [2]], "int64")
    (res,) = exe.run(feed={"x": xv, "len": lv}, fetch_list=[out],
                     return_numpy=False)
    np.testing.assert_allclose(np.asarray(res.data)[0, :3], xv[0, :3],
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(res.data)[1, :2], xv[1, :2],
                               rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(res.lengths), [3, 2])


# ---------------------------------------------------------------------------
# random generators: statistical bounds
# ---------------------------------------------------------------------------
def test_truncated_gaussian_random_stats():
    from paddle_tpu import layers

    fluid.reset_default_env()
    v = fluid.default_main_program().global_block().create_var(
        name="tg", shape=[4000], dtype="float32")
    fluid.default_main_program().global_block().append_op(
        type="truncated_gaussian_random", inputs={},
        outputs={"Out": ["tg"]},
        attrs={"shape": [4000], "mean": 0.0, "std": 1.0})
    exe = fluid.Executor(fluid.CPUPlace())
    (out,) = exe.run(feed={}, fetch_list=["tg"])
    out = np.asarray(out)
    assert np.abs(out).max() <= 2.0 + 1e-5  # truncation at 2 std
    assert abs(out.mean()) < 0.1
    assert 0.5 < out.std() < 1.0  # truncated normal std ~ 0.88


def test_batch_size_like_randoms():
    from paddle_tpu import layers

    fluid.reset_default_env()
    ref = layers.data("ref", [7], dtype="float32")
    block = fluid.default_main_program().global_block()
    for name, op, attrs in (
        ("u", "uniform_random_batch_size_like",
         {"shape": [-1, 5], "min": -1.0, "max": 1.0}),
        ("g", "gaussian_random_batch_size_like",
         {"shape": [-1, 5], "mean": 0.0, "std": 1.0}),
    ):
        block.create_var(name=name, shape=[-1, 5], dtype="float32")
        block.append_op(type=op, inputs={"Input": [ref.name]},
                        outputs={"Out": [name]}, attrs=attrs)
    exe = fluid.Executor(fluid.CPUPlace())
    u, g = exe.run(feed={"ref": np.zeros((6, 7), "float32")},
                   fetch_list=["u", "g"])
    assert np.shape(u) == (6, 5) and np.shape(g) == (6, 5)
    assert (np.asarray(u) >= -1).all() and (np.asarray(u) <= 1).all()
    assert np.asarray(g).std() > 0.3


def test_sampling_id_distribution():
    from paddle_tpu import layers

    fluid.reset_default_env()
    probs = layers.data("p", [4], dtype="float32")
    block = fluid.default_main_program().global_block()
    block.create_var(name="sid", shape=[-1], dtype="int64")
    block.append_op(type="sampling_id", inputs={"X": [probs.name]},
                    outputs={"Out": ["sid"]}, attrs={})
    exe = fluid.Executor(fluid.CPUPlace())
    p = np.tile(np.array([[0.0, 0.0, 1.0, 0.0]], "float32"), (32, 1))
    (out,) = exe.run(feed={"p": p}, fetch_list=["sid"])
    assert (np.asarray(out).reshape(-1) == 2).all()  # degenerate dist
