"""Per-op sweep: the fused-op family
(reference: operators/fused/fusion_seqconv_eltadd_relu_op.cc,
fusion_seqexpand_concat_fc_op.cc, fused_embedding_fc_lstm_op.cc,
attention_lstm_op.cc, conv_fusion_op.cc,
fusion_transpose_flatten_concat_op.cc — MKLDNN/cuDNN-era fusions kept for
program parity; each numpy reference below re-derives the kernel math
independently)."""

import numpy as np

import paddle_tpu as fluid
from op_test import OpTest


def _rand(shape, seed=0, lo=-1.0, hi=1.0):
    return np.random.RandomState(seed).uniform(lo, hi, shape).astype("float32")


def _t(op_type, inputs, outputs, attrs=None):
    class T(OpTest):
        pass

    T.op_type = op_type
    t = T()
    t.inputs = inputs
    t.outputs = outputs
    t.attrs = attrs or {}
    return t


def _pad(flat, lens, feat):
    """token-major flat [sum(lens), F] -> padded [N, max(lens), F]."""
    n, t = len(lens), max(lens)
    out = np.zeros((n, t) + tuple(feat), dtype=flat.dtype)
    off = 0
    for i, li in enumerate(lens):
        out[i, :li] = flat[off:off + li]
        off += li
    return out


def _seqconv_ref(flat, lens, filt, clen, cstart):
    """numpy context-window conv per sequence (math/context_project.h)."""
    f = flat.shape[1]
    cols = np.zeros((flat.shape[0], clen * f), dtype=flat.dtype)
    off = 0
    for li in lens:
        for t in range(li):
            for j in range(clen):
                s = t + cstart + j
                if 0 <= s < li:
                    cols[off + t, j * f:(j + 1) * f] = flat[off + s]
        off += li
    return cols, cols @ filt


def test_fusion_seqconv_eltadd_relu():
    lens = [3, 1, 4]
    flat = _rand((sum(lens), 5), 1)
    clen, cstart = 3, -1
    filt = _rand((clen * 5, 6), 2)
    bias = _rand((1, 6), 3)
    cols, conv = _seqconv_ref(flat, lens, filt, clen, cstart)
    want = np.maximum(conv + bias, 0.0)
    t = _t("fusion_seqconv_eltadd_relu",
           {"X": (flat, lens), "Filter": filt, "Bias": bias},
           {"Out": (want, lens), "ColMat": (cols, lens)},
           {"contextLength": clen, "contextStart": cstart})
    t.check_output(atol=2e-5, rtol=2e-5)
    t.check_grad(["X", "Filter", "Bias"], "Out", max_relative_error=0.03)


def test_fusion_seqexpand_concat_fc():
    lens = [2, 3]
    m0, m1, m2, d_out = 4, 3, 2, 5
    flat = _rand((sum(lens), m0), 4)
    x1 = _rand((2, m1), 5)
    x2 = _rand((2, m2), 6)
    w = _rand((m0 + m1 + m2, d_out), 7)
    b = _rand((d_out,), 8)
    want = np.zeros((sum(lens), d_out), dtype="float32")
    off = 0
    for i, li in enumerate(lens):
        row = np.concatenate([x1[i], x2[i]]) @ w[m0:]
        for t in range(li):
            want[off + t] = flat[off + t] @ w[:m0] + row + b
        off += li
    want = np.tanh(want)
    t = _t("fusion_seqexpand_concat_fc",
           {"X": [(flat, lens), x1, x2], "FCWeight": w, "FCBias": b},
           {"Out": (want, lens)},
           {"fc_activation": "tanh"})
    t.check_output(atol=2e-5, rtol=2e-5)
    t.check_grad(["FCWeight", "FCBias"], "Out", max_relative_error=0.03)


def _lstm_ref(xx_pad, lens, wh, b4, h0, c0):
    """numpy LSTM over pre-projected gates, [cand, i, f, o] order
    (math/detail/lstm_cpu_kernel.h via fusion_lstm_op.h), no peepholes."""
    n, t, d4 = xx_pad.shape
    d = d4 // 4
    hs = np.zeros((n, t, d), dtype="float32")
    cs = np.zeros((n, t, d), dtype="float32")
    sig = lambda v: 1.0 / (1.0 + np.exp(-v))
    for i in range(n):
        h, c = h0[i].copy(), c0[i].copy()
        for s in range(lens[i]):
            g = xx_pad[i, s] + h @ wh + b4
            cand = np.tanh(g[:d])
            gi, gf, go = sig(g[d:2 * d]), sig(g[2 * d:3 * d]), sig(g[3 * d:])
            c = cand * gi + c * gf
            h = go * np.tanh(c)
            hs[i, s], cs[i, s] = h, c
    return hs, cs


def test_fused_embedding_fc_lstm():
    lens = [3, 2]
    vocab, d = 11, 4
    ids_flat = np.random.RandomState(9).randint(
        0, vocab, (sum(lens), 1)).astype("int64")
    emb = _rand((vocab, 4 * d), 10)
    wh = _rand((d, 4 * d), 11)
    bias = _rand((1, 4 * d), 12)
    xx_flat = emb[ids_flat[:, 0]]
    hs, cs = _lstm_ref(
        _pad(xx_flat, lens, (4 * d,)), lens, wh, bias[0],
        np.zeros((2, d), "float32"), np.zeros((2, d), "float32"))
    n = len(lens)
    t_ = _t("fused_embedding_fc_lstm",
            {"Ids": (ids_flat, lens), "Embeddings": emb, "WeightH": wh,
             "Bias": bias},
            {"Hidden": (np.concatenate([hs[i, :lens[i]] for i in range(n)]),
                        lens),
             "Cell": (np.concatenate([cs[i, :lens[i]] for i in range(n)]),
                      lens)},
            {"use_peepholes": False})
    t_.check_output(atol=2e-5, rtol=2e-5)
    t_.check_grad(["Embeddings", "WeightH"], "Hidden",
                  max_relative_error=0.03)


def _attention_lstm_ref(x_pad, lens, aw, ab, a_scal, a_scal_b, lw, lb):
    """numpy re-derivation of attention_lstm_op.cc's kernel loop."""
    n, t, m = x_pad.shape
    d = lw.shape[1] // 4
    sig = lambda v: 1.0 / (1.0 + np.exp(-v))
    hs = np.zeros((n, t, d), "float32")
    cs = np.zeros((n, t, d), "float32")
    for i in range(n):
        li = lens[i]
        h = np.zeros((d,), "float32")
        c = np.zeros((d,), "float32")
        atted = x_pad[i, :li] @ aw[:m] + (ab if ab is not None else 0.0)
        for s in range(li):
            score = np.maximum(atted + c @ aw[m:], 0.0)
            if a_scal is not None:
                score = score * a_scal
                if a_scal_b is not None:
                    score = score + a_scal_b
                score = np.maximum(score, 0.0)
            e = np.exp(score - score.max())
            alpha = e / e.sum()
            lstm_x = alpha @ x_pad[i, :li]
            g = lstm_x @ lw[d:] + h @ lw[:d] + lb
            f, gi, o = sig(g[:d]), sig(g[d:2 * d]), sig(g[2 * d:3 * d])
            cand = np.tanh(g[3 * d:])
            c = f * c + gi * cand
            h = o * np.tanh(c)
            hs[i, s], cs[i, s] = h, c
    return hs, cs


def test_attention_lstm():
    lens = [4, 2]
    m, d = 3, 2
    n = len(lens)
    flat = _rand((sum(lens), m), 13)
    aw = _rand((m + d, 1), 14)
    ab = _rand((1, 1), 15)
    a_scal = _rand((1, 1), 16, 0.5, 1.5)
    a_scal_b = _rand((1, 1), 17)
    lw = _rand((d + m, 4 * d), 18)
    lb = _rand((1, 4 * d), 19)
    c0 = np.zeros((n, d), "float32")
    hs, cs = _attention_lstm_ref(
        _pad(flat, lens, (m,)), lens, aw[:, 0], ab[0, 0], a_scal[0, 0],
        a_scal_b[0, 0], lw, lb[0])
    t_ = _t("attention_lstm",
            {"X": (flat, lens), "C0": c0, "AttentionWeight": aw,
             "AttentionBias": ab, "AttentionScalar": a_scal,
             "AttentionScalarBias": a_scal_b, "LSTMWeight": lw,
             "LSTMBias": lb},
            {"Hidden": (np.concatenate([hs[i, :lens[i]] for i in range(n)]),
                        lens),
             "Cell": (np.concatenate([cs[i, :lens[i]] for i in range(n)]),
                      lens)},
            {})
    t_.check_output(atol=2e-5, rtol=2e-5)


def test_conv2d_fusion():
    # 1x1 kernel => per-pixel channel matmul; easy independent reference
    x = _rand((2, 3, 4, 4), 20)
    f = _rand((5, 3, 1, 1), 21)
    bias = _rand((5,), 22)
    resid = _rand((2, 5, 4, 4), 23)
    conv = np.einsum("nchw,oc->nohw", x, f[:, :, 0, 0])
    want = np.maximum(conv + resid + bias[None, :, None, None], 0.0)
    t = _t("conv2d_fusion",
           {"Input": x, "Filter": f, "Bias": bias, "ResidualData": resid},
           {"Output": want}, {"activation": "relu"})
    t.check_output(atol=2e-5, rtol=2e-5)

    want_id = conv + bias[None, :, None, None]
    t = _t("conv2d_fusion", {"Input": x, "Filter": f, "Bias": bias},
           {"Output": want_id}, {"activation": "identity"})
    t.check_output(atol=2e-5, rtol=2e-5)


def test_fusion_transpose_flatten_concat():
    x1 = _rand((2, 3, 4), 24)
    x2 = _rand((2, 3, 5), 25)
    trans, flat_axis = [0, 2, 1], 1
    f1 = x1.transpose(trans).reshape(2, -1)
    f2 = x2.transpose(trans).reshape(2, -1)
    t = _t("fusion_transpose_flatten_concat", {"X": [x1, x2]},
           {"Out": np.concatenate([f1, f2], axis=1)},
           {"trans_axis": trans, "flatten_axis": flat_axis,
            "concat_axis": 1})
    t.check_output()
    t.check_grad(["X"], "Out", max_relative_error=0.02)


def test_average_accumulates_window_rotation():
    p = _rand((3,), 26)
    s1 = np.zeros((3,), "float32")
    s2 = np.zeros((3,), "float32")
    s3 = np.zeros((3,), "float32")
    zero = np.zeros((1,), "int64")

    # after min_average_window=2 accumulations the window closes:
    # step1: s1=p, num_acc=1 (no close); step2 from those outputs would
    # close.  Exercise both phases through the op itself.
    t = _t("average_accumulates",
           {"param": p, "in_sum_1": s1, "in_sum_2": s2, "in_sum_3": s3,
            "in_num_accumulates": zero, "in_old_num_accumulates": zero,
            "in_num_updates": zero},
           {"out_sum_1": p, "out_sum_2": s2, "out_sum_3": s3,
            "out_num_accumulates": np.array([1], "int64"),
            "out_old_num_accumulates": zero,
            "out_num_updates": np.array([1], "int64")},
           {"average_window": 1.0, "min_average_window": 2,
            "max_average_window": 100})
    t.check_output()

    one = np.array([1], "int64")
    # the close rotates the POST-update sums: the reference kernel's
    # in_/out_ slots alias the same variables, so its
    # "out_sum_3 = in_sum_1 + in_sum_2" reads sum_1 + param through
    # the alias (average_accumulates_op.h with optimizer.py:1490 wiring)
    t = _t("average_accumulates",
           {"param": p, "in_sum_1": p.copy(), "in_sum_2": s2, "in_sum_3": s3,
            "in_num_accumulates": one, "in_old_num_accumulates": zero,
            "in_num_updates": one},
           {"out_sum_1": s1, "out_sum_2": s2, "out_sum_3": 2 * p,
            "out_num_accumulates": zero,
            "out_old_num_accumulates": np.array([2], "int64"),
            "out_num_updates": np.array([2], "int64")},
           {"average_window": 1.0, "min_average_window": 2,
            "max_average_window": 100})
    t.check_output()


def test_save_load_roundtrip_ops(tmp_path):
    """save / save_combine / load_combine as in-graph ops (reference:
    operators/save_op.cc, save_combine_op.cc, load_combine_op.cc)."""
    val = _rand((2, 3), 27)
    val2 = _rand((4,), 28)
    p1 = str(tmp_path / "a")
    p2 = str(tmp_path / "ab")

    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        block = prog.global_block()
        block.create_var(name="x", shape=[2, 3], dtype="float32")
        block.create_var(name="y", shape=[4], dtype="float32")
        block.append_op(type="save", inputs={"X": ["x"]}, outputs={},
                        attrs={"file_path": p1})
        block.append_op(type="save_combine", inputs={"X": ["x", "y"]},
                        outputs={},
                        attrs={"file_path": p2, "var_names": ["x", "y"]})
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(program=prog, feed={"x": val, "y": val2}, fetch_list=[])

    got = np.load(p1 + ".npy")
    np.testing.assert_allclose(got, val, rtol=1e-6)

    prog2 = fluid.Program()
    with fluid.program_guard(prog2, fluid.Program()):
        block = prog2.global_block()
        block.create_var(name="x2", shape=[2, 3], dtype="float32")
        block.create_var(name="y2", shape=[4], dtype="float32")
        block.append_op(type="load_combine", inputs={},
                        outputs={"Out": ["x2", "y2"]},
                        attrs={"file_path": p2, "var_names": ["x", "y"]})
        exe = fluid.Executor(fluid.CPUPlace())
        x2, y2 = exe.run(program=prog2, feed={}, fetch_list=["x2", "y2"])
    np.testing.assert_allclose(np.asarray(x2), val, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(y2), val2, rtol=1e-6)


def test_get_places():
    import jax

    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        block = prog.global_block()
        block.create_var(name="places", shape=[-1], dtype="int32")
        block.append_op(type="get_places", inputs={},
                        outputs={"Out": ["places"]},
                        attrs={"device_count": 2})
        exe = fluid.Executor(fluid.CPUPlace())
        (got,) = exe.run(program=prog, feed={}, fetch_list=["places"])
    assert len(np.asarray(got)) == min(2, len(jax.devices()))


def test_ref_by_trainer_id():
    xs = [_rand((3, 2), s) for s in (30, 31, 32)]
    tid = np.array([2], dtype="int64")
    t = _t("ref_by_trainer_id", {"X": xs, "TrainerId": tid},
           {"Out": xs[2]})
    t.check_output()


def test_split_byref():
    x = _rand((7, 3), 33)
    t = _t("split_byref", {"X": x},
           {"Out": [x[:2], x[2:5], x[5:]]},
           {"sections": [2, 3, 2]})
    t.check_output()


def test_attention_lstm_grads():
    """Numeric-grad check through the per-step attention + LSTM scan (the
    reference registers DefaultGradOpDescMaker for attention_lstm; here
    the grad falls out of jax.vjp through the scan)."""
    lens = [3, 2]
    m, d = 2, 2
    n = len(lens)
    flat = _rand((sum(lens), m), 40)
    aw = _rand((m + d, 1), 41)
    ab = _rand((1, 1), 42)
    lw = _rand((d + m, 4 * d), 43)
    lb = _rand((1, 4 * d), 44)
    c0 = np.zeros((n, d), "float32")
    hs, cs = _attention_lstm_ref(
        _pad(flat, lens, (m,)), lens, aw[:, 0], ab[0, 0], None, None,
        lw, lb[0])
    t_ = _t("attention_lstm",
            {"X": (flat, lens), "C0": c0, "AttentionWeight": aw,
             "AttentionBias": ab, "LSTMWeight": lw, "LSTMBias": lb},
            {"Hidden": (np.concatenate([hs[i, :lens[i]] for i in range(n)]),
                        lens),
             "Cell": (np.concatenate([cs[i, :lens[i]] for i in range(n)]),
                      lens)},
            {})
    t_.check_output(atol=2e-5, rtol=2e-5)
    t_.check_grad(["X", "AttentionWeight", "LSTMWeight", "LSTMBias"],
                  "Hidden", max_relative_error=0.05)
