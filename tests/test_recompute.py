"""Rematerialization: fluid.recompute_scope tags ops whose backward
re-runs the forward lowering (jax.checkpoint) instead of keeping internal
activations.  TPU-native memory feature; later Paddle's RecomputeOptimizer
plays the same role."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers


def _build(recompute):
    import contextlib

    fluid.reset_default_env()
    x = layers.data("x", [16], dtype="float32")
    y = layers.data("y", [1], dtype="float32")
    h = layers.fc(x, size=64, act="relu")
    cm = fluid.recompute_scope() if recompute else contextlib.nullcontext()
    with cm:
        h = layers.fc(h, size=64, act="tanh")
        h = layers.fc(h, size=32, act="relu")
    pred = layers.fc(h, size=1)
    loss = layers.mean(layers.square_error_cost(pred, y))
    fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
    return loss


def _run(loss, steps=5):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(5)
    xv = rng.randn(8, 16).astype("float32")
    yv = rng.randn(8, 1).astype("float32")
    return [
        float(np.ravel(np.asarray(
            exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])[0]
        ))[0])
        for _ in range(steps)
    ]


def test_recompute_scope_matches_plain_training():
    ref = _run(_build(recompute=False))
    got = _run(_build(recompute=True))
    # recompute changes memory scheduling, not math
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
    assert got[-1] < got[0]


def test_recompute_attr_reaches_compiled_program():
    """The tagged ops carry @recompute@ and the lowered computation really
    contains remat regions (jax.checkpoint made it into the trace)."""
    import jax

    from paddle_tpu.core.compiler import CompiledBlock
    from paddle_tpu.core.executor import _RunPlan

    loss = _build(recompute=True)
    prog = fluid.default_main_program()
    tagged = [op.type for op in prog.desc.block(0).ops
              if op.attrs.get("@recompute@")]
    assert "mul" in tagged  # the fc matmuls inside the scope

    plan = _RunPlan(prog, ["x", "y"], [loss.name])
    compiled = CompiledBlock(
        prog, 0, plan.feed_names, plan.fetch_names, plan.state_names,
        donate_states=False,
    )
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    block0 = prog.desc.block(0)
    rng = np.random.RandomState(5)
    feed = {"x": rng.randn(8, 16).astype("float32"),
            "y": rng.randn(8, 1).astype("float32")}
    feed_vals = plan.feed_values(feed, block0)
    state_vals = plan.state_values(fluid.global_scope(), block0)
    jaxpr = jax.make_jaxpr(compiled.raw_fn)(
        feed_vals, state_vals, jax.random.PRNGKey(0))
    assert "remat" in str(jaxpr)


def test_transformer_recompute_trains():
    from paddle_tpu import models

    fluid.reset_default_env()
    spec = models.transformer(models.TransformerConfig(
        src_vocab_size=64, trg_vocab_size=64, max_length=8, n_layer=2,
        n_head=2, d_model=16, d_inner=32, dropout=0.0, use_recompute=True,
    ))
    prog = fluid.default_main_program()
    assert any(op.attrs.get("@recompute@") for op in prog.desc.block(0).ops)
    fluid.optimizer.Adam(learning_rate=1e-3).minimize(spec.loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    batch = spec.synthetic_batch(4)
    l0 = float(np.ravel(np.asarray(
        exe.run(feed=batch, fetch_list=[spec.loss])[0]))[0])
    for _ in range(4):
        (lv,) = exe.run(feed=batch, fetch_list=[spec.loss])
    l1 = float(np.ravel(np.asarray(lv))[0])
    assert np.isfinite(l0) and np.isfinite(l1) and l1 < l0
