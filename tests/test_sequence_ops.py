"""Sequence-op numerics vs numpy ragged references — the OpTest idea
(reference: unittests/op_test.py + test_sequence_*.py): compute each op on a
ragged python batch with numpy, compare against the padded lowering."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.core.lod import LoDValue, create_lod_tensor

RNG = np.random.RandomState(7)
LENS = [3, 5, 1, 4]


def ragged(feat=(6,), lens=LENS, dtype=np.float32):
    return [RNG.randn(l, *feat).astype(dtype) for l in lens]


def run_fetch(build, feeds):
    out = build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    fetched = exe.run(feed=feeds, fetch_list=[out] if not isinstance(out, (list, tuple)) else out)
    return fetched


def lod_feed(seqs):
    return create_lod_tensor(seqs)


@pytest.mark.parametrize("ptype,ref", [
    ("sum", lambda s: s.sum(0)),
    ("average", lambda s: s.mean(0)),
    ("sqrt", lambda s: s.sum(0) / np.sqrt(len(s))),
    ("max", lambda s: s.max(0)),
    ("first", lambda s: s[0]),
    ("last", lambda s: s[-1]),
])
def test_sequence_pool(ptype, ref):
    seqs = ragged()
    (res,) = run_fetch(
        lambda: fluid.layers.sequence_pool(
            fluid.layers.data("x", [6], dtype="float32", lod_level=1), ptype
        ),
        {"x": lod_feed(seqs)},
    )
    expect = np.stack([ref(s) for s in seqs])
    np.testing.assert_allclose(np.asarray(res), expect, rtol=1e-5, atol=1e-6)


def test_sequence_softmax():
    seqs = ragged(feat=(1,))
    (res,) = run_fetch(
        lambda: fluid.layers.sequence_softmax(
            fluid.layers.data("x", [1], dtype="float32", lod_level=1)
        ),
        {"x": lod_feed(seqs)},
    )
    res = res.data
    for i, s in enumerate(seqs):
        e = np.exp(s - s.max())
        np.testing.assert_allclose(res[i, : len(s)], e / e.sum(), rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(res[i, len(s):], 0.0, atol=1e-7)


def test_sequence_reverse():
    seqs = ragged()
    (res,) = run_fetch(
        lambda: fluid.layers.sequence_reverse(
            fluid.layers.data("x", [6], dtype="float32", lod_level=1)
        ),
        {"x": lod_feed(seqs)},
    )
    for i, s in enumerate(seqs):
        np.testing.assert_allclose(res.data[i, : len(s)], s[::-1], rtol=1e-6)


def test_sequence_concat():
    a, b = ragged(feat=(4,)), ragged(feat=(4,), lens=[2, 1, 3, 2])
    (res,) = run_fetch(
        lambda: fluid.layers.sequence_concat([
            fluid.layers.data("a", [4], dtype="float32", lod_level=1),
            fluid.layers.data("b", [4], dtype="float32", lod_level=1),
        ]),
        {"a": lod_feed(a), "b": lod_feed(b)},
    )
    for i in range(len(a)):
        cat = np.concatenate([a[i], b[i]], axis=0)
        np.testing.assert_allclose(res.data[i, : len(cat)], cat, rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(res.lengths), [5, 6, 4, 6])


def test_sequence_expand_dense():
    x = RNG.randn(4, 3).astype(np.float32)
    yseqs = ragged(feat=(2,))
    (res,) = run_fetch(
        lambda: fluid.layers.sequence_expand(
            fluid.layers.data("x", [3], dtype="float32"),
            fluid.layers.data("y", [2], dtype="float32", lod_level=1),
        ),
        {"x": x, "y": lod_feed(yseqs)},
    )
    for i, s in enumerate(yseqs):
        np.testing.assert_allclose(res.data[i, : len(s)], np.tile(x[i], (len(s), 1)), rtol=1e-6)


def test_sequence_pad_unpad_mask():
    seqs = ragged(feat=(2,))
    x = fluid.layers.data("x", [2], dtype="float32", lod_level=1)
    pad_value = fluid.layers.fill_constant([1], "float32", 9.0)
    out, length = fluid.layers.sequence_pad(x, pad_value)
    mask = fluid.layers.sequence_mask(x, maxlen=5, dtype="float32")
    exe = fluid.Executor(fluid.CPUPlace())
    o, l, m = exe.run(feed={"x": lod_feed(seqs)}, fetch_list=[out, length, mask])
    assert o.shape == (4, 5, 2)
    np.testing.assert_array_equal(np.asarray(l).ravel(), LENS)
    for i, s in enumerate(seqs):
        np.testing.assert_allclose(o[i, : len(s)], s, rtol=1e-6)
        np.testing.assert_allclose(o[i, len(s):], 9.0)
        np.testing.assert_array_equal(m[i], (np.arange(5) < len(s)).astype(np.float32))


def test_sequence_reshape():
    seqs = [RNG.randn(l, 4).astype(np.float32) for l in [2, 4]]
    (res,) = run_fetch(
        lambda: fluid.layers.sequence_reshape(
            fluid.layers.data("x", [4], dtype="float32", lod_level=1), new_dim=2
        ),
        {"x": lod_feed(seqs)},
    )
    for i, s in enumerate(seqs):
        flat = s.reshape(-1, 2)
        np.testing.assert_allclose(res.data[i, : len(flat)], flat, rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(res.lengths), [4, 8])


def test_sequence_erase():
    seqs = [np.array([[1], [2], [3], [2]], np.int64), np.array([[2], [2]], np.int64)]
    (res,) = run_fetch(
        lambda: fluid.layers.sequence_erase(
            fluid.layers.data("x", [1], dtype="int64", lod_level=1), tokens=[2]
        ),
        {"x": lod_feed(seqs)},
    )
    np.testing.assert_array_equal(np.asarray(res.lengths), [2, 0])
    np.testing.assert_array_equal(res.data[0, :2, 0], [1, 3])


def test_sequence_enumerate():
    seqs = [np.array([[1], [2], [3]], np.int64), np.array([[4], [5]], np.int64)]
    (res,) = run_fetch(
        lambda: fluid.layers.sequence_enumerate(
            fluid.layers.data("x", [1], dtype="int64", lod_level=1),
            win_size=2, pad_value=0,
        ),
        {"x": lod_feed(seqs)},
    )
    np.testing.assert_array_equal(res.data[0, :3], [[1, 2], [2, 3], [3, 0]])
    np.testing.assert_array_equal(res.data[1, :2], [[4, 5], [5, 0]])


def test_sequence_conv_matches_manual_window():
    seqs = ragged(feat=(3,))
    x = fluid.layers.data("x", [3], dtype="float32", lod_level=1)
    out = fluid.layers.sequence_conv(
        x, num_filters=4, filter_size=3,
        param_attr=fluid.ParamAttr(name="sconv_w"),
    )
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    (res,) = exe.run(feed={"x": lod_feed(seqs)}, fetch_list=[out])
    w = np.asarray(fluid.global_scope().find_var("sconv_w"))  # [9, 4]
    for i, s in enumerate(seqs):
        padded = np.concatenate([np.zeros((1, 3)), s, np.zeros((1, 3))], axis=0)
        for t in range(len(s)):
            win = padded[t : t + 3].reshape(-1).astype(np.float32)
            np.testing.assert_allclose(res.data[i, t], win @ w, rtol=1e-4, atol=1e-5)


def test_row_conv():
    seqs = ragged(feat=(3,), lens=[4, 2])
    x = fluid.layers.data("x", [3], dtype="float32", lod_level=1)
    out = fluid.layers.row_conv(
        x, future_context_size=2, param_attr=fluid.ParamAttr(name="rc_w")
    )
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    (res,) = exe.run(feed={"x": lod_feed(seqs)}, fetch_list=[out])
    w = np.asarray(fluid.global_scope().find_var("rc_w"))  # [3, 3]
    for i, s in enumerate(seqs):
        for t in range(len(s)):
            exp = sum(s[t + j] * w[j] for j in range(3) if t + j < len(s))
            np.testing.assert_allclose(res.data[i, t], exp, rtol=1e-4, atol=1e-5)


def test_im2sequence():
    img = RNG.randn(2, 1, 4, 4).astype(np.float32)
    (res,) = run_fetch(
        lambda: fluid.layers.im2sequence(
            fluid.layers.data("img", [1, 4, 4], dtype="float32"),
            filter_size=2, stride=2,
        ),
        {"img": img},
    )
    assert res.data.shape == (2, 4, 4)
    np.testing.assert_allclose(res.data[0, 0], img[0, 0, :2, :2].reshape(-1), rtol=1e-6)


def test_sequence_ops_have_gradients():
    """End-to-end: loss through sequence_conv+pool backprops and trains."""
    seqs = ragged(feat=(3,))
    x = fluid.layers.data("x", [3], dtype="float32", lod_level=1, stop_gradient=True)
    conv = fluid.layers.sequence_conv(x, num_filters=4, filter_size=3)
    pool = fluid.layers.sequence_pool(conv, "sum")
    loss = fluid.layers.mean(pool)
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    v0 = exe.run(feed={"x": lod_feed(seqs)}, fetch_list=[loss])[0]
    for _ in range(5):
        v = exe.run(feed={"x": lod_feed(seqs)}, fetch_list=[loss])[0]
    assert float(np.ravel(v)[0]) != pytest.approx(float(np.ravel(v0)[0]))


def test_concat_split_feature_axis_on_lod():
    """concat/split with axis=1 on LoD inputs address the reference's
    unpadded [sum(T), F] layout — the FEATURE axis, not padded time
    (reference: concat_op with LoD inputs; the bi-LSTM encoder pattern in
    book/test_rnn_encoder_decoder.py)."""
    seqs_a = [np.random.RandomState(1).rand(3, 4).astype("float32"),
              np.random.RandomState(2).rand(2, 4).astype("float32")]
    seqs_b = [np.random.RandomState(3).rand(3, 6).astype("float32"),
              np.random.RandomState(4).rand(2, 6).astype("float32")]
    a = fluid.layers.data("ca", [4], dtype="float32", lod_level=1)
    b = fluid.layers.data("cb", [6], dtype="float32", lod_level=1)
    cat = fluid.layers.concat([a, b], axis=1)
    assert cat.lod_level == 1 and cat.shape[-1] == 10
    back_a, back_b = fluid.layers.split(cat, [4, 6], dim=1)
    exe = fluid.Executor(fluid.CPUPlace())
    feed = {"ca": create_lod_tensor(np.concatenate(seqs_a), [[3, 2]]),
            "cb": create_lod_tensor(np.concatenate(seqs_b), [[3, 2]])}
    c, ra, rb = exe.run(feed=feed, fetch_list=[cat, back_a, back_b],
                        return_numpy=False)
    for i, (sa, sb) in enumerate(zip(seqs_a, seqs_b)):
        np.testing.assert_allclose(
            np.asarray(c.data)[i, : len(sa)],
            np.concatenate([sa, sb], axis=1), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(ra.data)[i, : len(sa)], sa,
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(rb.data)[i, : len(sb)], sb,
                                   rtol=1e-6)


def test_concat_feature_axis_two_level_lod():
    """N-level LoD: desc axis 1 is still the FEATURE axis for a 2-level
    sequence padded to [N, L1, L2, F] (lod_padded_axis handles nesting);
    sub_lengths survive the round trip."""
    import jax.numpy as jnp

    from paddle_tpu.core.registry import OpRegistry

    lower = OpRegistry._ops["concat"].lower
    d = jnp.arange(2 * 3 * 2 * 4, dtype=jnp.float32).reshape(2, 3, 2, 4)
    two_level = LoDValue(d, jnp.asarray([3, 2]),
                         (jnp.asarray([[2, 1, 2], [1, 2, 0]]),))
    out = lower(None, {"X": [two_level, two_level]}, {"axis": 1})["Out"][0]
    assert isinstance(out, LoDValue)
    assert out.data.shape == (2, 3, 2, 8)  # feature axis doubled
    assert len(out.sub_lengths) == 1  # nesting preserved

    split = OpRegistry._ops["split"].lower
    parts = split(None, {"X": [out]}, {"axis": 1, "num": 2})["Out"]
    assert all(isinstance(p, LoDValue) and p.data.shape == (2, 3, 2, 4)
               for p in parts)
    np.testing.assert_allclose(np.asarray(parts[0].data), np.asarray(d))


def test_split_negative_axis_on_lod_uses_desc_rank():
    """split(dim=-1) on a LoD input addresses the unpadded layout's last
    (feature) axis, not the padded array's."""
    x = fluid.layers.data("nsx", [6], dtype="float32", lod_level=1)
    a, b = fluid.layers.split(x, 2, dim=-1)
    exe = fluid.Executor(fluid.CPUPlace())
    seqs = [np.random.RandomState(7).rand(3, 6).astype("float32")]
    ra, rb = exe.run(
        feed={"nsx": create_lod_tensor(seqs[0], [[3]])},
        fetch_list=[a, b], return_numpy=False)
    np.testing.assert_allclose(np.asarray(ra.data)[0, :3], seqs[0][:, :3],
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(rb.data)[0, :3], seqs[0][:, 3:],
                               rtol=1e-6)


def test_concat_axis0_row_concat_on_lod():
    """concat(axis=0) on LoD inputs appends the sequence batches
    (concatenated lod, like the reference's LoD concat)."""
    a = fluid.layers.data("r0a", [3], dtype="float32", lod_level=1)
    b = fluid.layers.data("r0b", [3], dtype="float32", lod_level=1)
    cat = fluid.layers.concat([a, b], axis=0)
    assert cat.lod_level == 1
    sa = [np.full((2, 3), 1.0, "float32"), np.full((4, 3), 2.0, "float32")]
    sb = [np.full((1, 3), 3.0, "float32")]
    exe = fluid.Executor(fluid.CPUPlace())
    (res,) = exe.run(
        feed={"r0a": create_lod_tensor(np.concatenate(sa), [[2, 4]]),
              "r0b": create_lod_tensor(np.concatenate(sb), [[1]])},
        fetch_list=[cat], return_numpy=False)
    np.testing.assert_array_equal(np.asarray(res.lengths), [2, 4, 1])
    np.testing.assert_allclose(np.asarray(res.data)[0, :2], sa[0])
    np.testing.assert_allclose(np.asarray(res.data)[1, :4], sa[1])
    np.testing.assert_allclose(np.asarray(res.data)[2, :1], sb[0])


def test_argmax_feature_axis_on_lod_keeps_lengths():
    """arg_max over the feature axis of a sequence keeps the LoD view
    (desc-level axis semantics shared with concat/split)."""
    x = fluid.layers.data("amx", [5], dtype="float32", lod_level=1)
    idx = fluid.layers.argmax(x, axis=1)
    exe = fluid.Executor(fluid.CPUPlace())
    seq = np.random.RandomState(3).rand(4, 5).astype("float32")
    (res,) = exe.run(feed={"amx": create_lod_tensor(seq, [[4]])},
                     fetch_list=[idx], return_numpy=False)
    np.testing.assert_array_equal(np.asarray(res.lengths), [4])
    np.testing.assert_array_equal(np.asarray(res.data)[0, :4],
                                  seq.argmax(axis=1))


def test_reduce_on_lod_ignores_padding():
    """reduce_mean / reduce_sum on a sequence input address the unpadded
    [sum(T), F] layout: padded slots never contribute, and reduce_all
    means over the TRUE element count."""
    seqs = [np.array([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]], "float32"),
            np.array([[10.0, 20.0]], "float32")]
    x = fluid.layers.data("rm", [2], dtype="float32", lod_level=1)
    total_mean = fluid.layers.reduce_mean(x)  # reduce_all
    feat_sum = fluid.layers.reduce_sum(x, dim=1)  # feature axis
    exe = fluid.Executor(fluid.CPUPlace())
    m, s = exe.run(
        feed={"rm": create_lod_tensor(np.concatenate(seqs), [[3, 1]])},
        fetch_list=[total_mean, feat_sum], return_numpy=False)
    flat = np.concatenate(seqs)
    np.testing.assert_allclose(float(np.ravel(np.asarray(m))[0]),
                               flat.mean(), rtol=1e-6)
    s = np.asarray(s.data if hasattr(s, "data") else s)
    np.testing.assert_allclose(s[0, :3], flat[:3].sum(axis=1), rtol=1e-6)
    np.testing.assert_allclose(s[1, :1], flat[3:].sum(axis=1), rtol=1e-6)


def test_reduce_and_argmax_desc_axis0_on_lod():
    """Desc axis 0 on a 1-level sequence spans the unpadded rows: reduce
    collapses both padded axes; argmax returns UNPADDED row indices; int
    max/min use dtype-aware identities."""
    seqs = [np.array([[1.0, -5.0], [2.0, 7.0]], "float32"),
            np.array([[9.0, 0.0]], "float32")]
    flat = np.concatenate(seqs)  # rows 0,1 (seq 0) + row 2 (seq 1)
    x = fluid.layers.data("ra0", [2], dtype="float32", lod_level=1)
    s0 = fluid.layers.reduce_sum(x, dim=0)
    am = fluid.layers.argmax(x, axis=0)
    exe = fluid.Executor(fluid.CPUPlace())
    s, a = exe.run(
        feed={"ra0": create_lod_tensor(flat, [[2, 1]])},
        fetch_list=[s0, am])
    np.testing.assert_allclose(np.asarray(s), flat.sum(axis=0), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(a), flat.argmax(axis=0))

    # integer reduce_max over a sequence: no inf-cast crash, pads ignored
    fluid.reset_default_env()
    ids = fluid.layers.data("ri0", [1], dtype="int64", lod_level=1)
    mx = fluid.layers.reduce_max(ids, dim=0)
    exe = fluid.Executor(fluid.CPUPlace())
    got, = exe.run(
        feed={"ri0": create_lod_tensor(
            np.array([[3], [9], [4]], "int64"), [[2, 1]])},
        fetch_list=[mx])
    assert int(np.ravel(got)[0]) == 9


def test_reshape_on_lod_is_featurewise_or_loud():
    """reshape on a sequence addresses the unpadded layout: [-1, F']
    feature reshapes keep lengths and never mix pad slots in; row
    re-chunking raises instead of silently corrupting."""
    x = fluid.layers.data("rs", [6], dtype="float32", lod_level=1)
    y = fluid.layers.reshape(x, shape=[-1, 2, 3])
    exe = fluid.Executor(fluid.CPUPlace())
    seqs = [np.arange(12, dtype="float32").reshape(2, 6),
            np.arange(100, 106, dtype="float32").reshape(1, 6)]
    res, = exe.run(
        feed={"rs": create_lod_tensor(np.concatenate(seqs), [[2, 1]])},
        fetch_list=[y], return_numpy=False)
    np.testing.assert_array_equal(np.asarray(res.lengths), [2, 1])
    np.testing.assert_allclose(np.asarray(res.data)[0, :2],
                               seqs[0].reshape(2, 2, 3))
    np.testing.assert_allclose(np.asarray(res.data)[1, :1],
                               seqs[1].reshape(1, 2, 3))

    fluid.reset_default_env()
    x2 = fluid.layers.data("rs2", [6], dtype="float32", lod_level=1)
    bad = fluid.layers.reshape(x2, shape=[-1, 4])  # re-chunks rows
    exe2 = fluid.Executor(fluid.CPUPlace())
    with pytest.raises(Exception, match="sequence_reshape|re-chunks"):
        exe2.run(feed={"rs2": create_lod_tensor(
            np.ones((3, 6), "float32"), [[3]])}, fetch_list=[bad])


def test_reduce_keep_dim_axis0_on_lod_shape():
    """keep_dim with desc axis 0 keeps ONE row dim, matching the declared
    (unpadded-layout) shape."""
    x = fluid.layers.data("kd", [3], dtype="float32", lod_level=1)
    s = fluid.layers.reduce_sum(x, dim=0, keep_dim=True)
    exe = fluid.Executor(fluid.CPUPlace())
    got, = exe.run(feed={"kd": create_lod_tensor(
        np.ones((4, 3), "float32"), [[2, 2]])}, fetch_list=[s])
    assert np.shape(got) == (1, 3), np.shape(got)
    np.testing.assert_allclose(np.asarray(got)[0], [4.0, 4.0, 4.0])
