"""Per-op sweep: the last two metric ops (reference:
operators/positive_negative_pair_op.h,
operators/metrics/precision_recall_op.h).  Numpy references below are
written independently from the reference kernels' documented semantics."""

import numpy as np

from op_test import OpTest


def _pnp_ref(score, label, query, weight=None, column=-1,
             acc=(0.0, 0.0, 0.0)):
    n, width = score.shape
    col = column if column >= 0 else column + width
    s = score[:, col]
    lab = label.reshape(-1)
    q = query.reshape(-1)
    w = np.ones(n) if weight is None else weight.reshape(-1)
    pos, neg, neu = acc
    for i in range(n):
        for j in range(i + 1, n):
            if q[i] != q[j] or lab[i] == lab[j]:
                continue
            pw = 0.5 * (w[i] + w[j])
            if s[i] == s[j]:
                neu += pw
            if (s[i] - s[j]) * (lab[i] - lab[j]) > 0:
                pos += pw
            else:
                neg += pw  # equal scores fall here too, like the reference
    return (np.array([pos], "float32"), np.array([neg], "float32"),
            np.array([neu], "float32"))


def test_positive_negative_pair():
    r = np.random.RandomState(7)
    n = 12
    score = r.uniform(0, 1, (n, 1)).astype("float32")
    label = r.randint(0, 3, (n, 1)).astype("float32")
    query = np.array([k // 4 for k in range(n)], dtype="int64").reshape(n, 1)
    # a few deliberate score ties inside one query group
    score[1] = score[2]
    pos, neg, neu = _pnp_ref(score, label, query)

    class T(OpTest):
        op_type = "positive_negative_pair"

    t = T()
    t.inputs = {"Score": score, "Label": label, "QueryID": query}
    t.outputs = {"PositivePair": pos, "NegativePair": neg,
                 "NeutralPair": neu}
    t.check_output(atol=1e-5, rtol=1e-5)


def test_positive_negative_pair_weighted_accumulated():
    r = np.random.RandomState(8)
    n = 10
    score = r.uniform(0, 1, (n, 3)).astype("float32")
    label = r.randint(0, 2, (n, 1)).astype("float32")
    query = r.randint(0, 3, (n, 1)).astype("int64")
    weight = r.uniform(0.5, 1.5, (n, 1)).astype("float32")
    acc = (2.0, 1.0, 0.5)
    pos, neg, neu = _pnp_ref(score, label, query, weight, column=1, acc=acc)

    class T(OpTest):
        op_type = "positive_negative_pair"

    t = T()
    t.inputs = {"Score": score, "Label": label, "QueryID": query,
                "Weight": weight,
                "AccumulatePositivePair": np.array([acc[0]], "float32"),
                "AccumulateNegativePair": np.array([acc[1]], "float32"),
                "AccumulateNeutralPair": np.array([acc[2]], "float32")}
    t.attrs = {"column": 1}
    t.outputs = {"PositivePair": pos, "NegativePair": neg,
                 "NeutralPair": neu}
    t.check_output(atol=1e-5, rtol=1e-5)


def _pr_states(idx, label, weight, cls):
    states = np.zeros((cls, 4), "float64")  # TP FP TN FN
    for i in range(idx.shape[0]):
        c, l, w = int(idx[i, 0]), int(label[i, 0]), float(weight[i, 0])
        if c == l:
            states[c, 0] += w
            states[:, 2] += w
            states[c, 2] -= w
        else:
            states[l, 3] += w
            states[c, 1] += w
            states[:, 2] += w
            states[c, 2] -= w
            states[l, 2] -= w
    return states


def _pr_metrics(states):
    def ratio(a, b):
        return a / (a + b) if (a > 0 or b > 0) else 1.0

    def f1(p, r):
        return 2 * p * r / (p + r) if (p > 0 or r > 0) else 0.0

    prec = [ratio(s[0], s[1]) for s in states]
    rec = [ratio(s[0], s[3]) for s in states]
    mp, mr = np.mean(prec), np.mean(rec)
    tp, fp, fn = states[:, 0].sum(), states[:, 1].sum(), states[:, 3].sum()
    up, ur = ratio(tp, fp), ratio(tp, fn)
    return np.array([mp, mr, f1(mp, mr), up, ur, f1(up, ur)], "float32")


def test_precision_recall():
    r = np.random.RandomState(9)
    n, cls = 20, 4
    idx = r.randint(0, cls, (n, 1)).astype("int32")
    label = r.randint(0, cls, (n, 1)).astype("int32")
    weight = r.uniform(0.2, 1.8, (n, 1)).astype("float32")
    states = _pr_states(idx, label, weight, cls)

    class T(OpTest):
        op_type = "precision_recall"

    t = T()
    t.inputs = {"Indices": idx, "Labels": label, "Weights": weight}
    t.attrs = {"class_number": cls}
    t.outputs = {"BatchMetrics": _pr_metrics(states),
                 "AccumMetrics": _pr_metrics(states),
                 "AccumStatesInfo": states.astype("float32")}
    t.check_output(atol=1e-5, rtol=1e-5)


def test_precision_recall_accumulating():
    r = np.random.RandomState(10)
    n, cls = 15, 3
    idx = r.randint(0, cls, (n, 1)).astype("int32")
    label = r.randint(0, cls, (n, 1)).astype("int32")
    weight = np.ones((n, 1), "float32")
    prev = r.uniform(0, 5, (cls, 4)).astype("float32")
    batch = _pr_states(idx, label, weight, cls)
    accum = batch + prev.astype("float64")

    class T(OpTest):
        op_type = "precision_recall"

    t = T()
    t.inputs = {"Indices": idx, "Labels": label, "Weights": weight,
                "StatesInfo": prev}
    t.attrs = {"class_number": cls}
    t.outputs = {"BatchMetrics": _pr_metrics(batch),
                 "AccumMetrics": _pr_metrics(accum),
                 "AccumStatesInfo": accum.astype("float32")}
    t.check_output(atol=1e-5, rtol=1e-5)


def test_precision_recall_empty_class_defaults():
    """A class with no samples keeps the reference's precision=recall=1
    convention (affects the macro average)."""
    idx = np.array([[0], [0], [1]], "int32")
    label = np.array([[0], [1], [1]], "int32")
    weight = np.ones((3, 1), "float32")
    cls = 3  # class 2 never appears
    states = _pr_states(idx, label, weight, cls)

    class T(OpTest):
        op_type = "precision_recall"

    t = T()
    t.inputs = {"Indices": idx, "Labels": label, "Weights": weight}
    t.attrs = {"class_number": cls}
    t.outputs = {"BatchMetrics": _pr_metrics(states),
                 "AccumMetrics": _pr_metrics(states),
                 "AccumStatesInfo": states.astype("float32")}
    t.check_output(atol=1e-5, rtol=1e-5)
