"""Fused conv+BN-stats+epilogue pallas kernels (kernels/conv_epilogue.py;
reference counterpart conv_fusion_op.cu.cc — cuDNN fused conv+bias+act).

Interpret-mode parity against the XLA conv + BN + residual + relu chain;
the on-chip compile path is gated by tools/conv_epilogue_probe.py."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.kernels.conv_epilogue import (
    conv_bn_act,
    conv_bn_act_reference,
)


def _case(K, stride, C, F, H=12, N=2, res=True, dtype="float32", seed=0):
    r = np.random.RandomState(seed)
    x = jnp.asarray(r.randn(N, H, H, C).astype(dtype))
    w = jnp.asarray((r.randn(K, K, C, F) * 0.2).astype(dtype))
    g = jnp.asarray((r.rand(F) + 0.5).astype("float32"))
    b = jnp.asarray((r.randn(F) * 0.1).astype("float32"))
    Ho = -(-H // stride)
    z = jnp.asarray(r.randn(N, Ho, Ho, F).astype(dtype)) if res else None
    return x, w, g, b, z


@pytest.mark.parametrize("K,stride,res", [
    (3, 1, True), (3, 1, False), (1, 1, True), (1, 1, False),
    (3, 2, True), (1, 2, False),
])
def test_parity_vs_xla_chain(K, stride, res):
    x, w, g, b, z = _case(K, stride, C=8, F=16, res=res)
    y, m, v = conv_bn_act(x, w, g, b, z, stride=stride, interpret=True)
    yr, mr, vr = conv_bn_act_reference(x, w, g, b, z, stride=stride)
    np.testing.assert_allclose(np.asarray(m), np.asarray(mr),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(v), np.asarray(vr),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(y), np.asarray(yr),
                               rtol=2e-4, atol=2e-4)


def test_bf16_activations_fp32_stats():
    """keep-bf16 mode: bf16 in/out, statistics still accumulate fp32."""
    x, w, g, b, z = _case(3, 1, C=8, F=16, dtype="bfloat16")
    y, m, v = conv_bn_act(x, w, g, b, z, interpret=True)
    yr, mr, vr = conv_bn_act_reference(x, w, g, b, z)
    assert y.dtype == jnp.bfloat16
    assert m.dtype == jnp.float32 and v.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(m), np.asarray(mr),
                               rtol=5e-2, atol=5e-2)
    np.testing.assert_allclose(
        np.asarray(y, dtype="float32"), np.asarray(yr, dtype="float32"),
        rtol=1e-1, atol=1e-1)


def test_valid_padding():
    x, w, g, b, _ = _case(3, 1, C=8, F=16, res=False)
    y, m, v = conv_bn_act(x, w, g, b, None, padding="VALID", interpret=True)
    yr, mr, vr = conv_bn_act_reference(x, w, g, b, None, padding="VALID")
    assert y.shape == yr.shape == (2, 10, 10, 16)
    np.testing.assert_allclose(np.asarray(y), np.asarray(yr),
                               rtol=2e-4, atol=2e-4)


def test_no_activation():
    x, w, g, b, z = _case(3, 1, C=8, F=16)
    y, _, _ = conv_bn_act(x, w, g, b, z, act="", interpret=True)
    yr, _, _ = conv_bn_act_reference(x, w, g, b, z, act="")
    assert float(np.asarray(y).min()) < 0  # activation really off
    np.testing.assert_allclose(np.asarray(y), np.asarray(yr),
                               rtol=2e-4, atol=2e-4)


def test_bad_weight_shape_raises():
    x, w, g, b, _ = _case(3, 1, C=8, F=16, res=False)
    with pytest.raises(ValueError, match="incompatible"):
        conv_bn_act(x, jnp.swapaxes(w, 2, 3)[:, :, :3], g, b,
                    interpret=True)


def test_unsupported_act_raises():
    """review r5: an unknown act must raise up front, not silently skip
    the activation (the reference raises too)."""
    x, w, g, b, _ = _case(3, 1, C=8, F=16, res=False)
    with pytest.raises(ValueError, match="unsupported act"):
        conv_bn_act(x, w, g, b, act="gelu", interpret=True)


@pytest.mark.parametrize("res", [True, False])
def test_trainable_gradients_match_reference(res):
    """make_conv_bn_act: pallas forward + recompute backward must produce
    the same gradients as differentiating the XLA chain directly."""
    from paddle_tpu.kernels.conv_epilogue import make_conv_bn_act

    x, w, g, b, z = _case(3, 1, C=8, F=16, res=res)
    f = make_conv_bn_act(has_residual=res, interpret=True)
    args = (x, w, g, b) + ((z,) if res else ())

    def loss_fused(*a):
        y, m, v = f(*a)
        return jnp.sum(y * y) + jnp.sum(m) + jnp.sum(v)

    def loss_ref(*a):
        y, m, v = conv_bn_act_reference(
            a[0], a[1], a[2], a[3], a[4] if res else None)
        return jnp.sum(y * y) + jnp.sum(m) + jnp.sum(v)

    got = jax.grad(loss_fused, argnums=tuple(range(len(args))))(*args)
    want = jax.grad(loss_ref, argnums=tuple(range(len(args))))(*args)
    for gg, ww in zip(got, want):
        np.testing.assert_allclose(np.asarray(gg), np.asarray(ww),
                                   rtol=2e-3, atol=2e-3)


def test_trainable_forward_is_pallas_path():
    """The trainable wrapper's primal must equal the pallas forward
    (not the reference it differentiates)."""
    from paddle_tpu.kernels.conv_epilogue import make_conv_bn_act

    x, w, g, b, z = _case(3, 1, C=8, F=16)
    f = make_conv_bn_act(interpret=True)
    y1, m1, v1 = f(x, w, g, b, z)
    y2, m2, v2 = conv_bn_act(x, w, g, b, z, interpret=True)
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))
