"""Per-op sweep: normalization + clip family (reference:
test_batch_norm_op.py, test_group_norm_op.py, test_norm_op.py,
test_clip_op.py, test_l1_norm_op.py over operators/*norm*_op.cc)."""

import numpy as np
import pytest

from op_test import OpTest


def _rand(shape, seed=0, lo=-2.0, hi=2.0):
    return np.random.RandomState(seed).uniform(lo, hi, shape).astype("float32")


def test_batch_norm_train():
    x = _rand((4, 3, 5, 5), seed=1)
    scale = _rand((3,), seed=2, lo=0.5, hi=1.5)
    bias = _rand((3,), seed=3)
    mean0 = np.zeros(3, "float32")
    var0 = np.ones(3, "float32")
    eps, momentum = 1e-5, 0.9

    xd = x.astype(np.float64)
    m = xd.mean(axis=(0, 2, 3))
    v = xd.var(axis=(0, 2, 3))
    y = (xd - m.reshape(1, 3, 1, 1)) / np.sqrt(v.reshape(1, 3, 1, 1) + eps)
    y = y * scale.reshape(1, 3, 1, 1) + bias.reshape(1, 3, 1, 1)

    class T(OpTest):
        op_type = "batch_norm"

    t = T()
    t.inputs = {"X": x, "Scale": scale, "Bias": bias,
                "Mean": mean0, "Variance": var0}
    t.attrs = {"epsilon": eps, "momentum": momentum}
    t.outputs = {
        "Y": y.astype("float32"),
        "MeanOut": (momentum * mean0 + (1 - momentum) * m).astype("float32"),
        "VarianceOut": (momentum * var0 + (1 - momentum) * v).astype("float32"),
        "SavedMean": m.astype("float32"),
        "SavedVariance": (1.0 / np.sqrt(v + eps)).astype("float32"),
    }
    t.check_output(atol=2e-4, rtol=2e-4)
    # fp32 variance chain: the reference's test_batch_norm_op also runs at
    # max_relative_error=0.05
    t.check_grad(["X", "Scale", "Bias"], "Y", max_relative_error=0.05)


def test_batch_norm_test_mode():
    x = _rand((4, 3, 5, 5), seed=4)
    scale = _rand((3,), seed=5, lo=0.5, hi=1.5)
    bias = _rand((3,), seed=6)
    mean = _rand((3,), seed=7, lo=-0.5, hi=0.5)
    var = _rand((3,), seed=8, lo=0.5, hi=1.5)
    eps = 1e-5
    xd = x.astype(np.float64)
    y = (xd - mean.reshape(1, 3, 1, 1)) / np.sqrt(
        var.reshape(1, 3, 1, 1).astype(np.float64) + eps)
    y = y * scale.reshape(1, 3, 1, 1) + bias.reshape(1, 3, 1, 1)

    class T(OpTest):
        op_type = "batch_norm"

    t = T()
    t.inputs = {"X": x, "Scale": scale, "Bias": bias,
                "Mean": mean, "Variance": var}
    t.attrs = {"epsilon": eps, "is_test": True}
    t.outputs = {"Y": y.astype("float32")}
    t.check_output(atol=2e-4, rtol=2e-4)


def test_layer_norm():
    x = _rand((4, 3, 6), seed=9)
    scale = _rand((18,), seed=10, lo=0.5, hi=1.5)
    bias = _rand((18,), seed=11)
    eps = 1e-5
    xd = x.astype(np.float64).reshape(4, -1)
    m = xd.mean(axis=1, keepdims=True)
    v = xd.var(axis=1, keepdims=True)
    y = ((xd - m) / np.sqrt(v + eps) * scale + bias).reshape(x.shape)

    class T(OpTest):
        op_type = "layer_norm"

    t = T()
    t.inputs = {"X": x, "Scale": scale, "Bias": bias}
    t.attrs = {"begin_norm_axis": 1, "epsilon": eps}
    t.outputs = {"Y": y.astype("float32"),
                 "Mean": m.ravel().astype("float32"),
                 "Variance": v.ravel().astype("float32")}
    t.check_output(atol=2e-4, rtol=2e-4)
    t.check_grad(["X", "Scale", "Bias"], "Y", max_relative_error=0.02)


def test_group_norm():
    x = _rand((2, 6, 4, 4), seed=12)
    scale = _rand((6,), seed=13, lo=0.5, hi=1.5)
    bias = _rand((6,), seed=14)
    g, eps = 3, 1e-5
    xd = x.astype(np.float64).reshape(2, g, 2, 4, 4)
    m = xd.mean(axis=(2, 3, 4), keepdims=True)
    v = xd.var(axis=(2, 3, 4), keepdims=True)
    y = ((xd - m) / np.sqrt(v + eps)).reshape(x.shape)
    y = y * scale.reshape(1, 6, 1, 1) + bias.reshape(1, 6, 1, 1)

    class T(OpTest):
        op_type = "group_norm"

    t = T()
    t.inputs = {"X": x, "Scale": scale, "Bias": bias}
    t.attrs = {"groups": g, "epsilon": eps}
    t.outputs = {"Y": y.astype("float32"),
                 "Mean": m.reshape(2, g).astype("float32"),
                 "Variance": v.reshape(2, g).astype("float32")}
    t.check_output(atol=2e-4, rtol=2e-4)
    t.check_grad(["X", "Scale", "Bias"], "Y", max_relative_error=0.02)


def test_norm_l2_normalize():
    x = _rand((3, 5), seed=15, lo=0.5, hi=2.0)
    eps = 1e-10
    xd = x.astype(np.float64)
    n = np.sqrt((xd * xd).sum(axis=1, keepdims=True) + eps)

    class T(OpTest):
        op_type = "norm"

    t = T()
    t.inputs = {"X": x}
    t.attrs = {"axis": 1, "epsilon": eps}
    t.outputs = {"Out": (xd / n).astype("float32"), "Norm": n.astype("float32")}
    t.check_output(atol=2e-5, rtol=2e-5)
    t.check_grad(["X"], "Out", max_relative_error=0.01)


def test_lrn():
    x = _rand((2, 8, 3, 3), seed=16, lo=0.1, hi=1.0)
    n, k, alpha, beta = 5, 1.0, 1e-4, 0.75
    xd = x.astype(np.float64)
    sq = np.zeros_like(xd)
    C = 8
    for c in range(C):
        lo = max(0, c - n // 2)
        hi = min(C, c + n // 2 + 1)
        sq[:, c] = (xd[:, lo:hi] ** 2).sum(axis=1)
    want = xd / np.power(k + alpha * sq, beta)

    class T(OpTest):
        op_type = "lrn"

    t = T()
    t.inputs = {"X": x}
    t.attrs = {"n": n, "k": k, "alpha": alpha, "beta": beta}
    t.outputs = {"Out": want.astype("float32")}
    t.check_output(atol=2e-4, rtol=2e-4)


def test_clip():
    x = _rand((4, 5), seed=17)
    # keep away from the clip boundaries so the subgradient is unambiguous
    x = np.where(np.abs(np.abs(x) - 1.0) < 0.05, x * 1.2, x).astype("float32")
    want = np.clip(x, -1.0, 1.0)

    class T(OpTest):
        op_type = "clip"

    t = T()
    t.inputs = {"X": x}
    t.attrs = {"min": -1.0, "max": 1.0}
    t.outputs = {"Out": want}
    t.check_output()
    t.check_grad(["X"], "Out", max_relative_error=0.01)


def test_clip_by_norm():
    x = _rand((4, 5), seed=18)
    max_norm = 1.0
    nrm = np.sqrt((x.astype(np.float64) ** 2).sum())
    want = x * (max_norm / max(nrm, max_norm))

    class T(OpTest):
        op_type = "clip_by_norm"

    t = T()
    t.inputs = {"X": x}
    t.attrs = {"max_norm": max_norm}
    t.outputs = {"Out": want.astype("float32")}
    t.check_output(atol=2e-5, rtol=2e-5)


def test_l1_norm():
    x = _rand((3, 4), seed=19)
    x = np.where(np.abs(x) < 0.05, x + 0.2, x).astype("float32")

    class T(OpTest):
        op_type = "l1_norm"

    t = T()
    t.inputs = {"X": x}
    t.outputs = {"Out": np.array([np.abs(x).sum()], dtype="float32")}
    t.check_output(atol=2e-5, rtol=2e-5)
    t.check_grad(["X"], "Out", max_relative_error=0.01)


def test_squared_l2_norm():
    x = _rand((3, 4), seed=20)

    class T(OpTest):
        op_type = "squared_l2_norm"

    t = T()
    t.inputs = {"X": x}
    t.outputs = {"Out": np.array([(x.astype(np.float64) ** 2).sum()],
                                 dtype="float32")}
    t.check_output(atol=2e-5, rtol=2e-5)
    t.check_grad(["X"], "Out", max_relative_error=0.01)
