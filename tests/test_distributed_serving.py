"""Mesh-sharded serving (paddle_tpu/serving/distributed/): chip-less
SPMD parity, router dispatch, and drain-based replica handoff.

Acceptance criteria pinned here (ISSUE 10):
(a) on a 4-device CPU mesh, ShardedDecodeProgram continuous-batching
    decode is TOKEN-IDENTICAL to the single-device oracle across >= 3
    overlapping ragged sequences (batched AND token prefill arms), with
    zero leaked pages and a clean pool invariant audit;
(b) the sharded pool's device view is genuinely per-shard: each device
    holds [L, H/n_shards, P, page_size, D] — 1/n of the pool bytes;
(c) the Router serves mixed traffic across 2 replicas with one replica
    drained mid-run: zero lost/duplicated requests, nothing routed to
    the drained replica after the handoff, and the drained engine
    finishes its queued work;
(d) health-aware dispatch skips BROKEN/DRAINING/lease-expired replicas
    (elastic-master heartbeat seam) and falls over between replicas on
    raced rejections;
(e) with observability on, flight events / request traces / health
    gauges / router decision counters all carry the replica label and
    survive a MetricsRegistry.aggregate_dir merge attributable.

ISSUE 16 adds the mesh speculation arms to (a): greedy speculative
decode through ShardedDecodeProgram.verify_step stays token-identical
to the full_decode oracle with rollbacks occurring, and sampled rows
riding the same verify step replay bit-identically.
"""

import os
import threading
import time

import numpy as np
import pytest

from paddle_tpu import flags as pflags
from paddle_tpu import observability as obs
from paddle_tpu import serving
from paddle_tpu.elastic.master import InMemStore, MasterService
from paddle_tpu.serving import (
    ContinuousBatchingLoop,
    DecodeConfig,
    DecodeRequest,
    Engine,
    EngineConfig,
    KVCachePool,
)
from paddle_tpu.serving.distributed import (
    ReplicaDirectory,
    ReplicaUnavailableError,
    Router,
    ShardedDecodeProgram,
    ShardedKVCachePool,
    host_mesh_devices,
)

N_SHARDS = 4


def _cfg(**kw):
    base = dict(vocab_size=64, d_model=32, n_head=4, n_layer=2,
                d_inner=64, max_length=48)
    base.update(kw)
    return DecodeConfig(**base)


def _ragged_requests(cfg, n=4, seed=0, max_new=8):
    rng = np.random.RandomState(seed)
    lens = [3, 7, 5, 2, 9, 4][:n]
    return [
        DecodeRequest(
            prompt=rng.randint(1, cfg.vocab_size, size=ln).tolist(),
            max_new_tokens=max_new)
        for ln in lens
    ]


# ---------------------------------------------------------------------------
# (a) SPMD parity: sharded continuous batching == single-device oracle


@pytest.mark.parametrize("prefill", ["batched", "token"])
def test_sharded_decode_token_identical_to_oracle(host_devices, prefill):
    devs = host_devices(N_SHARDS)
    cfg = _cfg()
    params = serving.init_decode_params(cfg, seed=3)
    reqs = _ragged_requests(cfg, n=4)

    oracle_pool = KVCachePool(num_pages=64, page_size=4,
                              num_layers=cfg.n_layer, num_heads=cfg.n_head,
                              head_dim=cfg.head_dim)
    oracle = ContinuousBatchingLoop(params, cfg, oracle_pool,
                                    max_batch=3, prefill=prefill)
    want = oracle.run([DecodeRequest(prompt=list(r.prompt),
                                     max_new_tokens=r.max_new_tokens)
                       for r in reqs])

    prog = ShardedDecodeProgram(params, cfg, devices=devs)
    pool = prog.make_pool(num_pages=64, page_size=4)
    loop = ContinuousBatchingLoop(None, None, pool, max_batch=3,
                                  prefill=prefill, program=prog)
    got = loop.run(reqs)

    # >= 3 sequences overlapped (max_batch=3 over 4 requests)
    assert len(got) == 4
    for w, g in zip(want, got):
        assert g.error is None
        assert g.tokens == w.tokens  # token-identical to the oracle
        np.testing.assert_allclose(
            np.stack(g.logits), np.stack(w.logits), atol=2e-4)
    # zero leaked pages, clean audit — retirement freed everything
    assert pool.stats()["used_pages"] == 0
    assert pool.check_invariants()["ok"]
    assert oracle_pool.stats()["used_pages"] == 0


def test_sharded_prefill_matches_full_forward(host_devices):
    devs = host_devices(N_SHARDS)
    cfg = _cfg()
    params = serving.init_decode_params(cfg, seed=5)
    prog = ShardedDecodeProgram(params, cfg, devices=devs)
    pool = prog.make_pool(num_pages=32, page_size=4)
    rng = np.random.RandomState(1)
    prompts = [rng.randint(1, cfg.vocab_size, size=n).tolist()
               for n in (5, 2, 8)]
    for i in range(len(prompts)):
        pool.allocate(i)
    logits = prog.prefill_step(pool, list(range(len(prompts))), prompts)
    for i, p in enumerate(prompts):
        want = serving.full_forward(params, cfg, p)[-1]
        np.testing.assert_allclose(logits[i], want, atol=2e-4)


def test_sharded_gqa_decode_token_identical_and_pool_shrinks(host_devices):
    """ISSUE 12 on the mesh: a GQA config (H_q=8 over H_kv=4) shards
    the pool over the KV-head axis — each device holds H_kv/n heads of
    an already-H_kv/H_q-smaller pool — and continuous-batching decode
    stays token-identical to the single-device oracle."""
    devs = host_devices(N_SHARDS)
    cfg = _cfg(n_head=8, n_kv_head=4)
    params = serving.init_decode_params(cfg, seed=11)
    reqs = _ragged_requests(cfg, n=4, seed=11)

    oracle_pool = KVCachePool(num_pages=64, page_size=4,
                              num_layers=cfg.n_layer, num_heads=cfg.n_head,
                              head_dim=cfg.head_dim,
                              num_kv_heads=cfg.num_kv_heads)
    oracle = ContinuousBatchingLoop(params, cfg, oracle_pool, max_batch=3)
    want = oracle.run([DecodeRequest(prompt=list(r.prompt),
                                     max_new_tokens=r.max_new_tokens)
                       for r in reqs])

    prog = ShardedDecodeProgram(params, cfg, devices=devs)
    pool = prog.make_pool(num_pages=64, page_size=4)
    # the GQA shrink shows in the pool shape: H_kv heads, not H_q
    assert pool.k_pages.shape[1] == cfg.num_kv_heads
    assert pool.heads_per_shard == cfg.num_kv_heads // N_SHARDS
    half = KVCachePool(num_pages=64, page_size=4,
                       num_layers=cfg.n_layer, num_heads=cfg.n_head,
                       head_dim=cfg.head_dim)
    assert pool.bytes_per_page() == half.bytes_per_page() // 2
    loop = ContinuousBatchingLoop(None, None, pool, max_batch=3,
                                  program=prog)
    got = loop.run(reqs)
    for w, g in zip(want, got):
        assert g.error is None and g.tokens == w.tokens
        np.testing.assert_allclose(
            np.stack(g.logits), np.stack(w.logits), atol=2e-4)
    assert pool.stats()["used_pages"] == 0
    assert pool.check_invariants()["ok"]


def test_sharded_layout_consuming_pallas_path_token_identical(
        host_devices):
    """ISSUE 14 layout fix: decode_step_fn feeds the paged kernel the
    pool_layout="xla" view (the [P, ps, H*D] slot-major re-view of the
    scatter-updated pool shard — what drives the banked sharded_decode
    relayout-copy-pair count to 0).  The interpret tier runs that exact
    lowering on the 4-device CPU mesh: continuous-batching decode must
    stay TOKEN-IDENTICAL to the single-device reference oracle, so the
    relayout-free program the zoo banks is the same math the serving
    loop ships."""
    devs = host_devices(N_SHARDS)
    # an in-envelope pool geometry (head_dim 128, page_size 8) — the
    # shape class the pallas path actually serves
    cfg = _cfg(d_model=512, n_head=4, n_layer=1, max_length=32)
    params = serving.init_decode_params(cfg, seed=7)
    reqs = _ragged_requests(cfg, n=3, seed=7, max_new=5)

    oracle_pool = KVCachePool(num_pages=32, page_size=8,
                              num_layers=cfg.n_layer, num_heads=cfg.n_head,
                              head_dim=cfg.head_dim)
    oracle = ContinuousBatchingLoop(params, cfg, oracle_pool, max_batch=3)
    want = oracle.run([DecodeRequest(prompt=list(r.prompt),
                                     max_new_tokens=r.max_new_tokens)
                       for r in reqs])

    prog = ShardedDecodeProgram(params, cfg, devices=devs,
                                paged_impl="interpret")
    pool = prog.make_pool(num_pages=32, page_size=8)
    loop = ContinuousBatchingLoop(None, None, pool, max_batch=3,
                                  program=prog)
    got = loop.run(reqs)
    assert prog.paged_impl == "interpret"  # resolved — no fallback
    for w, g in zip(want, got):
        assert g.error is None
        assert g.tokens == w.tokens  # token-identical to the oracle
        np.testing.assert_allclose(
            np.stack(g.logits), np.stack(w.logits), atol=5e-4)
    assert pool.stats()["used_pages"] == 0
    assert pool.check_invariants()["ok"]


def test_sharded_gqa_and_int8_validation(host_devices):
    """KV-head divisibility is validated loudly, and int8 pages are
    rejected on the mesh (the SPMD step writes K/V device-side where
    the host scale bookkeeping cannot reach)."""
    devs = host_devices(N_SHARDS)
    cfg = _cfg(n_head=8, n_kv_head=2)  # 2 KV heads cannot split 4 ways
    params = serving.init_decode_params(cfg, seed=0)
    with pytest.raises(ValueError, match="n_kv_head"):
        ShardedDecodeProgram(params, cfg, devices=devs)
    ok = _cfg(n_head=8, n_kv_head=4)
    prog = ShardedDecodeProgram(serving.init_decode_params(ok, seed=0),
                                ok, devices=devs)
    with pytest.raises(ValueError, match="int8"):
        prog.make_pool(num_pages=8, page_size=4, dtype="int8")


def test_sharded_decode_quarantine_keeps_pool_leak_free(host_devices):
    """A NaN-poisoned sequence under the SPMD program quarantines alone
    — batch-mates finish, pages all return (the loop's fault isolation
    is step-implementation-agnostic)."""
    devs = host_devices(N_SHARDS)
    cfg = _cfg()
    params = serving.init_decode_params(cfg, seed=3)
    prog = ShardedDecodeProgram(params, cfg, devices=devs)
    pool = prog.make_pool(num_pages=64, page_size=4)
    loop = ContinuousBatchingLoop(None, None, pool, max_batch=3,
                                  program=prog, check_every=1)
    os.environ["FAULT_SERVE_NAN_SEQ"] = "1@1"
    try:
        results = loop.run(_ragged_requests(cfg, n=3))
    finally:
        os.environ.pop("FAULT_SERVE_NAN_SEQ", None)
        from paddle_tpu.resilience import faultinject

        faultinject.reset()
    errs = [r for r in results if r.error is not None]
    assert len(errs) == 1 and loop.quarantined == 1
    ok = [r for r in results if r.error is None]
    assert all(len(r.tokens) == 8 for r in ok)
    assert pool.stats()["used_pages"] == 0
    assert pool.check_invariants()["ok"]


@pytest.mark.parametrize("prefill", ["batched", "token"])
def test_sharded_prefix_cache_cow_token_identical(host_devices, prefill):
    """ISSUE 11 (mesh CoW): overlapping shared-prefix sequences through
    the ShardedKVCachePool — the host-global page tables mean the
    prefix cache's refcount/CoW bookkeeping lands once and works on
    the mesh unchanged.  Both prefill arms stay token-identical to the
    full_decode oracle, with zero leaked pages and refcount invariants
    green (and the per-shard device view intact after CoW copies)."""
    devs = host_devices(N_SHARDS)
    cfg = _cfg()
    params = serving.init_decode_params(cfg, seed=7)
    rng = np.random.RandomState(7)
    # 14 shared tokens, NON-page-aligned (page_size 4): hits attach
    # mid-page and the first divergent append copy-on-writes the
    # shared tail page on the sharded arrays
    shared = rng.randint(1, cfg.vocab_size, size=14).tolist()
    prompts = [shared + rng.randint(1, cfg.vocab_size, size=3).tolist()
               for _ in range(4)]
    oracles = [serving.full_decode(params, cfg, p, 6)[0]
               for p in prompts]

    prog = ShardedDecodeProgram(params, cfg, devices=devs)
    pool = prog.make_pool(num_pages=64, page_size=4)
    cache = serving.PrefixCache(pool)
    loop = ContinuousBatchingLoop(None, None, pool, max_batch=2,
                                  prefill=prefill, program=prog,
                                  prefix_cache=cache)
    got = loop.run([DecodeRequest(prompt=list(p), max_new_tokens=6)
                    for p in prompts])
    for want, g in zip(oracles, got):
        assert g.error is None
        assert g.tokens == want  # token-identical to the oracle
    # sharing + CoW actually happened on the mesh pool
    assert loop.prefix_hits >= 1
    assert loop.cached_prefill_tokens > 0
    assert pool.stats()["cow_copies"] >= 1
    # refcount invariants green; per-shard view intact after CoW
    assert pool.check_invariants()["ok"]
    shards = pool.k_pages.addressable_shards
    assert len(shards) == N_SHARDS
    assert shards[0].data.shape[1] == cfg.n_head // N_SHARDS
    # zero leaked pages once the cache releases its holds
    cache.clear()
    assert pool.stats()["used_pages"] == 0
    assert pool.check_invariants()["ok"]


def test_sharded_speculative_decode_token_identical_to_oracle(
        host_devices):
    """ISSUE 16 on the mesh: greedy speculative decode through the
    SPMD program's multi-token verify_step (Sq = 1 + d per sequence)
    is token-identical to the single-device full_decode oracle, WITH
    rollbacks occurring and every page freed afterwards — speculation
    is a pure latency move, invisible in the emitted stream."""
    devs = host_devices(N_SHARDS)
    cfg = _cfg()
    params = serving.init_decode_params(cfg, seed=3)
    rng = np.random.RandomState(9)
    # repeating prompt structure so prompt-lookup drafting fires early
    prompts = [(rng.randint(1, cfg.vocab_size, size=n).tolist() * 2)[:8]
               for n in (4, 5, 6, 4)]
    oracles = [serving.full_decode(params, cfg, p, 10)[0]
               for p in prompts]

    prog = ShardedDecodeProgram(params, cfg, devices=devs)
    pool = prog.make_pool(num_pages=64, page_size=4)
    loop = ContinuousBatchingLoop(None, None, pool, max_batch=3,
                                  program=prog, speculate=3,
                                  check_every=1)
    got = loop.run([DecodeRequest(prompt=list(p), max_new_tokens=10)
                    for p in prompts])
    for want, g in zip(oracles, got):
        assert g.error is None
        assert g.tokens == want  # token-identical to the oracle
    # speculation genuinely ran on the mesh — and imperfectly
    assert loop.spec_steps > 0 and loop.drafted_tokens > 0
    assert loop.accepted_tokens > 0
    assert loop.rolled_back_tokens > 0
    assert loop.invariant_violations == 0
    assert pool.stats()["used_pages"] == 0
    assert pool.check_invariants()["ok"]


def test_sharded_speculative_sampled_replay_identical(host_devices):
    """Sampled rows speculate on the mesh too (the accept/resample
    epilogue runs on the verify_step's [B, Sq, V] logits): an
    identical re-run regenerates the identical stream, and the greedy
    batch-mate keeps its oracle parity alongside."""
    devs = host_devices(N_SHARDS)
    cfg = _cfg()
    params = serving.init_decode_params(cfg, seed=4)
    rng = np.random.RandomState(11)
    prompt = (rng.randint(1, cfg.vocab_size, size=4).tolist() * 2)
    want = serving.full_decode(params, cfg, prompt, 8)[0]

    def run():
        prog = ShardedDecodeProgram(params, cfg, devices=devs)
        pool = prog.make_pool(num_pages=64, page_size=4)
        loop = ContinuousBatchingLoop(None, None, pool, max_batch=2,
                                      program=prog, speculate=2)
        out = loop.run([
            DecodeRequest(prompt=list(prompt), max_new_tokens=8),
            DecodeRequest(prompt=list(prompt), max_new_tokens=8,
                          sampling=serving.SamplingParams(
                              temperature=0.9, seed=5))])
        assert pool.stats()["used_pages"] == 0
        assert pool.check_invariants()["ok"]
        return loop, [o.tokens for o in out]

    loop, toks = run()
    assert toks[0] == want           # greedy mate: oracle-exact
    assert len(toks[1]) == 8 and toks[1] != want  # genuinely sampled
    assert loop.drafted_tokens > 0
    _, toks2 = run()
    assert toks2 == toks             # bit-identical replay


# ---------------------------------------------------------------------------
# (b) the per-shard pool view


def test_sharded_pool_head_shard_view(host_devices):
    devs = host_devices(N_SHARDS)
    cfg = _cfg()
    prog = ShardedDecodeProgram(
        serving.init_decode_params(cfg, seed=0), cfg, devices=devs)
    pool = prog.make_pool(num_pages=16, page_size=4)
    assert isinstance(pool, ShardedKVCachePool)
    assert pool.n_shards == N_SHARDS
    assert pool.heads_per_shard == cfg.n_head // N_SHARDS
    # each device holds exactly its heads' pages: [L, H/n, P, ps, D]
    shards = pool.k_pages.addressable_shards
    assert len(shards) == N_SHARDS
    local = shards[0].data.shape
    assert local == (cfg.n_layer, cfg.n_head // N_SHARDS, 16, 4,
                     cfg.head_dim)
    assert pool.bytes_per_page_per_shard() * N_SHARDS \
        == pool.bytes_per_page()
    # host-side bookkeeping is the inherited single-pool protocol
    pool.allocate(0)
    pages, slots = pool.append_tokens([0], [5])
    assert len(pages) == 5
    pool.free_seq(0)
    assert pool.check_invariants()["ok"]


def test_sharded_validation_errors(host_devices):
    devs = host_devices(N_SHARDS)
    cfg = _cfg(n_head=3)  # 3 heads don't divide over 4 shards
    with pytest.raises(ValueError, match="divide"):
        ShardedDecodeProgram(serving.init_decode_params(cfg, seed=0),
                             cfg, devices=devs)
    with pytest.raises(RuntimeError, match="xla_force_host_platform"):
        host_mesh_devices(4096)
    cfg4 = _cfg()
    prog = ShardedDecodeProgram(serving.init_decode_params(cfg4, seed=0),
                                cfg4, devices=devs)
    plain = KVCachePool(num_pages=8, page_size=4, num_layers=cfg4.n_layer,
                        num_heads=cfg4.n_head, head_dim=cfg4.head_dim)
    plain.allocate(0)
    with pytest.raises(ValueError, match="mesh"):
        prog.decode_step(plain, [0], [1], [0])


# ---------------------------------------------------------------------------
# (c)+(d) router: mixed traffic, drain handoff, health/lease skipping


class _SleepyBackend:
    feed_names = ["x"]
    fetch_names = ["y"]
    meta: dict = {}

    def __init__(self, delay=0.0015):
        self.delay = delay
        self.calls = 0

    def __call__(self, feed):
        self.calls += 1
        time.sleep(self.delay)
        return [np.asarray(feed["x"]) * 2.0]


def _engine(name, **kw):
    cfg = EngineConfig(buckets=(1, 2, 4), max_wait_s=0.001, **kw)
    return Engine(_SleepyBackend(), config=cfg, name=name)


def test_router_drain_handoff_zero_lost():
    e0, e1 = _engine("r0"), _engine("r1")
    router = Router([e0, e1])
    rng = np.random.RandomState(0)
    futs = []
    drained_at = 24
    for i in range(48):
        if i == drained_at:
            done = router.drain_replica("r0", timeout=0)  # claim, no wait
            assert done in (False, True)
        futs.append(router.submit(
            {"x": rng.rand(1, 4).astype(np.float32)}))
    outs = [f.result(timeout=30) for f in futs]
    # zero lost, zero duplicated: every request resolved exactly once,
    # with its own payload (x * 2 round-trips bit-exact)
    assert len(outs) == 48
    for f, out in zip(futs, outs):
        assert out[0].shape == (1, 4)
    # nothing routed to the drained replica after the handoff
    assert all(f.replica == "r1" for f in futs[drained_at:])
    # both replicas actually served before it
    served = {f.replica for f in futs[:drained_at]}
    assert served == {"r0", "r1"}
    # the drained replica finished its queued work
    assert router.drain_replica("r0", timeout=10.0) is True
    assert e0.queue_depth() == 0
    st = router.stats()
    assert st["handoffs"] == 1
    assert st["routed"] == 48
    router.close()


def test_router_skips_draining_and_broken_and_falls_over():
    e0, e1 = _engine("r0"), _engine("r1")
    router = Router([e0, e1])
    # DRAINING: engine drained outside the router (e.g. SIGTERM) — the
    # health poll must skip it without a drain_replica claim
    e0.begin_drain()
    fut = router.submit({"x": np.ones((1, 4), np.float32)})
    assert fut.replica == "r1"
    fut.result(10)
    assert router.stats()["replicas"]["r0"]["skipped"] >= 1
    # nothing admitting -> typed unavailable error naming reasons
    e1.begin_drain()
    with pytest.raises(ReplicaUnavailableError) as ei:
        router.submit({"x": np.ones((1, 4), np.float32)})
    assert "r1" in ei.value.skipped
    router.close()


def test_router_lease_expiry_via_elastic_master_seam():
    master = MasterService(InMemStore(), timeout_dur=5.0)
    directory = ReplicaDirectory(master, max_silence_s=0.15)
    e0, e1 = _engine("r0"), _engine("r1")
    router = Router([e0, e1], directory=directory)
    # both leased: traffic may land anywhere
    directory.beat("r0")
    directory.beat("r1")
    router.submit({"x": np.ones((1, 4), np.float32)}).result(10)
    # r0's lease lapses; r1 keeps beating — all traffic moves to r1
    deadline = time.perf_counter() + 5.0
    while time.perf_counter() < deadline:
        directory.beat("r1")
        if "r0" in directory.expired():
            break
        time.sleep(0.02)
    assert "r0" in directory.expired()
    futs = [router.submit({"x": np.ones((1, 4), np.float32)})
            for _ in range(4)]
    assert all(f.replica == "r1" for f in futs)
    [f.result(10) for f in futs]
    h = router.health()
    assert h["replicas"]["r0"]["lease_expired"] is True
    assert h["replicas"]["r0"]["routing"] is False
    assert h["replicas"]["r1"]["routing"] is True
    router.close()


def test_router_concurrent_submit_thread_safe():
    e0, e1 = _engine("r0", queue_depth=512), _engine("r1", queue_depth=512)
    router = Router([e0, e1])
    results = []
    lock = threading.Lock()
    rng = np.random.RandomState(2)
    feeds = [rng.rand(1, 4).astype(np.float32) for _ in range(40)]

    def worker(lo, hi):
        for i in range(lo, hi):
            out = router.infer({"x": feeds[i]})
            with lock:
                results.append((i, out[0]))

    threads = [threading.Thread(target=worker, args=(i * 10, (i + 1) * 10))
               for i in range(4)]
    [t.start() for t in threads]
    [t.join(30) for t in threads]
    assert len(results) == 40
    for i, out in results:
        np.testing.assert_array_equal(out, feeds[i] * 2.0)
    router.close()


# ---------------------------------------------------------------------------
# (e) replica-labeled observability, attributable after aggregate_dir


def test_replica_labels_flow_through_observability(tmp_path):
    pflags.set_flags({"FLAGS_observability": True})
    obs.reset()
    try:
        e0, e1 = _engine("r0"), _engine("r1")
        router = Router([e0, e1])
        for _ in range(6):
            router.submit({"x": np.ones((1, 4), np.float32)}).result(10)
        router.health()  # records per-replica gauges
        e0.health()      # engine-side gauges carry the replica label too

        # flight events are replica-attributable
        evs = obs.default_flight().events()
        assert any(e.get("replica") in ("r0", "r1") for e in evs
                   if e["kind"] == "submit")

        # kept request traces annotate the replica on the root span
        spans = obs.default_tracer().spans()
        roots = [s for s in spans if s.name == "request"]
        assert roots and any(
            s.args.get("replica") in ("r0", "r1") for s in roots)

        # counters/gauges keep the replica label through a dump ->
        # aggregate_dir merge (the multi-process fleet view)
        reg = obs.default_registry()
        reg.dump(str(tmp_path / "metrics_0.json"))
        merged = obs.MetricsRegistry.aggregate_dir(str(tmp_path))
        routed = merged.counter(
            "paddle_tpu_serving_router_decisions",
            "admission-router routing decisions by replica")
        total = sum(
            routed.value(decision="routed", replica=r)
            for r in ("r0", "r1"))
        assert total == 6
        health = merged.gauge(
            "paddle_tpu_serving_replica_health_state", "")
        assert health.value(replica="r0") is not None
        router.close()
    finally:
        pflags.set_flags({"FLAGS_observability": False})
        obs.reset()


# ---------------------------------------------------------------------------
# serve_bench wiring (--replicas / --mesh on the 0/2/3 exit contract)


def test_serve_bench_router_mode_gate(tmp_path, capsys):
    import json

    from tools.serve_bench import main as bench_main

    bank = tmp_path / "bank.json"
    bank.write_text(json.dumps({
        "lost_requests": 0, "post_drain_misroutes": 0,
        "drain_completed": 1,
    }))
    out_json = tmp_path / "out.json"
    rc = bench_main([
        "--replicas", "2", "--model", "tiny", "--requests", "16",
        "--rate", "800", "--no-warmup", "--json", str(out_json),
        "--baseline", str(bank), "--gate",
    ])
    capsys.readouterr()
    assert rc == 0
    result = json.loads(out_json.read_text())
    assert result["mode"] == "router" and result["replicas"] == 2
    assert result["lost_requests"] == 0
    assert result["post_drain_misroutes"] == 0
    assert set(result["per_replica"]) == {"replica0", "replica1"}


def test_serve_bench_mesh_mode(tmp_path, capsys, host_devices):
    import json

    host_devices(4)  # skip early if the platform cannot provide a mesh
    from tools.serve_bench import main as bench_main

    out_json = tmp_path / "out.json"
    rc = bench_main([
        "--mode", "decode", "--mesh", "4", "--sequences", "4",
        "--max-new", "6", "--pages", "64", "--page-size", "4",
        "--d-model", "32", "--max-len", "48", "--json", str(out_json),
    ])
    capsys.readouterr()
    assert rc == 0
    result = json.loads(out_json.read_text())
    assert result["mesh"] == 4
    assert result["pages_leaked"] == 0
    assert result["tokens"] == 4 * 6


def test_serve_bench_usage_errors(capsys):
    from tools.serve_bench import main as bench_main

    assert bench_main(["--mode", "decode", "--replicas", "2"]) == 2
    assert bench_main(["--mesh", "4"]) == 2  # mesh needs decode mode
    # --replicas --chaos became a SUPPORTED scenario (replica-kill
    # failover, tests/test_fleet.py) — but N must still be sane
    assert bench_main(["--replicas", "0", "--chaos"]) == 2
    capsys.readouterr()
