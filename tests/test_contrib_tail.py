"""Dataset tail (voc2012/sentiment/mq2007/image) + contrib tail
(op_frequence, ctr_reader, Trainer/Inferencer, lookup_table_utils,
StateCell/TrainingDecoder/BeamSearchDecoder)
(reference: python/paddle/dataset/tests, contrib/tests)."""

import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers


# -- datasets ----------------------------------------------------------
def test_voc2012_schema():
    from paddle_tpu.dataset import voc2012

    img, label = next(voc2012.train()())
    assert img.dtype == np.float32 and img.ndim == 3 and img.shape[0] == 3
    assert label.dtype == np.int32 and label.shape == img.shape[1:]
    classes = set(np.unique(label)) - {255}
    assert classes <= set(range(21))


def test_sentiment_schema_and_signal():
    from paddle_tpu.dataset import sentiment

    wd = sentiment.get_word_dict()
    assert len(wd) == sentiment.VOCAB_SIZE
    pos_hits = neg_hits = 0
    for words, label in list(sentiment.train()())[:200]:
        assert all(0 <= w < sentiment.VOCAB_SIZE for w in words)
        band = np.sum([100 <= w < 400 for w in words])
        if label == 1:
            pos_hits += band
        else:
            neg_hits += band
    assert pos_hits > neg_hits  # the polarity signal exists


def test_mq2007_formats():
    from paddle_tpu.dataset import mq2007

    rel, feat = next(mq2007.train(format="pointwise")())
    assert feat.shape == (mq2007.FEATURE_DIM,) and rel in (0, 1, 2)

    label, hi, lo = next(mq2007.train(format="pairwise")())
    assert label == 1.0 and hi.shape == lo.shape == (mq2007.FEATURE_DIM,)

    rels, feats = next(mq2007.train(format="listwise")())
    assert feats.shape == (len(rels), mq2007.FEATURE_DIM)

    qid, rel, feat = next(mq2007.train(format="plain_txt")())
    assert isinstance(qid, int)


def test_image_transforms():
    from paddle_tpu.dataset import image as img_util

    im = (np.random.RandomState(0).rand(48, 64, 3) * 255).astype(np.uint8)
    r = img_util.resize_short(im, 32)
    assert min(r.shape[:2]) == 32 and r.shape[2] == 3
    c = img_util.center_crop(r, 24)
    assert c.shape[:2] == (24, 24)
    f = img_util.left_right_flip(c)
    np.testing.assert_array_equal(f, c[:, ::-1, :])
    out = img_util.simple_transform(im, 36, 24, is_train=False,
                                    mean=[127.0, 127.0, 127.0])
    assert out.shape == (3, 24, 24) and out.dtype == np.float32
    # .npy round trip through load_image
    import tempfile

    p = os.path.join(tempfile.mkdtemp(), "im.npy")
    np.save(p, im)
    np.testing.assert_array_equal(img_util.load_image(p), im)


# -- op census ---------------------------------------------------------
def test_op_freq_statistic():
    fluid.reset_default_env()
    x = layers.data("x", [4])
    h = layers.fc(x, 8, act="relu")
    out = layers.fc(h, 1)
    loss = layers.reduce_mean(layers.square(out))
    uni, adj = fluid.contrib.op_freq_statistic(fluid.default_main_program())
    assert uni["mul"] == 2  # two fc layers
    assert any(k.startswith("relu,") or k.endswith(",relu") for k in adj)


# -- ctr_reader --------------------------------------------------------
def test_ctr_reader_feeds_program(tmp_path):
    from paddle_tpu.contrib.reader import ctr_reader

    fluid.reset_default_env()
    rng = np.random.RandomState(0)
    files = []
    for fi in range(2):
        p = str(tmp_path / f"ctr{fi}.txt")
        with open(p, "w") as f:
            for _ in range(40):
                sid = rng.randint(50)
                f.write(f"{rng.randint(2)} slot_a:{sid} "
                        f"slot_b:{rng.randint(50)}\n")
        files.append(p)

    label = layers.data("label", [1], dtype="int64")
    a = layers.data("a_ids", [1], dtype="int64", lod_level=1)
    b = layers.data("b_ids", [1], dtype="int64", lod_level=1)
    reader = ctr_reader(
        feed_data=[label, a, b], capacity=8, thread_num=2, batch_size=10,
        file_list=files, slots=["slot_a", "slot_b"],
    )
    emb_a = layers.embedding(a, size=[50, 8])
    emb_b = layers.embedding(b, size=[50, 8])
    feat = layers.concat(
        [layers.sequence_pool(emb_a, "sum"),
         layers.sequence_pool(emb_b, "sum")], axis=1)
    pred = layers.fc(feat, 1)
    loss = layers.reduce_mean(layers.square(pred))

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    reader.start()
    n = 0
    while True:
        try:
            exe.run(feed=None, fetch_list=[loss])
            n += 1
        except fluid.core.EOFException:
            reader.reset()
            break
    assert n == 8  # 2 files x 40 lines / batch 10


# -- Trainer / Inferencer ---------------------------------------------
def _reg_train_func():
    x = layers.data("x", [1], dtype="float32")
    y = layers.data("y", [1], dtype="float32")
    pred = layers.fc(x, size=1, param_attr=fluid.ParamAttr(name="tw"))
    loss = layers.reduce_mean(layers.square_error_cost(pred, y))
    return [loss]


def _reg_infer_func():
    x = layers.data("x", [1], dtype="float32")
    return layers.fc(x, size=1, param_attr=fluid.ParamAttr(name="tw"))


def _reg_reader():
    rng = np.random.RandomState(0)
    for _ in range(12):
        xb = rng.uniform(-1, 1, (16, 1)).astype(np.float32)
        yield [(xb[i], 2.0 * xb[i] + 1.0) for i in range(16)]


def test_trainer_and_inferencer(tmp_path):
    from paddle_tpu.contrib import (
        BeginEpochEvent, CheckpointConfig, EndStepEvent, Inferencer, Trainer,
    )

    fluid.reset_default_env()
    events = {"epochs": 0, "losses": []}

    def handler(ev):
        if isinstance(ev, BeginEpochEvent):
            events["epochs"] += 1
        elif isinstance(ev, EndStepEvent):
            events["losses"].append(float(np.ravel(
                np.asarray(ev.metrics[0]))[0]))

    ckpt = CheckpointConfig(str(tmp_path / "tck"), step_interval=5)
    trainer = Trainer(
        train_func=_reg_train_func,
        optimizer_func=lambda: fluid.optimizer.SGD(learning_rate=0.3),
        place=fluid.CPUPlace(), checkpoint_config=ckpt,
    )
    trainer.train(num_epochs=3, event_handler=handler, reader=_reg_reader,
                  feed_order=["x", "y"])
    assert events["epochs"] == 3
    assert events["losses"][-1] < events["losses"][0] * 0.1
    # checkpoints exist with success markers
    serials = [n for n in os.listdir(str(tmp_path / "tck")) if n.isdigit()]
    assert serials

    test_metrics = trainer.test(reader=_reg_reader, feed_order=["x", "y"])
    assert test_metrics[0] < 0.05

    params = str(tmp_path / "params")
    trainer.save_params(params)

    inf = Inferencer(_reg_infer_func, params, place=fluid.CPUPlace())
    out = inf.infer({"x": np.array([[0.5]], dtype=np.float32)})
    assert abs(float(np.ravel(np.asarray(out[0]))[0]) - 2.0) < 0.3

    # a fresh Trainer resumes epoch counter from the checkpoint
    fluid.reset_default_env()
    t2 = Trainer(
        train_func=_reg_train_func,
        optimizer_func=lambda: fluid.optimizer.SGD(learning_rate=0.3),
        place=fluid.CPUPlace(),
        checkpoint_config=CheckpointConfig(str(tmp_path / "tck")),
    )
    assert t2.checkpoint_cfg.epoch_id == 2


# -- lookup_table_utils ------------------------------------------------
def test_lookup_table_utils(tmp_path):
    from paddle_tpu.contrib.utils import (
        convert_dist_to_sparse_program,
        load_persistables_for_increment,
    )

    fluid.reset_default_env()
    ids = layers.data("ids", [1], dtype="int64")
    emb = layers.embedding(ids, size=[40, 4], is_distributed=True,
                           param_attr=fluid.ParamAttr(name="big_table"))
    pred = layers.fc(emb, 1, param_attr=fluid.ParamAttr(name="w1"))
    loss = layers.reduce_mean(layers.square(pred))

    prog = fluid.default_main_program()
    sparse = convert_dist_to_sparse_program(prog)
    types = [op.type for op in sparse.global_block().desc.ops]
    assert "lookup_sparse_table" in types and "lookup_table" not in types

    # shard reassembly: table saved as two row-slices
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    d = str(tmp_path / "inc")
    os.makedirs(d)
    full = np.arange(160, dtype=np.float32).reshape(40, 4)
    np.save(os.path.join(d, "big_table.block0.npy"), full[:25])
    np.save(os.path.join(d, "big_table.block1.npy"), full[25:])
    # dense persistables saved the normal way (pserver path: table rides
    # shard files, everything else a regular checkpoint)
    fluid.io.save_vars(
        exe, d, main_program=prog,
        predicate=lambda v: fluid.io.is_persistable(v)
        and v.name != "big_table",
    )
    load_persistables_for_increment(d, exe, prog, "big_table")
    np.testing.assert_array_equal(
        np.asarray(fluid.global_scope().find_var("big_table")), full
    )


# -- StateCell / decoders ----------------------------------------------
V, EMB, HID, END = 12, 8, 16, 1


def _build_state_cell():
    from paddle_tpu.contrib.decoder import InitState, StateCell

    enc_final = layers.data("enc_final", [HID], dtype="float32")
    h_init = InitState(init=enc_final)
    cell = StateCell(
        inputs={"x": None}, states={"h": h_init}, out_state="h"
    )

    @cell.state_updater
    def updater(state_cell):
        x = state_cell.get_input("x")
        h = state_cell.get_state("h")
        new_h = layers.fc(
            layers.concat([x, h], axis=1), size=HID, act="tanh",
            param_attr=fluid.ParamAttr(name="cell_w"),
            bias_attr=fluid.ParamAttr(name="cell_b"),
        )
        state_cell.set_state("h", new_h)

    return cell


def test_training_decoder_trains():
    from paddle_tpu.contrib.decoder import TrainingDecoder

    fluid.reset_default_env()
    cell = _build_state_cell()
    trg = layers.data("trg", [1], dtype="int64", lod_level=1)
    trg_emb = layers.embedding(trg, size=[V, EMB],
                               param_attr=fluid.ParamAttr(name="trg_emb"))
    decoder = TrainingDecoder(cell)
    with decoder.block():
        cur = decoder.step_input(trg_emb)
        decoder.state_cell.compute_state(inputs={"x": cur})
        out = layers.fc(decoder.state_cell.out_state(), size=V,
                        act="softmax",
                        param_attr=fluid.ParamAttr(name="out_w"))
        decoder.state_cell.update_states()
        decoder.output(out)
    probs = decoder()
    label = layers.data("label", [1], dtype="int64", lod_level=1)
    cost = layers.cross_entropy(probs, label)
    loss = layers.mean(layers.sequence_pool(cost, "sum"))
    fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)

    def batch():
        # deterministic task: emit the current input token (learnable to
        # ~zero loss through the embedding alone; the state just rides)
        seqs = [rng.randint(2, V, size=(rng.randint(3, 6), 1))
                for _ in range(8)]
        return {
            "trg": fluid.create_lod_tensor([s.astype(np.int64) for s in seqs]),
            "label": fluid.create_lod_tensor(
                [s.astype(np.int64) for s in seqs]),
            "enc_final": rng.randn(8, HID).astype(np.float32) * 0.1,
        }

    losses = []
    for i in range(60):
        (lv,) = exe.run(feed=batch(), fetch_list=[loss])
        losses.append(float(np.ravel(np.asarray(lv))[0]))
    first, last = np.mean(losses[:5]), np.mean(losses[-5:])
    assert last < first * 0.5, f"decoder did not learn: {first} -> {last}"


def test_beam_search_decoder_decodes():
    from paddle_tpu.contrib.decoder import BeamSearchDecoder

    fluid.reset_default_env()
    BEAM = 2
    cell = _build_state_cell()
    init_ids = layers.data("init_ids", [BEAM, 1], append_batch_size=False,
                           dtype="int64")
    init_scores = layers.data("init_scores", [BEAM, 1],
                              append_batch_size=False, dtype="float32")
    decoder = BeamSearchDecoder(
        state_cell=cell, init_ids=init_ids, init_scores=init_scores,
        target_dict_dim=V, word_dim=EMB, topk_size=V, sparse_emb=False,
        max_len=5, beam_size=2, end_id=END,
    )
    decoder.decode()
    ids, scores = decoder()

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    feed = {
        "init_ids": np.full((BEAM, 1), 2, dtype=np.int64),
        "init_scores": np.array([[0.0], [-1e9]], dtype=np.float32),
        "enc_final": np.random.RandomState(0).randn(BEAM, HID)
        .astype(np.float32) * 0.1,
    }
    (got_ids,) = exe.run(feed=feed, fetch_list=[ids], return_numpy=False)
    seqs = np.asarray(got_ids.data)
    lens = np.asarray(got_ids.lengths)
    assert seqs.ndim >= 2 and lens.shape[0] == BEAM
    assert lens.max() <= 5 + 1  # max_len steps (+ possible end token)
    assert np.all((seqs >= 0) & (seqs < V))
