"""Pipeline (`pp`) and expert (`ep`) parallelism — the two mesh axes the
reference never had (SURVEY §2.6 lists them absent in 2018).  Both must
match a serial single-device execution bit-for-bit (modulo float assoc.)
on the 8-device virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.parallel import make_mesh
from paddle_tpu.parallel.moe import switch_moe
from paddle_tpu.parallel.pipeline import pipeline_apply


def _stage_fn(params, x):
    w, b = params
    return jnp.tanh(x @ w + b)


def test_pipeline_matches_serial():
    S, M, N, D = 4, 6, 3, 8  # 4 stages, 6 microbatches
    r = np.random.RandomState(0)
    ws = jnp.asarray(r.randn(S, D, D).astype("float32") * 0.3)
    bs = jnp.asarray(r.randn(S, D).astype("float32") * 0.1)
    x = jnp.asarray(r.randn(M, N, D).astype("float32"))

    mesh = make_mesh({"pp": S, "dp": 2}, devices=jax.devices()[:8])
    got = pipeline_apply(_stage_fn, (ws, bs), x, mesh, pp_axis="pp")

    want = x
    for s in range(S):
        want = jax.vmap(lambda mb: _stage_fn((ws[s], bs[s]), mb))(want)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_two_stage_any_mb_count():
    S, M, N, D = 2, 5, 2, 4
    r = np.random.RandomState(1)
    ws = jnp.asarray(r.randn(S, D, D).astype("float32") * 0.3)
    bs = jnp.asarray(r.randn(S, D).astype("float32") * 0.1)
    x = jnp.asarray(r.randn(M, N, D).astype("float32"))
    mesh = make_mesh({"pp": S}, devices=jax.devices()[:S])
    got = pipeline_apply(_stage_fn, (ws, bs), x, mesh)
    want = x
    for s in range(S):
        want = jax.vmap(lambda mb: _stage_fn((ws[s], bs[s]), mb))(want)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def _moe_serial(x, gate_w, w1, b1, w2, b2, cap):
    """Dense reference: every token through its argmax expert, capacity
    drops applied in token order."""
    T, D = x.shape
    E = gate_w.shape[1]
    probs = np.asarray(jax.nn.softmax(x @ gate_w, axis=-1))
    expert = probs.argmax(-1)
    gate = probs[np.arange(T), expert]
    counts = {}
    out = np.zeros((T, D), "float32")
    for t in range(T):
        e = int(expert[t])
        c = counts.get(e, 0)
        counts[e] = c + 1
        if c >= cap:
            continue  # dropped
        h = np.maximum(np.asarray(x[t]) @ np.asarray(w1[e])
                       + np.asarray(b1[e]), 0.0)
        out[t] = (h @ np.asarray(w2[e]) + np.asarray(b2[e])) * gate[t]
    return out


def test_switch_moe_matches_serial():
    T, D, H, E, ep = 16, 6, 10, 8, 4
    r = np.random.RandomState(2)
    x = jnp.asarray(r.randn(T, D).astype("float32"))
    gate_w = jnp.asarray(r.randn(D, E).astype("float32"))
    w1 = jnp.asarray(r.randn(E, D, H).astype("float32") * 0.3)
    b1 = jnp.asarray(r.randn(E, H).astype("float32") * 0.1)
    w2 = jnp.asarray(r.randn(E, H, D).astype("float32") * 0.3)
    b2 = jnp.asarray(r.randn(E, D).astype("float32") * 0.1)
    cap = T  # no drops: parity must be exact

    mesh = make_mesh({"ep": ep, "dp": 2}, devices=jax.devices()[:8])
    got = switch_moe(x, gate_w, w1, b1, w2, b2, mesh, capacity=cap)
    want = _moe_serial(x, gate_w, w1, b1, w2, b2, cap)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-4)


def test_switch_moe_capacity_drops():
    """Tokens past an expert's capacity pass through as zeros (standard
    switch capacity semantics) — and the kept ones still match."""
    T, D, H, E, ep = 12, 4, 6, 4, 2
    r = np.random.RandomState(3)
    x = jnp.asarray(r.randn(T, D).astype("float32"))
    # zero gate logits: argmax ties break to expert 0 for every token
    gate_w = jnp.zeros((D, E), "float32")
    w1 = jnp.asarray(r.randn(E, D, H).astype("float32") * 0.3)
    b1 = jnp.zeros((E, H), "float32")
    w2 = jnp.asarray(r.randn(E, H, D).astype("float32") * 0.3)
    b2 = jnp.zeros((E, D), "float32")
    cap = 5

    mesh = make_mesh({"ep": ep}, devices=jax.devices()[:ep])
    got = np.asarray(switch_moe(x, gate_w, w1, b1, w2, b2, mesh,
                                capacity=cap))
    want = _moe_serial(x, gate_w, w1, b1, w2, b2, cap)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
    assert (got[cap:] == 0).all()  # overflow tokens dropped to zero


def test_pipeline_gradients_match_serial():
    """The GPipe schedule is one differentiable XLA program: grads wrt
    stage params must equal the serial composition's grads."""
    S, M, N, D = 2, 3, 2, 4
    r = np.random.RandomState(4)
    ws = jnp.asarray(r.randn(S, D, D).astype("float32") * 0.3)
    bs = jnp.asarray(r.randn(S, D).astype("float32") * 0.1)
    x = jnp.asarray(r.randn(M, N, D).astype("float32"))
    mesh = make_mesh({"pp": S}, devices=jax.devices()[:S])

    def loss_pipe(params):
        y = pipeline_apply(_stage_fn, params, x, mesh)
        return jnp.sum(y * y)

    def loss_serial(params):
        ws_, bs_ = params
        y = x
        for s in range(S):
            y = jax.vmap(lambda mb: _stage_fn((ws_[s], bs_[s]), mb))(y)
        return jnp.sum(y * y)

    g_pipe = jax.grad(loss_pipe)((ws, bs))
    g_ser = jax.grad(loss_serial)((ws, bs))
    for a, b in zip(jax.tree_util.tree_leaves(g_pipe),
                    jax.tree_util.tree_leaves(g_ser)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_switch_moe_gradients_flow():
    """Expert and gate weights receive gradients through the all_to_all
    dispatch (routing argmax is non-differentiable by design; the gate
    probability multiplier carries the router grad)."""
    T, D, H, E, ep = 8, 4, 6, 4, 2
    r = np.random.RandomState(5)
    x = jnp.asarray(r.randn(T, D).astype("float32"))
    gate_w = jnp.asarray(r.randn(D, E).astype("float32"))
    w1 = jnp.asarray(r.randn(E, D, H).astype("float32") * 0.3)
    b1 = jnp.asarray(r.randn(E, H).astype("float32") * 0.1)
    w2 = jnp.asarray(r.randn(E, H, D).astype("float32") * 0.3)
    b2 = jnp.asarray(r.randn(E, D).astype("float32") * 0.1)
    mesh = make_mesh({"ep": ep}, devices=jax.devices()[:ep])

    def loss(params):
        gw, w1_, w2_ = params
        y = switch_moe(x, gw, w1_, b1, w2_, b2, mesh, capacity=T)
        return jnp.sum(y * y)

    g_gate, g_w1, g_w2 = jax.grad(loss)((gate_w, w1, w2))
    assert np.isfinite(np.asarray(g_gate)).all()
    assert float(jnp.abs(g_w1).sum()) > 0
    assert float(jnp.abs(g_w2).sum()) > 0
    assert float(jnp.abs(g_gate).sum()) > 0
