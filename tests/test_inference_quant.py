"""Inference predictor + quantization (reference: inference/tests/api
analyzer testers, test_quantize_transpiler.py)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.inference import AnalysisConfig, NativeConfig, PaddleTensor, create_paddle_predictor


def _train_and_export(tmp_path):
    x = layers.data("x", [6], dtype="float32")
    y = layers.data("y", [1], dtype="float32")
    pred = layers.fc(layers.fc(x, size=8, act="relu"), size=1)
    loss = layers.mean(layers.square_error_cost(pred, y))
    fluid.optimizer.AdamOptimizer(learning_rate=0.05).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    xv = rng.randn(16, 6).astype("float32")
    yv = rng.randn(16, 1).astype("float32")
    for _ in range(5):
        exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])
    d = str(tmp_path / "model")
    fluid.io.save_inference_model(d, ["x"], [pred], exe)
    infer_prog = fluid.io.get_inference_program([pred])
    (ref,) = exe.run(program=infer_prog, feed={"x": xv}, fetch_list=[pred])
    return d, xv, np.asarray(ref)


def test_native_predictor_roundtrip(tmp_path):
    d, xv, ref = _train_and_export(tmp_path)
    predictor = create_paddle_predictor(NativeConfig(model_dir=d))
    assert predictor.get_input_names() == ["x"]
    outs = predictor.run([PaddleTensor(name="x", data=xv)])
    np.testing.assert_allclose(np.asarray(outs[0].data), ref, rtol=1e-6)


def test_analysis_predictor_and_clone(tmp_path):
    d, xv, ref = _train_and_export(tmp_path)
    cfg = AnalysisConfig(model_dir=d)
    cfg.enable_tensorrt_engine()
    predictor = create_paddle_predictor(cfg)
    (out,) = predictor.run_dict({"x": xv})
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-6)
    p2 = predictor.clone()
    (out2,) = p2.run_dict({"x": xv})
    np.testing.assert_allclose(np.asarray(out2), ref, rtol=1e-6)


def test_quantize_transpiler_inserts_and_trains():
    from paddle_tpu.contrib.quantize import QuantizeTranspiler

    x = layers.data("x", [8], dtype="float32")
    y = layers.data("y", [1], dtype="float32")
    pred = layers.fc(x, size=1)
    loss = layers.mean(layers.square_error_cost(pred, y))
    fluid.optimizer.SGDOptimizer(learning_rate=0.05).minimize(loss)

    QuantizeTranspiler().training_transpile()
    types = [op.type for op in fluid.default_main_program().desc.block(0).ops]
    assert "fake_quantize_abs_max" in types

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    xv = rng.randn(16, 8).astype("float32")
    yv = (xv.sum(1, keepdims=True) * 0.3).astype("float32")
    losses = [
        float(np.ravel(np.asarray(
            exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])[0]
        ))[0])
        for _ in range(20)
    ]
    assert losses[-1] < losses[0] * 0.5  # STE gradients flow


def test_qat_gradients_match_quantized_forward():
    """The ADVICE round-1 finding: backward must differentiate the QUANTIZED
    network.  Grad ops replay the forward op's vjp, which is traced after
    training_transpile renamed the forward inputs — so W@GRAD must equal the
    analytic gradient of the quantized forward (x_q^T g via the STE), and
    must differ from the unquantized network's gradient at coarse bits."""
    from paddle_tpu.contrib.quantize import QuantizeTranspiler

    x = layers.data("x", [8], dtype="float32")
    y = layers.data("y", [1], dtype="float32")
    pred = layers.fc(x, size=1, bias_attr=False, param_attr="qat_w")
    loss = layers.mean(layers.square_error_cost(pred, y))
    # lr=0: the sgd op runs but leaves W unchanged, so the manual expectation
    # below sees the same W the step used
    fluid.optimizer.SGDOptimizer(learning_rate=0.0).minimize(loss)
    QuantizeTranspiler(weight_bits=4, activation_bits=4).training_transpile()

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    rng = np.random.RandomState(7)
    xv = rng.randn(16, 8).astype("float32") * 3.0
    yv = rng.randn(16, 1).astype("float32")
    w = np.asarray(fluid.global_scope().find_var("qat_w"))

    got = np.asarray(
        exe.run(feed={"x": xv, "y": yv}, fetch_list=["qat_w@GRAD"])[0]
    )

    def quant(v, bits):
        bin_cnt = (1 << (bits - 1)) - 1
        s = max(np.abs(v).max(), 1e-8)
        return np.clip(np.round(v / s * bin_cnt), -bin_cnt, bin_cnt) * s / bin_cnt

    xq, wq = quant(xv, 4), quant(w, 4)
    g_out = 2.0 * (xq @ wq - yv) / yv.size
    expected_quant = xq.T @ g_out
    g_out_fp = 2.0 * (xv @ w - yv) / yv.size
    expected_fp = xv.T @ g_out_fp

    np.testing.assert_allclose(got, expected_quant, rtol=1e-4, atol=1e-5)
    assert not np.allclose(got, expected_fp, rtol=1e-3, atol=1e-4)


def test_fake_quant_levels():
    # quantized output has at most 2^bits-1 distinct levels
    x = layers.data("x", [32], dtype="float32")
    helper_block = fluid.default_main_program().global_block()
    from paddle_tpu.layer_helper import LayerHelper

    h = LayerHelper("fq")
    out = h.create_variable_for_type_inference("float32")
    scale = h.create_variable_for_type_inference("float32")
    h.append_op(
        type="fake_quantize_abs_max", inputs={"X": [x]},
        outputs={"Out": [out], "OutScale": [scale]},
        attrs={"bit_length": 4},
    )
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.random.RandomState(0).randn(1, 32).astype("float32")
    (got,) = exe.run(feed={"x": xv}, fetch_list=[out])
    levels = np.unique(np.round(np.asarray(got) / np.abs(np.asarray(got)).max() * 7))
    assert len(levels) <= 15


def test_freeze_program_runs_real_int8():
    """freeze_program converts the QAT program into genuine int8 compute
    (reference: quantize_transpiler.py freeze_program; here the frozen
    ops do int8 x int8 -> int32 dots): int8 weights land in scope, the
    fake_quantize ops disappear, and frozen predictions track the
    QAT-simulated ones."""
    import numpy as np

    from paddle_tpu.contrib.quantize import QuantizeTranspiler

    fluid.reset_default_env()
    img = layers.data("img", [1, 8, 8], dtype="float32")
    y = layers.data("y", [1], dtype="int64")
    c = layers.conv2d(img, num_filters=4, filter_size=3, padding=1,
                      act="relu")
    p = layers.pool2d(c, pool_size=8, pool_type="avg")
    pred = layers.fc(p, size=3, act="softmax")
    loss = layers.mean(layers.cross_entropy(pred, y))

    qt = QuantizeTranspiler()
    qt.training_transpile()
    # the inference program is cloned BEFORE backward, like the reference's
    # QAT flow: it holds fake_quantize + forward ops only
    test_prog = fluid.default_main_program().clone(for_test=True)
    fluid.optimizer.SGDOptimizer(learning_rate=0.05).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(9)
    feed = {"img": rng.rand(4, 1, 8, 8).astype("float32"),
            "y": rng.randint(0, 3, (4, 1)).astype("int64")}
    for _ in range(3):
        exe.run(feed=feed, fetch_list=[loss])

    (qat_pred,) = exe.run(program=test_prog, feed=feed,
                          fetch_list=[pred.name])

    qt.freeze_program(test_prog)
    types = [op.type for op in test_prog.desc.block(0).ops]
    assert "conv2d_int8" in types and "mul_int8" in types
    assert not any(t.startswith("fake_quantize") for t in types)
    # int8 weights materialized in scope (names discovered from the
    # frozen ops — unique-name counters depend on suite ordering)
    i8_names = [
        op.input(slot)[0]
        for op in test_prog.desc.block(0).ops
        for slot in ("Y", "Filter")
        if op.type in ("mul_int8", "conv2d_int8") and op.input(slot)
        and op.input(slot)[0].endswith(".int8")
    ]
    i8 = [np.asarray(fluid.global_scope().find_var(n)) for n in i8_names]
    assert len(i8) == 2 and all(v.dtype == np.int8 for v in i8)

    (int8_pred,) = exe.run(program=test_prog, feed=feed,
                           fetch_list=[pred.name])
    np.testing.assert_allclose(np.asarray(int8_pred), np.asarray(qat_pred),
                               atol=0.05, rtol=0.1)


def test_freeze_mixed_bits_scales_correctly():
    """weight_bits != activation_bits: the frozen rescale must divide by
    the weight's own bin count, not the activation's."""
    import numpy as np

    from paddle_tpu.contrib.quantize import QuantizeTranspiler

    fluid.reset_default_env()
    x = layers.data("x", [8], dtype="float32")
    pred = layers.fc(x, size=4, bias_attr=False)
    qt = QuantizeTranspiler(weight_bits=4, activation_bits=8)
    qt.training_transpile()
    test_prog = fluid.default_main_program().clone(for_test=True)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(3)
    xv = rng.randn(5, 8).astype("float32")
    (qat,) = exe.run(program=test_prog, feed={"x": xv},
                     fetch_list=[pred.name])
    qt.freeze_program(test_prog)
    (frozen,) = exe.run(program=test_prog, feed={"x": xv},
                        fetch_list=[pred.name])
    # 4-bit weights are coarse; magnitudes must still agree (a wrong bin
    # count would be off by ~7/127 = 18x)
    np.testing.assert_allclose(np.asarray(frozen), np.asarray(qat),
                               atol=0.15, rtol=0.25)
