"""Distributed stack: transpiler API, sharded embeddings over the mesh,
AsyncExecutor (reference: test_dist_transpiler.py, test_dist_base.py
"dist loss ~= local loss" harness, test_async_executor.py)."""

import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.parallel import ParallelExecutor, make_mesh


def _build_model(seed=0):
    rng = np.random.RandomState(seed)
    x = layers.data("x", [8], dtype="float32")
    y = layers.data("y", [1], dtype="float32")
    pred = layers.fc(x, size=1, param_attr=fluid.ParamAttr(name="w"))
    loss = layers.mean(layers.square_error_cost(pred, y))
    fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
    return loss


def test_transpiler_pserver_program_inspection():
    loss = _build_model()
    config = fluid.DistributeTranspilerConfig()
    t = fluid.DistributeTranspiler(config=config)
    eps = "127.0.0.1:6174,127.0.0.1:6175"
    t.transpile(trainer_id=0, pservers=eps, trainers=2)
    trainer_prog = t.get_trainer_program()
    assert trainer_prog is fluid.default_main_program()
    # every param's optimizer op lands on exactly one endpoint
    n_params = len(fluid.default_main_program().global_block().all_parameters())
    found = 0
    for ep in eps.split(","):
        ps = t.get_pserver_program(ep)
        found += sum(1 for op in ps.desc.block(0).ops if op.type == "sgd")
    assert found == n_params == 2  # fc weight 'w' + fc bias


def test_slice_variable_blocks():
    from paddle_tpu.transpiler import slice_variable

    class V:
        def __init__(self, name, shape):
            self.name, self.shape = name, shape

    blocks = slice_variable([V("p", [100, 100])], 4, min_block_size=1024)
    assert len(blocks) == 4
    assert sum(b[2] for b in blocks) == 100 * 100


def test_dist_loss_matches_local_loss():
    """The reference's core distributed assertion (test_dist_base.py:502):
    N-way data-parallel training over the mesh produces the same losses as
    serial execution on the same global batch."""
    import jax

    rng = np.random.RandomState(0)
    xv = rng.randn(16, 8).astype("float32")
    yv = rng.randn(16, 1).astype("float32")

    def run(parallel):
        from paddle_tpu.core import framework, scope as scope_mod

        framework.switch_main_program(fluid.Program())
        framework.switch_startup_program(fluid.Program())
        scope_mod._current_scope = scope_mod.Scope()
        loss = _build_model()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        # identical init for both runs
        fluid.global_scope().set_var(
            "w", np.linspace(-1, 1, 8).astype("float32").reshape(8, 1)
        )
        losses = []
        if parallel:
            t = fluid.DistributeTranspiler(
                config=fluid.DistributeTranspilerConfig(mode="collective")
            )
            t.transpile(trainer_id=0, trainers=4)
            pe = ParallelExecutor(
                loss_name=loss.name,
                mesh=make_mesh({"dp": 4}, devices=jax.devices()[:4]),
                main_program=t.get_trainer_program(),
            )
            for _ in range(5):
                (lv,) = pe.run(fetch_list=[loss], feed={"x": xv, "y": yv})
                losses.append(float(np.ravel(np.asarray(lv))[0]))
        else:
            for _ in range(5):
                (lv,) = exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])
                losses.append(float(np.ravel(np.asarray(lv))[0]))
        return losses

    serial = run(False)
    dist = run(True)
    np.testing.assert_allclose(dist, serial, rtol=1e-5)


def test_vocab_sharded_embedding_trains():
    """The pserver sparse-table path, TPU-native: the embedding table shards
    over a model-parallel mesh axis; XLA inserts the gather collectives the
    reference did over gRPC prefetch (SURVEY 2.5)."""
    V, E = 64, 16
    ids = layers.data("ids", [1], dtype="int64", lod_level=1)
    emb = layers.embedding(
        ids, size=[V, E],
        param_attr=fluid.ParamAttr(name="table", sharding=["mp", None]),
    )
    pooled = layers.sequence_pool(emb, "sum")
    loss = layers.mean(layers.fc(pooled, size=1))
    fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)

    pe = ParallelExecutor(
        loss_name=loss.name, mesh=make_mesh({"dp": 2, "mp": 4})
    )
    from paddle_tpu.core.lod import create_lod_tensor

    rng = np.random.RandomState(0)
    feed_ids = create_lod_tensor(
        [rng.randint(0, V, size=(l, 1)).astype("int64") for l in (3, 5, 2, 4)]
    )
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    losses = []
    for _ in range(6):
        (lv,) = pe.run(fetch_list=[loss], feed={"ids": feed_ids})
        losses.append(float(np.ravel(np.asarray(lv))[0]))
    assert np.isfinite(losses).all()
    assert abs(losses[-1]) < abs(losses[0]) or losses[-1] < losses[0]


def test_async_executor_multislot(tmp_path):
    # MultiSlot files: sparse id slot + dense float label slot
    rng = np.random.RandomState(0)
    files = []
    for fi in range(3):
        p = tmp_path / f"part-{fi}"
        with open(p, "w") as f:
            for _ in range(8):
                n = rng.randint(1, 5)
                ids = rng.randint(0, 50, size=n)
                label = float(rng.randint(0, 2))
                f.write(
                    f"{n} " + " ".join(map(str, ids)) + f" 1 {label}\n"
                )
        files.append(str(p))

    ids = layers.data("words", [1], dtype="int64", lod_level=1)
    label = layers.data("label", [1], dtype="float32")
    emb = layers.embedding(ids, size=[50, 8])
    pooled = layers.sequence_pool(emb, "sum")
    pred = layers.fc(pooled, size=1, act="sigmoid")
    loss = layers.mean(layers.log_loss(pred, label))
    fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)

    desc = fluid.DataFeedDesc(proto_desc="""
name: "MultiSlotDataFeed"
batch_size: 4
multi_slot_desc {
  slots { name: "words" type: "uint64" is_dense: false is_used: true }
  slots { name: "label" type: "float" is_dense: true is_used: true }
}
""")
    exe = fluid.AsyncExecutor(fluid.CPUPlace())
    fluid.Executor(fluid.CPUPlace()).run(fluid.default_startup_program())
    exe.run(
        fluid.default_main_program(), desc, files, thread_num=2,
        fetch=[loss],
    )
    # table moved => training happened
    tbl = np.asarray(fluid.global_scope().find_var(
        fluid.default_main_program().global_block().all_parameters()[0].name
    ))
    assert np.abs(tbl).sum() > 0


def test_dc_asgd_pserver_program():
    """enable_dc_asgd rewrites the pserver optimize block with delay
    compensation: g_dc = g + lambda*g*g*(param - param_bak)
    (reference: distribute_transpiler.py:869 _append_dc_asgd_ops)."""
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu.transpiler import (
        DistributeTranspiler, DistributeTranspilerConfig,
    )

    fluid.reset_default_env()
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    pred = fluid.layers.fc(x, 1)
    loss = fluid.layers.reduce_mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(0.1).minimize(loss)

    cfg = DistributeTranspilerConfig()
    cfg.enable_dc_asgd = True
    cfg.slice_var_up = False
    lam = cfg.dc_asgd_lambda
    t = DistributeTranspiler(config=cfg)
    eps = ["127.0.0.1:6170"]
    t.transpile(trainer_id=0, pservers=",".join(eps), trainers=1)
    prog = t.get_pserver_program(eps[0])
    types = [op.type for op in prog.desc.block(0).ops]
    assert "elementwise_mul" in types and "assign" in types
    assert "sgd" in types

    # execute the pserver block: feed param/grad/lr, check the DC update
    rng = np.random.RandomState(0)
    block = prog.desc.block(0)
    scope = fluid.global_scope().new_scope()
    inits = {}
    for op in block.ops:
        if op.type != "sgd":
            continue
        pn = op.input("Param")[0]
        gn = pn + "@GRAD"  # grads feed the DC chain under their source name
        shape = [abs(d) for d in block.vars[pn].shape]
        inits[pn] = rng.randn(*shape).astype("float32")
        inits[pn + "@BAK"] = rng.randn(*shape).astype("float32")
        scope.set_var(op.input("LearningRate")[0],
                      np.array([0.1], dtype="float32"))
    # DC chains read the original grad names: find them from the mul ops
    for op in block.ops:
        if op.type == "elementwise_mul" and op.input("X") == op.input("Y"):
            gn = op.input("X")[0]
            shape = [abs(d) for d in block.vars[gn].shape]
            inits[gn] = rng.randn(*shape).astype("float32")
    for n, v in inits.items():
        scope.set_var(n, v)
    sgd_op = [op for op in block.ops if op.type == "sgd"][0]
    pname = sgd_op.input("Param")[0]
    gname = [op for op in block.ops
             if op.type == "elementwise_mul"
             and op.output("Out")[0].startswith(pname)][0].input("X")[0]
    p0, g0, bak0 = inits[pname], inits[gname], inits[pname + "@BAK"]
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(program=prog, feed={}, fetch_list=[])
    g_dc = g0 + lam * g0 * g0 * (p0 - bak0)
    want = p0 - 0.1 * g_dc
    np.testing.assert_allclose(np.asarray(scope.find_var(pname)), want,
                               rtol=1e-5)
    # param_bak snapshots the updated param
    np.testing.assert_allclose(np.asarray(scope.find_var(pname + "@BAK")),
                               want, rtol=1e-5)


def test_dc_asgd_startup_initializes_bak():
    """The public get_pserver_programs() pair runs out of the box: startup
    initializes param@BAK from the param (review finding r2)."""
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu.transpiler import (
        DistributeTranspiler, DistributeTranspilerConfig,
    )

    fluid.reset_default_env()
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    pred = fluid.layers.fc(x, 1)
    loss = fluid.layers.reduce_mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(0.1).minimize(loss)

    cfg = DistributeTranspilerConfig()
    cfg.enable_dc_asgd = True
    cfg.slice_var_up = False
    t = DistributeTranspiler(config=cfg)
    ep = "127.0.0.1:6170"
    t.transpile(trainer_id=0, pservers=ep, trainers=1)
    prog, startup = t.get_pserver_programs(ep)

    scope = fluid.global_scope().new_scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(program=startup)
        # grads arrive from trainers; zero grads -> params unchanged
        for op in prog.desc.block(0).ops:
            if op.type == "elementwise_mul" and op.input("X") == op.input("Y"):
                gn = op.input("X")[0]
                shape = [abs(d) for d in prog.desc.block(0).vars[gn].shape]
                scope.set_var(gn, np.zeros(shape, dtype="float32"))
        exe.run(program=prog, feed={}, fetch_list=[])


def test_pserver_program_executes_sgd_update():
    """Run (not just inspect) a transpiled pserver optimize program
    (reference pattern: test_dist_base.py starts real pserver processes;
    here the optimize block the listen_and_serv loop would run is executed
    directly and its SGD math checked)."""
    fluid.reset_default_env()
    _build_model()
    t = fluid.DistributeTranspiler()
    eps = "127.0.0.1:6174,127.0.0.1:6175"
    t.transpile(trainer_id=0, pservers=eps, trainers=1)

    ran_any = False
    for ep in eps.split(","):
        prog = t.get_pserver_program(ep)
        opt_ops = list(prog.desc.block(0).ops)
        if not opt_ops:
            continue
        for op in opt_ops:
            assert op.type == "sgd"
            pname = op.input("Param")[0]
            gname = op.input("Grad")[0]
            lrname = op.input("LearningRate")[0]
            pdesc = prog.global_block().vars[pname]
            shape = [int(s) for s in pdesc.shape]
            rng = np.random.RandomState(7)
            p0 = rng.rand(*shape).astype("float32")
            g0 = rng.rand(*shape).astype("float32")
            scope = fluid.Scope()
            exe = fluid.Executor(fluid.CPUPlace())
            (p1,) = exe.run(
                program=prog,
                feed={pname: p0, gname: g0,
                      lrname: np.array([0.1], "float32")},
                fetch_list=[pname], scope=scope)
            np.testing.assert_allclose(
                np.asarray(p1), p0 - 0.1 * g0, rtol=1e-5,
                err_msg=f"pserver sgd update wrong for {pname} on {ep}")
            ran_any = True
    assert ran_any, "no pserver endpoint owned any optimize op"
