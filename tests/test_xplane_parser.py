"""tools/xplane.py: minimal protobuf wire-format reader for profiler dumps.
The fixture hand-encodes a tiny XSpace so the parser is pinned to the wire
format, not to any installed protobuf."""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

from xplane import device_op_times, parse_xspace  # noqa: E402


def _varint(n):
    out = b""
    while True:
        b7 = n & 0x7F
        n >>= 7
        out += bytes([b7 | (0x80 if n else 0)])
        if not n:
            return out


def _field(num, wt, payload):
    tag = _varint(num << 3 | wt)
    if wt == 2:
        return tag + _varint(len(payload)) + payload
    return tag + _varint(payload)


def _xspace():
    # event: metadata_id=7, duration_ps=2_000_000 (2 us)
    ev1 = _field(1, 0, 7) + _field(3, 0, 2_000_000)
    ev2 = _field(1, 0, 9) + _field(3, 0, 1_000_000)
    line_ops = (_field(2, 2, b"XLA Ops")
                + _field(4, 2, ev1) + _field(4, 2, ev1) + _field(4, 2, ev2))
    line_steps = _field(2, 2, b"Steps") + _field(4, 2, ev2)
    meta7 = _field(1, 0, 7) + _field(2, 2, b"fusion.1")
    meta9 = _field(1, 0, 9) + _field(2, 2, b"convolution.3")
    entry7 = _field(1, 0, 7) + _field(2, 2, meta7)
    entry9 = _field(1, 0, 9) + _field(2, 2, meta9)
    plane = (_field(2, 2, b"/device:TPU:0")
             + _field(3, 2, line_ops) + _field(3, 2, line_steps)
             + _field(4, 2, entry7) + _field(4, 2, entry9))
    host = _field(2, 2, b"/host:CPU") + _field(3, 2, line_steps)
    return _field(1, 2, plane) + _field(1, 2, host)


def test_parse_xspace_structure():
    planes = parse_xspace(_xspace())
    assert [p["name"] for p in planes] == ["/device:TPU:0", "/host:CPU"]
    tpu = planes[0]
    assert tpu["event_metadata"] == {7: "fusion.1", 9: "convolution.3"}
    assert [name for name, _ in tpu["lines"]] == ["XLA Ops", "Steps"]


def test_device_op_times_aggregates_ops_line_only():
    totals = device_op_times(_xspace())
    # two fusion.1 events at 2us + one convolution.3 at 1us; the Steps line
    # and the host plane must not contribute
    np.testing.assert_allclose(totals["fusion.1"], 4.0)
    np.testing.assert_allclose(totals["convolution.3"], 1.0)
    assert set(totals) == {"fusion.1", "convolution.3"}


def test_device_op_times_host_fallback():
    host_only = _field(1, 2, _field(2, 2, b"/host:CPU") + _field(
        3, 2, _field(2, 2, b"python") + _field(
            4, 2, _field(1, 0, 1) + _field(3, 0, 5_000_000))))
    totals = device_op_times(host_only)
    assert sum(totals.values()) == 5.0
