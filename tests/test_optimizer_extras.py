"""Proximal optimizer classes, GradientMergeOptimizer, ModelAverage
(reference: optimizer.py ProximalGDOptimizer/ProximalAdagradOptimizer,
the multi_batch_merge pass, optimizer.py:1373 ModelAverage)."""

import numpy as np

import paddle_tpu as fluid


def _regression_problem(seed=0):
    fluid.reset_default_env()
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    pred = fluid.layers.fc(x, 1)
    loss = fluid.layers.reduce_mean(fluid.layers.square_error_cost(pred, y))
    rng = np.random.RandomState(seed)
    xs = rng.randn(16, 4).astype("float32")
    ys = (xs @ rng.randn(4, 1) + 0.1).astype("float32")
    return loss, xs, ys


def _train(loss, xs, ys, steps=30):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    losses = []
    for i in range(steps):
        (lv,) = exe.run(feed={"x": xs, "y": ys}, fetch_list=[loss])
        losses.append(float(np.ravel(lv)[0]))
    return exe, losses


def test_proximal_gd_trains():
    loss, xs, ys = _regression_problem(1)
    fluid.optimizer.ProximalGDOptimizer(0.05, l1=1e-4, l2=1e-4).minimize(loss)
    _, losses = _train(loss, xs, ys)
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_proximal_adagrad_trains():
    loss, xs, ys = _regression_problem(2)
    fluid.optimizer.ProximalAdagradOptimizer(
        0.1, l1=1e-4, l2=1e-4).minimize(loss)
    _, losses = _train(loss, xs, ys)
    assert losses[-1] < losses[0] * 0.5


def test_gradient_merge_matches_big_batch_sgd():
    """k accumulation steps on batch shards == one SGD step on the merged
    batch (averaged grads): final params must match to fp tolerance."""
    k = 4
    rng = np.random.RandomState(3)
    xs = rng.randn(8, 4).astype("float32")
    ys = (xs @ rng.randn(4, 1)).astype("float32")
    shards = [(xs[i::k], ys[i::k]) for i in range(k)]

    def params(prog):
        from paddle_tpu.core.framework import Parameter

        scope = fluid.global_scope()
        return {
            n: np.asarray(scope.find_var(n))
            for n, v in prog.global_block().vars.items()
            if isinstance(v, Parameter)
        }

    # merged: k shard-steps per apply, 2 applies
    loss, _, _ = _regression_problem(3)
    inner = fluid.optimizer.SGD(0.1)
    fluid.optimizer.GradientMergeOptimizer(inner, k_steps=k).minimize(loss)
    prog1 = fluid.default_main_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    w0 = params(prog1)
    for _ in range(2):
        for sx, sy in shards:
            exe.run(feed={"x": sx, "y": sy}, fetch_list=[loss])
    merged_params = params(prog1)

    # reference: big-batch SGD with lr scaled by shard/batch loss weighting:
    # mean-loss over shard then averaged over k == mean-loss over the full
    # batch (equal shard sizes), so plain SGD(0.1) on the full batch matches
    loss2, _, _ = _regression_problem(3)
    fluid.optimizer.SGD(0.1).minimize(loss2)
    prog2 = fluid.default_main_program()
    exe2 = fluid.Executor(fluid.CPUPlace())
    exe2.run(fluid.default_startup_program())
    # identical init: copy program-1 params onto program-2's (sorted pairing)
    p2names = sorted(params(prog2), key=lambda n: n.split(".")[-1])
    for (n1, v), n2 in zip(sorted(w0.items(),
                                  key=lambda kv: kv[0].split(".")[-1]),
                           p2names):
        fluid.global_scope().set_var(n2, v)
    for _ in range(2):
        exe2.run(feed={"x": xs, "y": ys}, fetch_list=[loss2])
    ref_params = params(prog2)

    # param names differ between programs (session-wide unique_name
    # counter); compare in sorted-suffix order (.w vs .b)
    mk = sorted(merged_params, key=lambda n: n.split(".")[-1])
    rk = sorted(ref_params, key=lambda n: n.split(".")[-1])
    assert len(mk) == len(rk) == 2
    for a, b in zip(mk, rk):
        np.testing.assert_allclose(
            merged_params[a], ref_params[b], rtol=2e-4, atol=2e-5,
            err_msg=f"{a} vs {b}")


def test_model_average_apply_restore():
    loss, xs, ys = _regression_problem(4)
    fluid.optimizer.SGD(0.1).minimize(loss)
    ma = fluid.optimizer.ModelAverage(0.15)
    exe, losses = _train(loss, xs, ys, steps=20)
    scope = fluid.global_scope()
    pname = [n for n in scope.local_var_names()
             if n.startswith("fc_") and ".w" in n][0]
    trained = np.asarray(scope.find_var(pname)).copy()
    with ma.apply(exe):
        averaged = np.asarray(scope.find_var(pname))
        assert not np.allclose(averaged, trained)  # swapped in
        # eval still runs with averaged weights
        (lv,) = exe.run(feed={"x": xs, "y": ys}, fetch_list=[loss])
        assert np.isfinite(float(np.ravel(lv)[0]))
    restored = np.asarray(scope.find_var(pname))
    np.testing.assert_array_equal(restored, trained)


def test_model_average_reenter_guard_and_accumulator_snapshot():
    loss, xs, ys = _regression_problem(5)
    fluid.optimizer.SGD(0.1).minimize(loss)
    ma = fluid.optimizer.ModelAverage(0.15)
    exe, _ = _train(loss, xs, ys, steps=5)
    scope = fluid.global_scope()
    sums_before = {
        sn: np.asarray(scope.find_var(sn)).copy()
        for sn in ma._param_sums.values()
    }
    with ma.apply(exe):
        # eval runs the program (accumulation ops execute) but must not
        # pollute the running sums after restore
        exe.run(feed={"x": xs, "y": ys}, fetch_list=[loss])
        import pytest as _pytest
        with _pytest.raises(RuntimeError, match="re-entered"):
            ma._swap_in_averages(scope)
    for sn, want in sums_before.items():
        np.testing.assert_array_equal(np.asarray(scope.find_var(sn)), want)


def test_model_average_three_tier_window_rotates():
    """Small window: the average must cover only the current window (sum_3
    rotation, average_accumulates_op.h), not all history."""
    loss, xs, ys = _regression_problem(6)
    fluid.optimizer.SGD(0.1).minimize(loss)
    ma = fluid.optimizer.ModelAverage(
        1.0, min_average_window=2, max_average_window=3)
    exe, _ = _train(loss, xs, ys, steps=7)
    scope = fluid.global_scope()
    accs = next(iter(ma._param_accs.values()))
    ona = int(np.ravel(np.asarray(scope.find_var(accs["old_num_accumulates"])))[0])
    nu = int(np.ravel(np.asarray(scope.find_var(accs["num_updates"])))[0])
    assert nu == 7
    assert 0 < ona <= 3  # the window closed at least once and is bounded
    with ma.apply(exe):
        pass  # swap + restore round-trips with the tiered sums
