"""Tiered KV cache (ISSUE 18): spill idle sessions to host RAM.

Acceptance pinned here:
(a) a session that spills and resumes between EVERY turn is
    token-identical to a never-spilled resident oracle across
    H_kv ∈ {8, 2} × {fp32, int8} × prefix-cache hit/miss, with zero
    pages leaked in either tier and invariants green mid-park;
(b) admission reserves against the COMBINED tier: more concurrent
    sessions than HBM fits stay resumable (``make_room`` spills on
    demand), every turn still token-identical to ``full_decode``;
(c) victim policy: idle sessions spill LRU-first; a bounded host tier
    LRU-evicts parked payloads (their next turn re-prefills, counted);
(d) pool pressure (the reclaimer hook inside ``append_tokens``)
    proactively spills idle sessions inline;
(e) tier-aware audits: a parked session's pinned prefix pages are
    OWNED (``check_invariants`` ok, ``reclaim_orphans`` repairs
    nothing), and a corrupted host payload fails the tier audit;
(f) int8 exports round-trip the host tier byte-identical, scales
    included;
(g) a retained-history mismatch resets the session typed (resident and
    parked arms) instead of resuming the wrong KV;
(h) tier observability is gated: FLAGS_observability off mints NO tier
    metrics; on, the spill/resume counters, transfer bytes, and
    occupancy gauges appear.
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import observability as obs
from paddle_tpu.serving import (
    ContinuousBatchingLoop,
    DecodeConfig,
    DecodeRequest,
    HostKVTier,
    HostTierFullError,
    KVCachePool,
    PrefixCache,
    TieredSessionManager,
    full_decode,
    init_decode_params,
)


def _cfg(**kw):
    base = dict(vocab_size=61, d_model=16, n_head=2, n_layer=2,
                d_inner=32, max_length=64)
    base.update(kw)
    return DecodeConfig(**base)


def _pool(cfg, num_pages=64, page_size=4, dtype="float32"):
    return KVCachePool(num_pages=num_pages, page_size=page_size,
                       num_layers=cfg.n_layer, num_heads=cfg.n_head,
                       head_dim=cfg.head_dim, dtype=dtype)


def _multi_turn(loop, mgr, first_prompt, extras, max_new,
                spill_each=False):
    """Drive one chat session: each turn's prompt is the full
    transcript (previous prompt + generated + the user's new tokens).
    With ``spill_each`` the session round-trips the host tier between
    every turn, auditing both tiers mid-park."""
    sess = mgr.open_session()
    outs = []
    p = list(first_prompt)
    for i in range(len(extras) + 1):
        if i:
            p = p + outs[-1] + list(extras[i - 1])
        (res,) = loop.run([DecodeRequest(prompt=list(p),
                                         max_new_tokens=max_new,
                                         session=sess)])
        assert res.error is None, res.error
        outs.append(res.tokens)
        if spill_each:
            assert mgr.spill(sess, wait=True), sess.state
            assert sess.state == "parked"
            rep = mgr.check_invariants()
            assert rep["ok"], rep
    return sess, outs


# -- (a) the headline parity matrix --------------------------------------

@pytest.mark.parametrize("dtype", ["float32", "int8"])
@pytest.mark.parametrize("n_head", [2, 8])
@pytest.mark.parametrize("with_cache", [True, False])
def test_spill_resume_parity_matrix(dtype, n_head, with_cache):
    cfg = _cfg(n_head=n_head, d_model=8 * n_head)
    params = init_decode_params(cfg, seed=5)
    rng = np.random.RandomState(5)
    ps, max_new = 4, 4
    prompt1 = rng.randint(1, cfg.vocab_size, size=9).tolist()
    extras = [rng.randint(1, cfg.vocab_size, size=3).tolist()
              for _ in range(2)]

    def run(spill_each):
        pool = _pool(cfg, num_pages=64, page_size=ps, dtype=dtype)
        cache = PrefixCache(pool) if with_cache else None
        mgr = TieredSessionManager(pool, prefix_cache=cache,
                                   host_bytes=1 << 26)
        loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=2,
                                      prefix_cache=cache,
                                      session_manager=mgr)
        sess, outs = _multi_turn(loop, mgr, prompt1, extras, max_new,
                                 spill_each=spill_each)
        if spill_each and with_cache:
            # the spill pinned the cached full-page prefix and shipped
            # only the unshared tail host-side
            assert sess.pinned_tokens > 0
        st = mgr.stats()
        mgr.close()
        if cache is not None:
            cache.clear()
        # zero pages leaked in EITHER tier
        assert pool.used_pages == 0, pool.used_pages
        assert pool.check_invariants()["ok"]
        assert len(mgr.tier) == 0
        return outs, st, loop

    outs_resident, st_res, _ = run(spill_each=False)
    outs_spilled, st_sp, loop_sp = run(spill_each=True)

    # token-identical to the never-spilled oracle, every turn
    assert outs_spilled == outs_resident
    assert st_sp["spills"] == 3 and st_sp["resumed_host"] == 2
    assert st_sp["re_prefills"] == 0
    assert st_res["spills"] == 0 and st_res["resumed_resident"] == 2
    assert loop_sp.session_resumes == 2
    assert loop_sp.session_resumed_tokens > 0
    if dtype == "float32":
        # fp32 also matches the full-recompute transcript oracle
        p = list(prompt1)
        for i, out in enumerate(outs_spilled):
            if i:
                p = p + outs_spilled[i - 1] + extras[i - 1]
            assert out == full_decode(params, cfg, p, max_new)[0]


# -- (b) combined-tier admission -----------------------------------------

def test_combined_tier_admits_more_sessions_than_hbm_fits():
    cfg = _cfg()
    params = init_decode_params(cfg, seed=7)
    rng = np.random.RandomState(7)
    ps, max_new = 4, 4
    # a retired turn retains 12 tokens (9 prompt + 3 appended) = 3
    # pages, so 12 pages = at most 4 resident sessions; we keep 6 open
    pool = _pool(cfg, num_pages=12, page_size=ps)
    mgr = TieredSessionManager(pool, host_bytes=1 << 26)
    loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=1,
                                  session_manager=mgr)
    sessions = [mgr.open_session() for _ in range(6)]
    prompts = [rng.randint(1, cfg.vocab_size, size=9).tolist()
               for _ in range(6)]
    extras = [rng.randint(1, cfg.vocab_size, size=3).tolist()
              for _ in range(6)]

    transcripts = []
    for s, p in zip(sessions, prompts):
        (r,) = loop.run([DecodeRequest(prompt=list(p),
                                       max_new_tokens=max_new,
                                       session=s)])
        assert r.error is None, r.error
        assert r.tokens == full_decode(params, cfg, p, max_new)[0]
        transcripts.append(list(p) + r.tokens)
    st = mgr.stats()
    # all 6 sessions are retained although HBM only fits 4: admission
    # spilled idle victims through make_room
    assert st["sessions"] == 6
    assert st["spills"] >= 2 and st["parked_sessions"] >= 2
    retained = sum(len(t) for t in transcripts)
    assert retained > pool.num_pages * ps  # > no-tier session capacity

    # turn 2 on every session, oldest (certainly parked) first
    for s, t, ext in zip(sessions, transcripts, extras):
        p2 = t + list(ext)
        (r,) = loop.run([DecodeRequest(prompt=list(p2),
                                       max_new_tokens=max_new,
                                       session=s)])
        assert r.error is None, r.error
        assert r.tokens == full_decode(params, cfg, p2, max_new)[0]
    st = mgr.stats()
    assert st["resumes"] == 6 and st["resumed_host"] >= 1
    assert st["re_prefills"] == 0

    rep = mgr.check_invariants()
    assert rep["ok"], rep
    mgr.close()
    assert pool.used_pages == 0
    assert pool.check_invariants()["ok"]
    assert len(mgr.tier) == 0


# -- (c) victim policy ----------------------------------------------------

def _idle_sessions(mgr, loop, params, cfg, rng, n, max_new=3):
    sessions = []
    for _ in range(n):
        s = mgr.open_session()
        p = rng.randint(1, cfg.vocab_size, size=9).tolist()
        (r,) = loop.run([DecodeRequest(prompt=p, max_new_tokens=max_new,
                                       session=s)])
        assert r.error is None, r.error
        sessions.append(s)
    return sessions


def test_idle_victims_spill_lru_first():
    cfg = _cfg()
    params = init_decode_params(cfg, seed=3)
    rng = np.random.RandomState(3)
    pool = _pool(cfg, num_pages=32, page_size=4)
    mgr = TieredSessionManager(pool, host_bytes=1 << 26)
    loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=1,
                                  session_manager=mgr)
    s0, s1, s2 = _idle_sessions(mgr, loop, params, cfg, rng, 3)
    s0.last_used, s1.last_used, s2.last_used = 0.0, 1.0, 2.0
    # one session's worth of pressure: only the LRU victim spills
    freed = mgr.make_room(3)
    assert freed >= 3
    assert s0.state == "parked"
    assert s1.state == "idle" and s2.state == "idle"
    mgr.close()
    assert pool.used_pages == 0 and len(mgr.tier) == 0


def test_bounded_host_tier_evicts_lru_parked():
    cfg = _cfg()
    params = init_decode_params(cfg, seed=4)
    rng = np.random.RandomState(4)

    # phase 1: measure one parked payload's size, unbounded
    pool = _pool(cfg, num_pages=32, page_size=4)
    mgr = TieredSessionManager(pool, host_bytes=1 << 26)
    loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=1,
                                  session_manager=mgr)
    (s,) = _idle_sessions(mgr, loop, params, cfg,
                          np.random.RandomState(4), 1)
    assert mgr.spill(s, wait=True)
    one = s.parked_bytes
    assert one > 0
    mgr.close()

    # phase 2: a host tier that fits ONE payload; parking the second
    # LRU-evicts the first (its session resets, next turn re-prefills)
    pool = _pool(cfg, num_pages=32, page_size=4)
    mgr = TieredSessionManager(pool, host_bytes=int(1.5 * one))
    loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=1,
                                  session_manager=mgr)
    s0, s1 = _idle_sessions(mgr, loop, params, cfg, rng, 2)
    assert mgr.spill(s0, wait=True) and s0.state == "parked"
    assert mgr.spill(s1, wait=True) and s1.state == "parked"
    assert s0.state == "fresh"  # LRU-evicted to make room, not lost
    st = mgr.stats()
    assert st["evictions"] >= 1
    assert len(mgr.tier) == 1
    mgr.close()
    assert pool.used_pages == 0 and len(mgr.tier) == 0


def test_host_tier_park_raises_typed_when_unevictable():
    cfg = _cfg()
    pool = _pool(cfg, num_pages=8, page_size=4)
    pool.allocate(7)
    pool.append_tokens([7], [8])
    exp = pool.export_seq(7)
    tier = HostKVTier(capacity_bytes=max(1, exp.nbytes() - 1))
    with pytest.raises(HostTierFullError):
        tier.park("a", exp)
    assert len(tier) == 0 and tier.bytes_used == 0


# -- (d) pool pressure spills proactively --------------------------------

def test_pool_pressure_reclaimer_spills_idle_sessions():
    cfg = _cfg()
    params = init_decode_params(cfg, seed=9)
    rng = np.random.RandomState(9)
    pool = _pool(cfg, num_pages=12, page_size=4)
    mgr = TieredSessionManager(pool, host_bytes=1 << 26)
    loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=1,
                                  session_manager=mgr)
    (s,) = _idle_sessions(mgr, loop, params, cfg, rng, 1)
    assert s.state == "idle"
    used = pool.used_pages
    # claim more pages than are free: append_tokens runs the
    # registered reclaimer mid-claim, which spills the idle session
    # INLINE (under the pool lock) instead of failing the claim
    pool.allocate(99)
    need_tokens = (pool.num_pages - used + 1) * pool.page_size
    pool.append_tokens([99], [need_tokens])
    assert s.state == "parked"
    assert mgr.stats()["pressure_spills"] >= 1
    pool.free_seq(99)
    rep = mgr.check_invariants()
    assert rep["ok"], rep
    mgr.close()
    assert pool.used_pages == 0 and len(mgr.tier) == 0


# -- (e) tier-aware audits mid-park --------------------------------------

def test_invariants_and_orphan_repair_mid_park():
    cfg = _cfg()
    params = init_decode_params(cfg, seed=6)
    rng = np.random.RandomState(6)
    ps = 4
    pool = _pool(cfg, num_pages=32, page_size=ps)
    cache = PrefixCache(pool)
    mgr = TieredSessionManager(pool, prefix_cache=cache,
                               host_bytes=1 << 26)
    loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=1,
                                  prefix_cache=cache,
                                  session_manager=mgr)
    s = mgr.open_session()
    p = rng.randint(1, cfg.vocab_size, size=9).tolist()
    (r,) = loop.run([DecodeRequest(prompt=p, max_new_tokens=4,
                                   session=s)])
    assert r.error is None
    assert mgr.spill(s, wait=True) and s.state == "parked"
    assert s.pinned_pages, "prefix pages should stay pinned mid-park"

    # a parked session's pinned pages are OWNED, not orphaned: the
    # audit is green and the repair arm must not free them
    assert pool.check_invariants()["ok"]
    used_before = pool.used_pages
    assert pool.reclaim_orphans() == 0
    assert pool.used_pages == used_before
    rep = mgr.check_invariants()
    assert rep["ok"] and rep["pool"]["ok"] and rep["tier"]["ok"]

    # teeth: a flipped payload byte fails the HOST tier audit
    entry = next(iter(mgr.tier._entries.values()))
    entry.export.k = entry.export.k.copy()  # exports of jax pools are RO
    entry.export.k.reshape(-1).view(np.uint8)[0] ^= 0xFF
    rep = mgr.check_invariants()
    assert not rep["ok"] and not rep["tier"]["ok"]
    assert rep["tier"]["errors"]
    entry.export.k.reshape(-1).view(np.uint8)[0] ^= 0xFF  # restore
    assert mgr.check_invariants()["ok"]
    mgr.close()
    cache.clear()
    assert pool.used_pages == 0 and len(mgr.tier) == 0


# -- (f) int8 payloads round-trip the host tier byte-identical -----------

def test_int8_export_roundtrips_host_tier_with_scales():
    cfg = _cfg()
    pool = _pool(cfg, num_pages=8, page_size=4, dtype="int8")
    pool.allocate(7)
    pool.append_tokens([7], [10])
    rng = np.random.RandomState(0)
    import jax.numpy as jnp

    pool.k_pages = jnp.asarray(rng.randint(
        -128, 128, size=pool.k_pages.shape).astype(np.int8))
    pool.v_pages = jnp.asarray(rng.randint(
        -128, 128, size=pool.v_pages.shape).astype(np.int8))
    pool.k_scales[:] = rng.rand(*pool.k_scales.shape)
    pool.v_scales[:] = rng.rand(*pool.v_scales.shape)
    exp = pool.export_seq(7)
    tier = HostKVTier(capacity_bytes=1 << 24)
    tier.park("s", exp)
    assert tier.check_invariants()["ok"]
    back = tier.fetch("s")
    assert back.k.tobytes() == exp.k.tobytes()
    assert back.v.tobytes() == exp.v.tobytes()
    assert back.k_scales.tobytes() == exp.k_scales.tobytes()
    assert back.v_scales.tobytes() == exp.v_scales.tobytes()
    assert len(tier) == 0 and tier.bytes_used == 0


# -- (g) history mismatch degrades typed ---------------------------------

def test_history_mismatch_resets_instead_of_resuming_wrong_kv():
    cfg = _cfg()
    params = init_decode_params(cfg, seed=8)
    pool = _pool(cfg, num_pages=32, page_size=4)
    mgr = TieredSessionManager(pool, host_bytes=1 << 26)
    loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=1,
                                  session_manager=mgr)

    # parked arm: a next-turn prompt unrelated to the parked history
    # discards the payload and prefills fresh — still correct
    s = mgr.open_session()
    p1 = [5, 1, 2, 3, 4, 5, 6, 7, 8]
    (r,) = loop.run([DecodeRequest(prompt=list(p1), max_new_tokens=3,
                                   session=s)])
    assert r.error is None
    assert mgr.spill(s, wait=True)
    p_other = [7, 9, 11, 13, 15, 17, 19]
    (r,) = loop.run([DecodeRequest(prompt=list(p_other),
                                   max_new_tokens=3, session=s)])
    assert r.error is None
    assert r.tokens == full_decode(params, cfg, p_other, 3)[0]
    st = mgr.stats()
    assert st["mismatch_resets"] >= 1 and st["evictions"] >= 1

    # resident arm: a first-token divergence against resident KV
    # resets too (common prefix 0 — nothing worth keeping)
    p_other2 = [11, 2, 4, 6, 8, 10, 12, 14]
    (r,) = loop.run([DecodeRequest(prompt=list(p_other2),
                                   max_new_tokens=3, session=s)])
    assert r.error is None
    assert r.tokens == full_decode(params, cfg, p_other2, 3)[0]
    assert mgr.stats()["mismatch_resets"] >= 2
    mgr.close()
    assert pool.used_pages == 0 and len(mgr.tier) == 0


# -- (h) observability is gated ------------------------------------------

def _tiered_turns():
    cfg = _cfg()
    params = init_decode_params(cfg, seed=2)
    rng = np.random.RandomState(2)
    pool = _pool(cfg, num_pages=32, page_size=4)
    mgr = TieredSessionManager(pool, host_bytes=1 << 26)
    loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=1,
                                  session_manager=mgr)
    p1 = rng.randint(1, cfg.vocab_size, size=9).tolist()
    _, outs = _multi_turn(loop, mgr, p1,
                          [rng.randint(1, cfg.vocab_size,
                                       size=3).tolist()],
                          3, spill_each=True)
    mgr.close()
    assert pool.used_pages == 0


def test_tier_metrics_disabled_path_mints_nothing():
    obs.reset()
    try:
        _tiered_turns()  # FLAGS_observability defaults off
        names = {m.name for m in obs.default_registry().metrics()}
        assert not any("kvtier" in n or "host_tier" in n
                       for n in names), names
    finally:
        obs.reset()


def test_tier_metrics_enabled_records_events_and_gauges():
    fluid.set_flags({"FLAGS_observability": True})
    obs.reset()
    try:
        _tiered_turns()
        reg = obs.default_registry()
        ev = reg.counter("paddle_tpu_serving_kvtier_events", "")
        assert ev.value(event="spill") == 2
        assert ev.value(event="resume_host") == 1
        tx = reg.counter("paddle_tpu_serving_kvtier_transfer_bytes", "")
        assert tx.value(direction="spill") > 0
        assert tx.value(direction="resume") > 0
        names = {m.name for m in reg.metrics()}
        assert "paddle_tpu_serving_host_tier_bytes" in names
        assert "paddle_tpu_serving_parked_sessions" in names
    finally:
        obs.reset()
        fluid.set_flags({"FLAGS_observability": False})
