"""Program/Block/Operator construction + shape inference + serde
(reference analogue: framework unit tests like op_registry_test.cc and
program-text assertions in test_dist_transpiler.py)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.core.proto import DataType, ProgramDesc


def test_program_build_and_infer_shapes():
    img = fluid.layers.data("img", [784], dtype="float32")
    hidden = fluid.layers.fc(img, size=128, act="relu")
    pred = fluid.layers.fc(hidden, size=10, act="softmax")
    assert tuple(hidden.shape) == (-1, 128)
    assert tuple(pred.shape) == (-1, 10)
    prog = fluid.default_main_program()
    types = [op.type for op in prog.global_block().ops]
    assert types == ["mul", "elementwise_add", "relu", "mul", "elementwise_add", "softmax"]
    # params live in the global block and are persistable
    params = prog.global_block().all_parameters()
    assert len(params) == 4
    assert all(p.persistable for p in params)


def test_program_serde_roundtrip():
    x = fluid.layers.data("x", [4], dtype="float32")
    y = fluid.layers.fc(x, size=3)
    prog = fluid.default_main_program()
    data = prog.desc.serialize_to_string()
    clone = ProgramDesc.parse_from_string(data)
    assert clone.num_blocks() == prog.desc.num_blocks()
    assert [o.type for o in clone.block(0).ops] == [o.type for o in prog.desc.block(0).ops]
    assert clone.block(0).vars[y.name].shape == list(y.shape)


def test_program_clone_for_test_flips_dropout():
    x = fluid.layers.data("x", [4], dtype="float32")
    d = fluid.layers.dropout(x, dropout_prob=0.5)
    prog = fluid.default_main_program()
    test_prog = prog.clone(for_test=True)
    drop_ops = [op for op in test_prog.desc.block(0).ops if op.type == "dropout"]
    assert drop_ops and drop_ops[0].attrs["is_test"] is True
    # original untouched
    assert not prog.desc.block(0).ops[-1].attrs.get("is_test", False)


def test_append_backward_creates_grad_ops():
    x = fluid.layers.data("x", [4], dtype="float32")
    y = fluid.layers.fc(x, size=1)
    loss = fluid.layers.mean(y)
    params_grads = fluid.append_backward(loss)
    assert len(params_grads) == 2  # weight + bias
    prog = fluid.default_main_program()
    types = [op.type for op in prog.desc.block(0).ops]
    assert "mean_grad" in types
    assert "mul_grad" in types
    assert "elementwise_add_grad" in types
    # grad vars exist with forward shapes
    for p, g in params_grads:
        assert tuple(g.shape) == tuple(p.shape)


def test_grad_dedup_inserts_sum():
    # x used by two branches -> d(x) produced twice -> sum op expected
    x = fluid.layers.data("x", [4], dtype="float32", stop_gradient=False)
    w = fluid.layers.create_parameter([4, 4], "float32", name="w")
    h = fluid.layers.mul(x, w)
    out = fluid.layers.elementwise_add(h, h)
    loss = fluid.layers.mean(out)
    fluid.append_backward(loss)
    types = [op.type for op in fluid.default_main_program().desc.block(0).ops]
    assert "sum" in types


def test_unregistered_op_raises():
    prog = fluid.default_main_program()
    block = prog.global_block()
    block.create_var(name="z", shape=[1], dtype="float32")
    block.append_op(type="bogus_op_name", outputs={"Out": ["z"]})
    exe = fluid.Executor(fluid.CPUPlace())
    with pytest.raises(NotImplementedError):
        exe.run(prog, fetch_list=["z"])


def test_shared_parameter_gradient_accumulates():
    """A parameter consumed by two ops gets the SUM of both uses' grads
    (reference: backward.py _addup_repetitive_outputs_).  loss = x*W*W with
    x=2, W=3: dL/dW = 2*x*W = 12."""
    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.core.backward import append_backward

    x = layers.data("x", [1], dtype="float32")
    x.stop_gradient = False
    shared = fluid.ParamAttr(name="W_shared_grad_test")
    h = layers.fc(x, size=1, param_attr=shared, bias_attr=False)
    out = layers.fc(h, size=1, param_attr=shared, bias_attr=False)
    loss = layers.reduce_sum(out)
    append_backward(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    fluid.executor.global_scope().set_var(
        "W_shared_grad_test", np.array([[3.0]], dtype="float32"))
    outs = exe.run(feed={"x": np.array([[2.0]], dtype="float32")},
                   fetch_list=[loss, "W_shared_grad_test@GRAD"])
    np.testing.assert_allclose(np.asarray(outs[0]), [18.0])
    np.testing.assert_allclose(np.asarray(outs[1]), [[12.0]])
