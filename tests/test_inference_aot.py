"""AOT compiled inference artifacts (inference/aot.py): StableHLO export
round-trip, symbolic batch, parity with the live executor."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.inference import (
    load_compiled_inference_model,
    save_compiled_inference_model,
)


def _build_small_cnn():
    img = layers.data("image", [1, 8, 8], dtype="float32")
    c = layers.conv2d(img, num_filters=4, filter_size=3, padding=1)
    b = layers.batch_norm(c, act="relu")
    p = layers.pool2d(b, pool_size=8, pool_type="avg")
    pred = layers.fc(p, size=3, act="softmax")
    return img, pred


def test_aot_roundtrip_matches_executor(tmp_path):
    img, pred = _build_small_cnn()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    fetch = save_compiled_inference_model(
        str(tmp_path), ["image"], [pred], exe)
    assert fetch == [pred.name]

    test_prog = fluid.default_main_program().clone(for_test=True)
    rng = np.random.RandomState(0)
    xv = rng.rand(4, 1, 8, 8).astype(np.float32)
    (want,) = exe.run(test_prog, feed={"image": xv}, fetch_list=[pred])

    predict = load_compiled_inference_model(str(tmp_path))
    assert predict.feed_names == ["image"]
    (got,) = predict({"image": xv})
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_aot_symbolic_batch_serves_any_size(tmp_path):
    img, pred = _build_small_cnn()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    save_compiled_inference_model(str(tmp_path), ["image"], [pred], exe)
    predict = load_compiled_inference_model(str(tmp_path))
    if predict.meta["batch"] != "symbolic":
        pytest.skip("program fell back to static batch")
    for bs in (1, 5):
        (out,) = predict({"image": np.zeros((bs, 1, 8, 8), np.float32)})
        assert out.shape[0] == bs


def test_aot_rejects_missing_feed(tmp_path):
    img, pred = _build_small_cnn()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    save_compiled_inference_model(str(tmp_path), ["image"], [pred], exe)
    predict = load_compiled_inference_model(str(tmp_path))
    with pytest.raises(KeyError, match="image"):
        predict({})


def test_aot_rejects_unknown_feed(tmp_path):
    """Extra keys were silently IGNORED — an unknown feed is almost
    always a typo of a real one, so it must raise (symmetric with the
    missing-keys check), naming both the strays and the real feeds."""
    img, pred = _build_small_cnn()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    save_compiled_inference_model(str(tmp_path), ["image"], [pred], exe)
    predict = load_compiled_inference_model(str(tmp_path))
    assert "symbolic_error" in predict.meta  # the bucket planner's input
    with pytest.raises(KeyError, match="imagee"):
        predict({"image": np.zeros((1, 1, 8, 8), np.float32),
                 "imagee": np.zeros((1, 1, 8, 8), np.float32)})


def test_aot_multi_feed_symbolic_batch(tmp_path):
    """Two dynamic-batch feeds must share ONE symbolic scope — per-feed
    scopes made every multi-input model silently fall back to static."""
    a = layers.data("a", [4], dtype="float32")
    b = layers.data("b", [4], dtype="float32")
    out = layers.fc(layers.concat([a, b], axis=1), size=2)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    save_compiled_inference_model(str(tmp_path), ["a", "b"], [out], exe)
    predict = load_compiled_inference_model(str(tmp_path))
    assert predict.meta["batch"] == "symbolic", predict.meta["symbolic_error"]
    for bs in (2, 7):
        (o,) = predict({"a": np.ones((bs, 4), np.float32),
                        "b": np.ones((bs, 4), np.float32)})
        assert o.shape == (bs, 2)


def test_aot_static_artifact_validates_shapes(tmp_path, monkeypatch):
    """A static-fallback artifact must reject mismatched batch with a
    clear message, not a deep jax shape error."""
    import paddle_tpu.inference.aot as aot_mod
    from jax import export as jexport

    img, pred = _build_small_cnn()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    real = jexport.export
    calls = {"n": 0}

    def flaky_export(fn, **kw):
        wrapped = real(fn, **kw)

        def call(*specs):
            calls["n"] += 1
            if calls["n"] == 1:  # poison the symbolic attempt
                raise ValueError("synthetic: polymorphism unsupported")
            return wrapped(*specs)

        return call

    monkeypatch.setattr(jexport, "export", flaky_export)
    save_compiled_inference_model(str(tmp_path), ["image"], [pred], exe)
    predict = load_compiled_inference_model(str(tmp_path))
    assert predict.meta["batch"] == "static"
    assert "synthetic" in predict.meta["symbolic_error"]
    with pytest.raises(ValueError, match="STATIC shape"):
        predict({"image": np.zeros((4, 1, 8, 8), np.float32)})
    (out,) = predict({"image": np.zeros((1, 1, 8, 8), np.float32)})
    assert out.shape == (1, 3)
