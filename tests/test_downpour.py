"""Downpour async parameter-server mode
(reference: python/paddle/fluid/distributed/ DownpourSGD/node/ps_instance +
async_executor.py pslib hooks; the executable server here is
paddle_tpu/distributed/ps_core.py instead of Baidu's closed PSLIB)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.distributed import (
    DownpourSGD,
    PaddlePSInstance,
    PSCore,
    SparseTable,
)

VOCAB = 100
EMB_DIM = 8


def _write_ctr_files(tmp_path, n_files=2, lines=300, seed=0):
    """MultiSlot lines: '1 <id> 1 <label>'; label is a learnable function
    of the id (reference data: dist_ctr_reader-style synthetic slots)."""
    rng = np.random.RandomState(seed)
    files = []
    for f in range(n_files):
        path = str(tmp_path / f"part-{f}")
        with open(path, "w") as fh:
            for _ in range(lines):
                i = int(rng.randint(VOCAB))
                label = 1.0 if i % 2 == 0 else 0.0
                fh.write(f"1 {i} 1 {label}\n")
        files.append(path)
    return files


def _build_ctr_model():
    ids = fluid.layers.data(name="ids", shape=[1], dtype="int64")
    label = fluid.layers.data(name="label", shape=[1], dtype="float32")
    emb = fluid.layers.embedding(
        ids, size=[VOCAB, EMB_DIM], is_distributed=True,
        param_attr=fluid.ParamAttr(name="dist_emb"),
    )
    fc1 = fluid.layers.fc(emb, size=16, act="relu")
    logit = fluid.layers.fc(fc1, size=1)
    loss = fluid.layers.reduce_mean(
        fluid.layers.sigmoid_cross_entropy_with_logits(logit, label)
    )
    return loss


FEED_DESC = """
name: "MultiSlotDataFeed"
batch_size: 32
multi_slot_desc {
  slots { name: "ids" type: "uint64" is_dense: true is_used: true }
  slots { name: "label" type: "float" is_dense: true is_used: true }
}
"""


def test_downpour_minimize_descs():
    """minimize returns [ps_param, worker_skipped_ops] with the reference's
    desc structure (distributed/downpour.py:46)."""
    fluid.reset_default_env()
    loss = _build_ctr_model()
    ps_param, skipped = DownpourSGD(learning_rate=0.1, window=1).minimize(loss)

    assert skipped == ["lookup_table", "lookup_table_grad"]
    assert ps_param["table_name"] == "dist_emb"
    tables = ps_param["server_param"]["downpour_server_param"][
        "downpour_table_param"]
    assert [t["table_class"] for t in tables] == [
        "DownpourSparseTable", "DownpourDenseTable"]
    assert tables[0]["accessor"]["embedx_dim"] == EMB_DIM
    # dense table holds every non-embedding param element
    n_dense = sum(
        int(np.prod(p.shape))
        for p in loss.block.program.global_block().all_parameters()
        if p.name != "dist_emb"
    )
    assert tables[1]["accessor"]["fea_dim"] == n_dense
    trainer = ps_param["trainer_param"]
    assert trainer["sparse_table"][0]["slot_key"] == ["ids"]
    assert trainer["sparse_table"][0]["slot_gradient"][0].endswith("@GRAD")
    assert "dist_emb" not in trainer["dense_table"][0]["dense_variable_name"]


def test_downpour_trains_end_to_end(tmp_path):
    """Hogwild workers against the in-process PS: loss drops from the
    ~log(2) cold start, rows materialize lazily, checkpoints round-trip
    (reference flow: async_executor.py init_server/init_worker/run)."""
    fluid.reset_default_env()
    loss = _build_ctr_model()
    ps_param, _ = DownpourSGD(learning_rate=0.2, window=1).minimize(loss)
    # dense adam's desc default LR is pserver-scale tiny; crank it for test
    ps_param["server_param"]["downpour_server_param"][
        "downpour_table_param"][1]["accessor"]["dense_sgd_param"]["adam"][
        "learning_rate"] = 0.05

    exe = fluid.AsyncExecutor(fluid.CPUPlace())
    exe.init_server(ps_param)
    exe.init_worker(ps_param)
    fluid.Executor(fluid.CPUPlace()).run(fluid.default_startup_program())
    # the distributed table must not materialize on the worker
    assert fluid.global_scope().find_var("dist_emb") is None
    exe.init_model()

    files = _write_ctr_files(tmp_path)
    desc = fluid.DataFeedDesc(FEED_DESC)

    def eval_loss():
        exe._pull_dense_into_scope()
        rng = np.random.RandomState(7)
        ids = rng.randint(VOCAB, size=(64, 1)).astype(np.int64)
        label = (ids % 2 == 0).astype(np.float32)
        rows = exe._ps.sparse(0).pull(ids.reshape(-1))
        emb_out = exe._emb_map[0][1]
        v = fluid.Executor(fluid.CPUPlace(), donate_states=False).run(
            program=exe._worker_program,
            feed={"ids": ids, "label": label,
                  emb_out: rows.reshape(64, EMB_DIM)},
            fetch_list=[loss.name],
        )
        return float(np.ravel(np.asarray(v[0]))[0])

    first = eval_loss()
    assert abs(first - np.log(2.0)) < 0.05  # cold start: logits ~ 0

    for _ in range(4):  # multiple passes over the files
        exe.run(fluid.default_main_program(), desc, files, thread_num=2,
                fetch=[loss])
    final = eval_loss()
    assert final < first - 0.05, f"loss did not drop: {first} -> {final}"
    # only touched rows exist — never the dense vocab
    assert 0 < len(exe._ps.sparse(0)) <= VOCAB

    # checkpoint round-trip (reference: save_model / PSLIB load)
    path = str(tmp_path / "ps_ckpt.npz")
    exe.save_model(path)
    ps2 = PSCore.from_server_desc(ps_param["server_param"])
    ps2.load(path)
    ids = np.array([2, 4, 6], dtype=np.int64)
    np.testing.assert_allclose(
        ps2.sparse(0).pull(ids), exe._ps.sparse(0).pull(ids), rtol=1e-6
    )
    np.testing.assert_allclose(ps2.dense(1).pull(), exe._ps.dense(1).pull())


def test_sparse_table_uint64_ids_checkpoint(tmp_path):
    """Hashed uint64 feature ids (bit-pattern int64 from the MultiSlot
    parser, or raw ints >= 2**63) are one row either way, and survive a
    save/load round trip (state_dict keeps a uint64 id vector)."""
    t = SparseTable(dim=2, initial_range=0.1)
    big = 2 ** 63 + 17
    as_int64 = np.array([big], dtype=np.uint64).view(np.int64)  # negative
    row_a = t.pull(np.array([big], dtype=np.uint64))
    row_b = t.pull(as_int64)
    np.testing.assert_array_equal(row_a, row_b)
    assert len(t) == 1

    core = PSCore()
    core.tables[0] = t
    path = str(tmp_path / "u64.npz")
    core.save(path)
    t2 = SparseTable(dim=2)
    core2 = PSCore()
    core2.tables[0] = t2
    core2.load(path)
    np.testing.assert_array_equal(t2.pull(as_int64), row_a)
    assert len(t2) == 1


def test_async_executor_stop_restores_startup():
    """stop() re-inserts the distributed table's initializer so a later
    non-downpour run can materialize and train the table locally."""
    fluid.reset_default_env()
    loss = _build_ctr_model()
    ps_param, _ = DownpourSGD(learning_rate=0.1).minimize(loss)
    sp = fluid.default_startup_program()
    n_ops_before = len(sp.global_block().ops)

    exe = fluid.AsyncExecutor(fluid.CPUPlace())
    exe.init_server(ps_param)
    exe.init_worker(ps_param)
    assert len(sp.global_block().ops) < n_ops_before
    exe.stop()
    assert len(sp.global_block().ops) == n_ops_before
    assert len(sp.global_block().desc.ops) == n_ops_before
    # the restored startup program initializes the table again
    fluid.Executor(fluid.CPUPlace()).run(sp)
    tbl = fluid.global_scope().find_var("dist_emb")
    assert tbl is not None and np.asarray(tbl).shape == (VOCAB, EMB_DIM)


def test_sparse_table_accessor_semantics():
    """Row-wise adagrad with lazy init, duplicate-id merge, and weight
    bounds (reference: DownpourFeatureValueAccessor sparse_sgd_param)."""
    t = SparseTable(dim=2, learning_rate=1.0, initial_g2sum=0.0,
                    initial_range=0.0, weight_bounds=(-0.5, 0.5))
    w0 = t.pull(np.array([3]))
    np.testing.assert_allclose(w0, 0.0)  # initial_range=0 -> zero init

    # one push with a duplicated id accumulates before the update
    t.push(np.array([3, 3]), np.array([[1.0, 0.0], [1.0, 0.0]]))
    w1 = t.pull(np.array([3]))
    # g=2 merged, g2sum=4, step = lr*g/sqrt(g2sum) = 1.0 -> clipped to bound
    np.testing.assert_allclose(w1[0, 0], -0.5)
    np.testing.assert_allclose(w1[0, 1], 0.0)
    assert len(t) == 1


def test_ps_instance_role_math():
    """Rank->role assignment matches the reference's two modes
    (ps_instance.py _set_nodetype)."""
    import os

    env = {"PADDLE_TRAINER_ID": None, "PADDLE_TRAINERS": None}
    saved = {k: os.environ.get(k) for k in env}
    try:
        os.environ["PADDLE_TRAINERS"] = "4"  # 4 procs = 2 nodes x 2 procs
        roles_mode1 = []
        for rank in range(4):
            os.environ["PADDLE_TRAINER_ID"] = str(rank)
            inst = PaddlePSInstance(server_worker_mode=1, proc_per_node=2)
            roles_mode1.append(
                "s" if inst.is_server() else "w" if inst.is_worker() else "-"
            )
        assert roles_mode1 == ["s", "w", "s", "w"]  # interleaved per node

        roles_mode0 = []
        for rank in range(4):
            os.environ["PADDLE_TRAINER_ID"] = str(rank)
            inst = PaddlePSInstance(server_worker_mode=0, proc_per_node=2)
            roles_mode0.append("s" if inst.is_server() else "w")
        assert roles_mode0 == ["s", "s", "w", "w"]  # servers first

        os.environ["PADDLE_TRAINER_ID"] = "1"
        inst = PaddlePSInstance(server_worker_mode=1, proc_per_node=2)
        assert inst.get_worker_num() == 2 and inst.get_server_num() == 2
        assert inst.is_worker() and inst.get_worker_index() == 0
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
