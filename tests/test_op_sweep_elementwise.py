"""Per-op sweep: elementwise/broadcast family (reference:
test_elementwise_*_op.py over operators/elementwise/, REGISTER_ELEMWISE_OP
macros) including the axis broadcast rule, plus compare/logical ops."""

import numpy as np
import pytest

from op_test import OpTest


def _rand(shape, lo=-2.0, hi=2.0, seed=3):
    return np.random.RandomState(seed).uniform(lo, hi, shape).astype("float32")


ELEMENTWISE = {
    "elementwise_add": (lambda x, y: x + y, (-2, 2), True),
    "elementwise_sub": (lambda x, y: x - y, (-2, 2), True),
    "elementwise_mul": (lambda x, y: x * y, (-2, 2), True),
    "elementwise_div": (lambda x, y: x / y, (0.5, 2.0), True),
    "elementwise_max": (np.maximum, (-2, 2), True),
    "elementwise_min": (np.minimum, (-2, 2), True),
    "elementwise_pow": (np.power, (0.5, 2.0), True),
    "elementwise_mod": (np.fmod, (1.0, 5.0), False),
    "elementwise_floordiv": (lambda x, y: np.floor_divide(x, y), (1.0, 5.0), False),
}


@pytest.mark.parametrize("op", sorted(ELEMENTWISE))
def test_elementwise_same_shape(op):
    ref, (lo, hi), do_grad = ELEMENTWISE[op]
    x = _rand((3, 8), lo, hi, seed=1)
    y = _rand((3, 8), lo, hi, seed=2)
    if op in ("elementwise_max", "elementwise_min"):
        # keep |x-y| away from 0 so the max/min subgradient is unambiguous
        y = np.where(np.abs(x - y) < 0.1, y + 0.3, y).astype("float32")

    class T(OpTest):
        op_type = op

    t = T()
    t.inputs = {"X": x, "Y": y}
    t.outputs = {"Out": ref(x.astype(np.float64), y.astype(np.float64)).astype("float32")}
    t.check_output(atol=2e-5, rtol=2e-5)
    if do_grad:
        t.check_grad(["X", "Y"], "Out", max_relative_error=0.01)


@pytest.mark.parametrize("op", ["elementwise_add", "elementwise_mul"])
def test_elementwise_broadcast_axis(op):
    """Y broadcasts along `axis` (reference broadcast rule: Y's shape must
    match a contiguous run of X's dims starting at axis)."""
    ref = {"elementwise_add": lambda x, y: x + y,
           "elementwise_mul": lambda x, y: x * y}[op]
    x = _rand((2, 3, 4, 5), seed=4)
    y = _rand((3, 4), seed=5)
    want = ref(x, y.reshape(1, 3, 4, 1))

    class T(OpTest):
        op_type = op

    t = T()
    t.inputs = {"X": x, "Y": y}
    t.attrs = {"axis": 1}
    t.outputs = {"Out": want}
    t.check_output(atol=2e-5, rtol=2e-5)
    t.check_grad(["X", "Y"], "Out", max_relative_error=0.01)


COMPARE = {
    "equal": np.equal,
    "not_equal": np.not_equal,
    "less_than": np.less,
    "less_equal": np.less_equal,
    "greater_than": np.greater,
    "greater_equal": np.greater_equal,
}


@pytest.mark.parametrize("op", sorted(COMPARE))
def test_compare(op):
    x = np.array([[1, 2, 3], [4, 5, 6]], dtype="float32")
    y = np.array([[1, 3, 2], [4, 4, 7]], dtype="float32")

    class T(OpTest):
        op_type = op

    t = T()
    t.inputs = {"X": x, "Y": y}
    t.outputs = {"Out": COMPARE[op](x, y)}
    t.check_output()


LOGICAL = {
    "logical_and": np.logical_and,
    "logical_or": np.logical_or,
    "logical_xor": np.logical_xor,
}


@pytest.mark.parametrize("op", sorted(LOGICAL))
def test_logical(op):
    rng = np.random.RandomState(0)
    x = rng.rand(3, 4) > 0.5
    y = rng.rand(3, 4) > 0.5

    class T(OpTest):
        op_type = op

    t = T()
    t.inputs = {"X": x, "Y": y}
    t.outputs = {"Out": LOGICAL[op](x, y)}
    t.check_output()


def test_logical_not():
    x = np.random.RandomState(0).rand(3, 4) > 0.5

    class T(OpTest):
        op_type = "logical_not"

    t = T()
    t.inputs = {"X": x}
    t.outputs = {"Out": np.logical_not(x)}
    t.check_output()


def test_mod_floordiv_truncated_semantics():
    """Reference C++ semantics: sign of the DIVIDEND (trunc), not numpy's
    floored mod (review finding r2)."""
    import paddle_tpu as fluid

    x = np.array([[-3.0, 3.0, -7.0, 7.0]], dtype="float32")
    y = np.array([[2.0, 2.0, -2.0, -2.0]], dtype="float32")
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        xv = fluid.layers.data(name="x", shape=[4], dtype="float32")
        yv = fluid.layers.data(name="y", shape=[4], dtype="float32")
        m = fluid.layers.elementwise_mod(xv, yv)
        d = fluid.layers.elementwise_floordiv(xv, yv)
    exe = fluid.Executor(fluid.CPUPlace())
    gm, gd = exe.run(program=prog, feed={"x": x, "y": y},
                     fetch_list=[m, d])
    np.testing.assert_allclose(gm, np.fmod(x, y), rtol=1e-6)
    np.testing.assert_allclose(gd, np.trunc(x / y), rtol=1e-6)


def test_broadcast_axis_fuzz():
    """Seeded fuzz of the reference's axis-based broadcasting
    (elementwise_op.h: Y's shape must match a contiguous slice of X's
    dims starting at `axis`; trailing X dims broadcast): random ranks,
    slice positions, and ops, checked against explicit numpy expansion."""
    rng = np.random.RandomState(42)
    ops = {
        "elementwise_add": np.add,
        "elementwise_sub": np.subtract,
        "elementwise_mul": np.multiply,
        "elementwise_div": np.divide,
        "elementwise_max": np.maximum,
        "elementwise_min": np.minimum,
    }
    for trial in range(30):
        xrank = rng.randint(2, 5)
        xshape = tuple(rng.randint(1, 5) for _ in range(xrank))
        ylen = rng.randint(1, xrank + 1)
        axis = rng.randint(0, xrank - ylen + 1)
        yshape = xshape[axis:axis + ylen]
        x = rng.randn(*xshape).astype("float32")
        y = (rng.randn(*yshape).astype("float32") + 3.0)  # div-safe
        name = list(ops)[trial % len(ops)]

        expanded = y.reshape(yshape + (1,) * (xrank - axis - ylen))
        want = ops[name](x, expanded)

        class T(OpTest):
            op_type = name

        t = T()
        t.inputs = {"X": x, "Y": y}
        t.attrs = {"axis": axis}
        t.outputs = {"Out": want}
        try:
            t.check_output(atol=1e-5, rtol=1e-5)
        except Exception as e:  # pragma: no cover - diagnostic context
            raise AssertionError(
                f"trial {trial}: {name} x{xshape} y{yshape} axis={axis}"
            ) from e
