"""Per-op sweep: previously untested tail
(reference: test_matmul_op.py, test_transpose_op.py, test_reshape_op.py,
test_squeeze_op.py / test_unsqueeze_op.py, test_prelu_op.py,
test_maxout_op.py, test_bilinear_tensor_product_op.py,
test_conv2d_transpose_op.py, test_bilinear_interp_op.py,
test_nearest_interp_op.py, test_mean_iou_op.py, test_edit_distance_op.py,
test_fake_quantize_op.py, test_fake_dequantize_op.py, test_auc_op.py,
test_assign_value_op.py, test_lod_reset_op.py, test_isfinite_op.py,
test_uniform_random_op.py, test_gaussian_random_op.py over the matching
operators/*.cc)."""

import numpy as np

import paddle_tpu as fluid
from op_test import OpTest


def _rand(shape, seed=0, lo=-2.0, hi=2.0):
    return np.random.RandomState(seed).uniform(lo, hi, shape).astype("float32")


def _t(op_type, inputs, outputs, attrs=None):
    class T(OpTest):
        pass

    T.op_type = op_type
    t = T()
    t.inputs = inputs
    t.outputs = outputs
    t.attrs = attrs or {}
    return t


def test_matmul_plain_and_transposed():
    x, y = _rand((3, 4), 1), _rand((4, 5), 2)
    t = _t("matmul", {"X": x, "Y": y}, {"Out": x @ y})
    t.check_output(atol=2e-5, rtol=2e-5)
    t.check_grad(["X", "Y"], "Out", max_relative_error=0.02)

    xt = _rand((4, 3), 3)
    t = _t("matmul", {"X": xt, "Y": y}, {"Out": xt.T @ y},
           {"transpose_X": True})
    t.check_output(atol=2e-5, rtol=2e-5)

    # batched with alpha
    xb, yb = _rand((2, 3, 4), 4), _rand((2, 4, 5), 5)
    t = _t("matmul", {"X": xb, "Y": yb}, {"Out": 0.5 * (xb @ yb)},
           {"alpha": 0.5})
    t.check_output(atol=2e-5, rtol=2e-5)


def test_transpose2():
    x = _rand((2, 3, 4), 6)
    t = _t("transpose2", {"X": x}, {"Out": x.transpose(2, 0, 1)},
           {"axis": [2, 0, 1]})
    t.check_output()
    t.check_grad(["X"], "Out", max_relative_error=0.02)


def test_reshape_squeeze_unsqueeze_flatten2():
    x = _rand((2, 3, 4), 7)
    t = _t("reshape2", {"X": x}, {"Out": x.reshape(6, 4)},
           {"shape": [6, 4]})
    t.check_output()

    xs = _rand((3, 1, 4), 8)
    t = _t("squeeze2", {"X": xs}, {"Out": xs.reshape(3, 4)},
           {"axes": [1]})
    t.check_output()

    t = _t("unsqueeze2", {"X": x}, {"Out": x[:, None]},
           {"axes": [1]})
    t.check_output()

    t = _t("flatten2", {"X": x}, {"Out": x.reshape(2, 12)},
           {"axis": 1})
    t.check_output()


def test_prelu_modes():
    x = _rand((3, 4, 5), 9)
    alpha_all = np.array([0.25], dtype="float32")
    want = np.where(x > 0, x, 0.25 * x)
    t = _t("prelu", {"X": x, "Alpha": alpha_all}, {"Out": want},
           {"mode": "all"})
    t.check_output()
    t.check_grad(["X", "Alpha"], "Out", max_relative_error=0.03)

    alpha_c = _rand((4,), 10, 0.1, 0.9)
    want = np.where(x > 0, x, alpha_c[None, :, None] * x)
    t = _t("prelu", {"X": x, "Alpha": alpha_c}, {"Out": want},
           {"mode": "channel"})
    t.check_output()


def test_maxout():
    x = _rand((2, 6, 3, 3), 11)
    want = x.reshape(2, 3, 2, 3, 3).max(axis=2)
    t = _t("maxout", {"X": x}, {"Out": want}, {"groups": 2})
    t.check_output()
    t.check_grad(["X"], "Out", max_relative_error=0.02)


def test_bilinear_tensor_product():
    x, y = _rand((4, 3), 12), _rand((4, 5), 13)
    w = _rand((6, 3, 5), 14)
    bias = _rand((1, 6), 15)
    want = np.einsum("bi,kij,bj->bk", x, w, y) + bias
    t = _t("bilinear_tensor_product",
           {"X": x, "Y": y, "Weight": w, "Bias": bias}, {"Out": want})
    t.check_output(atol=2e-5, rtol=2e-5)
    t.check_grad(["X", "Y", "Weight"], "Out", max_relative_error=0.03)


def test_conv2d_transpose_matches_scatter():
    # stride-2 transpose conv == scatter-add of input-scaled kernels
    x = _rand((1, 2, 3, 3), 16)
    f = _rand((2, 3, 2, 2), 17)  # [Cin, Cout, H, W]
    stride = 2
    out = np.zeros((1, 3, 3 * stride - stride + 2, 3 * stride - stride + 2),
                   dtype="float32")
    for i in range(3):
        for j in range(3):
            patch = np.einsum("c,cokl->okl", x[0, :, i, j], f)
            out[0, :, i * stride:i * stride + 2,
                j * stride:j * stride + 2] += patch
    t = _t("conv2d_transpose", {"Input": x, "Filter": f},
           {"Output": out}, {"strides": [2, 2], "paddings": [0, 0]})
    t.check_output(atol=2e-5, rtol=2e-5)
    t.check_grad(["Input", "Filter"], "Output", max_relative_error=0.03)


def test_depthwise_conv2d():
    x = _rand((1, 3, 5, 5), 18)
    f = _rand((3, 1, 3, 3), 19)
    want = np.zeros((1, 3, 3, 3), dtype="float32")
    for c in range(3):
        for i in range(3):
            for j in range(3):
                want[0, c, i, j] = (x[0, c, i:i + 3, j:j + 3]
                                    * f[c, 0]).sum()
    t = _t("depthwise_conv2d", {"Input": x, "Filter": f},
           {"Output": want},
           {"strides": [1, 1], "paddings": [0, 0], "groups": 3})
    t.check_output(atol=2e-4, rtol=2e-4)
    t.check_grad(["Input", "Filter"], "Output", max_relative_error=0.03)


def test_nearest_interp():
    x = _rand((1, 2, 2, 2), 20)
    want = x.repeat(2, axis=2).repeat(2, axis=3)
    t = _t("nearest_interp", {"X": x}, {"Out": want},
           {"out_h": 4, "out_w": 4})
    t.check_output()


def test_bilinear_interp_preserves_constant():
    x = np.full((1, 1, 3, 3), 2.5, dtype="float32")
    want = np.full((1, 1, 6, 6), 2.5, dtype="float32")
    t = _t("bilinear_interp", {"X": x}, {"Out": want},
           {"out_h": 6, "out_w": 6})
    t.check_output(atol=1e-5)
    xg = _rand((1, 1, 4, 4), 21)
    # independent align-corners reference (interpolate_op.h:171 ratio math)
    def _ref_bilinear(x, oh, ow):
        _, _, ih, iw = x.shape
        rh = (ih - 1) / (oh - 1)
        rw = (iw - 1) / (ow - 1)
        out = np.zeros(x.shape[:2] + (oh, ow), dtype=np.float64)
        for k in range(oh):
            for l in range(ow):
                sh, sw = rh * k, rw * l
                h0, w0 = int(sh), int(sw)
                h1, w1 = min(h0 + 1, ih - 1), min(w0 + 1, iw - 1)
                dh, dw = sh - h0, sw - w0
                out[..., k, l] = (
                    x[..., h0, w0] * (1 - dh) * (1 - dw)
                    + x[..., h0, w1] * (1 - dh) * dw
                    + x[..., h1, w0] * dh * (1 - dw)
                    + x[..., h1, w1] * dh * dw
                )
        return out.astype("float32")

    want = _ref_bilinear(xg, 8, 8)
    t = _t("bilinear_interp", {"X": xg}, {"Out": want},
           {"out_h": 8, "out_w": 8})
    t.check_output(atol=1e-5, rtol=1e-5)
    t.check_grad(["X"], "Out", max_relative_error=0.03)


def test_mean_iou():
    pred = np.array([0, 1, 2, 2, 1], dtype="int64")
    label = np.array([0, 1, 1, 2, 2], dtype="int64")
    # per class: c0 1/1; c1 1/3; c2 1/3 -> mean = (1 + 1/3 + 1/3)/3
    want_iou = np.array([(1.0 + 1 / 3 + 1 / 3) / 3], dtype="float32")
    t = _t("mean_iou", {"Predictions": pred, "Labels": label},
           {"OutMeanIou": want_iou,
            "OutWrong": np.array([0, 2, 2], dtype="int32"),
            "OutCorrect": np.array([1, 1, 1], dtype="int32")},
           {"num_classes": 3})
    t.check_output(atol=1e-6)


def test_edit_distance():
    # LoD pairs as (flat_data, lengths): "123"/"13" and "45"/"456"
    hyps = (np.array([[1], [2], [3], [4], [5]], dtype="int64"), [3, 2])
    refs = (np.array([[1], [3], [4], [5], [6]], dtype="int64"), [2, 3])
    t = _t("edit_distance", {"Hyps": hyps, "Refs": refs},
           {"Out": np.array([[1.0], [1.0]], dtype="float32"),
            "SequenceNum": np.array([2], dtype="int64")})
    t.check_output()


def test_fake_quantize_dequantize_range_abs_max():
    fluid.reset_default_env()
    x = _rand((4, 4), 22)
    scale = float(np.abs(x).max())
    levels = 127.0
    # fake-quant emits DEQUANTIZED values (round to the grid, scale back)
    want = np.round(x / scale * levels).clip(-levels, levels) \
        * scale / levels
    t = _t("fake_quantize_range_abs_max",
           {"X": x, "InScale": np.array([0.0], dtype="float32")},
           {"Out": want.astype("float32"),
            "OutScale": np.array([scale], dtype="float32")},
           {"bit_length": 8, "is_test": False})
    t.check_output(atol=1e-4)

    q = np.round(x / scale * levels).astype("float32")
    t = _t("fake_dequantize_max_abs",
           {"X": q, "Scale": np.array([scale], dtype="float32")},
           {"Out": q * scale / 127.0}, {"max_range": 127.0})
    t.check_output(atol=1e-5)


def test_assign_value():
    vals = np.arange(6, dtype="float32").reshape(2, 3)
    t = _t("assign_value", {},
           {"Out": vals},
           {"shape": [2, 3], "fp32_values": vals.reshape(-1).tolist(),
            "dtype": int(fluid.core.DataType.FP32)})
    t.check_output()


def test_isfinite_family():
    x = np.array([1.0, np.inf, -np.inf, np.nan, 2.0], dtype="float32")
    t = _t("isfinite", {"X": x},
           {"Out": np.array([False], dtype=bool)})
    t.check_output()
    t = _t("isinf", {"X": x}, {"Out": np.array([True], dtype=bool)})
    t.check_output()
    t = _t("isnan", {"X": x}, {"Out": np.array([True], dtype=bool)})
    t.check_output()


def test_lod_reset_with_target_lengths():
    flat = np.arange(1.0, 7.0, dtype="float32")[:, None]
    # re-slice the 6 tokens [3, 3] -> [2, 4]; target_lod is OFFSETS
    t = _t("lod_reset", {"X": (flat, [3, 3])},
           {"Out": (flat, [2, 4])},
           {"target_lod": [0, 2, 6]})
    t.check_output()

    # non-offset target_lod is rejected, not guessed at
    import pytest
    from paddle_tpu.core.enforce import EnforceNotMet

    bad = _t("lod_reset", {"X": (flat, [3, 3])}, {"Out": (flat, [2, 4])},
             {"target_lod": [2, 4]})
    with pytest.raises(EnforceNotMet, match="offsets"):
        bad.check_output()


def test_uniform_and_gaussian_random_statistics():
    fluid.reset_default_env()
    from paddle_tpu import layers

    u = layers.uniform_random([2000], min=-1.0, max=3.0)
    g = layers.gaussian_random([2000], mean=1.0, std=2.0)
    exe = fluid.Executor(fluid.CPUPlace())
    uv, gv = exe.run(fetch_list=[u, g])
    uv, gv = np.asarray(uv), np.asarray(gv)
    assert uv.min() >= -1.0 and uv.max() <= 3.0
    assert abs(uv.mean() - 1.0) < 0.15
    assert abs(gv.mean() - 1.0) < 0.2 and abs(gv.std() - 2.0) < 0.25
