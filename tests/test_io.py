"""Checkpoint/IO round-trips (reference: test_io_save_load-style book tests,
dist_save_load.py)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers


def _build_and_train(steps=3):
    x = layers.data("x", [4], dtype="float32")
    y = layers.data("y", [1], dtype="float32")
    pred = layers.fc(x, size=1, param_attr=fluid.ParamAttr(name="w"))
    loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
    fluid.optimizer.AdamOptimizer(learning_rate=0.05).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    xv = rng.randn(8, 4).astype("float32")
    yv = rng.randn(8, 1).astype("float32")
    for _ in range(steps):
        exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])
    return exe, pred, loss, xv, yv


def test_save_load_persistables_roundtrip(tmp_path):
    exe, pred, loss, xv, yv = _build_and_train()
    # eval through a pruned program so fetching pred does not step Adam
    infer_prog = fluid.io.get_inference_program([pred])
    (before,) = exe.run(program=infer_prog, feed={"x": xv}, fetch_list=[pred])
    fluid.io.save_persistables(exe, str(tmp_path / "ckpt"))

    # clobber the scope, reload, same predictions (incl. optimizer moments)
    w = np.asarray(fluid.global_scope().find_var("w")).copy()
    fluid.global_scope().set_var("w", np.zeros_like(w))
    fluid.io.load_persistables(exe, str(tmp_path / "ckpt"))
    np.testing.assert_allclose(
        np.asarray(fluid.global_scope().find_var("w")), w
    )
    (after,) = exe.run(program=infer_prog, feed={"x": xv}, fetch_list=[pred])
    np.testing.assert_allclose(np.asarray(after), np.asarray(before), rtol=1e-6)


def test_save_load_combined_file(tmp_path):
    exe, *_ = _build_and_train()
    fluid.io.save_params(exe, str(tmp_path / "c"), filename="params")
    w = np.asarray(fluid.global_scope().find_var("w")).copy()
    fluid.global_scope().set_var("w", np.zeros_like(w))
    fluid.io.load_params(exe, str(tmp_path / "c"), filename="params")
    np.testing.assert_allclose(np.asarray(fluid.global_scope().find_var("w")), w)


def test_inference_model_roundtrip(tmp_path):
    exe, pred, loss, xv, yv = _build_and_train()
    infer_prog = fluid.io.get_inference_program([pred])
    (before,) = exe.run(program=infer_prog, feed={"x": xv}, fetch_list=[pred])
    fluid.io.save_inference_model(
        str(tmp_path / "model"), ["x"], [pred], exe
    )

    # fresh program + scope, as a serving process would have
    from paddle_tpu.core import framework, scope as scope_mod

    framework.switch_main_program(fluid.Program())
    framework.switch_startup_program(fluid.Program())
    scope_mod._current_scope = scope_mod.Scope()

    exe2 = fluid.Executor(fluid.CPUPlace())
    program, feed_names, fetch_targets = fluid.io.load_inference_model(
        str(tmp_path / "model"), exe2
    )
    assert feed_names == ["x"]
    (out,) = exe2.run(
        program=program, feed={"x": xv}, fetch_list=fetch_targets
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(before), rtol=1e-6)


def test_inference_prune_drops_training_ops(tmp_path):
    exe, pred, loss, xv, yv = _build_and_train()
    fluid.io.save_inference_model(str(tmp_path / "m"), ["x"], [pred], exe)
    program, _, _ = fluid.io.load_inference_model(str(tmp_path / "m"), exe)
    types = {op.type for op in program.global_block().ops}
    assert "adam" not in types
    assert not any(t.endswith("_grad") for t in types)
