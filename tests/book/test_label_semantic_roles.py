"""label_semantic_roles: SRL tagger with a linear-chain CRF head on
conll05 (reference: book/test_label_semantic_roles.py — word+context
embeddings -> hidden -> linear_chain_crf, decoded with crf_decoding)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.dataset import conll05

EMB = 16
HID = 32


def test_label_semantic_roles():
    fluid.reset_default_env()
    word_dict, verb_dict, label_dict = conll05.get_dict()
    word_dict_len = len(word_dict)
    # the reference's BIO tag space is ~60 labels; our synthetic conll05
    # emits ids over the full label vocab, so fold them into a small tag
    # space — a [V,V] CRF transition over thousands of tags is not the
    # book model and only slows the test
    label_dict_len = 32
    pred_len = len(verb_dict)
    PAD_LEN = 40  # fixed padded length: varying batch max would recompile

    word = layers.data(name="word_data", shape=[1], dtype="int64",
                       lod_level=1)
    predicate = layers.data(name="verb_data", shape=[1], dtype="int64",
                            lod_level=1)
    target = layers.data(name="target", shape=[1], dtype="int64",
                         lod_level=1)

    word_emb = layers.embedding(word, size=[word_dict_len, EMB])
    pred_emb = layers.embedding(predicate, size=[pred_len, EMB])
    feat = layers.concat([word_emb, pred_emb], axis=-1)
    hidden = layers.fc(feat, size=HID, act="tanh")
    feature_out = layers.fc(hidden, size=label_dict_len)

    crf_cost = layers.linear_chain_crf(
        input=feature_out, label=target,
        param_attr=fluid.ParamAttr(name="crfw"))
    avg_cost = layers.mean(crf_cost)
    fluid.optimizer.SGD(learning_rate=0.3).minimize(avg_cost)

    crf_decode = layers.crf_decoding(
        input=feature_out, param_attr=fluid.ParamAttr(name="crfw"))

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    def lod_fixed(seqs):
        v = fluid.create_lod_tensor(seqs)
        data = np.asarray(v.data)
        if data.shape[1] < PAD_LEN:
            pad = np.zeros((data.shape[0], PAD_LEN - data.shape[1])
                           + data.shape[2:], dtype=data.dtype)
            data = np.concatenate([data, pad], axis=1)
        return fluid.LoDValue(data, v.lengths)

    def feed(batch):
        batch = [s for s in batch if len(s[0]) <= PAD_LEN]
        words = [np.asarray(s[0], dtype=np.int64)[:, None] for s in batch]
        verbs = [np.asarray(s[6], dtype=np.int64)[:, None] for s in batch]
        tags = [np.asarray(s[8], dtype=np.int64)[:, None] % label_dict_len
                for s in batch]
        return {
            "word_data": lod_fixed(words),
            "verb_data": lod_fixed(verbs),
            "target": lod_fixed(tags),
        }

    # fixed batch set, multiple epochs: per-batch CRF loss scales with
    # sequence lengths, so compare the same data epoch over epoch
    reader = fluid.batch(conll05.test(), batch_size=8)
    batches = []
    for i, batch in enumerate(reader()):
        batches.append(batch)
        if i >= 5:
            break
    epoch_means = []
    for _ in range(5):
        ls = []
        for batch in batches:
            (lv,) = exe.run(feed=feed(batch), fetch_list=[avg_cost])
            ls.append(float(np.ravel(np.asarray(lv))[0]))
        epoch_means.append(np.mean(ls))
    assert epoch_means[-1] < epoch_means[0] * 0.9, (
        f"CRF loss did not drop: {epoch_means}")

    # viterbi decode emits one tag per token within the label vocab
    (decoded,) = exe.run(feed=feed(batches[0]),
                         fetch_list=[crf_decode], return_numpy=False)
    tags = np.asarray(decoded.data if hasattr(decoded, "data") else decoded)
    assert tags.min() >= 0 and tags.max() < label_dict_len
