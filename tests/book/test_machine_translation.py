"""machine_translation: attention seq2seq training convergence
(reference: book/test_machine_translation.py training half; the beam
decode half is covered by
tests/test_contrib_tail.py::test_beam_search_decoder_decodes and
tests/test_beam_search.py::test_decode_loop_end_to_end)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import models


def test_machine_translation_trains():
    fluid.reset_default_env()
    spec = models.machine_translation(
        dict_size=80, embedding_dim=16,
        encoder_size=24, decoder_size=24, beam_size=2, max_length=8,
    )
    fluid.optimizer.Adam(learning_rate=0.01).minimize(spec.loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    losses = []
    for i in range(25):
        batch = spec.synthetic_batch(8, seed=i)
        (lv,) = exe.run(feed=batch, fetch_list=[spec.loss])
        losses.append(float(np.ravel(np.asarray(lv))[0]))
    assert np.mean(losses[-5:]) < np.mean(losses[:5]), (
        f"{np.mean(losses[:5])} -> {np.mean(losses[-5:])}")
