"""rnn_encoder_decoder: bi-LSTM encoder + attention-free DynamicRNN LSTM
decoder, trained end-to-end (reference: book/test_rnn_encoder_decoder.py —
bi_lstm_encoder :42, lstm_decoder_without_attention :87, seq_to_seq_net
:117; the model is rebuilt here through the paddle_tpu layer surface)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers

DICT = 60
EMB = 12
HID = 16
DEC = 16


def _bi_lstm_encoder(seq, hidden):
    fwd_proj = layers.fc(seq, size=hidden * 4, bias_attr=False)
    fwd, _ = layers.dynamic_lstm(fwd_proj, size=hidden * 4,
                                 use_peepholes=False)
    bwd_proj = layers.fc(seq, size=hidden * 4, bias_attr=False)
    bwd, _ = layers.dynamic_lstm(bwd_proj, size=hidden * 4,
                                 use_peepholes=False, is_reverse=True)
    return fwd, bwd


def _decoder_without_attention(trg_emb, boot, context, size):
    rnn = layers.DynamicRNN()
    with rnn.block():
        word = rnn.step_input(trg_emb)
        ctx = rnn.static_input(context)
        h_prev = rnn.memory(init=boot, need_reorder=True)
        c_prev = rnn.memory(shape=[size], value=0.0)
        x_t = layers.concat([word, ctx], axis=1)
        h, c = layers.lstm_unit(
            x_t=layers.fc(x_t, size=size * 4),
            hidden_t_prev=h_prev, cell_t_prev=c_prev)
        rnn.update_memory(h_prev, h)
        rnn.update_memory(c_prev, c)
        out = layers.fc(h, size=DICT, act="softmax")
        rnn.output(out)
    return rnn()


def _build():
    src = layers.data("src_word", [1], dtype="int64", lod_level=1)
    src_emb = layers.embedding(src, size=[DICT, EMB])
    fwd, bwd = _bi_lstm_encoder(src_emb, HID)
    # decoder boot = first step of the backward pass, like the reference
    boot = layers.fc(layers.sequence_first_step(bwd), size=DEC, act="tanh")
    context = layers.sequence_last_step(layers.concat([fwd, bwd], axis=1))

    trg = layers.data("trg_word", [1], dtype="int64", lod_level=1)
    trg_emb = layers.embedding(trg, size=[DICT, EMB])
    pred = _decoder_without_attention(trg_emb, boot, context, DEC)

    label = layers.data("label", [1], dtype="int64", lod_level=1)
    cost = layers.cross_entropy(pred, label)
    return layers.mean(cost)


def _batch(rng, n=6, tmax=7):
    from paddle_tpu.core.lod import create_lod_tensor

    src_lens = rng.randint(2, tmax, n)
    trg_lens = rng.randint(2, tmax, n)
    mk = lambda lens: create_lod_tensor(
        rng.randint(1, DICT, (int(np.sum(lens)), 1)).astype("int64"),
        [list(map(int, lens))])
    trg = mk(trg_lens)
    # label = target shifted conceptually; reuse lengths with fresh ids
    lab = mk(trg_lens)
    return {"src_word": mk(src_lens), "trg_word": trg, "label": lab}


def test_rnn_encoder_decoder_trains():
    fluid.reset_default_env()
    loss = _build()
    fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    fixed = _batch(rng)  # one fixed batch: the net must overfit it
    losses = []
    for _ in range(30):
        (lv,) = exe.run(feed=fixed, fetch_list=[loss])
        losses.append(float(np.ravel(np.asarray(lv))[0]))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])
