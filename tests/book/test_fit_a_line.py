"""fit_a_line: linear regression on uci_housing
(reference: python/paddle/fluid/tests/book/test_fit_a_line.py — train
until loss drops, then save_inference_model + reload + infer)."""

import os

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.dataset import uci_housing


def test_fit_a_line(tmp_path):
    fluid.reset_default_env()
    x = layers.data(name="x", shape=[13], dtype="float32")
    y = layers.data(name="y", shape=[1], dtype="float32")
    y_predict = layers.fc(input=x, size=1, act=None)
    cost = layers.square_error_cost(input=y_predict, label=y)
    avg_cost = layers.mean(cost)
    fluid.optimizer.SGD(learning_rate=0.01).minimize(avg_cost)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    feeder = fluid.DataFeeder(feed_list=[x, y], place=fluid.CPUPlace())

    train_reader = fluid.batch(uci_housing.train(), batch_size=20)
    first = last = None
    for epoch in range(4):
        for data in train_reader():
            (loss_v,) = exe.run(feed=feeder.feed(data),
                                fetch_list=[avg_cost])
            last = float(np.ravel(np.asarray(loss_v))[0])
            if first is None:
                first = last
    assert last < first * 0.25, f"{first} -> {last}"

    # inference round trip (reference: save/load_inference_model)
    path = str(tmp_path / "fit_a_line.model")
    fluid.io.save_inference_model(path, ["x"], [y_predict], exe)
    infer_prog, feed_names, fetch_targets = fluid.io.load_inference_model(
        path, exe)
    assert feed_names == ["x"]
    xb, yb = next(uci_housing.test()())
    (pred,) = exe.run(program=infer_prog, feed={"x": xb[None, :]},
                      fetch_list=fetch_targets)
    assert np.isfinite(float(np.ravel(np.asarray(pred))[0]))
