"""image_classification: small VGG on cifar10
(reference: book/test_image_classification.py vgg16_bn_drop on cifar;
shrunk to one conv group for test budget)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers, nets
from paddle_tpu.dataset import cifar


def small_vgg(input):
    g = nets.img_conv_group(
        input=input, conv_num_filter=[16, 16], pool_size=2,
        conv_padding=1, conv_filter_size=3, conv_act="relu",
        conv_with_batchnorm=True, pool_stride=2, pool_type="max")
    fc1 = layers.fc(input=g, size=64, act=None)
    bn = layers.batch_norm(input=fc1, act="relu")
    return layers.fc(input=bn, size=10, act="softmax")


def test_image_classification_vgg():
    fluid.reset_default_env()
    images = layers.data(name="pixel", shape=[3, 32, 32], dtype="float32")
    label = layers.data(name="label", shape=[1], dtype="int64")
    predict = small_vgg(images)
    cost = layers.cross_entropy(input=predict, label=label)
    avg_cost = layers.mean(cost)
    acc = layers.accuracy(input=predict, label=label)
    fluid.optimizer.Adam(learning_rate=0.003).minimize(avg_cost)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    def feed(batch):
        xs = np.stack([s[0].reshape(3, 32, 32) for s in batch])
        ys = np.array([[s[1]] for s in batch], dtype=np.int64)
        return {"pixel": xs.astype(np.float32), "label": ys}

    reader = fluid.batch(cifar.train10(), batch_size=32)
    losses = []
    for i, data in enumerate(reader()):
        (loss_v,) = exe.run(feed=feed(data), fetch_list=[avg_cost])
        losses.append(float(np.ravel(np.asarray(loss_v))[0]))
        if i >= 25:
            break
    assert np.mean(losses[-5:]) < np.mean(losses[:5]), (
        f"{np.mean(losses[:5])} -> {np.mean(losses[-5:])}")
