"""recommender_system: user/movie twin towers + cos_sim rating regression
on movielens (reference: book/test_recommender_system.py — id embeddings
fused per side, scaled cosine similarity as the predicted rating)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.dataset import movielens

EMB = 16


def get_usr_combined_features():
    usr_id = layers.data(name="user_id", shape=[1], dtype="int64")
    gender = layers.data(name="gender_id", shape=[1], dtype="int64")
    age = layers.data(name="age_id", shape=[1], dtype="int64")
    job = layers.data(name="job_id", shape=[1], dtype="int64")
    parts = [
        layers.fc(layers.embedding(usr_id,
                                   size=[movielens.max_user_id() + 1, EMB]),
                  size=EMB),
        layers.fc(layers.embedding(gender, size=[2, EMB]), size=EMB),
        layers.fc(layers.embedding(age, size=[8, EMB]), size=EMB),
        layers.fc(layers.embedding(job,
                                   size=[movielens.max_job_id() + 1, EMB]),
                  size=EMB),
    ]
    return layers.fc(layers.concat(parts, axis=1), size=32, act="tanh")


def get_mov_combined_features():
    mov_id = layers.data(name="movie_id", shape=[1], dtype="int64")
    category = layers.data(name="category_id", shape=[1], dtype="int64",
                           lod_level=1)
    title = layers.data(name="movie_title", shape=[1], dtype="int64",
                        lod_level=1)
    parts = [
        layers.fc(layers.embedding(mov_id,
                                   size=[movielens.max_movie_id() + 1, EMB]),
                  size=EMB),
        layers.sequence_pool(layers.embedding(category, size=[64, EMB]),
                             pool_type="sum"),
        layers.sequence_pool(layers.embedding(title, size=[512, EMB]),
                             pool_type="sum"),
    ]
    return layers.fc(layers.concat(parts, axis=1), size=32, act="tanh")


def test_recommender_system():
    fluid.reset_default_env()
    usr = get_usr_combined_features()
    mov = get_mov_combined_features()
    inference = layers.cos_sim(X=usr, Y=mov)
    scale_infer = layers.scale(x=inference, scale=5.0)
    label = layers.data(name="score", shape=[1], dtype="float32")
    avg_cost = layers.mean(layers.square_error_cost(scale_infer, label))
    fluid.optimizer.SGD(learning_rate=0.2).minimize(avg_cost)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    def feed(batch):
        def col(i):
            return np.array([[int(s[i])] for s in batch], dtype=np.int64)

        cats = [np.asarray(s[5], dtype=np.int64)[:, None] % 64
                for s in batch]
        titles = [np.asarray(s[6], dtype=np.int64)[:, None] % 512
                  for s in batch]
        return {
            "user_id": col(0), "gender_id": col(1), "age_id": col(2),
            "job_id": col(3), "movie_id": col(4),
            "category_id": fluid.create_lod_tensor(cats),
            "movie_title": fluid.create_lod_tensor(titles),
            "score": np.array([[float(s[7])] for s in batch],
                              dtype=np.float32),
        }

    reader = fluid.batch(movielens.train(), batch_size=32)
    losses = []
    for i, batch in enumerate(reader()):
        (lv,) = exe.run(feed=feed(batch), fetch_list=[avg_cost])
        losses.append(float(np.ravel(np.asarray(lv))[0]))
        if i >= 30:
            break
    assert np.mean(losses[-5:]) < np.mean(losses[:5]), (
        f"{np.mean(losses[:5])} -> {np.mean(losses[-5:])}")
