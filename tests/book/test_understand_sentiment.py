"""understand_sentiment: sequence-conv and dynamic-LSTM text classifiers
on imdb (reference: book/test_understand_sentiment.py convolution_net /
stacked_lstm_net)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers, nets
from paddle_tpu.dataset import imdb

EMB = 16
HID = 16
CLASS = 2


def convolution_net(data, label, input_dim):
    emb = layers.embedding(input=data, size=[input_dim, EMB])
    conv_3 = nets.sequence_conv_pool(
        input=emb, num_filters=HID, filter_size=3, act="tanh",
        pool_type="sqrt")
    prediction = layers.fc(input=conv_3, size=CLASS, act="softmax")
    cost = layers.cross_entropy(input=prediction, label=label)
    return layers.mean(cost), layers.accuracy(prediction, label)


def stacked_lstm_net(data, label, input_dim):
    emb = layers.embedding(input=data, size=[input_dim, EMB])
    fc1 = layers.fc(input=emb, size=HID * 4)
    lstm1, _ = layers.dynamic_lstm(input=fc1, size=HID * 4)
    pooled = layers.sequence_pool(input=lstm1, pool_type="max")
    prediction = layers.fc(input=pooled, size=CLASS, act="softmax")
    cost = layers.cross_entropy(input=prediction, label=label)
    return layers.mean(cost), layers.accuracy(prediction, label)


def _train(net_fn, steps=25):
    fluid.reset_default_env()
    word_dict = imdb.word_dict()
    data = layers.data(name="words", shape=[1], dtype="int64", lod_level=1)
    label = layers.data(name="label", shape=[1], dtype="int64")
    avg_cost, acc = net_fn(data, label, len(word_dict))
    fluid.optimizer.Adagrad(learning_rate=0.05).minimize(avg_cost)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    def feed(batch):
        seqs = [np.asarray(s[0], dtype=np.int64)[:, None] for s in batch]
        ys = np.array([[s[1]] for s in batch], dtype=np.int64)
        return {"words": fluid.create_lod_tensor(seqs), "label": ys}

    reader = fluid.batch(imdb.train(word_dict), batch_size=16)
    losses = []
    for i, batch in enumerate(reader()):
        (lv,) = exe.run(feed=feed(batch), fetch_list=[avg_cost])
        losses.append(float(np.ravel(np.asarray(lv))[0]))
        if i >= steps:
            break
    assert np.mean(losses[-5:]) < np.mean(losses[:5]), (
        f"{np.mean(losses[:5])} -> {np.mean(losses[-5:])}")


def test_understand_sentiment_conv():
    _train(convolution_net)


def test_understand_sentiment_stacked_lstm():
    _train(stacked_lstm_net)
