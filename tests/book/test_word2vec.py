"""word2vec: N-gram language model on imikolov
(reference: book/test_word2vec.py — 4 context words, shared embedding,
concat -> hidden -> softmax)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.dataset import imikolov

EMB_SIZE = 16
HIDDEN_SIZE = 32
N = 5


def test_word2vec():
    fluid.reset_default_env()
    word_dict = imikolov.build_dict()
    dict_size = len(word_dict)

    words = [layers.data(name=f"word_{i}", shape=[1], dtype="int64")
             for i in range(N - 1)]
    next_word = layers.data(name="next_word", shape=[1], dtype="int64")

    embs = [
        layers.embedding(
            input=w, size=[dict_size, EMB_SIZE],
            param_attr=fluid.ParamAttr(name="shared_w"),
        )
        for w in words
    ]
    concat = layers.concat(input=embs, axis=1)
    hidden1 = layers.fc(input=concat, size=HIDDEN_SIZE, act="sigmoid")
    predict = layers.fc(input=hidden1, size=dict_size, act="softmax")
    cost = layers.cross_entropy(input=predict, label=next_word)
    avg_cost = layers.mean(cost)
    fluid.optimizer.SGD(learning_rate=0.1).minimize(avg_cost)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    def feed(batch):
        arr = np.array(batch, dtype=np.int64)  # [B, 5]
        out = {f"word_{i}": arr[:, i:i + 1] for i in range(N - 1)}
        out["next_word"] = arr[:, N - 1:N]
        return out

    reader = fluid.batch(imikolov.train(word_dict, N), batch_size=32)
    losses = []
    for i, batch in enumerate(reader()):
        (lv,) = exe.run(feed=feed(batch), fetch_list=[avg_cost])
        losses.append(float(np.ravel(np.asarray(lv))[0]))
        if i >= 30:
            break
    assert np.mean(losses[-5:]) < np.mean(losses[:5]), (
        f"{np.mean(losses[:5])} -> {np.mean(losses[-5:])}")
    # the shared embedding table actually exists once
    tbl = np.asarray(fluid.global_scope().find_var("shared_w"))
    assert tbl.shape == (dict_size, EMB_SIZE)
