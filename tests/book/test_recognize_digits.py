"""recognize_digits: LeNet-ish conv net on mnist
(reference: book/test_recognize_digits.py conv_net — two conv-pool
stacks, softmax head, accuracy metric, inference round trip)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers, nets
from paddle_tpu.dataset import mnist


def conv_net(img, label):
    conv_pool_1 = nets.simple_img_conv_pool(
        input=img, filter_size=5, num_filters=8, pool_size=2,
        pool_stride=2, act="relu")
    conv_pool_2 = nets.simple_img_conv_pool(
        input=conv_pool_1, filter_size=5, num_filters=16, pool_size=2,
        pool_stride=2, act="relu")
    prediction = layers.fc(input=conv_pool_2, size=10, act="softmax")
    cost = layers.cross_entropy(input=prediction, label=label)
    avg_cost = layers.mean(cost)
    acc = layers.accuracy(input=prediction, label=label)
    return prediction, avg_cost, acc


def test_recognize_digits_conv(tmp_path):
    fluid.reset_default_env()
    img = layers.data(name="img", shape=[1, 28, 28], dtype="float32")
    label = layers.data(name="label", shape=[1], dtype="int64")
    prediction, avg_cost, acc = conv_net(img, label)
    fluid.optimizer.Adam(learning_rate=0.01).minimize(avg_cost)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    def feed(batch):
        xs = np.stack([s[0].reshape(1, 28, 28) for s in batch])
        ys = np.array([[s[1]] for s in batch], dtype=np.int64)
        return {"img": xs.astype(np.float32), "label": ys}

    reader = fluid.batch(mnist.train(), batch_size=32)
    losses, accs = [], []
    for i, data in enumerate(reader()):
        loss_v, acc_v = exe.run(feed=feed(data), fetch_list=[avg_cost, acc])
        losses.append(float(np.ravel(np.asarray(loss_v))[0]))
        accs.append(float(np.ravel(np.asarray(acc_v))[0]))
        if i >= 100:
            break
    # 100 steps, not 40: with this jax version's initializer draws the
    # net needs ~60 steps to clear the margin (0.12 -> 0.27 at 40 vs
    # 0.66 at 100) — the shorter run asserted convergence speed, not
    # convergence
    assert np.mean(accs[-5:]) > np.mean(accs[:5]) + 0.2, (
        f"accuracy did not improve: {np.mean(accs[:5])} -> "
        f"{np.mean(accs[-5:])}")

    path = str(tmp_path / "digits.model")
    fluid.io.save_inference_model(path, ["img"], [prediction], exe)
    prog, names, targets = fluid.io.load_inference_model(path, exe)
    sample = next(mnist.test()())
    (probs,) = exe.run(
        program=prog,
        feed={"img": sample[0].reshape(1, 1, 28, 28).astype(np.float32)},
        fetch_list=targets)
    probs = np.ravel(np.asarray(probs))
    assert probs.shape == (10,) and abs(probs.sum() - 1.0) < 1e-3
