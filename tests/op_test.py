"""OpTest harness (reference: python/paddle/fluid/tests/unittests/op_test.py:132).

Subclasses declare `op_type`, `inputs`, `attrs`, and reference `outputs`
(numpy); `check_output` runs the single-op program and compares, and
`check_grad` compares program-built analytic gradients (append_backward ->
jax.vjp under the hood) against central finite differences — the same
contract as the reference's get_numeric_gradient (op_test.py:48).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.core.framework import Program
from paddle_tpu.core.lod import LoDValue, create_lod_tensor


class OpTest:
    op_type: str = ""
    inputs: Dict = {}
    attrs: Dict = {}
    outputs: Dict = {}

    # ------------------------------------------------------------------
    def _norm_value(self, v):
        """Accept np arrays, (array, lod) tuples, or lists of sequences.
        None means "declared but unchecked" (matches the reference's
        no_check_set)."""
        if v is None:
            return None
        if isinstance(v, tuple) and len(v) == 2:  # (flat_data, [lengths])
            return create_lod_tensor(v[0], [v[1]])
        return np.asarray(v)

    def _build(self):
        prog = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(prog, startup):
            block = prog.global_block()
            feed = {}
            in_names: Dict[str, List[str]] = {}
            for slot, val in self.inputs.items():
                vals = val if isinstance(val, list) else [val]
                names = []
                for i, v in enumerate(vals):
                    name = f"{slot.lower()}_{i}"
                    rv = self._norm_value(v)
                    if isinstance(rv, LoDValue):
                        shape = [-1] + list(np.shape(rv.data)[2:])
                        lod_level = 1
                    else:
                        shape = list(np.shape(rv))
                        lod_level = 0
                    block.create_var(
                        name=name, shape=shape, dtype=rv.dtype if not isinstance(rv, LoDValue) else rv.data.dtype,
                        lod_level=lod_level, stop_gradient=False,
                    )
                    feed[name] = rv
                    names.append(name)
                in_names[slot] = names
            out_names: Dict[str, List[str]] = {}
            for slot, val in self.outputs.items():
                vals = val if isinstance(val, list) else [val]
                names = [f"out_{slot.lower()}_{i}" for i in range(len(vals))]
                out_names[slot] = names
            block.append_op(
                type=self.op_type,
                inputs=in_names,
                outputs=out_names,
                attrs=dict(self.attrs),
            )
        return prog, startup, feed, in_names, out_names

    # ------------------------------------------------------------------
    def check_output(self, atol=1e-5, rtol=1e-5):
        prog, startup, feed, _, out_names = self._build()
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.program_guard(prog, startup):
            fetch = [n for ns in out_names.values() for n in ns]
            got = exe.run(program=prog, feed=feed, fetch_list=fetch,
                          return_numpy=False)
        i = 0
        for slot, val in self.outputs.items():
            vals = val if isinstance(val, list) else [val]
            for want in vals:
                want = self._norm_value(want)
                g = got[i]
                i += 1
                if want is None:
                    continue
                gd = np.asarray(g.data if isinstance(g, LoDValue) else g)
                wd = np.asarray(
                    want.data if isinstance(want, LoDValue) else want
                )
                np.testing.assert_allclose(
                    gd.astype(np.float64), wd.astype(np.float64),
                    atol=atol, rtol=rtol,
                    err_msg=f"{self.op_type} output {slot} mismatch",
                )

    # ------------------------------------------------------------------
    def _run_loss(self, feed, prog, loss_name, extra_fetch=()):
        exe = fluid.Executor(fluid.CPUPlace())
        outs = exe.run(program=prog, feed=feed,
                       fetch_list=[loss_name, *extra_fetch])
        return outs

    def check_grad(
        self,
        inputs_to_check: Sequence[str],
        output_names,
        max_relative_error: float = 0.005,
        numeric_grad_delta: float = 1e-3,
        no_grad_set=None,
    ):
        if isinstance(output_names, str):
            output_names = [output_names]
        prog, startup, feed, in_names, out_names = self._build()
        cot_rng = np.random.RandomState(12345)
        # forward once to learn the runtime output shapes (desc shapes may
        # carry -1 batch dims)
        exe0 = fluid.Executor(fluid.CPUPlace())
        all_out = [n for ns in out_names.values() for n in ns]
        fwd_vals = exe0.run(program=prog, feed=feed, fetch_list=all_out,
                            return_numpy=False)
        runtime_shape = {
            n: np.shape(v.data if isinstance(v, LoDValue) else v)
            for n, v in zip(all_out, fwd_vals)
        }
        with fluid.program_guard(prog, startup):
            block = prog.global_block()
            # scalar loss = sum(output * random_cotangent) so grads are
            # well-conditioned even for constant-sum outputs (softmax);
            # mirrors the reference's user_defined_grad_outputs
            parts = []
            for slot, slot_names in out_names.items():
                for n in slot_names:
                    if not (
                        slot in output_names
                        or n in output_names
                        or len(out_names) == 1
                    ):
                        continue
                    v = block.var(n)
                    wname = n + "@COT"
                    w = cot_rng.uniform(
                        0.5, 1.5, size=runtime_shape[n]
                    ).astype("float32")
                    block.create_var(
                        name=wname, shape=list(w.shape), dtype="float32",
                        stop_gradient=True,
                    )
                    feed[wname] = w
                    parts.append(
                        fluid.layers.reduce_sum(
                            fluid.layers.elementwise_mul(
                                v, block.var(wname)
                            )
                        )
                    )
            total = parts[0]
            for p in parts[1:]:
                total = fluid.layers.elementwise_add(total, p)
            loss = fluid.layers.scale(total, scale=1.0)
            fluid.append_backward(loss)

        # analytic grads for the checked inputs
        check_names = []
        for slot in inputs_to_check:
            check_names.extend(in_names[slot])
        grad_names = [n + "@GRAD" for n in check_names]
        exe = fluid.Executor(fluid.CPUPlace())
        analytic = exe.run(program=prog, feed=feed, fetch_list=grad_names,
                           return_numpy=False)
        analytic = [
            np.asarray(a.data if isinstance(a, LoDValue) else a)
            for a in analytic
        ]

        # numeric grads by central differences on the same loss
        def loss_value(cur_feed):
            (lv,) = exe.run(program=prog, feed=cur_feed, fetch_list=[loss])
            return float(np.ravel(np.asarray(lv))[0])

        for name, ana in zip(check_names, analytic):
            base = feed[name]
            if isinstance(base, LoDValue):
                arr = np.array(base.data, dtype=np.float64)
                rebuild = lambda a: LoDValue(
                    a.astype(np.asarray(base.data).dtype), base.lengths
                )
                valid_mask = (
                    np.arange(arr.shape[1])[None, :, None]
                    < np.asarray(base.lengths)[:, None, None]
                )
            else:
                arr = np.array(base, dtype=np.float64)
                rebuild = lambda a: a.astype(np.asarray(base).dtype)
                valid_mask = np.ones_like(arr, dtype=bool)
            num = np.zeros_like(arr)
            flat = arr.reshape(-1)
            mask_flat = np.broadcast_to(valid_mask, arr.shape).reshape(-1)
            for i in range(flat.size):
                if not mask_flat[i]:
                    continue
                orig = flat[i]
                flat[i] = orig + numeric_grad_delta
                feed_p = dict(feed)
                feed_p[name] = rebuild(arr)
                up = loss_value(feed_p)
                flat[i] = orig - numeric_grad_delta
                feed_p[name] = rebuild(arr)
                down = loss_value(feed_p)
                flat[i] = orig
                num.reshape(-1)[i] = (up - down) / (2 * numeric_grad_delta)
            feed[name] = rebuild(arr)

            ana_m = np.where(
                np.broadcast_to(valid_mask, ana.shape), ana, 0.0
            )
            denom = np.maximum(
                np.maximum(np.abs(ana_m), np.abs(num)).max(), 1e-3
            )
            rel = np.abs(ana_m - num).max() / denom
            assert rel <= max_relative_error, (
                f"{self.op_type} grad wrt {name}: max relative error "
                f"{rel:.5f} > {max_relative_error}\nanalytic:\n{ana_m}\n"
                f"numeric:\n{num}"
            )
