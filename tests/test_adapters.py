"""Multi-tenant serving (ISSUE 19): paged batched-LoRA adapter pool.

Acceptance pinned here:
(a) one continuous-batching step serving >= 3 adapters + base rows is
    TOKEN-IDENTICAL to a per-tenant sequential oracle decoding each
    request alone under densely-merged weights (W' = W + A@B), across
    H_kv ∈ {4, 2} × {fp32, int8} KV pools × speculation on/off ×
    prefix-cache on, with zero leaked pages and zero in-flight
    adapters after every run;
(b) the prefix cache and the drafter corpus are adapter-NAMESPACED:
    one tenant's cached K/V chains and n-gram continuations are never
    served to another tenant (or to base traffic) for the same
    prompt bytes;
(c) pool mechanics audit: typed geometry/registration validation,
    refcounted acquire/release with LRU spill of cold residents only
    (an in-flight adapter is NEVER evicted — a full pack rejects
    typed instead), a CRC-failed fault-in drops the registration
    (chaos knob FAULT_SERVE_ADAPTER_CORRUPT), a bounded host tier
    rejects typed, and publish/retire refuse in-flight tenants;
(d) an unloadable adapter is a typed PER-REQUEST admission reject —
    before any KV page is claimed; the rest of the batch decodes on;
(e) tiered-KV sessions carry the adapter stamp: resuming a session
    under a different adapter_id RESETS it (idle and parked arms,
    counted in adapter_mismatch_resets) instead of resuming the wrong
    K/V; SeqExport pickles the stamp across process boundaries and
    Handoff.admit rejects a payload/request mismatch typed;
(f) the disaggregated fleet serves mixed tenants end to end
    (prefill acquires before allocating, the handoff carries the
    stamp) and FleetController.rolling_adapter_update hot-publishes /
    retires variants under the drain seam on every pooled replica;
(g) Engine.submit(adapter_id=...) validates the type and threads the
    id pass-through-only, like sampling;
(h) adapter observability is gated: FLAGS_observability off mints NO
    adapter metrics; on, the lifecycle events (load / fault_in /
    reject) and pool gauges appear.
"""

import pickle

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import observability as obs
from paddle_tpu.serving import (
    AdapterCorruptError,
    AdapterGeometryError,
    AdapterInUseError,
    AdapterMismatchError,
    AdapterNotRegisteredError,
    AdapterPool,
    AdapterPoolFullError,
    ContinuousBatchingLoop,
    DecodeConfig,
    DecodeRequest,
    Engine,
    EngineConfig,
    KVCachePool,
    PrefixCache,
    TieredSessionManager,
    full_decode,
    init_decode_params,
    make_adapter,
)
from paddle_tpu.serving.adapters import (
    AdapterHostFullError,
    adapter_gather_bytes_per_step,
)
from paddle_tpu.serving.fleet import (
    DecodeReplica,
    Fleet,
    FleetController,
    PrefillReplica,
)
from paddle_tpu.serving.fleet.handoff import Handoff
from paddle_tpu.serving.kvcache import SeqExport


def _cfg(**kw):
    base = dict(vocab_size=61, d_model=32, n_head=4, n_layer=2,
                d_inner=64, max_length=64)
    base.update(kw)
    return DecodeConfig(**base)


def _pool(cfg, num_pages=64, page_size=4, dtype="float32"):
    return KVCachePool(num_pages=num_pages, page_size=page_size,
                       num_layers=cfg.n_layer, num_heads=cfg.n_head,
                       head_dim=cfg.head_dim,
                       num_kv_heads=cfg.num_kv_heads, dtype=dtype)


def _adapters(cfg, names, rank=2, slots=4, **kw):
    ap = AdapterPool(cfg, slots=slots, max_rank=rank, **kw)
    for k, n in enumerate(names, start=1):
        ap.register_adapter(n, make_adapter(cfg, rank=rank, seed=10 + k))
    return ap


def _mixed_requests(cfg, rng, tenants, max_new=4, n_base=1):
    """One request per tenant plus `n_base` base-model requests, all
    with distinct prompts — the mixed batch under test."""
    reqs = []
    for aid in list(tenants) + [None] * n_base:
        prompt = rng.randint(1, cfg.vocab_size,
                             size=int(rng.randint(5, 12))).tolist()
        reqs.append(DecodeRequest(prompt=prompt, max_new_tokens=max_new,
                                  adapter_id=aid))
    return reqs


def _oracle_tokens(params, cfg, ap, req, dtype="float32", speculate=0):
    """The sequential dense-merge oracle: this request decoded ALONE
    through the same machinery under W' = W + A@B (base params when
    the request carries no adapter)."""
    merged = (ap.merged_params(params, req.adapter_id)
              if req.adapter_id is not None else params)
    pool = _pool(cfg, dtype=dtype)
    loop = ContinuousBatchingLoop(merged, cfg, pool, max_batch=1,
                                  speculate=speculate)
    (res,) = loop.run([DecodeRequest(prompt=list(req.prompt),
                                     max_new_tokens=req.max_new_tokens)])
    assert res.error is None, res.error
    assert pool.used_pages == 0
    return res.tokens


# -- (a) the headline parity matrix --------------------------------------

@pytest.mark.parametrize("dtype", ["float32", "int8"])
@pytest.mark.parametrize("n_kv", [None, 2])
def test_mixed_tenant_batch_token_identical(dtype, n_kv):
    cfg = _cfg(n_kv_head=n_kv)
    params = init_decode_params(cfg, seed=3)
    rng = np.random.RandomState(3)
    tenants = ["t1", "t2", "t3"]
    ap = _adapters(cfg, tenants, slots=4)
    pool = _pool(cfg, dtype=dtype)
    cache = PrefixCache(pool)
    loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=4,
                                  prefix_cache=cache, adapter_pool=ap)
    reqs = _mixed_requests(cfg, rng, tenants)
    results = loop.run(reqs)
    for req, res in zip(reqs, results):
        assert res.error is None, res.error
        assert res.tokens == _oracle_tokens(params, cfg, ap, req,
                                            dtype=dtype), req.adapter_id
        if dtype == "float32" and req.adapter_id is None:
            want, _ = full_decode(params, cfg, req.prompt,
                                  req.max_new_tokens)
            assert res.tokens == want
    cache.clear()
    assert pool.used_pages == 0
    assert ap.stats()["in_flight"] == 0
    assert ap.check_invariants()["ok"]
    assert pool.check_invariants()["ok"]
    assert loop.adapter_rows > 0
    assert loop.adapter_gather_bytes > 0


def test_mixed_tenant_batch_with_speculation_token_identical():
    cfg = _cfg()
    params = init_decode_params(cfg, seed=7)
    rng = np.random.RandomState(7)
    tenants = ["t1", "t2", "t3"]
    ap = _adapters(cfg, tenants, slots=4)
    pool = _pool(cfg, num_pages=96)
    loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=4,
                                  adapter_pool=ap, speculate=2)
    # motif-tiled prompts: the traffic shape prompt-lookup drafting
    # actually accepts on — otherwise d=2 degenerates to d=0
    reqs = []
    for aid in tenants + [None]:
        motif = rng.randint(1, cfg.vocab_size, size=3).tolist()
        reqs.append(DecodeRequest(prompt=(motif * 4)[:10],
                                  max_new_tokens=6, adapter_id=aid))
    results = loop.run(reqs)
    for req, res in zip(reqs, results):
        assert res.error is None, res.error
        # greedy speculation must be token-identical to the d=0
        # sequential dense-merge oracle — acceptance is a perf knob,
        # never a correctness one, per tenant
        assert res.tokens == _oracle_tokens(params, cfg, ap, req), \
            req.adapter_id
    assert pool.used_pages == 0
    assert ap.stats()["in_flight"] == 0


# -- (b) cross-tenant isolation ------------------------------------------

def test_prefix_cache_is_adapter_namespaced():
    cfg = _cfg()
    params = init_decode_params(cfg, seed=5)
    rng = np.random.RandomState(5)
    ap = _adapters(cfg, ["t1", "t2"], slots=4)
    pool = _pool(cfg)
    cache = PrefixCache(pool)
    loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=2,
                                  prefix_cache=cache, adapter_pool=ap)
    prompt = rng.randint(1, cfg.vocab_size, size=13).tolist()
    (base_res,) = loop.run([DecodeRequest(prompt=list(prompt),
                                          max_new_tokens=3)])
    assert base_res.error is None
    # the base run cached full pages for ITS namespace only: the same
    # prompt bytes match nothing for a tenant (or vice versa)
    assert cache.match(prompt).tokens > 0
    assert cache.match(prompt, adapter_id="t1").tokens == 0
    served_before = cache.stats()["cached_tokens_served"]
    (t1_res,) = loop.run([DecodeRequest(prompt=list(prompt),
                                        max_new_tokens=3,
                                        adapter_id="t1")])
    assert t1_res.error is None
    # the tenant request prefilled from scratch — zero cached tokens
    # crossed the namespace boundary — and its output is the merged-
    # weights oracle's, not a replay of base K/V
    assert cache.stats()["cached_tokens_served"] == served_before
    assert t1_res.tokens == _oracle_tokens(
        params, cfg, ap,
        DecodeRequest(prompt=list(prompt), max_new_tokens=3,
                      adapter_id="t1"))
    # now BOTH namespaces hold the chain; each matches only its own,
    # and the drafter's n-gram probe honors the same boundary
    assert cache.match(prompt, adapter_id="t1").tokens > 0
    assert cache.match(prompt, adapter_id="t2").tokens == 0
    probe = list(prompt[:4])
    if cache.ngram_continuation(probe, 4):
        assert not cache.ngram_continuation(probe, 4, adapter_id="t2")
    cache.clear()
    assert pool.used_pages == 0


# -- (c) pool mechanics ---------------------------------------------------

def test_register_validates_geometry_typed():
    cfg = _cfg()
    ap = AdapterPool(cfg, slots=2, max_rank=2)
    good = make_adapter(cfg, rank=2, seed=1)
    with pytest.raises(AdapterGeometryError):
        ap.register_adapter("r", {**good, "wq": (
            good["wq"][0][:, :1], good["wq"][1])})  # rank mismatch A vs B
    with pytest.raises(AdapterGeometryError):
        bad_a = np.zeros((cfg.d_model + 1, 2), np.float32)
        ap.register_adapter("shape", {**good, "wq": (bad_a,
                                                     good["wq"][1])})
    with pytest.raises(AdapterGeometryError):
        ap.register_adapter("rank", make_adapter(cfg, rank=4, seed=2))
    ap.register_adapter("ok", good)
    with pytest.raises(ValueError, match="publish"):
        ap.register_adapter("ok", good)


def test_lru_spills_cold_never_in_flight():
    cfg = _cfg()
    ap = _adapters(cfg, ["t1", "t2"], slots=1)
    s1 = ap.acquire("t1")
    assert s1 == 1
    # t1 is IN FLIGHT in the only slot: t2 must reject typed, not
    # evict the tenant mid-decode
    with pytest.raises(AdapterPoolFullError):
        ap.acquire("t2")
    ap.release("t1")
    # cold now — t2's fault-in spills it
    assert ap.acquire("t2") == 1
    st = ap.stats()
    assert st["spills"] == 1
    assert st["fault_ins"] == 2
    ap.release("t2")
    assert ap.check_invariants()["ok"]
    # refcount audit: double-acquire needs double-release
    ap.acquire("t1"); ap.acquire("t1")
    assert ap.stats()["in_flight"] == 2
    ap.release("t1")
    assert ap.stats()["in_flight"] == 1
    ap.release("t1")
    assert ap.stats()["in_flight"] == 0
    assert ap.check_invariants()["ok"]


def test_corrupt_host_payload_fails_crc_and_drops(monkeypatch):
    cfg = _cfg()
    ap = AdapterPool(cfg, slots=2, max_rank=2)
    monkeypatch.setenv("FAULT_SERVE_ADAPTER_CORRUPT", "1")
    ap.register_adapter("bad", make_adapter(cfg, rank=2, seed=1))
    with pytest.raises(AdapterCorruptError):
        ap.acquire("bad")
    # the registration is GONE — a bit-rotted payload must not be
    # retried into a tenant forever
    assert not ap.loadable("bad")
    st = ap.stats()
    assert st["corrupt_drops"] == 1
    assert ap.check_invariants()["ok"]


def test_bounded_host_tier_rejects_typed():
    cfg = _cfg()
    w = make_adapter(cfg, rank=2, seed=1)
    nbytes = sum(a.nbytes + b.nbytes for a, b in w.values())
    ap = AdapterPool(cfg, slots=2, max_rank=2, host_bytes=nbytes)
    ap.register_adapter("fits", w)
    with pytest.raises(AdapterHostFullError):
        ap.register_adapter("over", make_adapter(cfg, rank=2, seed=2))
    ap.retire("fits")
    ap.register_adapter("over", make_adapter(cfg, rank=2, seed=2))
    assert ap.check_invariants()["ok"]


def test_publish_retire_refuse_in_flight():
    cfg = _cfg()
    ap = _adapters(cfg, ["t1"], slots=2)
    w2 = make_adapter(cfg, rank=2, seed=99)
    ap.acquire("t1")
    with pytest.raises(AdapterInUseError):
        ap.publish("t1", w2)
    with pytest.raises(AdapterInUseError):
        ap.retire("t1")
    ap.release("t1")
    old = ap.merged_params(init_decode_params(cfg, seed=0), "t1")
    ap.publish("t1", w2)  # register-or-replace once cold
    new = ap.merged_params(init_decode_params(cfg, seed=0), "t1")
    assert not np.allclose(old["layers"][0]["wq"],
                           new["layers"][0]["wq"])
    ap.publish("t9", w2)  # register arm of the same seam
    assert ap.loadable("t9")
    ap.retire("t1")
    assert not ap.loadable("t1")
    with pytest.raises(AdapterNotRegisteredError):
        ap.acquire("t1")
    assert ap.check_invariants()["ok"]


def test_gather_bytes_scale_with_rows_not_weights():
    cfg = _cfg()
    one = adapter_gather_bytes_per_step(cfg, 2, 1)
    assert one > 0
    assert adapter_gather_bytes_per_step(cfg, 2, 4) == 4 * one
    # base-only traffic gathers nothing
    assert adapter_gather_bytes_per_step(cfg, 2, 0) == 0


# -- (d) typed admission reject ------------------------------------------

def test_unloadable_adapter_rejects_before_pages_rest_decodes():
    cfg = _cfg()
    params = init_decode_params(cfg, seed=2)
    rng = np.random.RandomState(2)
    ap = _adapters(cfg, ["t1"], slots=2)
    pool = _pool(cfg)
    loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=2,
                                  adapter_pool=ap)
    good = DecodeRequest(
        prompt=rng.randint(1, cfg.vocab_size, size=6).tolist(),
        max_new_tokens=4, adapter_id="t1")
    bad = DecodeRequest(
        prompt=rng.randint(1, cfg.vocab_size, size=6).tolist(),
        max_new_tokens=4, adapter_id="ghost")
    res_good, res_bad = loop.run([good, bad])
    assert isinstance(res_bad.error, AdapterNotRegisteredError)
    assert res_bad.tokens == []
    assert res_good.error is None
    assert res_good.tokens == _oracle_tokens(params, cfg, ap, good)
    assert loop.adapter_rejects == 1
    assert pool.used_pages == 0  # the reject claimed nothing
    assert ap.stats()["in_flight"] == 0


def test_adapter_request_without_pool_is_config_error():
    cfg = _cfg()
    params = init_decode_params(cfg, seed=2)
    loop = ContinuousBatchingLoop(params, cfg, _pool(cfg))
    with pytest.raises(ValueError, match="adapter_pool"):
        loop.run([DecodeRequest(prompt=[1, 2, 3], max_new_tokens=2,
                                adapter_id="t1")])


# -- (e) the tiered-KV / handoff adapter stamp ---------------------------

def test_session_resume_under_other_adapter_resets():
    cfg = _cfg()
    params = init_decode_params(cfg, seed=9)
    rng = np.random.RandomState(9)
    ap = _adapters(cfg, ["t1"], slots=2)
    pool = _pool(cfg)
    mgr = TieredSessionManager(pool, host_bytes=1 << 26)
    loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=2,
                                  session_manager=mgr, adapter_pool=ap)
    prompt = rng.randint(1, cfg.vocab_size, size=9).tolist()

    # idle-resident arm: turn 1 under t1, turn 2 under base
    sess = mgr.open_session()
    (r1,) = loop.run([DecodeRequest(prompt=list(prompt),
                                    max_new_tokens=4, session=sess,
                                    adapter_id="t1")])
    assert r1.error is None
    p2 = prompt + r1.tokens + [5, 7]
    (r2,) = loop.run([DecodeRequest(prompt=list(p2), max_new_tokens=4,
                                    session=sess)])
    assert r2.error is None
    assert mgr.stats()["adapter_mismatch_resets"] == 1
    # the reset re-prefilled from scratch under BASE weights — exactly
    # what a fresh sessionless decode of the transcript produces
    want, _ = full_decode(params, cfg, p2, 4)
    assert r2.tokens == want

    # parked arm: spill between the mismatched turns
    sess2 = mgr.open_session()
    (r3,) = loop.run([DecodeRequest(prompt=list(prompt),
                                    max_new_tokens=4, session=sess2,
                                    adapter_id="t1")])
    assert r3.error is None
    assert mgr.spill(sess2, wait=True)
    p4 = prompt + r3.tokens + [5, 7]
    (r4,) = loop.run([DecodeRequest(prompt=list(p4), max_new_tokens=4,
                                    session=sess2)])
    assert r4.error is None
    assert mgr.stats()["adapter_mismatch_resets"] == 2
    want4, _ = full_decode(params, cfg, p4, 4)
    assert r4.tokens == want4

    # matching stamps DO resume: one more t1 turn on a t1 session
    sess3 = mgr.open_session()
    (r5,) = loop.run([DecodeRequest(prompt=list(prompt),
                                    max_new_tokens=4, session=sess3,
                                    adapter_id="t1")])
    p6 = prompt + r5.tokens + [5, 7]
    resumes = mgr.stats()["resumes"]
    (r6,) = loop.run([DecodeRequest(prompt=list(p6), max_new_tokens=4,
                                    session=sess3, adapter_id="t1")])
    assert r6.error is None
    assert mgr.stats()["resumes"] == resumes + 1
    assert mgr.stats()["adapter_mismatch_resets"] == 2
    mgr.close()
    assert ap.stats()["in_flight"] == 0


def test_seq_export_pickles_adapter_stamp_and_handoff_rejects():
    cfg = _cfg()
    pool = _pool(cfg)
    pool.allocate(1)
    pages, slots = pool.append_tokens([1], [6])
    rng = np.random.RandomState(0)
    for li in range(pool.num_layers):
        kv = rng.rand(6, pool.num_kv_heads,
                      pool.head_dim).astype(np.float32)
        pool.write_kv(li, pages, slots, kv, kv)
    export = pool.export_seq(1, adapter_id="t1")
    assert export.adapter_id == "t1"
    wire = pickle.loads(pickle.dumps(export))
    assert wire.adapter_id == "t1"  # the stamp crosses the proc plane
    # a broker mix-up: payload prefilled under t1, request wants t2 —
    # admit must reject typed BEFORE touching any pool state
    hd = Handoff(
        request=DecodeRequest(prompt=[1, 2], max_new_tokens=2,
                              adapter_id="t2"),
        first_token=3, first_logits=np.zeros(cfg.vocab_size,
                                             np.float32),
        payload=wire)
    with pytest.raises(AdapterMismatchError):
        hd.admit(None, None, 7)
    assert not hd.admitted
    pool.free_seq(1)
    assert pool.used_pages == 0


# -- (f) fleet: mixed tenants end to end + hot publish/retire ------------

def _mk_adapter_fleet(params, cfg, weights):
    pools = []

    def _ap():
        ap = AdapterPool(cfg, slots=4, max_rank=2)
        for aid, w in weights.items():
            ap.register_adapter(aid, w)
        pools.append(ap)
        return ap

    fleet = Fleet(
        lambda n: PrefillReplica(
            n, params, cfg, num_pages=64, page_size=4, max_batch=4,
            adapter_pool=_ap()),
        lambda n: DecodeReplica(
            n, params, cfg, num_pages=64, page_size=4, max_batch=4,
            adapter_pool=_ap()))
    return fleet, pools


def test_fleet_serves_mixed_tenants_and_hot_updates():
    cfg = _cfg()
    params = init_decode_params(cfg, seed=11)
    rng = np.random.RandomState(11)
    weights = {f"t{k}": make_adapter(cfg, rank=2, seed=20 + k)
               for k in (1, 2)}
    fleet, pools = _mk_adapter_fleet(params, cfg, weights)
    try:
        oracle_ap = _adapters(cfg, [])  # geometry holder for merges
        for aid, w in weights.items():
            oracle_ap.register_adapter(aid, w)
        reqs = _mixed_requests(cfg, rng, ["t1", "t2"], max_new=4)
        results = [f.result(timeout=60)
                   for f in [fleet.submit(r) for r in reqs]]
        for req, res in zip(reqs, results):
            assert res.error is None, res.error
            assert res.tokens == _oracle_tokens(params, cfg, oracle_ap,
                                                req), req.adapter_id
        audit = fleet.audit()
        assert audit["pages_leaked"] == 0
        assert audit["invariants_ok"] == 1

        # hot adapter update under the drain seam: publish t3
        # everywhere, retire t1 everywhere
        w3 = make_adapter(cfg, rank=2, seed=33)
        ctl = FleetController(fleet)
        updated = ctl.rolling_adapter_update(publish={"t3": w3},
                                             retire=["t1"])
        assert len(updated) == 2  # one prefill + one decode replica
        for ap in pools:
            assert ap.loadable("t3")
            assert not ap.loadable("t1")

        # the retired tenant fails typed; the published one serves
        with pytest.raises((AdapterNotRegisteredError, ValueError)):
            fleet.submit(DecodeRequest(
                prompt=[1, 2, 3], max_new_tokens=2,
                adapter_id="t1")).result(timeout=60)
        oracle_ap.register_adapter("t3", w3)
        req3 = DecodeRequest(
            prompt=rng.randint(1, cfg.vocab_size, size=7).tolist(),
            max_new_tokens=4, adapter_id="t3")
        res3 = fleet.submit(req3).result(timeout=60)
        assert res3.error is None
        assert res3.tokens == _oracle_tokens(params, cfg, oracle_ap,
                                             req3)
        audit = fleet.audit()
        assert audit["pages_leaked"] == 0
        assert audit["invariants_ok"] == 1
        for ap in pools:
            assert ap.stats()["in_flight"] == 0
    finally:
        fleet.close()


# -- (g) Engine.submit threading -----------------------------------------

class _AdapterEchoBackend:
    """Pass-through backend recording the adapter_id call kwarg — the
    decode-style seam Engine.submit threads per-request variants to."""

    feed_names = ["x"]
    fetch_names = ["y"]
    meta: dict = {}

    def __init__(self):
        self.seen = []

    def __call__(self, feed, adapter_id=None):
        self.seen.append(adapter_id)
        return [np.asarray(feed["x"])]


def test_engine_submit_threads_adapter_id_pass_through_only():
    backend = _AdapterEchoBackend()
    eng = Engine(backend, config=EngineConfig(buckets=()))
    try:
        with pytest.raises(TypeError, match="adapter_id"):
            eng.submit({"x": np.ones((1, 2), np.float32)}, adapter_id=5)
        eng.submit({"x": np.ones((1, 2), np.float32)},
                   adapter_id="tenant-a").result(timeout=10)
        eng.submit({"x": np.ones((1, 2), np.float32)}).result(timeout=10)
        assert backend.seen == ["tenant-a", None]
    finally:
        eng.close()
    # a bucketed ladder pads many requests into one batch — per-request
    # variants cannot apply, same contract as sampling/call_kwargs
    bucketed = Engine(_AdapterEchoBackend(),
                      config=EngineConfig(buckets=(1,), max_wait_s=0.0))
    try:
        with pytest.raises(ValueError, match="pass-through"):
            bucketed.submit({"x": np.ones((1, 2), np.float32)},
                            adapter_id="tenant-a")
    finally:
        bucketed.close()


# -- (h) gated observability ---------------------------------------------

def _tenanted_run(include_reject=False):
    cfg = _cfg()
    params = init_decode_params(cfg, seed=4)
    rng = np.random.RandomState(4)
    ap = _adapters(cfg, ["t1", "t2"], slots=4)
    pool = _pool(cfg)
    loop = ContinuousBatchingLoop(params, cfg, pool, max_batch=4,
                                  adapter_pool=ap)
    reqs = _mixed_requests(cfg, rng, ["t1", "t2"], max_new=3)
    if include_reject:
        reqs.append(DecodeRequest(prompt=[1, 2, 3], max_new_tokens=3,
                                  adapter_id="ghost"))
    loop.run(reqs)
    assert pool.used_pages == 0


def test_adapter_metrics_disabled_path_mints_nothing():
    obs.reset()
    try:
        _tenanted_run()  # FLAGS_observability defaults off
        names = {m.name for m in obs.default_registry().metrics()}
        assert not any("adapter" in n for n in names), names
    finally:
        obs.reset()


def test_adapter_metrics_enabled_records_events_and_gauges():
    fluid.set_flags({"FLAGS_observability": True})
    obs.reset()
    try:
        _tenanted_run(include_reject=True)
        reg = obs.default_registry()
        ev = reg.counter("paddle_tpu_serving_adapter_events", "")
        assert ev.value(event="load") == 2
        assert ev.value(event="fault_in") == 2
        assert ev.value(event="reject") == 1
        names = {m.name for m in reg.metrics()}
        assert "paddle_tpu_serving_adapter_pool_bytes" in names
        assert "paddle_tpu_serving_adapter_pool_utilization" in names
        assert ("paddle_tpu_serving_adapter_gather_bytes_per_step"
                in names)
    finally:
        obs.reset()
        fluid.set_flags({"FLAGS_observability": False})
