"""Cross-process elastic master: the task queue serves REAL worker
subprocesses over the elastic.rpc transport; one worker crashes mid-task
and the master's lease timeout re-queues its work (reference:
go/master/service.go timeout/failure re-queue :313-341, exercised by the
Go tests through a real RPC client)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SERVER = '''
import sys, time
sys.path.insert(0, {repo!r})
from paddle_tpu.elastic.master import InMemStore, MasterService
from paddle_tpu.elastic.rpc import serve_master

port = int(sys.argv[1])
globs = sys.argv[2]
svc = MasterService(InMemStore(), chunks_per_task=1, timeout_dur=2.0,
                    failure_max=3)
svc.set_dataset([globs])
srv = serve_master(svc, port=port)
print("SERVING", srv.endpoint, flush=True)
while True:
    time.sleep(0.2)
'''

_WORKER = '''
import json, os, sys, time
sys.path.insert(0, {repo!r})
from paddle_tpu.elastic.master import (NoMoreAvailableError,
    PassBeforeError)
from paddle_tpu.elastic.rpc import RemoteMaster

endpoint, out_path, crash_after = sys.argv[1], sys.argv[2], int(sys.argv[3])
m = RemoteMaster(endpoint)
done = []
n = 0
while True:
    try:
        task = m.get_task(0)
    except NoMoreAvailableError:
        # pass still draining (another worker's lease may yet expire and
        # re-queue) — wait and retry, like ElasticTrainer does
        time.sleep(0.3)
        continue
    except PassBeforeError:
        break  # the pass rolled over: nothing left for us
    n += 1
    if crash_after and n >= crash_after:
        # simulate a crash: exit WITHOUT reporting; the lease must expire
        print("CRASHING with task", task.id, flush=True)
        os._exit(17)
    m.heartbeat(out_path)
    time.sleep(0.1)  # "process" the chunk
    done.append(sorted(task.chunks))
    m.task_finished(task.id)
open(out_path, "w").write(json.dumps(done))
print("WORKER DONE", len(done), flush=True)
'''


def test_elastic_master_cross_process_crash_requeue(tmp_path):
    # 6 one-chunk tasks
    for i in range(6):
        (tmp_path / f"chunk-{i}.dat").write_text("x")
    server_py = str(tmp_path / "server.py")
    worker_py = str(tmp_path / "worker.py")
    open(server_py, "w").write(_SERVER.format(repo=REPO))
    open(worker_py, "w").write(_WORKER.format(repo=REPO))

    env = {**os.environ}
    server = subprocess.Popen(
        [sys.executable, server_py, "0",
         str(tmp_path / "chunk-*.dat")],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    try:
        line = server.stdout.readline()
        assert "SERVING" in line, line
        endpoint = line.split()[1]

        # worker A crashes after leasing its 2nd task; worker B survives
        out_a = str(tmp_path / "a.json")
        out_b = str(tmp_path / "b.json")
        wa = subprocess.Popen(
            [sys.executable, worker_py, endpoint, out_a, "2"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        oa, _ = wa.communicate(timeout=120)
        assert wa.returncode == 17 and "CRASHING" in oa, oa

        wb = subprocess.Popen(
            [sys.executable, worker_py, endpoint, out_b, "0"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        ob, _ = wb.communicate(timeout=120)
        assert wb.returncode == 0, ob

        done_b = json.loads(open(out_b).read())
        # every chunk processed exactly once across the pass, INCLUDING
        # the crashed worker's re-queued lease (worker A finished 1
        # before crashing with the 2nd)
        all_chunks = sorted(c for t in done_b for c in t)
        assert len(done_b) == 5, (len(done_b), done_b)
        crashed = [c for c in map(str, tmp_path.glob("chunk-*.dat"))
                   if c not in all_chunks]
        assert len(crashed) == 1  # only worker A's FIRST (finished) task
    finally:
        server.kill()
        server.wait()


def test_remote_master_exposes_failure_max():
    """ElasticTrainer reads master.failure_max for its give-up message —
    the RPC proxy must expose it too."""
    from paddle_tpu.elastic.master import InMemStore, MasterService
    from paddle_tpu.elastic.rpc import RemoteMaster, serve_master

    svc = MasterService(InMemStore(), failure_max=7)
    srv = serve_master(svc, port=0)
    try:
        m = RemoteMaster(srv.endpoint)
        assert m.failure_max == 7
    finally:
        srv.shutdown()
