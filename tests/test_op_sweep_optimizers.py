"""Per-op sweep: optimizer update math vs independent numpy references
(reference: test_sgd_op.py, test_adam_op.py, ... over
operators/optimizers/*_op.cc — optimizers are ops in the graph)."""

import numpy as np
import pytest

from op_test import OpTest


def _rand(shape, seed, lo=-1.0, hi=1.0):
    return np.random.RandomState(seed).uniform(lo, hi, shape).astype("float32")


P = _rand((4, 6), 1)
G = _rand((4, 6), 2)
LR = np.array([0.1], dtype="float32")


def _run(op_type, inputs, attrs, outputs, atol=1e-5):
    class T(OpTest):
        pass

    t = T()
    T.op_type = op_type
    t.inputs = inputs
    t.attrs = attrs
    t.outputs = outputs
    t.check_output(atol=atol, rtol=1e-5)


def test_sgd():
    _run("sgd", {"Param": P, "Grad": G, "LearningRate": LR}, {},
         {"ParamOut": P - 0.1 * G})


def test_momentum():
    v = _rand((4, 6), 3)
    mu = 0.9
    v_new = mu * v + G
    _run("momentum",
         {"Param": P, "Grad": G, "Velocity": v, "LearningRate": LR},
         {"mu": mu},
         {"ParamOut": P - 0.1 * v_new, "VelocityOut": v_new})


def test_momentum_nesterov():
    v = _rand((4, 6), 3)
    mu = 0.9
    v_new = mu * v + G
    _run("momentum",
         {"Param": P, "Grad": G, "Velocity": v, "LearningRate": LR},
         {"mu": mu, "use_nesterov": True},
         {"ParamOut": P - 0.1 * (G + mu * v_new), "VelocityOut": v_new})


def test_adam():
    m = _rand((4, 6), 4)
    v = np.abs(_rand((4, 6), 5))
    b1, b2, eps = 0.9, 0.999, 1e-8
    b1p = np.array([b1 ** 3], dtype="float32")
    b2p = np.array([b2 ** 3], dtype="float32")
    m_new = b1 * m + (1 - b1) * G
    v_new = b2 * v + (1 - b2) * G * G
    lr_t = 0.1 * np.sqrt(1 - b2p) / (1 - b1p)
    _run("adam",
         {"Param": P, "Grad": G, "Moment1": m, "Moment2": v,
          "Beta1Pow": b1p, "Beta2Pow": b2p, "LearningRate": LR},
         {"beta1": b1, "beta2": b2, "epsilon": eps},
         {"ParamOut": P - lr_t * m_new / (np.sqrt(v_new) + eps),
          "Moment1Out": m_new, "Moment2Out": v_new,
          "Beta1PowOut": b1p * b1, "Beta2PowOut": b2p * b2})


def test_adamax():
    m = _rand((4, 6), 6)
    u = np.abs(_rand((4, 6), 7)) + 0.1
    b1, b2, eps = 0.9, 0.999, 1e-8
    b1p = np.array([b1 ** 2], dtype="float32")
    m_new = b1 * m + (1 - b1) * G
    u_new = np.maximum(b2 * u, np.abs(G))
    _run("adamax",
         {"Param": P, "Grad": G, "Moment": m, "InfNorm": u,
          "Beta1Pow": b1p, "LearningRate": LR},
         {"beta1": b1, "beta2": b2, "epsilon": eps},
         {"ParamOut": P - (0.1 / (1 - b1p)) * m_new / (u_new + eps),
          "MomentOut": m_new, "InfNormOut": u_new})


def test_adagrad():
    m = np.abs(_rand((4, 6), 8))
    eps = 1e-6
    m_new = m + G * G
    _run("adagrad",
         {"Param": P, "Grad": G, "Moment": m, "LearningRate": LR},
         {"epsilon": eps},
         {"ParamOut": P - 0.1 * G / (np.sqrt(m_new) + eps), "MomentOut": m_new})


def test_decayed_adagrad():
    m = np.abs(_rand((4, 6), 9))
    decay, eps = 0.95, 1e-6
    m_new = decay * m + (1 - decay) * G * G
    _run("decayed_adagrad",
         {"Param": P, "Grad": G, "Moment": m, "LearningRate": LR},
         {"decay": decay, "epsilon": eps},
         {"ParamOut": P - 0.1 * G / (np.sqrt(m_new) + eps), "MomentOut": m_new})


def test_proximal_adagrad():
    m = np.abs(_rand((4, 6), 10)) + 0.1
    l1, l2 = 0.01, 0.02
    m_new = m + G * G
    lr_t = 0.1 / np.sqrt(m_new)
    prox = P - lr_t * G
    want = np.sign(prox) * np.maximum(np.abs(prox) - lr_t * l1, 0) / (1 + lr_t * l2)
    _run("proximal_adagrad",
         {"Param": P, "Grad": G, "Moment": m, "LearningRate": LR},
         {"l1": l1, "l2": l2},
         {"ParamOut": want, "MomentOut": m_new})


def test_proximal_gd():
    l1, l2 = 0.01, 0.02
    prox = P - 0.1 * G
    want = np.sign(prox) * np.maximum(np.abs(prox) - 0.1 * l1, 0) / (1 + 0.1 * l2)
    _run("proximal_gd",
         {"Param": P, "Grad": G, "LearningRate": LR},
         {"l1": l1, "l2": l2}, {"ParamOut": want})


def test_adadelta():
    asg = np.abs(_rand((4, 6), 11))
    asu = np.abs(_rand((4, 6), 12))
    rho, eps = 0.95, 1e-6
    asg_new = rho * asg + (1 - rho) * G * G
    update = -np.sqrt((asu + eps) / (asg_new + eps)) * G
    asu_new = rho * asu + (1 - rho) * update * update
    _run("adadelta",
         {"Param": P, "Grad": G, "AvgSquaredGrad": asg,
          "AvgSquaredUpdate": asu, "LearningRate": LR},
         {"rho": rho, "epsilon": eps},
         {"ParamOut": P + update, "AvgSquaredGradOut": asg_new,
          "AvgSquaredUpdateOut": asu_new})


def test_rmsprop():
    ms = np.abs(_rand((4, 6), 13)) + 0.1
    mom = _rand((4, 6), 14)
    rho, eps, momentum = 0.9, 1e-10, 0.5
    ms_new = rho * ms + (1 - rho) * G * G
    mom_new = momentum * mom + 0.1 * G / np.sqrt(ms_new + eps)
    _run("rmsprop",
         {"Param": P, "Grad": G, "MeanSquare": ms, "Moment": mom,
          "LearningRate": LR},
         {"decay": rho, "epsilon": eps, "momentum": momentum},
         {"ParamOut": P - mom_new, "MeanSquareOut": ms_new,
          "MomentOut": mom_new}, atol=1e-4)


def test_rmsprop_centered():
    ms = np.abs(_rand((4, 6), 13)) + 0.5
    mom = _rand((4, 6), 14)
    mg = _rand((4, 6), 15) * 0.1
    rho, eps, momentum = 0.9, 1e-10, 0.5
    ms_new = rho * ms + (1 - rho) * G * G
    mg_new = rho * mg + (1 - rho) * G
    mom_new = momentum * mom + 0.1 * G / np.sqrt(ms_new - mg_new ** 2 + eps)
    _run("rmsprop",
         {"Param": P, "Grad": G, "MeanSquare": ms, "Moment": mom,
          "MeanGrad": mg, "LearningRate": LR},
         {"decay": rho, "epsilon": eps, "momentum": momentum, "centered": True},
         {"ParamOut": P - mom_new, "MeanSquareOut": ms_new,
          "MomentOut": mom_new, "MeanGradOut": mg_new}, atol=1e-4)


def test_ftrl():
    sq = np.abs(_rand((4, 6), 16)) + 0.1
    lin = _rand((4, 6), 17)
    l1, l2, power = 0.1, 0.2, -0.5
    sq_new = sq + G * G
    sigma = (sq_new ** 0.5 - sq ** 0.5) / 0.1
    lin_new = lin + G - sigma * P
    quad = sq_new ** 0.5 / 0.1 + 2 * l2
    pre = np.sign(lin_new) * l1 - lin_new
    want = np.where(np.abs(lin_new) > l1, pre / quad, np.zeros_like(P))
    _run("ftrl",
         {"Param": P, "Grad": G, "SquaredAccumulator": sq,
          "LinearAccumulator": lin, "LearningRate": LR},
         {"l1": l1, "l2": l2, "lr_power": power},
         {"ParamOut": want, "SquaredAccumOut": sq_new,
          "LinearAccumOut": lin_new}, atol=1e-4)


def test_lars_momentum():
    v = _rand((4, 6), 18)
    mu, coeff, decay = 0.9, 1e-3, 5e-4
    p_norm = np.sqrt((P.astype(np.float64) ** 2).sum())
    g_norm = np.sqrt((G.astype(np.float64) ** 2).sum())
    local_lr = 0.1 * coeff * p_norm / (g_norm + decay * p_norm + 1e-12)
    v_new = mu * v + local_lr * (G + decay * P)
    _run("lars_momentum",
         {"Param": P, "Grad": G, "Velocity": v, "LearningRate": LR},
         {"mu": mu, "lars_coeff": coeff, "lars_weight_decay": decay},
         {"ParamOut": (P - v_new).astype("float32"),
          "VelocityOut": v_new.astype("float32")}, atol=1e-4)
