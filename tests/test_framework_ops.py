"""Framework-plumbing op tests (reference: test_hsigmoid_op.py,
test_tensor_array_to_tensor.py, test_merge_selectedrows_op.py,
test_get_tensor_from_selected_rows_op.py, test_split_ids_op.py,
test_merge_ids_op.py, test_split_selected_rows_op.py,
test_reorder_lod_tensor.py, test_fc_op.py,
test_fused_elemwise_activation_op.py)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.core.lod import create_lod_tensor
from paddle_tpu.core.selected_rows import SelectedRowsValue
from paddle_tpu.ops import framework_ops as F

from op_test import OpTest


def _rand(shape, seed=0):
    return np.random.RandomState(seed).uniform(-1, 1, shape).astype("float32")


# ---------------------------------------------------------------------------
# hierarchical_sigmoid
# ---------------------------------------------------------------------------
def _hsigmoid_ref(x, w, label, bias, num_classes):
    """Direct port of the bit-code walk (matrix_bit_code.h SimpleCode)."""
    N, D = x.shape
    L = int(num_classes - 1).bit_length()
    pre = np.zeros((N, L), dtype=np.float64)
    out = np.zeros((N,), dtype=np.float64)
    for i in range(N):
        c = int(label[i]) + num_classes
        length = c.bit_length() - 1
        for j in range(length):
            idx = (c >> (j + 1)) - 1
            bit = (c >> j) & 1
            v = float(x[i].astype(np.float64) @ w[idx].astype(np.float64))
            if bias is not None:
                v += float(bias[idx])
            v = np.clip(v, -40.0, 40.0)
            pre[i, j] = v
        # softplus over ALL L positions (out-of-path zeros add log 2,
        # matching the reference's zero-init pre_out)
        out[i] = np.log1p(np.exp(pre[i])).sum() - sum(
            ((c >> j) & 1) * pre[i, j] for j in range(length)
        )
    return pre, out


def test_hierarchical_sigmoid_output_and_grad():
    num_classes = 6
    x = _rand((4, 5), seed=1)
    w = _rand((num_classes - 1, 5), seed=2)
    bias = _rand((num_classes - 1, 1), seed=3)
    label = np.array([[0], [2], [4], [5]], dtype="int64")
    pre, out = _hsigmoid_ref(x, w, label.ravel(), bias.ravel(), num_classes)

    class T(OpTest):
        op_type = "hierarchical_sigmoid"

    t = T()
    t.inputs = {"X": x, "W": w, "Label": label, "Bias": bias}
    t.attrs = {"num_classes": num_classes}
    t.outputs = {"Out": out[:, None].astype("float32"),
                 "PreOut": pre.astype("float32")}
    t.check_output(atol=2e-5, rtol=2e-5)
    t.check_grad(["X", "W", "Bias"], "Out", max_relative_error=0.02)


def test_hsigmoid_layer_trains():
    x = fluid.layers.data(name="x", shape=[8], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="int64")
    cost = fluid.layers.hsigmoid(x, y, num_classes=10)
    loss = fluid.layers.reduce_mean(cost)
    fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    xs = rng.randn(16, 8).astype("float32")
    ys = rng.randint(0, 10, (16, 1)).astype("int64")
    losses = [
        float(np.ravel(exe.run(feed={"x": xs, "y": ys},
                               fetch_list=[loss])[0])[0])
        for _ in range(25)
    ]
    assert losses[-1] < losses[0], (losses[0], losses[-1])


# ---------------------------------------------------------------------------
# tensor_array_to_tensor
# ---------------------------------------------------------------------------
def test_tensor_array_to_tensor():
    prog = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.layers.data(name="x", shape=[3], dtype="float32")
        arr = fluid.layers.create_array("float32")
        i0 = fluid.layers.fill_constant(shape=[1], dtype="int64", value=0)
        i1 = fluid.layers.fill_constant(shape=[1], dtype="int64", value=1)
        fluid.layers.array_write(x, i0, array=arr)
        fluid.layers.array_write(x, i1, array=arr)
        out, idx = fluid.layers.tensor_array_to_tensor(arr, axis=0)
    exe = fluid.Executor(fluid.CPUPlace())
    xs = _rand((2, 3), seed=4)
    got, gidx = exe.run(program=prog, feed={"x": xs},
                        fetch_list=[out, idx])
    np.testing.assert_allclose(got, np.concatenate([xs, xs], axis=0),
                               rtol=1e-6)
    np.testing.assert_array_equal(gidx, [2, 2])


# ---------------------------------------------------------------------------
# SelectedRows utilities (direct lowering tests: these values only arise
# inside compiled programs, from sparse grads)
# ---------------------------------------------------------------------------
def test_merge_selected_rows():
    ids = np.array([1, 3, 1, 7], dtype=np.int32)
    rows = np.arange(8, dtype=np.float32).reshape(4, 2)
    sr = SelectedRowsValue(ids, rows, height=10)
    (merged,) = F._merge_selected_rows(None, {"X": [sr]}, {})["Out"]
    dense = np.asarray(merged.to_dense())
    want = np.zeros((10, 2), dtype=np.float32)
    for i, r in zip(ids, rows):
        want[i] += r
    np.testing.assert_allclose(dense, want, rtol=1e-6)


def test_get_tensor_from_selected_rows():
    ids = np.array([2, 5], dtype=np.int32)
    rows = _rand((2, 3), seed=5)
    sr = SelectedRowsValue(ids, rows, height=8)
    (t,) = F._get_tensor_from_selected_rows(None, {"X": [sr]}, {})["Out"]
    np.testing.assert_allclose(np.asarray(t), rows, rtol=1e-6)


def test_split_merge_ids_roundtrip():
    ids = np.array([0, 1, 2, 3, 4, 5], dtype=np.int64)
    shards = F._split_ids(None, {"Ids": [ids], "Out": [None, None]},
                          {"num_shards": 2})["Out"]
    assert len(shards) == 2
    s0 = np.asarray(shards[0]).ravel()
    np.testing.assert_array_equal(s0, [0, -1, 2, -1, 4, -1])
    # rows per shard: gather a fake table at each shard's ids
    table = np.arange(12, dtype=np.float32).reshape(6, 2)
    xs = []
    for s in shards:
        sid = np.asarray(s).ravel()
        r = np.where(sid[:, None] >= 0, table[np.maximum(sid, 0)], 0.0)
        xs.append(r.astype(np.float32))
    (merged,) = F._merge_ids(
        None, {"Ids": [ids], "X": xs}, {})["Out"]
    np.testing.assert_allclose(np.asarray(merged), table, rtol=1e-6)


def test_split_selected_rows():
    ids = np.array([1, 4, 7], dtype=np.int32)
    rows = _rand((3, 2), seed=6)
    sr = SelectedRowsValue(ids, rows, height=10)
    outs = F._split_selected_rows(
        None, {"X": [sr]}, {"height_sections": [5, 5]})["Out"]
    d0 = np.asarray(outs[0].to_dense())
    d1 = np.asarray(outs[1].to_dense())
    want0 = np.zeros((5, 2), dtype=np.float32)
    want0[1] = rows[0]
    want0[4] = rows[1]
    want1 = np.zeros((5, 2), dtype=np.float32)
    want1[2] = rows[2]
    np.testing.assert_allclose(d0, want0, rtol=1e-6)
    np.testing.assert_allclose(d1, want1, rtol=1e-6)


# ---------------------------------------------------------------------------
# reorder_lod_tensor_by_rank
# ---------------------------------------------------------------------------
def test_reorder_lod_tensor_by_rank():
    prog = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.layers.data(name="x", shape=[2], dtype="float32",
                              lod_level=1)
        table = fluid.layers.control_flow.lod_rank_table(x)
        block = prog.global_block()
        out = block.create_var(name="reordered", shape=x.shape,
                               dtype=x.dtype, lod_level=1)
        block.append_op(
            type="reorder_lod_tensor_by_rank",
            inputs={"X": [x], "RankTable": [table]},
            outputs={"Out": [out]},
        )
    exe = fluid.Executor(fluid.CPUPlace())
    # 3 sequences of lengths 1, 3, 2 -> rank order is seq1, seq2, seq0
    flat = np.arange(12, dtype="float32").reshape(6, 2)
    lod = create_lod_tensor(flat, [[1, 3, 2]])
    (got,) = exe.run(program=prog, feed={"x": lod}, fetch_list=[out],
                     return_numpy=False)
    lens = np.asarray(got.lengths)
    np.testing.assert_array_equal(lens, [3, 2, 1])
    padded = np.asarray(got.data)
    src = np.asarray(lod.data)
    np.testing.assert_allclose(padded[0], src[1], rtol=1e-6)
    np.testing.assert_allclose(padded[1], src[2], rtol=1e-6)
    np.testing.assert_allclose(padded[2], src[0], rtol=1e-6)


# ---------------------------------------------------------------------------
# fused ops
# ---------------------------------------------------------------------------
def test_fc_op():
    x = _rand((4, 6), seed=7)
    w = _rand((6, 3), seed=8)
    b = _rand((3,), seed=9)

    class T(OpTest):
        op_type = "fc"

    t = T()
    t.inputs = {"Input": x, "W": w, "Bias": b}
    t.attrs = {"in_num_col_dims": 1}
    t.outputs = {"Out": x @ w + b}
    t.check_output(atol=2e-5, rtol=2e-5)
    t.check_grad(["Input", "W"], "Out", max_relative_error=0.02)


def test_fused_elemwise_activation():
    x = _rand((3, 4), seed=10)
    y = _rand((3, 4), seed=11)

    class T(OpTest):
        op_type = "fused_elemwise_activation"

    t = T()
    t.inputs = {"X": x, "Y": y}
    t.attrs = {"functor_list": ["relu", "elementwise_add"]}
    t.outputs = {"Out": np.maximum(x + y, 0.0)}
    t.check_output(atol=2e-5, rtol=2e-5)
    t.check_grad(["X", "Y"], "Out", max_relative_error=0.02)


def test_fake_init():
    class T(OpTest):
        op_type = "fake_init"

    t = T()
    t.inputs = {}
    t.attrs = {"shape": [2, 3], "dtype": int(fluid.core.DataType.FP32)}
    t.outputs = {"Out": np.zeros((2, 3), dtype="float32")}
    t.check_output()


def test_fused_elemwise_binary_outer():
    """[binary, unary] form computes Binary(x, Unary(y)), unary on Y."""
    x = _rand((3, 4), seed=12)
    y = _rand((3, 4), seed=13)

    class T(OpTest):
        op_type = "fused_elemwise_activation"

    t = T()
    t.inputs = {"X": x, "Y": y}
    t.attrs = {"functor_list": ["elementwise_add", "relu"]}
    t.outputs = {"Out": x + np.maximum(y, 0.0)}
    t.check_output(atol=2e-5, rtol=2e-5)


def test_fused_elemwise_scale_is_unary():
    x = _rand((3, 4), seed=14)
    y = _rand((3, 4), seed=15)

    class T(OpTest):
        op_type = "fused_elemwise_activation"

    t = T()
    t.inputs = {"X": x, "Y": y}
    t.attrs = {"functor_list": ["elementwise_add", "scale"], "scale": 2.0}
    t.outputs = {"Out": x + 2.0 * y}
    t.check_output(atol=2e-5, rtol=2e-5)
