"""Native LoD packer (native/lodpack.cc): identical output to the Python
pack loop, across dtypes/feature shapes, plus direct ABI checks."""

import ctypes

import numpy as np
import pytest

from paddle_tpu import native
from paddle_tpu.core.lod import LoDValue, create_lod_tensor, _pack_native


def _python_pack(seqs):
    lengths = np.asarray([len(s) for s in seqs], dtype=np.int32)
    max_len = int(lengths.max())
    feat = seqs[0].shape[1:]
    out = np.zeros((len(seqs), max_len) + feat, dtype=seqs[0].dtype)
    for i, s in enumerate(seqs):
        out[i, : len(s)] = s
    return out, lengths


@pytest.mark.parametrize("dtype,feat", [
    ("float32", (8,)), ("int64", ()), ("float64", (3, 4)), ("uint8", (2,)),
])
def test_native_pack_matches_python(dtype, feat):
    lib = native.load("lodpack")
    if lib is None:
        pytest.skip("native toolchain unavailable")
    rng = np.random.default_rng(0)
    seqs = [
        rng.standard_normal((l,) + feat).astype(dtype)
        for l in [3, 1, 7, 5, 2]
    ]
    want, lengths = _python_pack(seqs)
    got = _pack_native(seqs, lengths, int(lengths.max()), feat,
                       np.dtype(dtype))
    assert got is not None
    np.testing.assert_array_equal(got, want)


def test_create_lod_tensor_uses_pack(monkeypatch):
    if native.load("lodpack") is None:
        pytest.skip("native toolchain unavailable")
    # prove the NATIVE path produced the result: poison the numpy fallback
    # (sys.modules lookup: the fluid-parity alias of paddle_tpu.core breaks
    # attribute-style `import paddle_tpu.core.lod as ...`)
    import sys as _sys

    lod_mod = _sys.modules["paddle_tpu.core.lod"]

    calls = {"native": 0}
    real = lod_mod._pack_native

    def counting(*a, **k):
        r = real(*a, **k)
        assert r is not None, "native pack unexpectedly fell back"
        calls["native"] += 1
        return r

    monkeypatch.setattr(lod_mod, "_pack_native", counting)
    seqs = [np.arange(6, dtype=np.float32).reshape(3, 2),
            np.arange(2, dtype=np.float32).reshape(1, 2)]
    v = create_lod_tensor(seqs)
    assert calls["native"] == 1
    assert isinstance(v, LoDValue)
    np.testing.assert_array_equal(v.lengths, [3, 1])
    np.testing.assert_array_equal(v.data[0], seqs[0])
    np.testing.assert_array_equal(v.data[1, :1], seqs[1])
    np.testing.assert_array_equal(v.data[1, 1:], 0)


def test_flat_path_uses_single_pass_pack():
    if native.load("lodpack") is None:
        pytest.skip("native toolchain unavailable")
    flat = np.arange(12, dtype=np.float32).reshape(6, 2)
    v = create_lod_tensor(flat, recursive_seq_lens=[[4, 2]])
    assert isinstance(v, LoDValue)
    np.testing.assert_array_equal(v.lengths, [4, 2])
    np.testing.assert_array_equal(v.data[0], flat[:4])
    np.testing.assert_array_equal(v.data[1, :2], flat[4:6])
    np.testing.assert_array_equal(v.data[1, 2:], 0)


def test_flat_abi_bad_lengths_rejected():
    lib = native.load("lodpack")
    if lib is None:
        pytest.skip("native toolchain unavailable")
    src = np.arange(4, dtype=np.float32)
    lens = np.asarray([5], dtype=np.int32)  # exceeds max_len
    dst = np.zeros((1, 4), dtype=np.float32)
    rc = lib.lp_pack_flat(
        src.ctypes.data_as(ctypes.c_char_p), ctypes.c_long(4),
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
        ctypes.c_long(1), ctypes.c_long(1), ctypes.c_long(4),
        dst.ctypes.data_as(ctypes.c_char_p),
    )
    assert rc != 0


def test_flat_lens_mismatch_raises():
    """sum(recursive_seq_lens) must match the data row count — the native
    packer would otherwise memcpy past the source buffer (reference
    lod_tensor.py validates the same invariant)."""
    flat = np.arange(8, dtype=np.float32).reshape(4, 2)
    with pytest.raises(ValueError, match="sums to 6"):
        create_lod_tensor(flat, recursive_seq_lens=[[3, 3]])
