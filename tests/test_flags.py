"""Flags tier + FLAGS_check_nan_inf (reference:
python/paddle/fluid/__init__.py:125 __bootstrap__ env gflags;
framework/operator.cc:777 nan/inf checking)."""

import numpy as np
import pytest

import paddle_tpu as fluid


def test_get_set_flags():
    flags = fluid.get_flags()
    assert "FLAGS_check_nan_inf" in flags
    assert flags["FLAGS_check_nan_inf"] is False
    fluid.set_flags({"FLAGS_check_nan_inf": True})
    try:
        assert fluid.get_flags("check_nan_inf")["FLAGS_check_nan_inf"] is True
    finally:
        fluid.set_flags({"FLAGS_check_nan_inf": False})
    with pytest.raises(KeyError):
        fluid.set_flags({"FLAGS_no_such_flag": 1})


def test_check_nan_inf_catches_diverged_step():
    fluid.reset_default_env()
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    # log of a negative number -> nan in the fetch
    out = fluid.layers.reduce_mean(fluid.layers.log(x))
    exe = fluid.Executor(fluid.CPUPlace())
    bad = np.full((2, 4), -1.0, dtype="float32")

    # flag off: nan flows through silently (reference default)
    (lv,) = exe.run(feed={"x": bad}, fetch_list=[out])
    assert np.isnan(lv).all()

    fluid.set_flags({"FLAGS_check_nan_inf": True})
    try:
        with pytest.raises(RuntimeError, match="nan/inf"):
            exe.run(feed={"x": bad}, fetch_list=[out])
        # clean inputs pass the check
        good = np.full((2, 4), 2.0, dtype="float32")
        (lv,) = exe.run(feed={"x": good}, fetch_list=[out])
        np.testing.assert_allclose(lv, np.log(2.0), rtol=1e-6)
    finally:
        fluid.set_flags({"FLAGS_check_nan_inf": False})


def test_check_nan_inf_names_state_var():
    """A diverging training step (lr too big -> inf weights) is caught and
    the error names a variable."""
    fluid.reset_default_env()
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    pred = fluid.layers.fc(x, 1)
    loss = fluid.layers.reduce_mean(
        fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(1e30).minimize(loss)  # guaranteed blow-up
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(8, 4).astype("float32") * 10,
            "y": rng.randn(8, 1).astype("float32")}
    fluid.set_flags({"FLAGS_check_nan_inf": True})
    try:
        with pytest.raises(RuntimeError, match="FLAGS_check_nan_inf"):
            for _ in range(3):
                exe.run(feed=feed, fetch_list=[loss])
    finally:
        fluid.set_flags({"FLAGS_check_nan_inf": False})


def test_env_bootstrap(monkeypatch):
    import importlib
    from paddle_tpu import flags as flagmod

    monkeypatch.setenv("FLAGS_check_nan_inf", "1")
    monkeypatch.setenv("FLAGS_paddle_num_threads", "4")
    try:
        flagmod._bootstrap()
        assert flagmod.flag("check_nan_inf") is True
        assert flagmod.flag("paddle_num_threads") == 4
    finally:
        monkeypatch.delenv("FLAGS_check_nan_inf")
        monkeypatch.delenv("FLAGS_paddle_num_threads")
        flagmod._bootstrap()


def test_conv_layout_nhwc_parity():
    """FLAGS_conv_layout=NHWC computes the same conv2d (internal layout
    only; program contract stays NCHW)."""
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu import layers

    x = np.random.RandomState(0).randn(2, 3, 16, 16).astype("float32")

    outs = {}
    for layout in ("NCHW", "NHWC"):
        fluid.set_flags({"FLAGS_conv_layout": layout})
        try:
            fluid.reset_default_env()
            img = layers.data("img", [3, 16, 16])
            y = layers.conv2d(img, num_filters=4, filter_size=3, padding=1,
                              groups=1,
                              param_attr=fluid.ParamAttr(
                                  name=f"w_{layout}",
                                  initializer=fluid.initializer.Constant(0.1)))
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(fluid.default_startup_program())
            out, = exe.run(feed={"img": x}, fetch_list=[y])
            outs[layout] = np.asarray(out)
        finally:
            fluid.set_flags({"FLAGS_conv_layout": "auto"})
    np.testing.assert_allclose(outs["NCHW"], outs["NHWC"],
                               rtol=1e-5, atol=1e-5)


def test_conv_layout_nhwc_pool_parity():
    """Under FLAGS_conv_layout=NHWC pool2d also pools channels-last behind
    boundary transposes; the conv->maxpool->avgpool chain (fwd AND the
    select-and-scatter backward, via one SGD step) matches NCHW."""
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu import layers

    x = np.random.RandomState(1).randn(2, 3, 16, 16).astype("float32")

    results = {}
    for layout in ("NCHW", "NHWC"):
        fluid.set_flags({"FLAGS_conv_layout": layout})
        try:
            fluid.reset_default_env()
            img = layers.data("img", [3, 16, 16])
            y = layers.conv2d(img, num_filters=4, filter_size=3, padding=1,
                              param_attr=fluid.ParamAttr(
                                  name=f"wp_{layout}",
                                  initializer=fluid.initializer.Constant(0.1)))
            y = layers.pool2d(y, pool_size=3, pool_type="max", pool_stride=2,
                              pool_padding=1, ceil_mode=True)
            y = layers.pool2d(y, pool_size=2, pool_type="avg", pool_stride=2,
                              exclusive=True)
            loss = layers.reduce_mean(y)
            fluid.optimizer.SGD(learning_rate=0.5).minimize(loss)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(fluid.default_startup_program())
            out, = exe.run(feed={"img": x}, fetch_list=[y])
            w, = exe.run(feed={"img": x}, fetch_list=[f"wp_{layout}"])
            results[layout] = (np.asarray(out), np.asarray(w))
        finally:
            fluid.set_flags({"FLAGS_conv_layout": "auto"})
    np.testing.assert_allclose(results["NCHW"][0], results["NHWC"][0],
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(results["NCHW"][1], results["NHWC"][1],
                               rtol=1e-5, atol=1e-5)


def test_compile_cache_dir_flag_applies(tmp_path, monkeypatch):
    """FLAGS_compile_cache_dir points jax's persistent executable cache at
    the directory on first block compile (tiny compiles may fall under
    jax's min-compile-time threshold, so the assertion is on the applied
    config, not on cache files)."""
    import jax

    import paddle_tpu as fluid
    from paddle_tpu import flags as fl
    from paddle_tpu.core import compiler
    from paddle_tpu import layers

    prev = jax.config.jax_compilation_cache_dir
    monkeypatch.setattr(compiler, "_compile_cache_applied_dir", None)
    fl.set_flags({"FLAGS_compile_cache_dir": str(tmp_path)})
    try:
        x = layers.data("x", [2], dtype="float32")
        loss = layers.mean(layers.fc(x, size=2))
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        exe.run(feed={"x": np.zeros((2, 2), "float32")}, fetch_list=[loss])
        assert jax.config.jax_compilation_cache_dir == str(tmp_path)

        # pointing the flag at a NEW directory re-applies (ADVICE r3: the
        # old latch silently ignored every later set_flags)
        other = tmp_path / "second"
        fl.set_flags({"FLAGS_compile_cache_dir": str(other)})
        exe.run(feed={"x": np.zeros((2, 2), "float32")}, fetch_list=[loss])
        assert jax.config.jax_compilation_cache_dir == str(other)

        # clearing the flag restores the user's own pre-apply jax setting
        # (None here = disabled; cold-compile measurements depend on this)
        fl.set_flags({"FLAGS_compile_cache_dir": ""})
        assert jax.config.jax_compilation_cache_dir == prev

        # a typo'd flag elsewhere in the dict must not half-apply: the
        # cache stays untouched when validation fails
        import pytest as _pytest
        with _pytest.raises(ValueError):
            fl.set_flags({"FLAGS_compile_cache_dir": str(tmp_path),
                          "FLAGS_conv_layout": "NHCW"})
        assert jax.config.jax_compilation_cache_dir == prev
    finally:
        fl.set_flags({"FLAGS_compile_cache_dir": ""})
        jax.config.update("jax_compilation_cache_dir", prev)


def test_auto_defaults_resolve_by_device_scope():
    """FLAGS_conv_layout defaults to "auto": NCHW outside a TPU trace
    scope (reference parity), NHWC inside one; un-set AMP resolves to
    keep-tier bf16 only inside the scope.  Explicit settings win over
    auto in both directions (VERDICT r3 item 5)."""
    from paddle_tpu import flags as fl
    from paddle_tpu.core import amp

    fluid.set_flags({"FLAGS_conv_layout": "auto"})  # the shipped default
    amp.reset_amp()  # clear any explicit policy left by earlier tests
    assert fl.conv_layout() == "NCHW"
    assert amp.state_key() is None
    with fl.tpu_trace_scope(True):
        assert fl.conv_layout() == "NHWC"
        assert amp.state_key() == ("bfloat16", True)
        assert fl.trace_key()[0] == "NHWC"

        # explicit pins win inside the scope
        fluid.set_flags({"FLAGS_conv_layout": "NCHW"})
        fluid.disable_amp()
        try:
            assert fl.conv_layout() == "NCHW"
            assert amp.state_key() is None
        finally:
            fluid.set_flags({"FLAGS_conv_layout": "auto"})
            amp.reset_amp()
    # back outside: auto resolves to parity defaults again
    assert fl.conv_layout() == "NCHW"
    assert amp.state_key() is None


def test_tpu_place_gets_tuned_defaults(monkeypatch):
    """A fresh Executor run against a TPU device picks keep-tier bf16 +
    NHWC with NO env vars or enable_amp calls: conv activations come back
    bfloat16 while params/loss stay fp32 master precision.  (The device
    check is monkeypatched — the suite runs on the CPU backend.)"""
    from paddle_tpu import layers
    from paddle_tpu.core import amp, executor as exec_mod

    amp.reset_amp()
    monkeypatch.setattr(exec_mod, "device_is_tpu", lambda d: True)
    fluid.reset_default_env()
    x = layers.data("x", [3, 8, 8], dtype="float32")
    c = layers.conv2d(x, num_filters=4, filter_size=3, padding=1)
    loss = layers.reduce_mean(c)
    fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(fluid.default_startup_program())
    xv = np.random.RandomState(3).randn(2, 3, 8, 8).astype("float32")
    w_name = next(op for op in fluid.default_main_program()
                  .global_block().ops
                  if op.type == "conv2d").input("Filter")[0]
    cv, wv = exe.run(feed={"x": xv}, fetch_list=[c, w_name],
                     return_numpy=False)
    import jax.numpy as jnp

    assert jnp.asarray(cv).dtype == jnp.bfloat16  # keep-tier activations
    assert jnp.asarray(wv).dtype == jnp.float32   # fp32 master weights

    # the same program on a non-TPU device stays fp32 (fresh executor;
    # the cache key includes the resolved policy so no stale reuse)
    monkeypatch.setattr(exec_mod, "device_is_tpu", lambda d: False)
    cv2, _ = exe.run(feed={"x": xv}, fetch_list=[c, loss],
                     return_numpy=False)
    assert jnp.asarray(cv2).dtype == jnp.float32


def test_compile_cache_coldstart_cross_process(tmp_path):
    """Relay-independence drill (VERDICT r5 item 2): a fresh process must
    be able to REUSE executables persisted by an earlier process — zero
    recompiles, bit-identical training losses.  On the TPU relay this is
    what lets a prewarmed cache produce numbers while the remote-compile
    service is down; here the same two-process contract is proven on CPU
    via tools/cache_coldstart.py."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "cache_coldstart.py"),
         "--cache-dir", str(tmp_path / "xla_cache")],
        capture_output=True, text=True, timeout=600,
    )
    lines = [json.loads(ln) for ln in out.stdout.splitlines()
             if ln.strip().startswith("{")]
    assert out.returncode == 0, out.stdout + out.stderr
    verdict = lines[-1]
    assert verdict["coldstart_ok"] is True
    assert verdict["cold_cache_hits"] > 0
    assert verdict["cold_cache_misses"] == 0
