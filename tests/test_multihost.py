"""Multi-host execution + sharded checkpointing (VERDICT r1 missing #6/#7;
reference pattern: test_dist_base.py:212 localhost subprocess clusters).

test_sharded_checkpoint_roundtrip runs in-process on the 8-device CPU mesh;
test_two_process_data_parallel spawns a real 2-process jax.distributed
cluster over localhost and asserts dist loss == serial loss."""

import json
import os
import socket
import subprocess
import sys
import tempfile

import numpy as np
import pytest

import paddle_tpu as fluid

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _build_mlp(seed=7):
    fluid.reset_default_env()
    fluid.default_main_program().random_seed = seed
    fluid.default_startup_program().random_seed = seed
    x = fluid.layers.data(name="x", shape=[16], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    h = fluid.layers.fc(x, 32, act="relu")
    pred = fluid.layers.fc(h, 1)
    loss = fluid.layers.reduce_mean(
        fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(0.1).minimize(loss)
    return loss


def test_sharded_checkpoint_roundtrip():
    """Params sharded over a tp axis save per-shard and restore bitwise,
    re-placed on the mesh."""
    import jax
    from paddle_tpu.parallel import ParallelExecutor, make_mesh

    loss = _build_mlp()
    prog = fluid.default_main_program()
    # shard the first fc weight over tp (names depend on the session-wide
    # unique_name counter, so match by pattern)
    w_name = sorted(
        n for n in prog.global_block().vars
        if n.startswith("fc_") and ".w" in n
    )[0]
    prog.global_block().var(w_name).sharding = [None, "tp"]

    mesh = make_mesh({"dp": 2, "tp": 2}, devices=jax.devices()[:4])
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    pe = ParallelExecutor(loss_name=loss.name, mesh=mesh)
    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(8, 16).astype("float32"),
            "y": rng.randn(8, 1).astype("float32")}
    pe.run(fetch_list=[loss], feed=feed)

    scope = fluid.global_scope()
    param_names = {
        n for n in prog.global_block().vars if n.startswith("fc_")
    }
    before = {
        n: np.asarray(fluid.io._to_host(scope.find_var(n))[0])
        for n in scope.local_var_names()
        if n in param_names
    }
    with tempfile.TemporaryDirectory() as d:
        fluid.io.save_sharded(d, prog, scope)
        # wipe and restore
        for n in before:
            scope.set_var(n, np.zeros_like(before[n]))
        fluid.io.load_sharded(d, prog, scope, mesh=mesh)
        for n, want in before.items():
            got = np.asarray(fluid.io._to_host(scope.find_var(n))[0])
            np.testing.assert_array_equal(got, want, err_msg=n)
        # restored param is re-placed with its mesh sharding
        v = scope.find_var(w_name)
        import jax as _jax
        assert isinstance(v, _jax.Array)
    # training continues after restore
    (l2,) = pe.run(fetch_list=[loss], feed=feed)
    assert np.isfinite(float(np.ravel(l2)[0]))


_WORKER = r"""
import json, os, sys
import numpy as np

sys.path.insert(0, {repo!r})
import paddle_tpu as fluid
from paddle_tpu import parallel

parallel.init_distributed()
import jax
assert jax.process_count() == 2, jax.process_count()

sys.path.insert(0, os.path.join({repo!r}, "tests"))
from test_multihost import _build_mlp

loss = _build_mlp()
prog = fluid.default_main_program()
exe = fluid.Executor(fluid.CPUPlace())
exe.run(fluid.default_startup_program())

from paddle_tpu.parallel import ParallelExecutor, make_mesh
mesh = make_mesh({{"dp": 4}}, devices=jax.devices())
pe = ParallelExecutor(loss_name=loss.name, mesh=mesh)

pid = jax.process_index()
rng = np.random.RandomState(0)
xs = rng.randn(8, 16).astype("float32")
ys = rng.randn(8, 1).astype("float32")
lo, hi = pid * 4, (pid + 1) * 4  # this process's batch shard

losses = []
for _ in range(3):
    (lv,) = pe.run(fetch_list=[loss], feed={{"x": xs[lo:hi], "y": ys[lo:hi]}})
    losses.append(float(np.ravel(np.asarray(lv))[0]))

# sharded checkpoint across the 2-process cluster
ckpt = os.path.join({outdir!r}, "ckpt")
os.makedirs(ckpt, exist_ok=True)
fluid.io.save_sharded(ckpt, prog, fluid.global_scope())

with open(os.path.join({outdir!r}, f"result_{{pid}}.json"), "w") as f:
    json.dump({{"losses": losses}}, f)
"""


@pytest.mark.timeout(300)
def test_two_process_data_parallel():
    port = socket.socket()
    port.bind(("127.0.0.1", 0))
    portno = port.getsockname()[1]
    port.close()

    with tempfile.TemporaryDirectory() as outdir:
        script = _WORKER.format(repo=REPO, outdir=outdir)
        procs = []
        for pid in range(2):
            env = dict(os.environ)
            env.pop("PYTHONPATH", None)  # keep the axon plugin out
            env.update(
                JAX_PLATFORMS="cpu",
                XLA_FLAGS="--xla_force_host_platform_device_count=2",
                PADDLE_TRAINER_ENDPOINTS=(
                    f"127.0.0.1:{portno},127.0.0.1:{portno + 1}"
                ),
                PADDLE_TRAINER_ID=str(pid),
                PADDLE_TRAINERS_NUM="2",
            )
            procs.append(subprocess.Popen(
                [sys.executable, "-c", script], env=env, cwd=outdir,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            ))
        outs = [p.communicate(timeout=240)[0].decode() for p in procs]
        for p, o in zip(procs, outs):
            assert p.returncode == 0, f"worker failed:\n{o[-3000:]}"

        results = []
        for pid in range(2):
            with open(os.path.join(outdir, f"result_{pid}.json")) as f:
                results.append(json.load(f))
        # both processes observe the same (replicated) global loss
        np.testing.assert_allclose(results[0]["losses"],
                                   results[1]["losses"], rtol=1e-5)

        # serial reference: same program, full batch, one device
        loss = _build_mlp()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        rng = np.random.RandomState(0)
        xs = rng.randn(8, 16).astype("float32")
        ys = rng.randn(8, 1).astype("float32")
        serial = []
        for _ in range(3):
            (lv,) = exe.run(feed={"x": xs, "y": ys}, fetch_list=[loss])
            serial.append(float(np.ravel(lv)[0]))
        np.testing.assert_allclose(results[0]["losses"], serial, rtol=1e-4)

        # the cluster's sharded checkpoint reassembles on a fresh process
        ckpt = os.path.join(outdir, "ckpt")
        assert os.path.exists(os.path.join(ckpt, "meta.json"))
        scope2 = fluid.global_scope().new_scope()
        fluid.io.load_sharded(ckpt, scope=scope2)
        with open(os.path.join(ckpt, "meta.json")) as f:
            meta = json.load(f)
        w = [n for n in meta if ".w" in n][0]
        got = scope2.find_var(w)
        assert got is not None and list(np.shape(got)) == meta[w]["shape"]
"""worker stdout is attached on failure for debuggability."""


def test_async_sharded_checkpoint(tmp_path):
    """save_sharded(asynchronous=True): device state snapshots before the
    call returns, files write on a background thread, and later scope
    mutations (donated/overwritten buffers) don't leak into the
    checkpoint."""
    import paddle_tpu as fluid
    from paddle_tpu import layers

    fluid.reset_default_env()
    x = layers.data("x", [4], dtype="float32")
    pred = layers.fc(x, size=2, param_attr=fluid.ParamAttr(name="acp_w"),
                     bias_attr=False)
    loss = layers.mean(pred)
    fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    scope = fluid.global_scope()
    snap = np.asarray(scope.find_var("acp_w")).copy()

    d = str(tmp_path / "ckpt")
    handle = fluid.io.save_sharded(d, asynchronous=True)
    assert handle is not None
    # mutate AFTER the async save: a training step replaces the param
    exe.run(feed={"x": np.ones((2, 4), "float32")}, fetch_list=[loss])
    handle.wait()
    assert handle.done()

    fluid.reset_default_env()
    x = layers.data("x", [4], dtype="float32")
    layers.fc(x, size=2, param_attr=fluid.ParamAttr(name="acp_w"),
              bias_attr=False)
    exe2 = fluid.Executor(fluid.CPUPlace())
    exe2.run(fluid.default_startup_program())
    fluid.io.load_sharded(d)
    got = np.asarray(fluid.global_scope().find_var("acp_w"))
    np.testing.assert_array_equal(got, snap)


def test_async_checkpoint_overlapping_saves(tmp_path):
    """Two async saves to the same dirname serialize: the second joins the
    first's writer before touching the directory, so the final meta.json
    and shards all belong to the newest save (no stale-meta race)."""
    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.io import _inflight_saves

    fluid.reset_default_env()
    x = layers.data("x", [4], dtype="float32")
    pred = layers.fc(x, size=2, param_attr=fluid.ParamAttr(name="ov_w"),
                     bias_attr=False)
    loss = layers.mean(pred)
    fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    scope = fluid.global_scope()

    d = str(tmp_path / "ckpt")
    h1 = fluid.io.save_sharded(d, asynchronous=True)
    exe.run(feed={"x": np.ones((2, 4), "float32")}, fetch_list=[loss])
    h2 = fluid.io.save_sharded(d, asynchronous=True)
    # the second save must have joined the first before starting
    assert h1.done()
    exe.run(feed={"x": np.ones((2, 4), "float32")}, fetch_list=[loss])
    snap2 = np.asarray(scope.find_var("ov_w")).copy()
    # a SYNC save to the same dir also joins the in-flight async writer
    fluid.io.save_sharded(d)
    h2.wait()
    assert h2.done()
    # finished writers self-prune from the in-flight registry
    assert os.path.abspath(d) not in _inflight_saves

    fluid.reset_default_env()
    x = layers.data("x", [4], dtype="float32")
    layers.fc(x, size=2, param_attr=fluid.ParamAttr(name="ov_w"),
              bias_attr=False)
    exe2 = fluid.Executor(fluid.CPUPlace())
    exe2.run(fluid.default_startup_program())
    fluid.io.load_sharded(d)
    got = np.asarray(fluid.global_scope().find_var("ov_w"))
    np.testing.assert_array_equal(got, snap2)
