"""ParallelExecutor parity: same model trained serially and SPMD over an
8-device virtual mesh must converge to matching losses (reference analogue:
unittests/parallel_executor_test_base.py, test_parallel_executor_mnist.py)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.parallel import make_mesh


def _build_model(seed=0):
    fluid.default_main_program().random_seed = seed
    fluid.default_startup_program().random_seed = seed
    x = fluid.layers.data("x", [8], dtype="float32")
    label = fluid.layers.data("label", [1], dtype="float32")
    h = fluid.layers.fc(x, size=16, act="relu",
                        param_attr=fluid.ParamAttr(name="w1"),
                        bias_attr=fluid.ParamAttr(name="b1"))
    pred = fluid.layers.fc(h, size=1,
                           param_attr=fluid.ParamAttr(name="w2"),
                           bias_attr=fluid.ParamAttr(name="b2"))
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, label))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return loss


def _data(n=64):
    rng = np.random.RandomState(42)
    x = rng.rand(n, 8).astype(np.float32)
    w = rng.rand(8, 1).astype(np.float32)
    y = (x @ w + 0.1).astype(np.float32)
    return x, y


def test_mesh_shapes():
    m = make_mesh({"dp": 4, "tp": 2})
    assert m.num_devices == 8
    assert m.axis_size("dp") == 4 and m.axis_size("tp") == 2
    m2 = make_mesh({"dp": -1})
    assert m2.axis_size("dp") == 8


def test_parallel_matches_serial():
    x, y = _data()

    loss = _build_model()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    serial_losses = [
        float(np.ravel(exe.run(feed={"x": x, "label": y}, fetch_list=[loss])[0])[0])
        for _ in range(5)
    ]
    serial_scope = fluid.global_scope()
    w_serial = np.asarray(serial_scope.find_var("w1"))

    # fresh identical program, trained through ParallelExecutor
    from paddle_tpu.core import framework, scope as scope_mod

    framework.switch_main_program(fluid.Program())
    framework.switch_startup_program(fluid.Program())
    scope_mod._current_scope = scope_mod.Scope()

    loss2 = _build_model()
    exe2 = fluid.Executor(fluid.CPUPlace())
    exe2.run(fluid.default_startup_program())
    pe = fluid.ParallelExecutor(loss_name=loss2.name, mesh=make_mesh({"dp": 8}))
    par_losses = [
        float(np.ravel(pe.run(fetch_list=[loss2], feed={"x": x, "label": y})[0])[0])
        for _ in range(5)
    ]
    w_par = np.asarray(fluid.global_scope().find_var("w1"))

    np.testing.assert_allclose(serial_losses, par_losses, rtol=2e-4, atol=1e-5)
    np.testing.assert_allclose(w_serial, w_par, rtol=2e-4, atol=1e-5)


def test_parallel_list_of_feed_dicts():
    x, y = _data(16)
    loss = _build_model()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    pe = fluid.ParallelExecutor(loss_name=loss.name, mesh=make_mesh({"dp": 8}))
    feeds = [
        {"x": x[i * 2:(i + 1) * 2], "label": y[i * 2:(i + 1) * 2]} for i in range(8)
    ]
    (lv,) = pe.run(fetch_list=[loss], feed=feeds)
    assert np.isfinite(lv)


def test_tensor_parallel_sharded_param():
    """Variable.sharding routes a weight onto the tp axis; program still
    compiles and matches the replicated answer."""
    x, y = _data(32)
    loss = _build_model()
    prog = fluid.default_main_program()
    prog.global_block().var("w1").sharding = [None, "tp"]
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    pe = fluid.ParallelExecutor(loss_name=loss.name, mesh=make_mesh({"dp": 2, "tp": 4}))
    losses = [
        float(np.ravel(pe.run(fetch_list=[loss], feed={"x": x, "label": y})[0])[0])
        for _ in range(3)
    ]
    assert losses[-1] < losses[0]


def test_indivisible_batch_raises_clear_error():
    """A 10-row batch over an 8-way dp mesh must fail with the framework's
    even-shard message, not a raw pjit sharding ValueError (reference
    analogue: data_balance redistributing uneven tail batches,
    details/data_balance_op_handle.cc)."""
    import paddle_tpu as fluid
    from paddle_tpu import layers

    x = layers.data("x", [4], dtype="float32")
    y = layers.data("y", [1], dtype="float32")
    pred = layers.fc(x, size=1)
    loss = layers.mean(layers.square_error_cost(pred, y))
    fluid.optimizer.SGDOptimizer(learning_rate=0.01).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    pe = fluid.ParallelExecutor(use_cuda=False, loss_name=loss.name)
    rng = np.random.RandomState(0)
    with pytest.raises(ValueError, match="not divisible by its dim-0 mesh axes"):
        pe.run(feed={"x": rng.randn(10, 4).astype("float32"),
                     "y": rng.randn(10, 1).astype("float32")},
               fetch_list=[loss.name])


def test_pe_run_steps_matches_stepwise():
    """ParallelExecutor.run_steps (K sharded steps under one pjit'd scan)
    must reproduce the exact trajectory of per-step pe.run on the same
    mesh, including the final fetches and updated parameters."""
    rng = np.random.RandomState(3)
    feeds = [{"x": rng.randn(8, 16).astype("float32"),
              "y": rng.randn(8, 1).astype("float32")} for _ in range(4)]

    def build():
        fluid.reset_default_env()
        fluid.default_main_program().random_seed = 7
        fluid.default_startup_program().random_seed = 7
        from paddle_tpu import layers
        x = layers.data("x", [16], dtype="float32")
        y = layers.data("y", [1], dtype="float32")
        h = layers.fc(x, size=8, act="relu")
        pred = layers.fc(h, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        fluid.optimizer.AdamOptimizer(learning_rate=0.05).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        import jax
        mesh = make_mesh({"dp": 4}, devices=jax.devices()[:4])
        pe = fluid.ParallelExecutor(loss_name=loss.name, mesh=mesh)
        return pe, loss

    pe, loss = build()
    for f in feeds:
        step_out = pe.run(feed=f, fetch_list=[loss.name])
    w_step = {
        n: np.asarray(fluid.global_scope().find_var(n))
        for n in ("fc_0.w_0", "fc_1.w_0")
    }

    pe2, loss2 = build()
    scan_out = pe2.run_steps(feed_list=feeds, fetch_list=[loss2.name])
    w_scan = {
        n: np.asarray(fluid.global_scope().find_var(n))
        for n in ("fc_0.w_0", "fc_1.w_0")
    }

    np.testing.assert_allclose(np.asarray(scan_out[0]),
                               np.asarray(step_out[0]), rtol=1e-5, atol=1e-6)
    for n in w_step:
        np.testing.assert_allclose(w_scan[n], w_step[n], rtol=1e-5,
                                   atol=1e-6, err_msg=n)


def test_pe_run_steps_with_tp_sharded_weight():
    """run_steps under a dp x tp mesh with a tensor-parallel weight keeps
    the sharded-state round-trip exact across the scan."""
    import jax

    rng = np.random.RandomState(4)
    feeds = [{"x": rng.randn(4, 16).astype("float32"),
              "y": rng.randn(4, 1).astype("float32")} for _ in range(3)]

    def build():
        fluid.reset_default_env()
        fluid.default_main_program().random_seed = 11
        fluid.default_startup_program().random_seed = 11
        from paddle_tpu import layers
        x = layers.data("x", [16], dtype="float32")
        y = layers.data("y", [1], dtype="float32")
        h = layers.fc(x, size=8, act="relu")
        pred = layers.fc(h, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
        prog = fluid.default_main_program()
        prog.global_block().var("fc_0.w_0").sharding = [None, "tp"]
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        mesh = make_mesh({"dp": 2, "tp": 2}, devices=jax.devices()[:4])
        return fluid.ParallelExecutor(loss_name=loss.name, mesh=mesh), loss

    pe, loss = build()
    for f in feeds:
        (want,) = pe.run(feed=f, fetch_list=[loss.name])
    w_want = np.asarray(fluid.global_scope().find_var("fc_0.w_0"))

    pe2, loss2 = build()
    (got,) = pe2.run_steps(feed_list=feeds, fetch_list=[loss2.name])
    w_got = np.asarray(fluid.global_scope().find_var("fc_0.w_0"))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(w_got, w_want, rtol=1e-5, atol=1e-6)


def test_parallel_conv_fused_bn_matches_serial():
    """The flagship conv path under SPMD: conv + fused_bn_add_act trained
    data-parallel over the 8-device mesh must match the serial trajectory.
    BN statistics reduce over the GLOBAL batch automatically (jnp.mean of
    a dp-sharded tensor — XLA inserts the cross-shard reduction), i.e.
    sync-BN semantics, so losses and weights agree with one-device runs."""
    def build(seed=5):
        fluid.default_main_program().random_seed = seed
        fluid.default_startup_program().random_seed = seed
        img = fluid.layers.data("img", [3, 8, 8], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="int64")
        conv = fluid.layers.conv2d(img, 4, 3, padding=1, bias_attr=False,
                                   param_attr=fluid.ParamAttr(name="pc_w"))
        h = fluid.layers.fused_bn_add_act(
            conv, None, act="relu",
            param_attr=fluid.ParamAttr(name="pc_scale"),
            bias_attr=fluid.ParamAttr(name="pc_bias"),
            moving_mean_name="pc_mean", moving_variance_name="pc_var")
        pool = fluid.layers.pool2d(h, pool_size=8, pool_type="avg")
        pred = fluid.layers.fc(pool, size=3, act="softmax",
                               param_attr=fluid.ParamAttr(name="pc_fc"))
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, y))
        fluid.optimizer.MomentumOptimizer(0.1, momentum=0.9).minimize(loss)
        return loss

    rng = np.random.RandomState(2)
    xv = rng.randn(16, 3, 8, 8).astype("float32")
    yv = rng.randint(0, 3, size=(16, 1)).astype("int64")

    fluid.reset_default_env()
    loss = build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    serial = [
        float(np.ravel(exe.run(feed={"img": xv, "y": yv},
                               fetch_list=[loss])[0])[0])
        for _ in range(4)
    ]
    w_serial = np.asarray(fluid.global_scope().find_var("pc_w")).copy()
    mean_serial = np.asarray(fluid.global_scope().find_var("pc_mean")).copy()

    fluid.reset_default_env()
    loss2 = build()
    exe2 = fluid.Executor(fluid.CPUPlace())
    exe2.run(fluid.default_startup_program())
    pe = fluid.ParallelExecutor(loss_name=loss2.name,
                                mesh=make_mesh({"dp": 8}))
    par = [
        float(np.ravel(pe.run(fetch_list=[loss2],
                              feed={"img": xv, "y": yv})[0])[0])
        for _ in range(4)
    ]
    np.testing.assert_allclose(serial, par, rtol=2e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(fluid.global_scope().find_var("pc_w")), w_serial,
        rtol=2e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(fluid.global_scope().find_var("pc_mean")), mean_serial,
        rtol=2e-4, atol=1e-6)


def test_parallel_run_steps_flat_matches_scan():
    """ParallelExecutor.run_steps(mode='flat') gives the scan trajectory
    exactly, SPMD over the 8-device mesh."""
    x, y = _data(32)
    feeds = [{"x": x[i * 8:(i + 1) * 8], "label": y[i * 8:(i + 1) * 8]}
             for i in range(4)]

    results = {}
    for mode in ("scan", "flat"):
        from paddle_tpu.core import framework, scope as scope_mod

        framework.switch_main_program(fluid.Program())
        framework.switch_startup_program(fluid.Program())
        scope_mod._current_scope = scope_mod.Scope()
        loss = _build_model(seed=4)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        pe = fluid.ParallelExecutor(loss_name=loss.name,
                                    mesh=make_mesh({"dp": 8}))
        (lv,) = pe.run_steps(feed_list=feeds, fetch_list=[loss], steps=6,
                             mode=mode)
        results[mode] = (np.ravel(lv)[0],
                         np.asarray(fluid.global_scope().find_var("w1")))
    np.testing.assert_allclose(results["scan"][0], results["flat"][0],
                               rtol=1e-6)
    np.testing.assert_allclose(results["scan"][1], results["flat"][1],
                               rtol=1e-6)
