"""Executor end-to-end: lowering, feeds/fetches, persistable state, RNG.
(reference analogue: book tests + executor tests)."""

import numpy as np
import pytest

import paddle_tpu as fluid


def test_fill_and_fetch():
    out = fluid.layers.fill_constant([2, 3], "float32", 7.0)
    exe = fluid.Executor(fluid.CPUPlace())
    (res,) = exe.run(fetch_list=[out])
    np.testing.assert_allclose(res, np.full((2, 3), 7.0, np.float32))


def test_feed_forward_fc():
    x = fluid.layers.data("x", [4], dtype="float32")
    y = fluid.layers.fc(x, size=3)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    xv = np.random.RandomState(0).rand(5, 4).astype(np.float32)
    (res,) = exe.run(feed={"x": xv}, fetch_list=[y])
    assert res.shape == (5, 3)


def test_startup_program_initializes_params():
    x = fluid.layers.data("x", [4], dtype="float32")
    fluid.layers.fc(x, size=3, param_attr=fluid.ParamAttr(name="fcw"))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    w = fluid.global_scope().find_var("fcw")
    assert w is not None and np.asarray(w).shape == (4, 3)


def test_uninitialized_param_raises():
    x = fluid.layers.data("x", [4], dtype="float32")
    y = fluid.layers.fc(x, size=3)
    exe = fluid.Executor(fluid.CPUPlace())
    with pytest.raises(RuntimeError, match="not initialized"):
        exe.run(feed={"x": np.zeros((2, 4), np.float32)}, fetch_list=[y])


def test_sgd_training_step_decreases_loss():
    np.random.seed(0)
    x = fluid.layers.data("x", [4], dtype="float32")
    label = fluid.layers.data("label", [1], dtype="float32")
    pred = fluid.layers.fc(x, size=1)
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, label))
    sgd = fluid.optimizer.SGD(learning_rate=0.05)
    sgd.minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    xv = np.random.rand(16, 4).astype(np.float32)
    yv = (xv @ np.array([[1.0], [2.0], [-1.0], [0.5]], np.float32) + 0.3).astype(np.float32)
    losses = []
    for _ in range(30):
        (lv,) = exe.run(feed={"x": xv, "label": yv}, fetch_list=[loss])
        losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.3, losses[:3] + losses[-3:]


def test_rng_stream_advances_between_runs():
    out = fluid.layers.ops.uniform_random([4], min=0.0, max=1.0)
    exe = fluid.Executor(fluid.CPUPlace())
    (a,) = exe.run(fetch_list=[out])
    (b,) = exe.run(fetch_list=[out])
    assert not np.allclose(a, b)


def test_dropout_train_vs_test():
    x = fluid.layers.data("x", [100], dtype="float32")
    out = fluid.layers.dropout(x, dropout_prob=0.5)
    prog = fluid.default_main_program()
    test_prog = prog.clone(for_test=True)
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.ones((2, 100), np.float32)
    (train_out,) = exe.run(prog, feed={"x": xv}, fetch_list=[out])
    assert (train_out == 0).any()
    (test_out,) = exe.run(test_prog, feed={"x": xv}, fetch_list=[out])
    np.testing.assert_allclose(test_out, xv * 0.5, rtol=1e-6)


def test_fetch_param_value():
    w = fluid.layers.create_parameter([3], "float32", name="pw",
                                      default_initializer=fluid.initializer.Constant(2.0))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    (res,) = exe.run(fetch_list=["pw"])
    np.testing.assert_allclose(res, [2.0, 2.0, 2.0])
