"""Executor end-to-end: lowering, feeds/fetches, persistable state, RNG.
(reference analogue: book tests + executor tests)."""

import numpy as np
import pytest

import paddle_tpu as fluid


def test_fill_and_fetch():
    out = fluid.layers.fill_constant([2, 3], "float32", 7.0)
    exe = fluid.Executor(fluid.CPUPlace())
    (res,) = exe.run(fetch_list=[out])
    np.testing.assert_allclose(res, np.full((2, 3), 7.0, np.float32))


def test_feed_forward_fc():
    x = fluid.layers.data("x", [4], dtype="float32")
    y = fluid.layers.fc(x, size=3)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    xv = np.random.RandomState(0).rand(5, 4).astype(np.float32)
    (res,) = exe.run(feed={"x": xv}, fetch_list=[y])
    assert res.shape == (5, 3)


def test_startup_program_initializes_params():
    x = fluid.layers.data("x", [4], dtype="float32")
    fluid.layers.fc(x, size=3, param_attr=fluid.ParamAttr(name="fcw"))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    w = fluid.global_scope().find_var("fcw")
    assert w is not None and np.asarray(w).shape == (4, 3)


def test_uninitialized_param_raises():
    x = fluid.layers.data("x", [4], dtype="float32")
    y = fluid.layers.fc(x, size=3)
    exe = fluid.Executor(fluid.CPUPlace())
    with pytest.raises(RuntimeError, match="not initialized"):
        exe.run(feed={"x": np.zeros((2, 4), np.float32)}, fetch_list=[y])


def test_sgd_training_step_decreases_loss():
    np.random.seed(0)
    x = fluid.layers.data("x", [4], dtype="float32")
    label = fluid.layers.data("label", [1], dtype="float32")
    pred = fluid.layers.fc(x, size=1)
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, label))
    sgd = fluid.optimizer.SGD(learning_rate=0.05)
    sgd.minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    xv = np.random.rand(16, 4).astype(np.float32)
    yv = (xv @ np.array([[1.0], [2.0], [-1.0], [0.5]], np.float32) + 0.3).astype(np.float32)
    losses = []
    for _ in range(30):
        (lv,) = exe.run(feed={"x": xv, "label": yv}, fetch_list=[loss])
        losses.append(float(np.ravel(lv)[0]))
    assert losses[-1] < losses[0] * 0.3, losses[:3] + losses[-3:]


def test_rng_stream_advances_between_runs():
    out = fluid.layers.ops.uniform_random([4], min=0.0, max=1.0)
    exe = fluid.Executor(fluid.CPUPlace())
    (a,) = exe.run(fetch_list=[out])
    (b,) = exe.run(fetch_list=[out])
    assert not np.allclose(a, b)


def test_dropout_train_vs_test():
    x = fluid.layers.data("x", [100], dtype="float32")
    out = fluid.layers.dropout(x, dropout_prob=0.5)
    prog = fluid.default_main_program()
    test_prog = prog.clone(for_test=True)
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.ones((2, 100), np.float32)
    (train_out,) = exe.run(prog, feed={"x": xv}, fetch_list=[out])
    assert (train_out == 0).any()
    (test_out,) = exe.run(test_prog, feed={"x": xv}, fetch_list=[out])
    np.testing.assert_allclose(test_out, xv * 0.5, rtol=1e-6)


def test_fetch_param_value():
    w = fluid.layers.create_parameter([3], "float32", name="pw",
                                      default_initializer=fluid.initializer.Constant(2.0))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    (res,) = exe.run(fetch_list=["pw"])
    np.testing.assert_allclose(res, [2.0, 2.0, 2.0])


def test_in_place_attr_mutation_recompiles():
    """VERDICT round-1 weak #5: the program cache must key on content, not
    object identity — an in-place attr edit has to trigger recompilation."""
    import paddle_tpu.layers as layers

    x = layers.data("x", [4], dtype="float32")
    out = layers.scale(x, scale=2.0)
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.ones((2, 4), dtype="float32")
    (r1,) = exe.run(feed={"x": xv}, fetch_list=[out])
    np.testing.assert_allclose(r1, 2 * xv)

    # mutate the scale op's attr in place (op count unchanged)
    block = fluid.default_main_program().global_block()
    for op in block.ops:
        if op.type == "scale":
            op._set_attr("scale", 5.0)
    (r2,) = exe.run(feed={"x": xv}, fetch_list=[out])
    np.testing.assert_allclose(r2, 5 * xv)


def test_amp_bf16_parity_and_dtype():
    """AMP: matmul computes in bf16 (output rounds through bf16) but params,
    state, and the rest of the graph stay fp32; loss stays within bf16
    tolerance of the fp32 run."""
    import paddle_tpu.layers as layers

    def build_and_run():
        from paddle_tpu.core import framework, scope as scope_mod
        framework.switch_main_program(fluid.Program())
        framework.switch_startup_program(fluid.Program())
        scope_mod._current_scope = scope_mod.Scope()
        x = layers.data("x", [16], dtype="float32")
        y = layers.data("y", [1], dtype="float32")
        pred = layers.fc(layers.fc(x, size=32, act="relu"), size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        rng = np.random.RandomState(3)
        xv = rng.randn(8, 16).astype("float32")
        yv = rng.randn(8, 1).astype("float32")
        losses = [
            float(np.ravel(np.asarray(
                exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])[0]
            ))[0])
            for _ in range(5)
        ]
        params = fluid.default_main_program().global_block().all_parameters()
        pval = np.asarray(fluid.global_scope().find_var(params[0].name))
        return losses, pval

    ref_losses, ref_p = build_and_run()
    fluid.enable_amp("bfloat16")
    try:
        amp_losses, amp_p = build_and_run()
    finally:
        fluid.disable_amp()

    assert amp_p.dtype == np.float32  # master weights stay fp32
    # bf16 has ~3 decimal digits; training for 5 steps stays close
    np.testing.assert_allclose(amp_losses, ref_losses, rtol=0.05, atol=0.05)
    assert amp_losses[-1] < amp_losses[0]  # still learns


def test_amp_keep_output_conv_bn_parity():
    """Aggressive AMP (keep_output=True): activations stay bf16 through the
    conv->bn->relu chain, BN stats accumulate fp32, master weights fp32;
    training stays close to the fp32 run."""
    import paddle_tpu.layers as layers

    def build_and_run():
        fluid.reset_default_env()
        img = layers.data("img", [3, 8, 8], dtype="float32")
        y = layers.data("y", [1], dtype="int64")
        c = layers.conv2d(img, num_filters=8, filter_size=3, padding=1)
        b = layers.batch_norm(c, act="relu")
        p = layers.pool2d(b, pool_size=8, pool_type="avg")
        pred = layers.fc(p, size=4, act="softmax")
        loss = layers.mean(layers.cross_entropy(pred, y))
        fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        rng = np.random.RandomState(3)
        xv = rng.rand(8, 3, 8, 8).astype("float32")
        yv = rng.randint(0, 4, (8, 1)).astype("int64")
        losses = [
            float(np.ravel(np.asarray(
                exe.run(feed={"img": xv, "y": yv}, fetch_list=[loss])[0]
            ))[0])
            for _ in range(6)
        ]
        params = fluid.default_main_program().global_block().all_parameters()
        pvals = {
            p.name: np.asarray(fluid.global_scope().find_var(p.name))
            for p in params
        }
        (act_v,) = exe.run(feed={"img": xv, "y": yv}, fetch_list=[b],
                           return_numpy=False)
        return losses, pvals, str(np.asarray(act_v).dtype)

    ref_losses, ref_p, ref_dt = build_and_run()
    assert ref_dt == "float32"
    fluid.enable_amp("bfloat16", keep_output=True)
    try:
        amp_losses, amp_p, amp_dt = build_and_run()
    finally:
        fluid.disable_amp()

    # the batch_norm output really is half-width — keep_output is not a
    # silent no-op (the conv bias add must not re-widen the chain)
    assert amp_dt == "bfloat16"
    for name, v in amp_p.items():
        assert v.dtype == np.float32, name  # master weights stay fp32
    np.testing.assert_allclose(amp_losses, ref_losses, rtol=0.08, atol=0.08)
    assert amp_losses[-1] < amp_losses[0]


def test_amp_keep_output_layer_norm_parity():
    """keep_output AMP through the matmul->layer_norm chain (the
    transformer block pattern): fp32 stats, bf16 activation writes."""
    import paddle_tpu.layers as layers

    def build_and_run():
        fluid.reset_default_env()
        x = layers.data("x", [16], dtype="float32")
        y = layers.data("y", [1], dtype="float32")
        h = layers.fc(x, size=32)
        h = layers.layer_norm(h)
        h = layers.fc(h, size=16, act="relu")
        pred = layers.fc(h, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        fluid.optimizer.SGDOptimizer(learning_rate=0.05).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        rng = np.random.RandomState(11)
        xv = rng.randn(8, 16).astype("float32")
        yv = rng.randn(8, 1).astype("float32")
        losses = [
            float(np.ravel(np.asarray(
                exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])[0]
            ))[0])
            for _ in range(6)
        ]
        (hn,) = exe.run(feed={"x": xv, "y": yv}, fetch_list=[h],
                        return_numpy=False)
        return losses, str(np.asarray(hn).dtype)

    ref, ref_dt = build_and_run()
    assert ref_dt == "float32"
    fluid.enable_amp("bfloat16", keep_output=True)
    try:
        got, got_dt = build_and_run()
    finally:
        fluid.disable_amp()
    assert got_dt == "bfloat16"  # the post-norm activation stays half-width
    np.testing.assert_allclose(got, ref, rtol=0.08, atol=0.08)
    assert got[-1] < got[0]


def test_run_steps_matches_stepwise_run():
    """run_steps (K iterations in one lax.scan dispatch) must reproduce the
    step-by-step Executor.run trajectory exactly: same params, same loss,
    same RNG advancement."""
    x = fluid.layers.data("x", [4], dtype="float32")
    label = fluid.layers.data("label", [1], dtype="float32")
    pred = fluid.layers.fc(x, size=1, param_attr=fluid.ParamAttr(name="rs_w"))
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, label))
    fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)

    rng = np.random.RandomState(7)
    feeds = [
        {"x": rng.rand(8, 4).astype(np.float32),
         "label": rng.rand(8, 1).astype(np.float32)}
        for _ in range(3)
    ]

    exe = fluid.Executor(fluid.CPUPlace())

    exe.run(fluid.default_startup_program())
    scope = fluid.global_scope()
    snapshot = {
        n: np.asarray(scope.find_var(n)).copy()
        for n in scope.local_var_names()
        if scope.find_var(n) is not None
    }
    serial_losses = []
    for i in range(7):  # 7 % 3 != 0: exercises batch cycling
        (lv,) = exe.run(feed=feeds[i % 3], fetch_list=[loss])
        serial_losses.append(float(np.ravel(lv)[0]))
    w_serial = np.asarray(scope.find_var("rs_w")).copy()

    # reset ALL post-startup state (params incl. the fc bias) and the rng
    # stream, rerun as one scanned dispatch
    for n in list(scope.local_var_names()):
        if n in snapshot:
            scope.set_var(n, snapshot[n])
        else:
            scope.erase(n)
    (lv,) = exe.run_steps(feed_list=feeds, fetch_list=[loss], steps=7)
    np.testing.assert_allclose(
        float(np.ravel(lv)[0]), serial_losses[-1], rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(fluid.global_scope().find_var("rs_w")), w_serial, rtol=1e-6)


def test_run_steps_advances_rng():
    out = fluid.layers.ops.uniform_random([4], min=0.0, max=1.0)
    exe = fluid.Executor(fluid.CPUPlace())
    (a,) = exe.run_steps(feed_list=[{}], fetch_list=[out], steps=2)
    (b,) = exe.run_steps(feed_list=[{}], fetch_list=[out], steps=2)
    assert not np.allclose(a, b)


def test_run_steps_rejects_lod():
    from paddle_tpu.core.lod import LoDValue

    x = fluid.layers.data("x", [4], dtype="float32", lod_level=1)
    y = fluid.layers.sequence_pool(x, "sum")
    exe = fluid.Executor(fluid.CPUPlace())
    lv = LoDValue(np.zeros((3, 4), np.float32), np.array([2, 1]))
    with pytest.raises(TypeError, match="LoD"):
        exe.run_steps(feed_list=[{"x": lv}], fetch_list=[y], steps=1)


def test_run_steps_mutable_feed_not_stale():
    """In-place mutation of a reused numpy feed buffer must reach the device
    on the next run_steps call (the feeds-stack cache only applies to
    immutable jax.Array feeds)."""
    x = fluid.layers.data("x", [2], dtype="float32")
    out = fluid.layers.reduce_mean(x)
    exe = fluid.Executor(fluid.CPUPlace())
    feed = {"x": np.ones((2, 2), np.float32)}
    (a,) = exe.run_steps(feed_list=[feed], fetch_list=[out], steps=1)
    feed["x"][:] = 5.0  # standard refill-the-buffer loading pattern
    (b,) = exe.run_steps(feed_list=[feed], fetch_list=[out], steps=1)
    np.testing.assert_allclose(np.ravel(a)[0], 1.0)
    np.testing.assert_allclose(np.ravel(b)[0], 5.0)


def test_run_steps_with_scheduler_and_dropout():
    """run_steps must advance in-graph LR-decay state and the dropout RNG
    stream exactly like per-step run(): the scan carries every persistable
    (incl. the scheduler's global step) plus the PRNG key."""
    import paddle_tpu.layers as layers

    x = fluid.layers.data("x", [8], dtype="float32")
    y = fluid.layers.data("y", [1], dtype="float32")
    h = layers.fc(x, size=16, act="relu")
    h = layers.dropout(h, dropout_prob=0.3)
    pred = layers.fc(h, size=1)
    loss = layers.mean(layers.square_error_cost(pred, y))
    lr = fluid.layers.exponential_decay(
        learning_rate=0.1, decay_steps=2, decay_rate=0.5, staircase=True)
    fluid.optimizer.SGD(learning_rate=lr).minimize(loss)

    rng = np.random.RandomState(11)
    feeds = [{"x": rng.rand(4, 8).astype(np.float32),
              "y": rng.rand(4, 1).astype(np.float32)} for _ in range(2)]
    exe = fluid.Executor(fluid.CPUPlace())

    exe.run(fluid.default_startup_program())
    scope = fluid.global_scope()
    snap = {n: np.asarray(scope.find_var(n)).copy()
            for n in scope.local_var_names()
            if scope.find_var(n) is not None}
    for i in range(6):
        exe.run(feed=feeds[i % 2], fetch_list=[loss])
    params_serial = {
        n: np.asarray(scope.find_var(n)).copy() for n in snap
    }

    for n in list(scope.local_var_names()):
        if n in snap:
            scope.set_var(n, snap[n])
        else:
            scope.erase(n)
    exe.run_steps(feed_list=feeds, fetch_list=[loss], steps=6)
    for n, want in params_serial.items():
        got = np.asarray(scope.find_var(n))
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7,
                                   err_msg=f"state {n} diverged")


def test_fetch_var_reads_persistable():
    """reference: test_fetch_var.py — _fetch_var reads a persistable var's
    current value straight from the scope."""
    import paddle_tpu as fluid
    from paddle_tpu import layers

    val = np.array([1, 3, 5]).astype("int32")
    x = layers.create_tensor(dtype="int32", persistable=True, name="x")
    layers.assign(input=val, output=x)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_main_program(), feed={}, fetch_list=[])
    got = fluid.executor._fetch_var("x")
    np.testing.assert_array_equal(got, val)

    # module facade parity: as_numpy refuses LoD-carrying values
    from paddle_tpu.core.lod import LoDValue
    lv = LoDValue(np.zeros((3, 2), "float32"), np.array([2, 1]), ())
    try:
        fluid.executor.as_numpy(lv)
        raise AssertionError("expected RuntimeError for LoD value")
    except RuntimeError:
        pass


def test_seeded_training_is_deterministic():
    """Same program.random_seed => bitwise-identical init, dropout stream,
    and loss trajectory across two from-scratch runs (the reference's
    FLAGS_cpu_deterministic / random_seed contract)."""
    import paddle_tpu as fluid
    from paddle_tpu import layers

    def run_once():
        fluid.reset_default_env()
        fluid.default_main_program().random_seed = 42
        fluid.default_startup_program().random_seed = 42
        x = layers.data("x", [8], dtype="float32")
        y = layers.data("y", [1], dtype="float32")
        h = layers.dropout(layers.fc(x, size=16, act="relu"),
                           dropout_prob=0.3)
        pred = layers.fc(h, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        fluid.optimizer.AdamOptimizer(learning_rate=0.01).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        rng = np.random.RandomState(0)
        xv = rng.randn(16, 8).astype("float32")
        yv = rng.randn(16, 1).astype("float32")
        return [np.asarray(exe.run(feed={"x": xv, "y": yv},
                                   fetch_list=[loss])[0]).item()
                for _ in range(4)]

    assert run_once() == run_once()


def test_run_steps_flat_matches_scan():
    """mode='flat' (straight-line K-step jit, no lax.scan — for dispatch
    layers that serialize loop iterations) must give the identical
    trajectory to the scan form: same final loss, params, and rng."""
    fluid.reset_default_env()
    x = fluid.layers.data("x", [4], dtype="float32")
    label = fluid.layers.data("label", [1], dtype="float32")
    pred = fluid.layers.fc(x, size=1, param_attr=fluid.ParamAttr(name="rf_w"))
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, label))
    fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)

    rng = np.random.RandomState(9)
    feeds = [
        {"x": rng.rand(8, 4).astype(np.float32),
         "label": rng.rand(8, 1).astype(np.float32)}
        for _ in range(3)
    ]
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    scope = fluid.global_scope()
    snapshot = {
        n: np.asarray(scope.find_var(n)).copy()
        for n in scope.local_var_names()
        if scope.find_var(n) is not None
    }
    (lv_scan,) = exe.run_steps(feed_list=feeds, fetch_list=[loss], steps=7)
    w_scan = np.asarray(scope.find_var("rf_w")).copy()
    rng_scan = np.asarray(scope.find_var("@rng_key@")).copy()

    for n in list(scope.local_var_names()):
        if n in snapshot:
            scope.set_var(n, snapshot[n])
        else:
            scope.erase(n)
    (lv_flat,) = exe.run_steps(feed_list=feeds, fetch_list=[loss], steps=7,
                               mode="flat")
    np.testing.assert_allclose(np.ravel(lv_flat), np.ravel(lv_scan),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(scope.find_var("rf_w")), w_scan,
                               rtol=1e-6)
    np.testing.assert_array_equal(
        np.asarray(scope.find_var("@rng_key@")), rng_scan)

    import pytest as _pytest
    with _pytest.raises(ValueError, match="mode"):
        exe.run_steps(feed_list=feeds, fetch_list=[loss], steps=2,
                      mode="bogus")


def test_cost_analysis_reports_bytes_and_flops():
    """Executor.cost_analysis returns the compiled step's XLA cost
    accounting (bytes accessed / flops) for the exact cached executable
    (VERDICT r5 item 4: bytes/step instrument)."""
    fluid.reset_default_env()
    x = fluid.layers.data("x", [16], dtype="float32")
    y = fluid.layers.data("y", [1], dtype="float32")
    h = fluid.layers.fc(x, size=32, act="relu")
    pred = fluid.layers.fc(h, size=1)
    loss = fluid.layers.mean(fluid.layers.square(pred - y))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    feed = {"x": np.ones((8, 16), "float32"), "y": np.ones((8, 1), "float32")}
    exe.run(feed=feed, fetch_list=[loss])
    ca = exe.cost_analysis(feed=feed, fetch_list=[loss])
    assert ca.get("bytes accessed", 0) > 0
    assert ca.get("flops", 0) > 0


def test_cost_analysis_rejects_compiled_program():
    fluid.reset_default_env()
    import pytest

    exe = fluid.Executor(fluid.CPUPlace())
    with pytest.raises(TypeError, match="plain Program"):
        exe.cost_analysis(program=fluid.CompiledProgram(fluid.Program()))


def test_no_recompile_on_second_run():
    """The written-back (committed) PRNG key must not change the lowering
    cache key: two identical exe.run calls = exactly ONE XLA compile
    (review r5: the uncommitted fresh key vs committed written-back key
    caused a silent full recompile on every program's second step —
    minutes per bench through the TPU relay)."""
    import os
    import subprocess
    import sys

    src = r"""
import os, sys
sys.path.insert(0, os.environ["REPO"])
import jax
jax.config.update("jax_platforms", "cpu")
compiles = {"n": 0}
from jax._src import monitoring
monitoring.register_event_duration_secs_listener(
    lambda event, dur, **kw: compiles.__setitem__("n", compiles["n"] + 1)
    if "backend_compile" in event else None)
import numpy as np
import paddle_tpu as fluid
x = fluid.layers.data("x", [8], dtype="float32")
h = fluid.layers.fc(x, size=8, act="tanh")
loss = fluid.layers.mean(h)
fluid.optimizer.SGD(0.1).minimize(loss)
exe = fluid.Executor(fluid.CPUPlace())
exe.run(fluid.default_startup_program())
feed = {"x": np.ones((4, 8), "float32")}
exe.run(feed=feed, fetch_list=[loss])   # first call: compiles once
print("WARMUP_COMPILES", compiles["n"])  # instrumentation liveness
base = compiles["n"]
for _ in range(3):
    exe.run(feed=feed, fetch_list=[loss])
print("MAIN_REPEAT_COMPILES", compiles["n"] - base)
feeds = [dict(feed) for _ in range(2)]
exe.run_steps(feed_list=feeds, fetch_list=[loss], steps=4, mode="flat")
base2 = compiles["n"]
for _ in range(3):
    exe.run_steps(feed_list=feeds, fetch_list=[loss], steps=4, mode="flat")
print("STEPS_REPEAT_COMPILES", compiles["n"] - base2)
"""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run([sys.executable, "-c", src],
                         capture_output=True, text=True, timeout=300,
                         env=dict(os.environ, REPO=repo))
    assert out.returncode == 0, out.stderr[-1500:]
    warm = int(out.stdout.split("WARMUP_COMPILES")[1].split()[0])
    assert warm >= 1, (
        "the backend_compile listener never fired - instrumentation is "
        "dead and the zero-recompile assertions below would be vacuous")
    n = int(out.stdout.split("MAIN_REPEAT_COMPILES")[1].split()[0])
    assert n == 0, f"repeated identical runs must not recompile, got {n}"
    ns = int(out.stdout.split("STEPS_REPEAT_COMPILES")[1].split()[0])
    assert ns == 0, (
        f"repeated identical run_steps must not recompile, got {ns}")
