"""Differential verification of the MXU-critical op lowerings against
torch (CPU) as a second INDEPENDENT reference implementation.

The numeric sweeps (tests/test_op_sweep_*.py) check each op against a
hand-written numpy reference; these tests cross-check the heavyweight and
convention-sensitive fwd+bwd paths — conv2d/conv3d/conv2d_transpose
(strided/grouped/dilated), pool2d (incl. exclusive-avg and adaptive),
batch_norm (train and eval), layer_norm, group_norm, lrn (the alpha/n
scaling trap), prelu, softmax_with_cross_entropy, smooth_l1 (sigma vs
beta), bilinear/nearest interp (align-corners), affine_grid+grid_sampler,
embedding padding_idx, sequence_conv-as-conv1d, warpctc-vs-ctc_loss, and
the lstm op under gate-order mapping — against torch, catching any bias
shared between our lowering and our own numpy references (reference
analogues: test_conv2d_op.py etc., which trusted the C++ CPU kernel the
same way).  This tier has already caught two real convention bugs:
half-pixel vs align-corners interp, and the space_to_depth reorg layout.

Everything runs through the full Program -> compiler -> Executor path, not
direct jnp calls: parameters are overwritten in the scope post-startup, and
gradients come from append_backward, so autodiff is exercised too.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.core.backward import append_backward


def _run_program(feeds, fetch, param_overrides=None, grad_of=None):
    """Build already happened in the caller's default program; run startup,
    override params, run main fetching `fetch` (+ gradients of grad_of)."""
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    scope = fluid.executor.global_scope()
    for name, val in (param_overrides or {}).items():
        scope.set_var(name, np.asarray(val))
    outs = exe.run(feed=feeds, fetch_list=fetch)
    return [np.asarray(o) for o in outs]


def test_conv2d_forward_and_grads_vs_torch():
    rng = np.random.RandomState(0)
    for stride, padding, groups, dilation in [
        (1, 1, 1, 1), (2, 0, 1, 1), (1, 2, 2, 1), (1, 2, 1, 2),
    ]:
        fluid.reset_default_env()
        N, C, H, W = 2, 4, 9, 9
        K, ks = 6, 3
        xv = rng.randn(N, C, H, W).astype("float32")
        wv = rng.randn(K, C // groups, ks, ks).astype("float32")

        x = layers.data("x", [C, H, W], dtype="float32")
        x.stop_gradient = False
        out = layers.conv2d(x, num_filters=K, filter_size=ks, stride=stride,
                            padding=padding, groups=groups, dilation=dilation,
                            bias_attr=False)
        loss = layers.reduce_sum(layers.square(out))
        pmap = append_backward(loss)
        w_name = next(p.name for p, _ in pmap)
        grads = [f"{w_name}@GRAD", f"{x.name}@GRAD"]
        got, gw, gx = _run_program(
            {"x": xv}, [out, *grads], param_overrides={w_name: wv},
        )

        xt = torch.tensor(xv, requires_grad=True)
        wt = torch.tensor(wv, requires_grad=True)
        ot = torch.nn.functional.conv2d(
            xt, wt, stride=stride, padding=padding, groups=groups,
            dilation=dilation)
        (ot ** 2).sum().backward()
        cfg = f"s={stride},p={padding},g={groups},d={dilation}"
        np.testing.assert_allclose(got, ot.detach().numpy(), rtol=2e-4,
                                   atol=2e-4, err_msg=cfg)
        np.testing.assert_allclose(gw, wt.grad.numpy(), rtol=2e-3,
                                   atol=2e-3, err_msg=cfg + " dW")
        np.testing.assert_allclose(gx, xt.grad.numpy(), rtol=2e-3,
                                   atol=2e-3, err_msg=cfg + " dX")


def test_pool2d_forward_and_grad_vs_torch():
    rng = np.random.RandomState(1)
    N, C, H, W = 2, 3, 8, 8
    xv = rng.randn(N, C, H, W).astype("float32")
    for ptype, exclusive in [("max", True), ("avg", True), ("avg", False)]:
        fluid.reset_default_env()
        x = layers.data("x", [C, H, W], dtype="float32")
        x.stop_gradient = False
        out = layers.pool2d(x, pool_size=3, pool_type=ptype, pool_stride=2,
                            pool_padding=1, exclusive=exclusive)
        loss = layers.reduce_sum(layers.square(out))
        append_backward(loss)
        got, gx = _run_program({"x": xv}, [out, f"{x.name}@GRAD"])

        xt = torch.tensor(xv, requires_grad=True)
        if ptype == "max":
            ot = torch.nn.functional.max_pool2d(xt, 3, stride=2, padding=1)
        else:
            # fluid exclusive=True == torch count_include_pad=False
            ot = torch.nn.functional.avg_pool2d(
                xt, 3, stride=2, padding=1, count_include_pad=not exclusive)
        (ot ** 2).sum().backward()
        cfg = f"{ptype},excl={exclusive}"
        np.testing.assert_allclose(got, ot.detach().numpy(), rtol=1e-5,
                                   atol=1e-5, err_msg=cfg)
        np.testing.assert_allclose(gx, xt.grad.numpy(), rtol=1e-4,
                                   atol=1e-4, err_msg=cfg + " dX")


@pytest.mark.parametrize("is_test", [False, True])
def test_batch_norm_vs_torch(is_test):
    rng = np.random.RandomState(2)
    N, C, H, W = 4, 5, 6, 6
    xv = rng.randn(N, C, H, W).astype("float32")
    scale = rng.rand(C).astype("float32") + 0.5
    bias = rng.randn(C).astype("float32")
    r_mean = rng.randn(C).astype("float32")
    r_var = rng.rand(C).astype("float32") + 0.5

    fluid.reset_default_env()
    x = layers.data("x", [C, H, W], dtype="float32")
    x.stop_gradient = False
    out = layers.batch_norm(x, is_test=is_test, momentum=0.9, epsilon=1e-5)
    bn_op = next(op for op in fluid.default_main_program().global_block().ops
                 if op.type == "batch_norm")
    names = {s: bn_op.input(s)[0] for s in ("Scale", "Bias", "Mean", "Variance")}
    overrides = {names["Scale"]: scale, names["Bias"]: bias,
                 names["Mean"]: r_mean, names["Variance"]: r_var}
    fetch = [out]
    if not is_test:
        loss = layers.reduce_sum(layers.square(out))
        append_backward(loss)
        fetch += [f"{x.name}@GRAD"]
    outs = _run_program({"x": xv}, fetch, param_overrides=overrides)

    xt = torch.tensor(xv, requires_grad=not is_test)
    ot = torch.nn.functional.batch_norm(
        xt, torch.tensor(r_mean), torch.tensor(r_var),
        weight=torch.tensor(scale), bias=torch.tensor(bias),
        training=not is_test, momentum=0.1, eps=1e-5)
    np.testing.assert_allclose(outs[0], ot.detach().numpy(), rtol=1e-4,
                               atol=1e-4)
    if not is_test:
        (ot ** 2).sum().backward()
        np.testing.assert_allclose(outs[1], xt.grad.numpy(), rtol=1e-3,
                                   atol=1e-3)


def test_layer_norm_vs_torch():
    rng = np.random.RandomState(3)
    N, D = 4, 12
    xv = rng.randn(N, D).astype("float32")
    scale = rng.rand(D).astype("float32") + 0.5
    bias = rng.randn(D).astype("float32")

    x = layers.data("x", [D], dtype="float32")
    x.stop_gradient = False
    out = layers.layer_norm(x, begin_norm_axis=1, epsilon=1e-5)
    ln_op = next(op for op in fluid.default_main_program().global_block().ops
                 if op.type == "layer_norm")
    overrides = {ln_op.input("Scale")[0]: scale, ln_op.input("Bias")[0]: bias}
    loss = layers.reduce_sum(layers.square(out))
    append_backward(loss)
    got, gx = _run_program({"x": xv}, [out, f"{x.name}@GRAD"],
                           param_overrides=overrides)

    xt = torch.tensor(xv, requires_grad=True)
    ot = torch.nn.functional.layer_norm(
        xt, (D,), weight=torch.tensor(scale), bias=torch.tensor(bias),
        eps=1e-5)
    (ot ** 2).sum().backward()
    np.testing.assert_allclose(got, ot.detach().numpy(), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(gx, xt.grad.numpy(), rtol=1e-3, atol=1e-3)


def test_softmax_with_cross_entropy_vs_torch():
    rng = np.random.RandomState(4)
    N, K = 8, 10
    xv = (rng.randn(N, K) * 3).astype("float32")
    yv = rng.randint(0, K, (N, 1)).astype("int64")

    x = layers.data("x", [K], dtype="float32")
    x.stop_gradient = False
    y = layers.data("y", [1], dtype="int64")
    loss_vec = layers.softmax_with_cross_entropy(x, y)
    loss = layers.reduce_mean(loss_vec)
    append_backward(loss)
    got, gx = _run_program({"x": xv, "y": yv}, [loss_vec, f"{x.name}@GRAD"])

    xt = torch.tensor(xv, requires_grad=True)
    lt = torch.nn.functional.cross_entropy(
        xt, torch.tensor(yv.reshape(-1)), reduction="none")
    lt.mean().backward()
    np.testing.assert_allclose(got.reshape(-1), lt.detach().numpy(),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(gx, xt.grad.numpy(), rtol=1e-5, atol=1e-6)


def test_conv2d_transpose_vs_torch():
    rng = np.random.RandomState(5)
    N, C, H, W = 2, 4, 7, 7
    K, ks = 3, 3
    xv = rng.randn(N, C, H, W).astype("float32")
    wv = rng.randn(C, K, ks, ks).astype("float32")  # fluid/torch: [Cin, Cout, kh, kw]

    x = layers.data("x", [C, H, W], dtype="float32")
    x.stop_gradient = False
    out = layers.conv2d_transpose(x, num_filters=K, filter_size=ks, stride=2,
                                  padding=1, bias_attr=False)
    loss = layers.reduce_sum(layers.square(out))
    pmap = append_backward(loss)
    w_name = next(p.name for p, _ in pmap)
    got, gx = _run_program({"x": xv}, [out, f"{x.name}@GRAD"],
                           param_overrides={w_name: wv})

    xt = torch.tensor(xv, requires_grad=True)
    ot = torch.nn.functional.conv_transpose2d(
        xt, torch.tensor(wv), stride=2, padding=1)
    (ot ** 2).sum().backward()
    np.testing.assert_allclose(got, ot.detach().numpy(), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(gx, xt.grad.numpy(), rtol=2e-3, atol=2e-3)


def test_dynamic_lstm_vs_torch():
    """The `lstm` op against torch.nn.LSTM: fluid feeds pre-projected 4H
    inputs with gate blocks ordered [c, i, f, o]; torch stacks [i, f, g, o].
    W_ih is set to the block permutation so torch consumes the same
    projections (reference gate order: operators/lstm_op.cc kernel;
    peepholes off, which torch has no equivalent for)."""
    from tests.op_test import OpTest

    rng = np.random.RandomState(6)
    hid = 5
    lens = [4, 2, 3]  # variable-length batch exercises the LoD mask path
    seqs = [rng.randn(t, 4 * hid).astype("float32") for t in lens]
    flat = np.concatenate(seqs, axis=0)
    w = (rng.randn(hid, 4 * hid) * 0.5).astype("float32")
    b = (rng.randn(1, 4 * hid) * 0.5).astype("float32")

    # fluid block order [c, i, f, o] -> torch row order [i, f, g(c), o]
    perm = np.r_[hid:2 * hid, 2 * hid:3 * hid, 0:hid, 3 * hid:4 * hid]
    lstm = torch.nn.LSTM(input_size=4 * hid, hidden_size=hid)
    with torch.no_grad():
        lstm.weight_ih_l0.copy_(torch.tensor(np.eye(4 * hid, dtype="float32")[perm]))
        lstm.weight_hh_l0.copy_(torch.tensor(w.T[perm]))
        lstm.bias_ih_l0.copy_(torch.tensor(b.reshape(-1)[perm]))
        lstm.bias_hh_l0.zero_()

    want_h, want_c = [], []
    for s in seqs:
        with torch.no_grad():
            h_seq, (h_T, c_T) = lstm(torch.tensor(s).unsqueeze(1))
        want_h.append(h_seq.squeeze(1).numpy())
        # torch only exposes the final cell state; recompute the per-step
        # cells by stepping the cell manually for the Cell output check
        cell = torch.nn.LSTMCell(4 * hid, hid)
        with torch.no_grad():
            cell.weight_ih.copy_(lstm.weight_ih_l0)
            cell.weight_hh.copy_(lstm.weight_hh_l0)
            cell.bias_ih.copy_(lstm.bias_ih_l0)
            cell.bias_hh.copy_(lstm.bias_hh_l0)
            hx = torch.zeros(1, hid)
            cx = torch.zeros(1, hid)
            cs = []
            for t in range(s.shape[0]):
                hx, cx = cell(torch.tensor(s[t:t + 1]), (hx, cx))
                cs.append(cx.numpy()[0])
        want_c.append(np.stack(cs))
        np.testing.assert_allclose(hx.numpy(), h_T.squeeze(0).numpy(),
                                   atol=1e-6)  # cell replay sanity

    class T(OpTest):
        op_type = "lstm"

    t = T()
    t.inputs = {"Input": (flat, lens), "Weight": w, "Bias": b}
    t.attrs = {"use_peepholes": False}
    t.outputs = {
        "Hidden": (np.concatenate(want_h), lens),
        "Cell": (np.concatenate(want_c), lens),
        "BatchGate": None,
        "BatchCellPreAct": None,
    }
    t.check_output(atol=2e-5, rtol=2e-5)


def test_bilinear_interp_vs_torch():
    """bilinear_interp uses the reference's (in-1)/(out-1) align-corners
    ratio (interpolate_op.h:171) == torch align_corners=True.  Covers both
    up- and down-sampling and gradients."""
    rng = np.random.RandomState(7)
    for ih, iw, oh, ow in [(4, 4, 9, 7), (9, 7, 4, 5)]:
        fluid.reset_default_env()
        xv = rng.randn(2, 3, ih, iw).astype("float32")
        x = layers.data("x", [3, ih, iw], dtype="float32")
        x.stop_gradient = False
        out = layers.resize_bilinear(x, out_shape=[oh, ow])
        loss = layers.reduce_sum(layers.square(out))
        append_backward(loss)
        got, gx = _run_program({"x": xv}, [out, f"{x.name}@GRAD"])

        xt = torch.tensor(xv, requires_grad=True)
        ot = torch.nn.functional.interpolate(
            xt, size=(oh, ow), mode="bilinear", align_corners=True)
        (ot ** 2).sum().backward()
        cfg = f"{ih}x{iw}->{oh}x{ow}"
        np.testing.assert_allclose(got, ot.detach().numpy(), rtol=1e-5,
                                   atol=1e-5, err_msg=cfg)
        np.testing.assert_allclose(gx, xt.grad.numpy(), rtol=1e-4,
                                   atol=1e-4, err_msg=cfg + " dX")


def test_nearest_interp_vs_torch_ref():
    """nearest_interp rounds ratio*k+0.5 with the align-corners ratio
    (interpolate_op.h:33).  torch's nearest uses floor(k*in/out) — a
    DIFFERENT convention — so the reference here is the op kernel's own
    formula, checked exactly."""
    rng = np.random.RandomState(8)
    ih, iw, oh, ow = 5, 4, 8, 9
    xv = rng.randn(2, 3, ih, iw).astype("float32")
    x = layers.data("x", [3, ih, iw], dtype="float32")
    out = layers.resize_nearest(x, out_shape=[oh, ow])
    (got,) = _run_program({"x": xv}, [out])

    # hand-derived from interpolate_op.h:33 floor(ratio*k + 0.5) with
    # ratio_h = 4/7, ratio_w = 3/8 — literals, so the test stays
    # independent of any formula shared with the implementation
    idx_h = np.array([0, 1, 1, 2, 2, 3, 3, 4])
    idx_w = np.array([0, 0, 1, 1, 2, 2, 2, 3, 3])
    assert np.array_equal(
        np.floor((ih - 1) / (oh - 1) * np.arange(oh) + 0.5).astype(int),
        idx_h)
    want = xv[:, :, idx_h][:, :, :, idx_w]
    np.testing.assert_array_equal(got, want)


def test_affine_grid_and_grid_sampler_vs_torch():
    """affine_grid (linspace(-1,1)) + grid_sampler ((g+1)(size-1)/2
    unnormalize, zero padding) both follow the reference's align-corners
    convention == torch {affine_grid, grid_sample}(align_corners=True).
    Theta deliberately pushes part of the grid out of bounds."""
    rng = np.random.RandomState(9)
    N, C, H, W = 2, 3, 6, 5
    xv = rng.randn(N, C, H, W).astype("float32")
    theta_v = (np.tile(np.array([[1.2, 0.1, 0.2], [-0.1, 0.9, -0.3]],
                                dtype="float32"), (N, 1, 1))
               + rng.randn(N, 2, 3).astype("float32") * 0.05)

    x = layers.data("x", [C, H, W], dtype="float32")
    x.stop_gradient = False
    theta = layers.data("theta", [2, 3], dtype="float32")
    theta.stop_gradient = False
    grid = layers.affine_grid(theta, out_shape=[N, C, H, W])
    out = layers.grid_sampler(x, grid)
    loss = layers.reduce_sum(layers.square(out))
    append_backward(loss)
    got, gx, gt = _run_program(
        {"x": xv, "theta": theta_v},
        [out, f"{x.name}@GRAD", f"{theta.name}@GRAD"])

    xt = torch.tensor(xv, requires_grad=True)
    tt = torch.tensor(theta_v, requires_grad=True)
    gridt = torch.nn.functional.affine_grid(
        tt, (N, C, H, W), align_corners=True)
    ot = torch.nn.functional.grid_sample(
        xt, gridt, mode="bilinear", padding_mode="zeros", align_corners=True)
    (ot ** 2).sum().backward()
    np.testing.assert_allclose(got, ot.detach().numpy(), rtol=1e-4,
                               atol=1e-4)
    np.testing.assert_allclose(gx, xt.grad.numpy(), rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(gt, tt.grad.numpy(), rtol=1e-3, atol=1e-3)


def test_lrn_vs_torch():
    """fluid's lrn uses alpha directly (lrn_op.h:37: k + alpha*SUM(x^2));
    torch LocalResponseNorm divides alpha by the window size n — so
    torch(alpha=n*a) must equal fluid(alpha=a).  The scaling trap this
    encodes is exactly the kind of shared-bias bug the numpy sweeps can't
    see."""
    rng = np.random.RandomState(10)
    N, C, H, W = 2, 7, 5, 5
    n, k, a, beta = 5, 1.0, 1e-2, 0.75
    xv = rng.randn(N, C, H, W).astype("float32")

    x = layers.data("x", [C, H, W], dtype="float32")
    x.stop_gradient = False
    out = layers.lrn(x, n=n, k=k, alpha=a, beta=beta)
    loss = layers.reduce_sum(layers.square(out))
    append_backward(loss)
    got, gx = _run_program({"x": xv}, [out, f"{x.name}@GRAD"])

    xt = torch.tensor(xv, requires_grad=True)
    ot = torch.nn.functional.local_response_norm(
        xt, size=n, alpha=a * n, beta=beta, k=k)
    (ot ** 2).sum().backward()
    np.testing.assert_allclose(got, ot.detach().numpy(), rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(gx, xt.grad.numpy(), rtol=1e-4, atol=1e-4)


def test_conv3d_vs_torch():
    rng = np.random.RandomState(11)
    N, C, D, H, W = 2, 3, 5, 6, 6
    K, ks = 4, 3
    xv = rng.randn(N, C, D, H, W).astype("float32")
    wv = rng.randn(K, C, ks, ks, ks).astype("float32")

    x = layers.data("x", [C, D, H, W], dtype="float32")
    x.stop_gradient = False
    out = layers.conv3d(x, num_filters=K, filter_size=ks, stride=2,
                        padding=1, bias_attr=False)
    loss = layers.reduce_sum(layers.square(out))
    pmap = append_backward(loss)
    w_name = next(p.name for p, _ in pmap)
    got, gw, gx = _run_program(
        {"x": xv}, [out, f"{w_name}@GRAD", f"{x.name}@GRAD"],
        param_overrides={w_name: wv})

    xt = torch.tensor(xv, requires_grad=True)
    wt = torch.tensor(wv, requires_grad=True)
    ot = torch.nn.functional.conv3d(xt, wt, stride=2, padding=1)
    (ot ** 2).sum().backward()
    np.testing.assert_allclose(got, ot.detach().numpy(), rtol=2e-4,
                               atol=2e-4)
    np.testing.assert_allclose(gw, wt.grad.numpy(), rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(gx, xt.grad.numpy(), rtol=2e-3, atol=2e-3)


def test_group_norm_vs_torch():
    rng = np.random.RandomState(12)
    N, C, H, W = 2, 8, 5, 5
    G = 4
    xv = rng.randn(N, C, H, W).astype("float32")
    scale = rng.rand(C).astype("float32") + 0.5
    bias = rng.randn(C).astype("float32")

    x = layers.data("x", [C, H, W], dtype="float32")
    x.stop_gradient = False
    out = layers.group_norm(x, groups=G, epsilon=1e-5)
    gn_op = next(op for op in fluid.default_main_program().global_block().ops
                 if op.type == "group_norm")
    overrides = {gn_op.input("Scale")[0]: scale,
                 gn_op.input("Bias")[0]: bias}
    loss = layers.reduce_sum(layers.square(out))
    append_backward(loss)
    got, gx = _run_program({"x": xv}, [out, f"{x.name}@GRAD"],
                           param_overrides=overrides)

    xt = torch.tensor(xv, requires_grad=True)
    ot = torch.nn.functional.group_norm(
        xt, G, weight=torch.tensor(scale), bias=torch.tensor(bias),
        eps=1e-5)
    (ot ** 2).sum().backward()
    np.testing.assert_allclose(got, ot.detach().numpy(), rtol=1e-4,
                               atol=1e-4)
    np.testing.assert_allclose(gx, xt.grad.numpy(), rtol=1e-3, atol=1e-3)


def test_prelu_channel_vs_torch():
    rng = np.random.RandomState(13)
    N, C, H, W = 2, 4, 5, 5
    xv = rng.randn(N, C, H, W).astype("float32")
    alpha = (rng.rand(C) * 0.5).astype("float32")

    x = layers.data("x", [C, H, W], dtype="float32")
    x.stop_gradient = False
    out = layers.prelu(x, mode="channel")
    pr_op = next(op for op in fluid.default_main_program().global_block().ops
                 if op.type == "prelu")
    overrides = {pr_op.input("Alpha")[0]: alpha.reshape(1, C, 1, 1)}
    loss = layers.reduce_sum(layers.square(out))
    append_backward(loss)
    got, gx = _run_program({"x": xv}, [out, f"{x.name}@GRAD"],
                           param_overrides=overrides)

    xt = torch.tensor(xv, requires_grad=True)
    ot = torch.nn.functional.prelu(xt, torch.tensor(alpha))
    (ot ** 2).sum().backward()
    np.testing.assert_allclose(got, ot.detach().numpy(), rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(gx, xt.grad.numpy(), rtol=1e-4, atol=1e-4)


def test_warpctc_vs_torch_ctc_loss():
    """warpctc (log-space alpha scan) against torch.nn.functional.ctc_loss
    with reduction='none': per-sequence -log p(l|x) must agree on a ragged
    batch, and the analytic gradient wrt raw logits must match torch's
    autograd through log_softmax -> ctc_loss."""
    from tests.op_test import OpTest

    rng = np.random.RandomState(14)
    C = 6          # classes incl. blank 0
    t_lens = [7, 5, 6]
    l_lens = [3, 2, 1]
    logits = [rng.randn(t, C).astype("float32") for t in t_lens]
    labels = [rng.randint(1, C, (l, 1)).astype("int64") for l in l_lens]

    lp = [torch.tensor(x, requires_grad=True) for x in logits]
    losses, grads = [], []
    for x, y in zip(lp, labels):
        log_probs = torch.nn.functional.log_softmax(x, dim=-1)
        loss = torch.nn.functional.ctc_loss(
            log_probs.unsqueeze(1), torch.tensor(y.reshape(1, -1)),
            input_lengths=torch.tensor([x.shape[0]]),
            target_lengths=torch.tensor([y.shape[0]]),
            blank=0, reduction="none", zero_infinity=False)
        loss.backward()
        losses.append(float(loss))
        grads.append(x.grad.numpy())
    want_loss = np.array(losses, dtype="float32").reshape(-1, 1)

    class T(OpTest):
        op_type = "warpctc"

    t = T()
    t.inputs = {"Logits": (np.concatenate(logits), t_lens),
                "Label": (np.concatenate(labels), l_lens)}
    t.attrs = {"blank": 0, "norm_by_times": False}
    t.outputs = {"Loss": want_loss}
    t.check_output(atol=2e-4, rtol=2e-4)

    # analytic dLogits vs torch, via the executor path with a grad fetch
    prog, startup, feed, in_names, out_names = t._build()
    with fluid.program_guard(prog, startup):
        loss_name = out_names["Loss"][0]
        total = layers.reduce_sum(prog.global_block().var(loss_name))
        append_backward(total)
        exe = fluid.Executor(fluid.CPUPlace())
        (g,) = exe.run(program=prog, feed=feed,
                       fetch_list=[in_names["Logits"][0] + "@GRAD"],
                       return_numpy=False)
    got_grad = np.asarray(g.data if hasattr(g, "data") else g)
    want_grad = np.concatenate(grads)
    # got_grad is the padded [N, maxT, C] layout; flatten valid rows
    if got_grad.ndim == 3:
        got_grad = np.concatenate(
            [got_grad[i, :t] for i, t in enumerate(t_lens)])
    np.testing.assert_allclose(got_grad, want_grad, rtol=2e-3, atol=2e-4)


def test_embedding_padding_idx_vs_torch():
    """lookup_table with padding_idx: the padded row reads ZEROS at run
    time (lookup_table_op.h memsets the output row — stronger than torch,
    which only zeroes the gradient) and receives zero gradient.  Zeroing
    the torch table's pad row makes the two semantics coincide, so torch
    still cross-checks the gather and the grad-exclusion."""
    rng = np.random.RandomState(15)
    V, D = 12, 6
    pad = 3
    ids = np.array([[1], [3], [5], [3], [0], [11]], dtype="int64")
    table = rng.randn(V, D).astype("float32")
    table[pad] = 0.0  # align torch's weaker convention with the reference

    x = layers.data("ids", [1], dtype="int64")
    emb = layers.embedding(x, size=[V, D], padding_idx=pad)
    w_name = next(op for op in
                  fluid.default_main_program().global_block().ops
                  if op.type == "lookup_table").input("W")[0]
    loss = layers.reduce_sum(layers.square(emb))
    append_backward(loss)
    got, gw = _run_program({"ids": ids}, [emb, f"{w_name}@GRAD"],
                           param_overrides={w_name: table})

    wt = torch.tensor(table, requires_grad=True)
    ot = torch.nn.functional.embedding(
        torch.tensor(ids.reshape(-1)), wt, padding_idx=pad)
    (ot ** 2).sum().backward()
    np.testing.assert_allclose(got.reshape(-1, D), ot.detach().numpy(),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(gw, wt.grad.numpy(), rtol=1e-5, atol=1e-6)
    assert np.all(gw[pad] == 0)


def test_sequence_conv_vs_torch_conv1d():
    """sequence_conv with context_start=-(k-1)/2 on equal-length sequences
    == 1D convolution with zero padding (sequence_conv_op math via the
    im2col-style context window)."""
    from tests.op_test import OpTest

    rng = np.random.RandomState(16)
    T, Din, Dout, k = 6, 4, 5, 3
    lens = [T, T]
    flat = rng.randn(sum(lens), Din).astype("float32")
    # fluid filter: [k*Din, Dout], rows ordered context-position-major
    w = rng.randn(k * Din, Dout).astype("float32")

    xt = torch.tensor(
        np.stack([flat[:T], flat[T:]]).transpose(0, 2, 1),
        requires_grad=False)  # [N, Din, T]
    # torch conv1d weight [Dout, Din, k]: fluid's rows are
    # [ctx0*Din..., ctx1*Din..., ctx2*Din...] -> permute accordingly
    wt = torch.tensor(
        w.reshape(k, Din, Dout).transpose(2, 1, 0).copy())
    ot = torch.nn.functional.conv1d(xt, wt, padding=(k - 1) // 2)
    want_flat = np.concatenate(
        [o.T for o in ot.detach().numpy()]).astype("float32")

    class Tst(OpTest):
        op_type = "sequence_conv"

    t = Tst()
    t.inputs = {"X": (flat, lens), "Filter": w}
    t.attrs = {"contextLength": k, "contextStart": -(k - 1) // 2,
               "contextStride": 1}
    t.outputs = {"Out": (want_flat, lens)}
    t.check_output(atol=2e-5, rtol=2e-5)


def test_adaptive_pool2d_vs_torch():
    """adaptive avg/max pooling bin bounds (math/pooling.h floor/ceil
    Adaptive{Start,End}Index) == torch adaptive_{avg,max}_pool2d.  The
    snapshot's Python layer doesn't expose adaptive (the C++ op grew the
    attr first, pool_op.cc:194), so this drives the op directly."""
    from tests.op_test import OpTest

    rng = np.random.RandomState(17)
    N, C, H, W = 2, 3, 7, 11
    xv = rng.randn(N, C, H, W).astype("float32")
    for ptype in ("avg", "max"):
        fn = (torch.nn.functional.adaptive_avg_pool2d if ptype == "avg"
              else torch.nn.functional.adaptive_max_pool2d)
        want = fn(torch.tensor(xv), (3, 4)).numpy()

        class T(OpTest):
            op_type = "pool2d"

        t = T()
        t.inputs = {"X": xv}
        t.attrs = {"pooling_type": ptype, "ksize": [3, 4], "adaptive": True,
                   "strides": [1, 1], "paddings": [0, 0]}
        t.outputs = {"Out": want}
        t.check_output(atol=1e-5, rtol=1e-5)
        t.check_grad(["X"], "Out", max_relative_error=0.02)


def test_smooth_l1_vs_torch():
    """fluid smooth_l1(sigma) == torch smooth_l1_loss(beta=1/sigma^2)
    summed over the trailing dim (smooth_l1_loss_op.h)."""
    rng = np.random.RandomState(18)
    N, D = 6, 5
    sigma = 2.0
    xv = rng.randn(N, D).astype("float32")
    yv = rng.randn(N, D).astype("float32")

    x = layers.data("x", [D], dtype="float32")
    x.stop_gradient = False
    y = layers.data("y", [D], dtype="float32")
    out = layers.smooth_l1(x, y, sigma=sigma)
    loss = layers.reduce_sum(out)
    append_backward(loss)
    got, gx = _run_program({"x": xv, "y": yv}, [out, f"{x.name}@GRAD"])

    xt = torch.tensor(xv, requires_grad=True)
    lt = torch.nn.functional.smooth_l1_loss(
        xt, torch.tensor(yv), beta=1.0 / sigma ** 2,
        reduction="none").sum(dim=1, keepdim=True)
    lt.sum().backward()
    np.testing.assert_allclose(got, lt.detach().numpy(), rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(gx, xt.grad.numpy(), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("name,torch_fn,attrs", [
    ("gelu", lambda x: torch.nn.functional.gelu(x, approximate="none"), {}),
    ("softplus", lambda x: torch.nn.functional.softplus(x), {}),
    ("elu", lambda x: torch.nn.functional.elu(x, alpha=1.0), {}),
    ("softsign", torch.nn.functional.softsign, {}),
    ("tanh_shrink", torch.nn.functional.tanhshrink, {}),
    ("softshrink", lambda x: torch.nn.functional.softshrink(x, lambd=0.4),
     {"lambda": 0.4}),
    ("hard_shrink", lambda x: torch.nn.functional.hardshrink(x, lambd=0.4),
     {"threshold": 0.4}),
    ("leaky_relu", lambda x: torch.nn.functional.leaky_relu(x, 0.1),
     {"alpha": 0.1}),
    ("relu6", torch.nn.functional.relu6, {}),
    ("selu", torch.nn.functional.selu, {}),
])
def test_activation_vs_torch(name, torch_fn, attrs):
    """Convention-sensitive activations (gelu erf-vs-tanh, shrink
    thresholds, selu's alpha/scale constants) vs torch, fwd + grad,
    through the op path."""
    from tests.op_test import OpTest

    rng = np.random.RandomState(19)
    x = (rng.randn(4, 7) * 2).astype("float32")
    # keep points away from the kink of piecewise activations so numeric
    # grads (check_grad) and torch agree
    for kink in ((0.4, -0.4) if "shrink" in name else (0.0,)):
        x[np.abs(x - kink) < 0.05] += 0.1

    xt = torch.tensor(x, requires_grad=True)
    ot = torch_fn(xt)
    ot.sum().backward()

    class T(OpTest):
        op_type = name

    t = T()
    t.inputs = {"X": x}
    t.attrs = dict(attrs)
    t.outputs = {"Out": ot.detach().numpy()}
    t.check_output(atol=1e-5, rtol=1e-5)
    # analytic dX through the program path vs torch autograd (check_grad
    # would only compare our analytic grad against our own FD)
    prog, startup, feed, in_names, out_names = t._build()
    with fluid.program_guard(prog, startup):
        total = layers.reduce_sum(
            prog.global_block().var(out_names["Out"][0]))
        append_backward(total)
        exe = fluid.Executor(fluid.CPUPlace())
        (g,) = exe.run(program=prog, feed=feed,
                       fetch_list=[in_names["X"][0] + "@GRAD"])
    np.testing.assert_allclose(np.asarray(g), xt.grad.numpy(), rtol=1e-4,
                               atol=1e-5, err_msg=name + " dX")
    t.check_grad(["X"], "Out", max_relative_error=0.01)


@pytest.mark.parametrize("name", [
    "sgd", "momentum", "nesterov", "adam", "adagrad", "rmsprop", "adadelta",
])
def test_optimizer_trajectory_vs_torch(name):
    """Five coupled training steps of a linear regression, our in-graph
    optimizer ops vs torch.optim on the identical model: catches
    convention bias (bias-correction form, eps placement, velocity
    scaling) the numpy sweeps could share.  Known benign formulation
    deltas (fluid's epsilon-hat adam, rmsprop's eps-inside-sqrt) stay
    under the tolerance at these scales."""
    rng = np.random.RandomState(20)
    D = 6
    w0 = rng.randn(D, 1).astype("float32") * 0.5
    feeds = [(rng.randn(8, D).astype("float32"),
              rng.randn(8, 1).astype("float32")) for _ in range(5)]

    x = layers.data("x", [D], dtype="float32")
    y = layers.data("y", [1], dtype="float32")
    pred = layers.fc(x, size=1, bias_attr=False)
    loss = layers.mean(layers.square_error_cost(pred, y))
    opt = {
        "sgd": lambda: fluid.optimizer.SGDOptimizer(learning_rate=0.1),
        "momentum": lambda: fluid.optimizer.MomentumOptimizer(
            learning_rate=0.1, momentum=0.9),
        "nesterov": lambda: fluid.optimizer.MomentumOptimizer(
            learning_rate=0.1, momentum=0.9, use_nesterov=True),
        "adam": lambda: fluid.optimizer.AdamOptimizer(
            learning_rate=0.05, beta1=0.9, beta2=0.999, epsilon=1e-8),
        "adagrad": lambda: fluid.optimizer.AdagradOptimizer(
            learning_rate=0.1, epsilon=1e-10),
        "rmsprop": lambda: fluid.optimizer.RMSPropOptimizer(
            learning_rate=0.05, rho=0.9, epsilon=1e-6, momentum=0.9),
        "adadelta": lambda: fluid.optimizer.AdadeltaOptimizer(
            learning_rate=1.0, epsilon=1e-6, rho=0.95),
    }[name]()
    opt.minimize(loss)
    w_name = next(op for op in
                  fluid.default_main_program().global_block().ops
                  if op.type == "mul").input("Y")[0]
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    fluid.global_scope().set_var(w_name, w0.copy())
    for xv, yv in feeds:
        exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])
    got = np.asarray(fluid.global_scope().find_var(w_name))

    lin = torch.nn.Linear(D, 1, bias=False)
    with torch.no_grad():
        lin.weight.copy_(torch.tensor(w0.T))
    topt = {
        "sgd": lambda p: torch.optim.SGD(p, lr=0.1),
        "momentum": lambda p: torch.optim.SGD(p, lr=0.1, momentum=0.9),
        "nesterov": lambda p: torch.optim.SGD(p, lr=0.1, momentum=0.9,
                                              nesterov=True),
        "adam": lambda p: torch.optim.Adam(p, lr=0.05, betas=(0.9, 0.999),
                                           eps=1e-8),
        "adagrad": lambda p: torch.optim.Adagrad(p, lr=0.1, eps=1e-10),
        "rmsprop": lambda p: torch.optim.RMSprop(p, lr=0.05, alpha=0.9,
                                                 eps=1e-6, momentum=0.9),
        "adadelta": lambda p: torch.optim.Adadelta(p, lr=1.0, rho=0.95,
                                                   eps=1e-6),
    }[name](lin.parameters())
    for xv, yv in feeds:
        topt.zero_grad()
        out = lin(torch.tensor(xv))
        tl = ((out - torch.tensor(yv)) ** 2).mean()
        tl.backward()
        topt.step()
    want = lin.weight.detach().numpy().T
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_loss_family_vs_torch():
    """sigmoid_cross_entropy_with_logits == torch BCE-with-logits;
    huber_loss(delta) == torch huber_loss(delta) on residual y-x;
    margin_rank_loss == torch margin_ranking_loss; cos_sim == torch
    cosine_similarity.  Each fwd + analytic dX vs torch autograd."""
    from tests.op_test import OpTest

    rng = np.random.RandomState(21)
    N, D = 6, 5

    def run_op(op_type, inputs, attrs, outputs=("Out",), out_slot="Out",
               grad_of="X"):
        class T(OpTest):
            pass
        T.op_type = op_type
        t = T()
        t.inputs = inputs
        t.attrs = attrs
        t.outputs = {slot: None for slot in outputs}
        prog, startup, feed, in_names, out_names = t._build()
        for slot in ("Label",):  # supervision inputs take no gradient
            for n in in_names.get(slot, []):
                prog.global_block().var(n).stop_gradient = True
        with fluid.program_guard(prog, startup):
            total = layers.reduce_sum(
                prog.global_block().var(out_names[out_slot][0]))
            append_backward(total)
            exe = fluid.Executor(fluid.CPUPlace())
            outs = exe.run(
                program=prog, feed=feed,
                fetch_list=[out_names[out_slot][0],
                            in_names[grad_of][0] + "@GRAD"])
        return [np.asarray(o) for o in outs]

    xv = (rng.randn(N, D) * 2).astype("float32")
    lv = rng.rand(N, D).astype("float32")
    got, gx = run_op("sigmoid_cross_entropy_with_logits",
                     {"X": xv, "Label": lv}, {})
    xt = torch.tensor(xv, requires_grad=True)
    want = torch.nn.functional.binary_cross_entropy_with_logits(
        xt, torch.tensor(lv), reduction="none")
    want.sum().backward()
    np.testing.assert_allclose(got, want.detach().numpy(), rtol=1e-5,
                               atol=1e-6, err_msg="sigmoid_ce")
    np.testing.assert_allclose(gx, xt.grad.numpy(), rtol=1e-4, atol=1e-6,
                               err_msg="sigmoid_ce dX")

    # huber_loss: fluid residual = Y - X, delta attr; torch(input=x,
    # target=y, delta) is symmetric in |y-x| so they coincide
    yv = (rng.randn(N, 1)).astype("float32")
    xv2 = (rng.randn(N, 1)).astype("float32")
    got, gx = run_op("huber_loss", {"X": xv2, "Y": yv}, {"delta": 0.7},
                     outputs=("Out", "Residual"))
    xt = torch.tensor(xv2, requires_grad=True)
    want = torch.nn.functional.huber_loss(
        xt, torch.tensor(yv), delta=0.7, reduction="none")
    want.sum().backward()
    np.testing.assert_allclose(got.reshape(-1), want.detach().numpy()
                               .reshape(-1), rtol=1e-5, atol=1e-6,
                               err_msg="huber")
    np.testing.assert_allclose(gx, xt.grad.numpy(), rtol=1e-4, atol=1e-6,
                               err_msg="huber dX")

    # margin_rank_loss: out = max(0, -label*(x1-x2) + margin)
    x1 = rng.randn(N, 1).astype("float32")
    x2 = rng.randn(N, 1).astype("float32")
    lab = np.where(rng.rand(N, 1) > 0.5, 1.0, -1.0).astype("float32")
    got, g1 = run_op("margin_rank_loss",
                     {"X1": x1, "X2": x2, "Label": lab}, {"margin": 0.3},
                     outputs=("Out", "Activated"), grad_of="X1")
    t1 = torch.tensor(x1, requires_grad=True)
    want = torch.nn.functional.margin_ranking_loss(
        t1, torch.tensor(x2), torch.tensor(lab), margin=0.3,
        reduction="none")
    want.sum().backward()
    np.testing.assert_allclose(got.reshape(-1),
                               want.detach().numpy().reshape(-1),
                               rtol=1e-5, atol=1e-6, err_msg="margin_rank")
    np.testing.assert_allclose(g1, t1.grad.numpy(), rtol=1e-4, atol=1e-6,
                               err_msg="margin_rank dX1")

    # cos_sim (row-wise cosine similarity)
    xa = rng.randn(N, D).astype("float32")
    xb = rng.randn(N, D).astype("float32")
    got, gx = run_op("cos_sim", {"X": xa, "Y": xb}, {},
                     outputs=("Out", "XNorm", "YNorm"))
    ta = torch.tensor(xa, requires_grad=True)
    want = torch.nn.functional.cosine_similarity(ta, torch.tensor(xb),
                                                 dim=1)
    want.sum().backward()
    np.testing.assert_allclose(got.reshape(-1), want.detach().numpy(),
                               rtol=1e-5, atol=1e-6, err_msg="cos_sim")
    np.testing.assert_allclose(gx, ta.grad.numpy(), rtol=1e-4, atol=1e-6,
                               err_msg="cos_sim dX")


def test_global_norm_clip_trajectory_vs_torch():
    """GradientClipByGlobalNorm + SGD over 4 steps vs torch
    clip_grad_norm_ + SGD: the global norm spans BOTH parameters and the
    clip factor is clip_norm/max(g_norm, clip_norm).  clip_norm=0.05 is
    small enough that clipping is active every step."""
    rng = np.random.RandomState(22)
    D = 6
    w0 = rng.randn(D, 4).astype("float32") * 0.5
    v0 = rng.randn(4, 1).astype("float32") * 0.5
    feeds = [(rng.randn(8, D).astype("float32"),
              rng.randn(8, 1).astype("float32")) for _ in range(4)]

    x = layers.data("x", [D], dtype="float32")
    y = layers.data("y", [1], dtype="float32")
    h = layers.fc(x, size=4, bias_attr=False)
    pred = layers.fc(h, size=1, bias_attr=False)
    loss = layers.mean(layers.square_error_cost(pred, y))
    fluid.clip.set_gradient_clip(
        fluid.clip.GradientClipByGlobalNorm(clip_norm=0.05))
    fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
    mul_ops = [op for op in fluid.default_main_program().global_block().ops
               if op.type == "mul"]
    w_name, v_name = (op.input("Y")[0] for op in mul_ops[:2])
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    fluid.global_scope().set_var(w_name, w0.copy())
    fluid.global_scope().set_var(v_name, v0.copy())
    for xv, yv in feeds:
        exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])
    got_w = np.asarray(fluid.global_scope().find_var(w_name))
    got_v = np.asarray(fluid.global_scope().find_var(v_name))

    l1 = torch.nn.Linear(D, 4, bias=False)
    l2 = torch.nn.Linear(4, 1, bias=False)
    with torch.no_grad():
        l1.weight.copy_(torch.tensor(w0.T))
        l2.weight.copy_(torch.tensor(v0.T))
    opt = torch.optim.SGD(list(l1.parameters()) + list(l2.parameters()),
                          lr=0.1)
    for xv, yv in feeds:
        opt.zero_grad()
        out = l2(l1(torch.tensor(xv)))
        ((out - torch.tensor(yv)) ** 2).mean().backward()
        torch.nn.utils.clip_grad_norm_(
            list(l1.parameters()) + list(l2.parameters()), 0.05)
        opt.step()
    np.testing.assert_allclose(got_w, l1.weight.detach().numpy().T,
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(got_v, l2.weight.detach().numpy().T,
                               rtol=1e-4, atol=1e-6)


def test_l2_regularizer_trajectory_vs_torch_weight_decay():
    """L2DecayRegularizer(coeff) appends coeff*param to the gradient ==
    torch SGD(weight_decay=coeff); four coupled steps must match."""
    rng = np.random.RandomState(23)
    D = 5
    w0 = rng.randn(D, 1).astype("float32")
    feeds = [(rng.randn(8, D).astype("float32"),
              rng.randn(8, 1).astype("float32")) for _ in range(4)]

    x = layers.data("x", [D], dtype="float32")
    y = layers.data("y", [1], dtype="float32")
    pred = layers.fc(x, size=1, bias_attr=False,
                     param_attr=fluid.ParamAttr(
                         regularizer=fluid.regularizer.L2Decay(0.1)))
    loss = layers.mean(layers.square_error_cost(pred, y))
    fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
    w_name = next(op for op in
                  fluid.default_main_program().global_block().ops
                  if op.type == "mul").input("Y")[0]
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    fluid.global_scope().set_var(w_name, w0.copy())
    for xv, yv in feeds:
        exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])
    got = np.asarray(fluid.global_scope().find_var(w_name))

    lin = torch.nn.Linear(D, 1, bias=False)
    with torch.no_grad():
        lin.weight.copy_(torch.tensor(w0.T))
    opt = torch.optim.SGD(lin.parameters(), lr=0.1, weight_decay=0.1)
    for xv, yv in feeds:
        opt.zero_grad()
        ((lin(torch.tensor(xv)) - torch.tensor(yv)) ** 2).mean().backward()
        opt.step()
    np.testing.assert_allclose(got, lin.weight.detach().numpy().T,
                               rtol=1e-5, atol=1e-6)
