"""Per-op sweep: pooling variants (reference: test_pool_max_op.py,
test_unpool_op.py, test_spp_op.py, test_adaptive_pool2d/3d in
test_pool2d_op.py, test_conv3d_transpose_op.py over pool_with_index_op.cc,
unpool_op.cc, spp_op.cc, pool_op.cc `adaptive`, conv_transpose_op.cc:358)."""

import numpy as np

import paddle_tpu as fluid
from op_test import OpTest


def _rand(shape, seed=0):
    return np.random.RandomState(seed).uniform(-1, 1, shape).astype("float32")


def _max_pool_with_index_ref(x, ksize, strides, paddings):
    n, c, h, w = x.shape
    oh = (h + 2 * paddings[0] - ksize[0]) // strides[0] + 1
    ow = (w + 2 * paddings[1] - ksize[1]) // strides[1] + 1
    out = np.zeros((n, c, oh, ow), dtype=x.dtype)
    mask = np.zeros((n, c, oh, ow), dtype=np.int32)
    for i in range(oh):
        for j in range(ow):
            hs = i * strides[0] - paddings[0]
            ws = j * strides[1] - paddings[1]
            best = np.full((n, c), -np.inf, dtype=np.float64)
            bidx = np.zeros((n, c), dtype=np.int64)
            for dh in range(ksize[0]):
                for dw in range(ksize[1]):
                    hh, ww = hs + dh, ws + dw
                    if 0 <= hh < h and 0 <= ww < w:
                        v = x[:, :, hh, ww]
                        upd = v > best
                        best = np.where(upd, v, best)
                        bidx = np.where(upd, hh * w + ww, bidx)
            out[:, :, i, j] = best
            mask[:, :, i, j] = bidx
    return out, mask


def test_max_pool2d_with_index():
    x = _rand((2, 3, 7, 7), seed=1)
    want, wmask = _max_pool_with_index_ref(x, [3, 3], [2, 2], [1, 1])

    class T(OpTest):
        op_type = "max_pool2d_with_index"

    t = T()
    t.inputs = {"X": x}
    t.attrs = {"ksize": [3, 3], "strides": [2, 2], "paddings": [1, 1]}
    t.outputs = {"Out": want, "Mask": wmask}
    t.check_output()
    t.check_grad(["X"], "Out", max_relative_error=0.02)


def test_max_pool3d_with_index():
    x = _rand((2, 2, 6, 6, 6), seed=2)

    class T(OpTest):
        op_type = "max_pool3d_with_index"

    t = T()
    t.inputs = {"X": x}
    t.attrs = {"ksize": [2, 2, 2], "strides": [2, 2, 2],
               "paddings": [0, 0, 0]}
    # reference by reshape trick: non-overlapping windows
    xr = x.reshape(2, 2, 3, 2, 3, 2, 3, 2)
    want = xr.max(axis=(3, 5, 7))
    t.outputs = {"Out": want,
                 "Mask": np.zeros_like(want, dtype=np.int32)}
    prog, startup, feed, _, out_names = t._build()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.program_guard(prog, startup):
        got, mask = exe.run(program=prog, feed=feed,
                            fetch_list=[out_names["Out"][0],
                                        out_names["Mask"][0]])
    np.testing.assert_allclose(got, want, rtol=1e-5)
    # mask decodes back to the max value
    flat = x.reshape(2, 2, -1)
    picked = np.take_along_axis(flat, mask.reshape(2, 2, -1), axis=2)
    np.testing.assert_allclose(picked.reshape(want.shape), want, rtol=1e-5)


def test_adaptive_pool2d():
    x = _rand((2, 3, 7, 5), seed=3)
    bins = [3, 2]
    want = np.zeros((2, 3, 3, 2), dtype="float32")
    for i in range(bins[0]):
        for j in range(bins[1]):
            h0, h1 = int(np.floor(i * 7 / 3)), int(np.ceil((i + 1) * 7 / 3))
            w0, w1 = int(np.floor(j * 5 / 2)), int(np.ceil((j + 1) * 5 / 2))
            want[:, :, i, j] = x[:, :, h0:h1, w0:w1].mean(axis=(2, 3))

    class T(OpTest):
        op_type = "pool2d"

    t = T()
    t.inputs = {"X": x}
    t.attrs = {"ksize": bins, "pooling_type": "avg", "adaptive": True}
    t.outputs = {"Out": want}
    t.check_output(atol=2e-5, rtol=2e-5)
    t.check_grad(["X"], "Out", max_relative_error=0.02)


def test_adaptive_pool3d_max():
    x = _rand((1, 2, 5, 5, 5), seed=4)
    bins = [2, 2, 2]
    want = np.zeros((1, 2, 2, 2, 2), dtype="float32")
    for i in range(2):
        for j in range(2):
            for k in range(2):
                s = [int(np.floor(d * 5 / 2)) for d in (i, j, k)]
                e = [int(np.ceil((d + 1) * 5 / 2)) for d in (i, j, k)]
                want[:, :, i, j, k] = x[:, :, s[0]:e[0], s[1]:e[1],
                                        s[2]:e[2]].max(axis=(2, 3, 4))

    class T(OpTest):
        op_type = "pool3d"

    t = T()
    t.inputs = {"X": x}
    t.attrs = {"ksize": bins, "pooling_type": "max", "adaptive": True}
    t.outputs = {"Out": want}
    t.check_output()


def test_unpool_roundtrip():
    x = _rand((2, 3, 8, 8), seed=5)
    pooled, mask = _max_pool_with_index_ref(x, [2, 2], [2, 2], [0, 0])

    class T(OpTest):
        op_type = "unpool"

    t = T()
    t.inputs = {"X": pooled, "Indices": mask}
    t.attrs = {"unpooling_type": "max", "ksize": [2, 2], "strides": [2, 2],
               "paddings": [0, 0]}
    want = np.zeros_like(x)
    n_ix, c_ix = np.meshgrid(range(2), range(3), indexing="ij")
    for i in range(pooled.shape[2]):
        for j in range(pooled.shape[3]):
            flat = mask[:, :, i, j]
            want.reshape(2, 3, -1)[n_ix, c_ix, flat] = pooled[:, :, i, j]
    t.outputs = {"Out": want}
    t.check_output()
    t.check_grad(["X"], "Out", max_relative_error=0.02)


def test_spp():
    x = _rand((2, 3, 7, 7), seed=6)
    ph = 3

    class T(OpTest):
        op_type = "spp"

    t = T()
    t.inputs = {"X": x}
    t.attrs = {"pyramid_height": ph, "pooling_type": "max"}
    total = sum(4 ** p for p in range(ph))
    t.outputs = {"Out": np.zeros((2, 3 * total), dtype="float32")}
    prog, startup, feed, _, out_names = t._build()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.program_guard(prog, startup):
        (got,) = exe.run(program=prog, feed=feed,
                         fetch_list=[out_names["Out"][0]])
    assert got.shape == (2, 3 * total)
    # level 0 is global max pool
    np.testing.assert_allclose(got[:, :3], x.max(axis=(2, 3)), rtol=1e-5)
    # level 1: 2x2 grid, kernel=ceil(7/2)=4, stride=4, pad=(4*2-7+1)/2=1
    xp = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)),
                constant_values=-np.inf)
    lvl1 = np.zeros((2, 3, 2, 2), dtype="float32")
    for i in range(2):
        for j in range(2):
            lvl1[:, :, i, j] = xp[:, :, i * 4:i * 4 + 4,
                                  j * 4:j * 4 + 4].max(axis=(2, 3))
    np.testing.assert_allclose(got[:, 3:15], lvl1.reshape(2, 12), rtol=1e-5)


def test_conv3d_transpose():
    x = _rand((1, 2, 3, 3, 3), seed=7)
    f = _rand((2, 3, 2, 2, 2), seed=8)  # [in_c, out_c, kd, kh, kw]
    # upsample-by-scatter reference: stride 2, no pad -> (3-1)*2 + 2 = 6
    want = np.zeros((1, 3, 6, 6, 6), dtype=np.float64)
    for d in range(3):
        for h in range(3):
            for w in range(3):
                for kd in range(2):
                    for kh in range(2):
                        for kw in range(2):
                            contrib = np.einsum(
                                "i,io->o", x[0, :, d, h, w].astype(np.float64),
                                f[:, :, kd, kh, kw].astype(np.float64))
                            want[0, :, d * 2 + kd, h * 2 + kh, w * 2 + kw] += contrib

    class T(OpTest):
        op_type = "conv3d_transpose"

    t = T()
    t.inputs = {"Input": x, "Filter": f}
    t.attrs = {"strides": [2, 2, 2], "paddings": [0, 0, 0],
               "dilations": [1, 1, 1]}
    t.outputs = {"Output": want.astype("float32")}
    t.check_output(atol=2e-5, rtol=2e-5)
    t.check_grad(["Input", "Filter"], "Output", max_relative_error=0.02)


def test_depthwise_conv2d_transpose():
    x = _rand((1, 3, 4, 4), seed=9)
    f = _rand((3, 1, 2, 2), seed=10)  # groups=3: [in_c, out/g, kh, kw]
    want = np.zeros((1, 3, 8, 8), dtype=np.float64)
    for c in range(3):
        for h in range(4):
            for w in range(4):
                for kh in range(2):
                    for kw in range(2):
                        want[0, c, h * 2 + kh, w * 2 + kw] += (
                            float(x[0, c, h, w]) * float(f[c, 0, kh, kw]))

    class T(OpTest):
        op_type = "depthwise_conv2d_transpose"

    t = T()
    t.inputs = {"Input": x, "Filter": f}
    t.attrs = {"strides": [2, 2], "paddings": [0, 0], "dilations": [1, 1],
               "groups": 3}
    t.outputs = {"Output": want.astype("float32")}
    t.check_output(atol=2e-5, rtol=2e-5)


def test_adaptive_pool2d_layer_with_index():
    x = _rand((2, 3, 6, 6), seed=11)
    prog = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(prog, startup):
        xv = fluid.layers.data(name="x", shape=[3, 6, 6], dtype="float32")
        out, mask = fluid.layers.adaptive_pool2d(xv, [3, 3], "max",
                                                 require_index=True)
        up = fluid.layers.unpool(out, mask, ksize=[2, 2], strides=[2, 2])
    exe = fluid.Executor(fluid.CPUPlace())
    got, gmask, gup = exe.run(program=prog, feed={"x": x},
                              fetch_list=[out, mask, up])
    # 6/3 = 2: exact reshape windows
    want = x.reshape(2, 3, 3, 2, 3, 2).max(axis=(3, 5))
    np.testing.assert_allclose(got, want, rtol=1e-5)
    assert gup.shape == (2, 3, 6, 6)
    # unpooled scatters each max back to its argmax position
    np.testing.assert_allclose(gup.sum(axis=(2, 3)), want.sum(axis=(2, 3)),
                               rtol=1e-5)
