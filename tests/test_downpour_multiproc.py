"""Cross-process Downpour: the PS serves its tables over the ps_rpc TCP
transport in one subprocess; two trainer subprocesses run Hogwild workers
against it (reference pattern: test_dist_base.py:212 forks real
pserver+trainer subprocesses on localhost and asserts dist loss ~= local
loss)."""

import json
import os
import socket
import subprocess
import sys
import tempfile
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

VOCAB = 100
EMB_DIM = 8

_COMMON = '''
import json, os, sys
import numpy as np
sys.path.insert(0, {repo!r})
os.environ["JAX_PLATFORMS"] = "cpu"
import paddle_tpu as fluid

VOCAB, EMB_DIM = {vocab}, {emb_dim}

def build_model():
    ids = fluid.layers.data(name="ids", shape=[1], dtype="int64")
    label = fluid.layers.data(name="label", shape=[1], dtype="float32")
    emb = fluid.layers.embedding(
        ids, size=[VOCAB, EMB_DIM], is_distributed=True,
        param_attr=fluid.ParamAttr(name="dist_emb"))
    fc1 = fluid.layers.fc(emb, size=16, act="relu")
    logit = fluid.layers.fc(fc1, size=1)
    return fluid.layers.reduce_mean(
        fluid.layers.sigmoid_cross_entropy_with_logits(logit, label))

def build_ps_param():
    from paddle_tpu.distributed import DownpourSGD
    loss = build_model()
    ps_param, _ = DownpourSGD(learning_rate=0.2, window=1).minimize(loss)
    ps_param["server_param"]["downpour_server_param"][
        "downpour_table_param"][1]["accessor"]["dense_sgd_param"]["adam"][
        "learning_rate"] = 0.05
    return loss, ps_param
'''

_SERVER = _COMMON + '''
from paddle_tpu.distributed.ps_core import PSCore
from paddle_tpu.distributed.ps_rpc import serve_ps

port = int(sys.argv[1])
loss, ps_param = build_ps_param()
core = PSCore.from_server_desc(ps_param["server_param"])

# seed the dense table from a startup-program init, like init_model()
exe = fluid.AsyncExecutor(fluid.CPUPlace())
exe.init_worker(ps_param, ps=core)
fluid.Executor(fluid.CPUPlace()).run(fluid.default_startup_program())
exe.init_model()

srv = serve_ps(core, port=port)
print("SERVING", srv.endpoint, flush=True)
srv.serve_forever if False else None
import threading, time
while True:
    time.sleep(0.2)
'''

_TRAINER = _COMMON + '''
from paddle_tpu.distributed.ps_rpc import RemotePS

endpoint, data_file, out_path = sys.argv[1], sys.argv[2], sys.argv[3]
loss, ps_param = build_ps_param()
exe = fluid.AsyncExecutor(fluid.CPUPlace())
exe.init_worker(ps_param, ps=RemotePS(endpoint))
fluid.Executor(fluid.CPUPlace()).run(fluid.default_startup_program())

desc = fluid.DataFeedDesc("""
name: "MultiSlotDataFeed"
batch_size: 32
multi_slot_desc {{
  slots {{ name: "ids" type: "uint64" is_dense: true is_used: true }}
  slots {{ name: "label" type: "float" is_dense: true is_used: true }}
}}
""")
for _ in range(4):
    exe.run(fluid.default_main_program(), desc, [data_file], thread_num=2,
            fetch=[loss])
open(out_path, "w").write("done")
print("TRAINED", flush=True)
'''

_EVAL = _COMMON + '''
from paddle_tpu.distributed.ps_rpc import RemotePS
from paddle_tpu.distributed.downpour import DENSE_TABLE_ID, SPARSE_TABLE_ID

endpoint, out_path = sys.argv[1], sys.argv[2]
loss, ps_param = build_ps_param()
exe = fluid.AsyncExecutor(fluid.CPUPlace())
ps = RemotePS(endpoint)
exe.init_worker(ps_param, ps=ps)
fluid.Executor(fluid.CPUPlace()).run(fluid.default_startup_program())
exe._pull_dense_into_scope()

rng = np.random.RandomState(7)
ids = rng.randint(VOCAB, size=(64, 1)).astype(np.int64)
label = (ids % 2 == 0).astype(np.float32)
rows = ps.sparse(SPARSE_TABLE_ID).pull(ids.reshape(-1))
emb_out = exe._emb_map[0][1]
v = fluid.Executor(fluid.CPUPlace(), donate_states=False).run(
    program=exe._worker_program,
    feed={{"ids": ids, "label": label,
          emb_out: rows.reshape(64, EMB_DIM)}},
    fetch_list=[loss.name])
result = {{"loss": float(np.ravel(np.asarray(v[0]))[0]),
          "sparse_rows": len(ps.sparse(SPARSE_TABLE_ID))}}
open(out_path, "w").write(json.dumps(result))
print("EVAL", result, flush=True)
'''


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _write_ctr_file(path, lines=300, seed=0):
    rng = np.random.RandomState(seed)
    with open(path, "w") as fh:
        for _ in range(lines):
            i = int(rng.randint(VOCAB))
            label = 1.0 if i % 2 == 0 else 0.0
            fh.write(f"1 {i} 1 {label}\n")


def test_downpour_cross_process_convergence(tmp_path):
    fmt = dict(repo=REPO, vocab=VOCAB, emb_dim=EMB_DIM)
    server_py = str(tmp_path / "server.py")
    trainer_py = str(tmp_path / "trainer.py")
    eval_py = str(tmp_path / "eval.py")
    open(server_py, "w").write(_SERVER.format(**fmt))
    open(trainer_py, "w").write(_TRAINER.format(**fmt))
    open(eval_py, "w").write(_EVAL.format(**fmt))

    data = [str(tmp_path / f"part-{i}") for i in range(2)]
    for i, p in enumerate(data):
        _write_ctr_file(p, seed=i)

    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO}
    env.pop("XLA_FLAGS", None)
    port = _free_port()
    server = subprocess.Popen(
        [sys.executable, server_py, str(port)], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    trainers = []
    try:
        # wait for SERVING with a hard deadline: readline() alone would
        # block forever on a wedged-but-alive server (review r5) —
        # a reader thread + join(timeout) bounds it
        import queue as _queue
        import threading as _threading

        lines: "_queue.Queue[str]" = _queue.Queue()
        _threading.Thread(
            target=lambda: [lines.put(ln) for ln in server.stdout],
            daemon=True).start()
        line = ""
        deadline = time.time() + 240
        while time.time() < deadline:
            try:
                line = lines.get(timeout=5)
            except _queue.Empty:
                assert server.poll() is None, "server died silently"
                continue
            if "SERVING" in line:
                break
            assert server.poll() is None, "server died: " + line
        assert "SERVING" in line, "server never reported SERVING in 240s"
        endpoint = line.split()[1]

        # cold-start loss ~ log(2)
        eval0 = str(tmp_path / "eval0.json")
        r = subprocess.run(
            [sys.executable, eval_py, endpoint, eval0], env=env,
            capture_output=True, text=True, timeout=480)
        assert r.returncode == 0, r.stdout + r.stderr
        first = json.loads(open(eval0).read())["loss"]
        assert abs(first - np.log(2.0)) < 0.05

        # two REAL trainer processes, different file shards
        trainers += [
            subprocess.Popen(
                [sys.executable, trainer_py, endpoint, data[i],
                 str(tmp_path / f"done{i}")],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True)
            for i in range(2)
        ]
        for t in trainers:
            out, _ = t.communicate(timeout=600)
            assert t.returncode == 0, out
            assert "TRAINED" in out

        evalf = str(tmp_path / "evalf.json")
        r = subprocess.run(
            [sys.executable, eval_py, endpoint, evalf], env=env,
            capture_output=True, text=True, timeout=480)
        assert r.returncode == 0, r.stdout + r.stderr
        result = json.loads(open(evalf).read())
        final = result["loss"]
        # convergence parity with the in-process run
        # (tests/test_downpour.py asserts the same drop on one process)
        assert final < first - 0.05, f"loss did not drop: {first} -> {final}"
        assert 0 < result["sparse_rows"] <= VOCAB
    finally:
        # kill EVERYTHING: a hung/failed trainer must not outlive the
        # test spinning against a dead PS endpoint (review r5)
        for t in trainers:
            if t.poll() is None:
                t.kill()
                t.wait()
        server.kill()
        server.wait()
